// dkps — native parameter-server transport core.
//
// Parity context: the reference's PS hot loop (reference
// distkeras/parameter_servers.py :: SocketParameterServer.run and
// distkeras/networking.py :: send_data/recv_data) served every worker from
// Python handler threads that pickled/unpickled the full weight set per
// round-trip while holding the GIL — SURVEY.md §3.3 calls the driver-side
// loop "GIL-contended" and names it the scalability choke point. This file
// is the rebuild's native equivalent for the genuinely-asynchronous
// parameter-server backend (ps_transport="native"): a C++ TCP service whose
// commit fold is a vectorized saxpy on a contiguous float32 center, with no
// interpreter, no pickle, and no GIL anywhere on the wire path. The Python
// side (distkeras_tpu/native_ps.py) only flattens pytrees to one f32 vector
// at the boundary.
//
// Fold semantics are the SAME linear forms MergeRule.fold defines
// (distkeras_tpu/parallel/merge_rules.py): every built-in rule folds one
// commit as center += scale * commit, where
//   ADAG                 scale = 1 / num_workers
//   DOWNPOUR / elastic   scale = 1
//   DynSGD               scale = 1 / (tau + 1), tau = center updates since
//                        that worker's last pull (tracked here, per worker)
// so MODE_FIXED covers the first three and MODE_INV_STALENESS the last.
//
// Wire protocol (little-endian, fixed-size frames — the payload length is
// pinned by the handshake, so a hostile frame can never trigger an
// attacker-sized allocation):
//   handshake: 6-byte magic "DKPS1\n" + u32 worker_id + u64 n_floats
//              server replies u8 (1 = accepted, 0 = length mismatch)
//   request:   u8 action; 1=PULL, 2=COMMIT (followed by n*4 payload bytes),
//              3=BYE, 4=COMMIT_INT8 (u32 S segments, then S x (u64 len +
//              f32 scale) headers with sum(len) validated == n, then n int8
//              bytes — the compressed-commit wire: 4x fewer payload bytes,
//              dequantized per segment into the fold, matching
//              parallel/compression.py's Int8Codec per-leaf scales),
//              5=PULL_INT8 (compressed-pull wire: the server block-
//              quantizes center+error_feedback in kPullBlock runs with one
//              f32 absmax scale per block and keeps the per-worker
//              quantization residual server-side — DoubleSqueeze-style
//              bidirectional compression, Tang et al. 2019; with int8
//              commits the round-trip moves ~2n bytes instead of 8n),
//              6=HEARTBEAT (u32 cumulative client retry count: renews the
//              worker's liveness lease, auto-registering — protocol parity
//              with the Python PS's "heartbeat" action; a worker whose
//              lease lapses past the server's lease_timeout is EVICTED:
//              counted in stats and its pull_version forgotten, so DynSGD
//              treats a zombie commit as maximally stale),
//              7=COMMIT_SEQ (u64 per-worker seqno + n*4 payload bytes:
//              the retry-safe commit — the server folds each (worker,
//              seq) at most once, so a client replaying a commit whose
//              ACK died cannot double-fold it; parity with the Python
//              PS's "seq"-carrying commit),
//              8=DEREGISTER (clean worker exit: drop the lease without
//              counting an eviction),
//              9=FENCE (u64 epoch: raise the server's fencing epoch —
//              monotone; the failover supervisor's last word to a
//              superseded primary, protocol parity with the Python PS's
//              "fence" action),
//              10=COMMIT_SEQ_E (u64 epoch + u64 seqno + n*4 payload:
//              the failover-safe commit — folded only when the client's
//              fencing epoch matches the server's, so a zombie
//              primary's (or a fenced server's) late folds are rejected
//              instead of absorbed into a superseded history),
//              12=JOIN (elastic live-join admission, parity with the
//              Python PS's "join" action: lease the worker quietly —
//              heartbeats stays a pure heartbeat count — and grow the
//              pool gauge; the joiner's next PULL records its
//              pull_version so DynSGD prices its first commit at the
//              true small tau),
//              13=DRAIN (u8 timeout flag: preemption drain — clean
//              deregister retiring the dedup seqno, plus the elastic
//              counters; timeout=1 records a deadline-lapsed drain),
//              14=EXCHANGE (u8 flags [bit0 seq, bit1 epoch, bit2 int8
//              reply, bit3 lag] + optional u64 epoch + optional u64 seq
//              + n*4 payload: the FUSED commit+pull — one round trip
//              folds the commit and answers with the fresh post-fold
//              center, halving the per-window wire cost of the classic
//              commit-then-pull pair; `lag` prices DynSGD tau from the
//              worker's PREVIOUS pull version, the pipelined worker's
//              honest one-window staleness)
//   reply:     PULL -> u64 center_version + n*4 bytes; COMMIT -> u8 ack;
//              PULL_INT8 -> u64 version + u32 nblocks + nblocks*f32 scales
//              + n int8 bytes; HEARTBEAT -> u8 (1 = renewed, 2 =
//              (re-)registered); COMMIT_SEQ -> u8 (1 = folded, 2 =
//              duplicate, dropped); DEREGISTER -> u8 ack; FENCE -> u8
//              ack + u64 epoch-now; COMMIT_SEQ_E -> u8 (1 = folded, 2 =
//              duplicate, 3 = FENCED — not folded) + u64 server epoch;
//              EXCHANGE -> u8 (1/2/3 as COMMIT_SEQ_E) + u64 server epoch
//              + unless fenced: u64 version + the PULL (or PULL_INT8)
//              reply payload
//
// Concurrency model matches the reference: accept loop + one handler thread
// per connection + one mutex around the center. The difference is what runs
// inside the lock: a memcpy or an auto-vectorized fused multiply-add over
// the flat center, not a Python bytecode loop.

#include <algorithm>
#include <array>
#include <atomic>
#include <cerrno>
#include <cfloat>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

namespace {

constexpr char kMagic[6] = {'D', 'K', 'P', 'S', '1', '\n'};
constexpr int MODE_FIXED = 0;
constexpr int MODE_INV_STALENESS = 1;
// compressed-pull quantization granularity: one f32 scale per 1024 values
// (scale overhead 4/4096 of the int8 payload; fine enough that a block's
// absmax never couples distant layers the way a whole-vector scale would)
constexpr uint64_t kPullBlock = 1024;

inline uint64_t pull_blocks(uint64_t n) {
  return (n + kPullBlock - 1) / kPullBlock;
}

// ---------------------------------------------------------------- crc32 --
// zlib-compatible CRC-32 (poly 0xEDB88320), slice-by-8: the payload hash
// runs once per durable commit OFF the center mutex, so it only needs to
// be fast enough not to dominate the handler thread (~1 B/cycle here).
// Python's zlib.crc32 verifies these frames on replay — same polynomial,
// same init/xorout, so the two sides agree bit-for-bit.
struct Crc32Tables {
  uint32_t t[8][256];
  Crc32Tables() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; ++i)
      for (int j = 1; j < 8; ++j)
        t[j][i] = (t[j - 1][i] >> 8) ^ t[0][t[j - 1][i] & 0xFF];
  }
};
const Crc32Tables kCrc;

uint32_t crc32_buf(const void* data, size_t len, uint32_t seed = 0) {
  const unsigned char* p = static_cast<const unsigned char*>(data);
  uint32_t c = ~seed;
  while (len >= 8) {
    uint32_t lo, hi;
    std::memcpy(&lo, p, 4);
    std::memcpy(&hi, p + 4, 4);
    c ^= lo;
    c = kCrc.t[7][c & 0xFF] ^ kCrc.t[6][(c >> 8) & 0xFF] ^
        kCrc.t[5][(c >> 16) & 0xFF] ^ kCrc.t[4][c >> 24] ^
        kCrc.t[3][hi & 0xFF] ^ kCrc.t[2][(hi >> 8) & 0xFF] ^
        kCrc.t[1][(hi >> 16) & 0xFF] ^ kCrc.t[0][hi >> 24];
    p += 8;
    len -= 8;
  }
  while (len--) c = kCrc.t[0][(c ^ *p++) & 0xFF] ^ (c >> 8);
  return ~c;
}

// -------------------------------------------------------------- adler32 --
// zlib-compatible Adler-32 for the O(model) WAL payload checksum (the
// fixed-size prefixes keep CRC-32). On the 1-hash-pass-per-durable-commit
// hot path the checksum IS the cost: slice-by-8 CRC runs ~1 B/cycle,
// while the SSSE3 maddubs formulation below runs ~5 B/cycle — and
// Python's zlib.adler32 verifies the same value on replay. Weaker mixing
// than CRC is fine for the job here (detecting torn/partial tails).
constexpr uint32_t kAdlerMod = 65521;
constexpr size_t kAdlerNMax = 5552;  // max bytes before the deferred mod

uint32_t adler32_scalar(const uint8_t* p, size_t len, uint32_t seed) {
  uint32_t a = seed & 0xFFFF, b = seed >> 16;
  while (len) {
    size_t n = len < kAdlerNMax ? len : kAdlerNMax;
    len -= n;
    for (size_t i = 0; i < n; ++i) {
      a += p[i];
      b += a;
    }
    p += n;
    a %= kAdlerMod;
    b %= kAdlerMod;
  }
  return (b << 16) | a;
}

#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>
__attribute__((target("ssse3"))) uint32_t adler32_ssse3(const uint8_t* p,
                                                        size_t len,
                                                        uint32_t seed) {
  uint32_t a = seed & 0xFFFF, b = seed >> 16;
  const __m128i zero = _mm_setzero_si128();
  const __m128i weights =
      _mm_setr_epi8(16, 15, 14, 13, 12, 11, 10, 9, 8, 7, 6, 5, 4, 3, 2, 1);
  const __m128i ones16 = _mm_set1_epi16(1);
  while (len >= 16) {
    size_t blocks = len / 16;
    if (blocks > kAdlerNMax / 16) blocks = kAdlerNMax / 16;
    // accumulators stay < 2^32 for <= 347 blocks (worst case ~3.92e9)
    __m128i vs2 = zero;   // weighted contributions to b
    __m128i vsum = zero;  // plain byte sum so far in this run
    const uint32_t a0 = a;
    for (size_t i = 0; i < blocks; ++i) {
      const __m128i chunk =
          _mm_loadu_si128(reinterpret_cast<const __m128i*>(p));
      p += 16;
      vs2 = _mm_add_epi32(vs2, _mm_slli_epi32(vsum, 4));
      const __m128i mad = _mm_maddubs_epi16(chunk, weights);
      vs2 = _mm_add_epi32(vs2, _mm_madd_epi16(mad, ones16));
      vsum = _mm_add_epi32(vsum, _mm_sad_epu8(chunk, zero));
    }
    alignas(16) uint32_t t[4];
    _mm_store_si128(reinterpret_cast<__m128i*>(t), vsum);
    const uint32_t sum = t[0] + t[2];  // sad lands in lanes 0 and 2
    _mm_store_si128(reinterpret_cast<__m128i*>(t), vs2);
    const uint32_t s2 = t[0] + t[1] + t[2] + t[3];
    const uint32_t nbytes = static_cast<uint32_t>(blocks * 16);
    b = (b + nbytes * a0 + s2) % kAdlerMod;
    a = (a0 + sum) % kAdlerMod;
    len -= blocks * 16;
  }
  return len ? adler32_scalar(p, len, (b << 16) | a) : (b << 16) | a;
}
#endif

uint32_t adler32_buf(const void* data, size_t len) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
#if defined(__x86_64__) || defined(__i386__)
  static const bool ssse3 = __builtin_cpu_supports("ssse3");
  if (ssse3) return adler32_ssse3(p, len, 1);
#endif
  return adler32_scalar(p, len, 1);
}

// WAL record types shared with resilience/wal.py (the flat, pickle-free
// family — Python's iter_records/replay_record decode them natively)
constexpr uint8_t REC_COMMIT_FLAT = 7;
constexpr uint8_t REC_PULL_FLAT = 8;
constexpr uint8_t REC_DEREG_FLAT = 9;
constexpr uint8_t REC_EVICT_FLAT = 10;
constexpr uint8_t REC_FENCE_FLAT = 11;
// frame header matches wal._HDR (">BII": type, crc32, len — BIG-endian)
constexpr size_t kWalHdr = 9;
// flat-commit prefix matches wal._CMTF ("<IqQQfI", packed little-endian):
// wid u32, seq i64 (-1 = none), pull_version u64, version u64,
// fold-scale f32, adler32(payload) u32
constexpr size_t kCmtPrefix = 36;

void put_hdr(char* out, uint8_t type, uint32_t crc, uint32_t len) {
  out[0] = static_cast<char>(type);
  uint32_t be_crc = __builtin_bswap32(crc);
  uint32_t be_len = __builtin_bswap32(len);
  std::memcpy(out + 1, &be_crc, 4);
  std::memcpy(out + 5, &be_len, 4);
}

// ---------------------------------------------------------------------------
// Shared-memory ring lane (ISSUE 12 — parity with distkeras_tpu/shm.py).
//
// A segment (created and owned by the Python wrapper, layout shared with
// the Python transport's header) carries two SPSC byte pipes: head/tail
// are monotonic u64 byte counters on their own cache lines, the writer
// owns head, the reader owns tail, and closed flags wake a blocked peer.
// The native wire protocol is already self-framing, so the rings move its
// exact frame bytes — no record layer: the whole TCP handler and client
// run UNCHANGED over a ring by representing a channel as a NEGATIVE fd
// (-2, -3, …) that send_all/recv_all dispatch on. Wakeup is a short
// relax-spin, then yields, then 50 µs sleeps (no GIL here, so spinning is
// safe and the common wake is sub-microsecond); client-side ops honour
// the same timeout_ms knob as SO_RCVTIMEO on the socket lane.
constexpr uint64_t kShmHdrBytes = 4096;
constexpr size_t kShmOffC2SHead = 64;
constexpr size_t kShmOffC2STail = 128;
constexpr size_t kShmOffS2CHead = 192;
constexpr size_t kShmOffS2CTail = 256;
constexpr size_t kShmOffClientClosed = 384;
constexpr size_t kShmOffServerClosed = 448;

struct ShmRing {
  std::atomic<uint64_t>* head = nullptr;
  std::atomic<uint64_t>* tail = nullptr;
  char* data = nullptr;
  uint64_t cap = 0;
};

struct ShmChan {
  ShmRing rx, tx;
  std::atomic<uint64_t>* my_closed = nullptr;
  std::atomic<uint64_t>* peer_closed = nullptr;
  std::atomic<int> timeout_ms{0};
};

// channels are registered once and retired by their closed flag — slots
// are never reused (bounded: one per connection; 4096 is far above any
// real colocated worker count and a leak of ~100 B per retired slot)
constexpr int kShmMaxChans = 4096;
ShmChan* g_shm_chans[kShmMaxChans];
std::atomic<int> g_shm_nchans{0};
std::mutex g_shm_mu;

inline ShmChan* shm_chan(int fd) { return g_shm_chans[-fd - 2]; }

// register one endpoint over an already-mapped segment; returns the
// pseudo-fd (< 0) or 0 when the channel table is full
int shm_register(void* base, uint64_t bytes, bool server_side) {
  if (bytes <= kShmHdrBytes) return 0;
  const uint64_t cap = (bytes - kShmHdrBytes) / 2;
  char* b = static_cast<char*>(base);
  auto at = [&](size_t off) {
    return reinterpret_cast<std::atomic<uint64_t>*>(b + off);
  };
  auto* ch = new ShmChan();
  ShmRing c2s{at(kShmOffC2SHead), at(kShmOffC2STail), b + kShmHdrBytes,
              cap};
  ShmRing s2c{at(kShmOffS2CHead), at(kShmOffS2CTail),
              b + kShmHdrBytes + cap, cap};
  if (server_side) {
    ch->rx = c2s;
    ch->tx = s2c;
    ch->my_closed = at(kShmOffServerClosed);
    ch->peer_closed = at(kShmOffClientClosed);
  } else {
    ch->rx = s2c;
    ch->tx = c2s;
    ch->my_closed = at(kShmOffClientClosed);
    ch->peer_closed = at(kShmOffServerClosed);
  }
  std::lock_guard<std::mutex> g(g_shm_mu);
  const int idx = g_shm_nchans.load(std::memory_order_relaxed);
  if (idx >= kShmMaxChans) {
    delete ch;
    return 0;
  }
  g_shm_chans[idx] = ch;
  g_shm_nchans.store(idx + 1, std::memory_order_release);
  return -(idx + 2);
}

inline bool shm_closed(ShmChan* ch) {
  return ch->my_closed->load(std::memory_order_relaxed) ||
         ch->peer_closed->load(std::memory_order_relaxed);
}

// spin-then-wait backoff: relax-spin first (the peer is usually mid-copy
// on another core), then yield, then bounded sleeps
struct ShmWaiter {
  int spins = 0;
  std::chrono::steady_clock::time_point deadline{};
  bool bounded = false;
  explicit ShmWaiter(int timeout_ms) {
    if (timeout_ms > 0) {
      bounded = true;
      deadline = std::chrono::steady_clock::now() +
                 std::chrono::milliseconds(timeout_ms);
    }
  }
  // returns false when the (client-side) timeout lapsed
  bool pause() {
    ++spins;
    if (spins < 256) {
      // plain relax iteration; the load in the caller's loop is the wait
    } else if (spins < 1024) {
      std::this_thread::yield();
    } else {
      std::this_thread::sleep_for(std::chrono::microseconds(50));
      if (bounded && std::chrono::steady_clock::now() >= deadline)
        return false;
    }
    return true;
  }
};

bool shm_send_chan(ShmChan* ch, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  ShmRing& r = ch->tx;
  uint64_t head = r.head->load(std::memory_order_relaxed);
  ShmWaiter w(ch->timeout_ms.load(std::memory_order_relaxed));
  while (n) {
    const uint64_t tail = r.tail->load(std::memory_order_acquire);
    const uint64_t free_b = r.cap - (head - tail);
    if (free_b == 0) {
      if (shm_closed(ch)) return false;
      if (!w.pause()) return false;
      continue;
    }
    const uint64_t pos = head % r.cap;
    uint64_t k = n;
    if (k > free_b) k = free_b;
    if (k > r.cap - pos) k = r.cap - pos;
    std::memcpy(r.data + pos, p, k);
    head += k;
    r.head->store(head, std::memory_order_release);
    p += k;
    n -= static_cast<size_t>(k);
    w.spins = 0;
  }
  return true;
}

bool shm_recv_chan(ShmChan* ch, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  ShmRing& r = ch->rx;
  uint64_t tail = r.tail->load(std::memory_order_relaxed);
  ShmWaiter w(ch->timeout_ms.load(std::memory_order_relaxed));
  while (n) {
    const uint64_t head = r.head->load(std::memory_order_acquire);
    const uint64_t avail = head - tail;
    if (avail == 0) {
      // drain-before-fail: buffered bytes stay readable past a close
      if (shm_closed(ch)) return false;
      if (!w.pause()) return false;
      continue;
    }
    const uint64_t pos = tail % r.cap;
    uint64_t k = n;
    if (k > avail) k = avail;
    if (k > r.cap - pos) k = r.cap - pos;
    std::memcpy(p, r.data + pos, k);
    tail += k;
    r.tail->store(tail, std::memory_order_release);
    p += k;
    n -= static_cast<size_t>(k);
    w.spins = 0;
  }
  return true;
}

// connection close that understands both lanes: a ring peer is woken by
// the closed flag (its next wait observes it), a socket is closed
void close_conn_fd(int fd) {
  if (fd < 0) {
    shm_chan(fd)->my_closed->store(1, std::memory_order_release);
    return;
  }
  ::close(fd);
}

void shutdown_conn_fd(int fd) {
  if (fd < 0) {
    shm_chan(fd)->my_closed->store(1, std::memory_order_release);
    return;
  }
  ::shutdown(fd, SHUT_RDWR);
}

bool send_all(int fd, const void* buf, size_t n) {
  if (fd < 0) return shm_send_chan(shm_chan(fd), buf, n);
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t k = ::send(fd, p, n, MSG_NOSIGNAL);
    if (k <= 0) {
      if (k < 0 && errno == EINTR) continue;
      return false;
    }
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  if (fd < 0) return shm_recv_chan(shm_chan(fd), buf, n);
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k <= 0) {
      if (k < 0 && errno == EINTR) continue;
      return false;
    }
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

void set_nodelay(int fd) {
  if (fd < 0) return;  // ring lane: no socket options to set
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

struct Server {
  std::vector<float> center;
  // Polyak/EMA of the center, updated per commit when ema_decay >= 0
  // (negative = off) — same semantics as the Python PS's get_ema()
  std::vector<float> ema;
  double ema_decay = -1.0;
  uint64_t n = 0;
  int mode = MODE_FIXED;
  double fixed_scale = 1.0;
  std::mutex mu;
  uint64_t num_updates = 0;
  std::unordered_map<uint32_t, uint64_t> pull_versions;
  // The PREVIOUS recorded pull version per worker (ISSUE 10): every
  // pull-version record shifts cur -> prev. A pipelined fused EXCHANGE
  // (action 14, lag flag) prices DynSGD tau from prev — the delta it
  // commits was computed from the center returned one exchange ago, and
  // that deliberate extra window of staleness must be priced. Under mu;
  // replay reconstructs it with the identical shift rule.
  std::unordered_map<uint32_t, uint64_t> prev_pull_versions;
  // Per-worker compressed-pull quantization residual (error feedback): the
  // part of center+e the int8 wire dropped, re-added to that worker's next
  // compressed pull so its received stream telescopes to the true center
  // stream. Sized lazily on a worker's first PULL_INT8; exact pulls and
  // workers that never compress cost nothing. Each worker's state carries
  // its OWN mutex: quantization runs outside the center lock so different
  // workers' pulls overlap, but a reconnecting client reusing a worker id
  // while the old handler is mid-quantize must serialize against it, not
  // race on the shared residual (map nodes are reference-stable, so the
  // struct address stays valid across other workers' insertions).
  struct PullErr {
    std::mutex m;
    std::vector<float> err;
  };
  std::unordered_map<uint32_t, PullErr> pull_errors;

  // Per-worker last APPLIED commit seqno (COMMIT_SEQ dedup) — under mu,
  // probed once per seq'd commit, so the fold's critical section stays
  // O(fold) + O(1).
  std::unordered_map<uint32_t, uint64_t> last_seq;

  // Liveness leases (HEARTBEAT/DEREGISTER; parity with the Python PS's
  // resilience/heartbeat.py registry): renewed by heartbeats, scanned
  // lazily (rate-limited to a quarter lease) under their OWN mutex —
  // never while holding mu; eviction then takes mu to forget the dead
  // worker's pull_version (zombie commits read as maximally stale).
  struct Lease {
    uint64_t deadline_ns = 0;
    uint64_t renewals = 0;
  };
  double lease_timeout_s = 30.0;
  std::mutex lease_mu;
  std::unordered_map<uint32_t, Lease> leases;
  uint64_t next_expiry_ns = 0;            // under lease_mu
  // Latest cumulative client-reported retry count per worker id, kept
  // across lease lifecycles (clients report running totals; folding into
  // a sum at eviction would double-count after re-admission). Under
  // lease_mu; summed at stats time.
  std::unordered_map<uint32_t, uint32_t> retries_by_wid;
  std::atomic<uint64_t> st_heartbeats{0}, st_evicted{0}, st_dups{0};

  // Fencing epoch (protocol parity with the Python PS / resilience
  // failover): COMMIT_SEQ_E folds only when the client's epoch matches;
  // FENCE raises it monotonically. Under mu (checked inside the fold's
  // critical section — one integer compare).
  uint64_t fence_epoch = 0;
  std::atomic<uint64_t> st_fenced{0};

  // Shard-map handshake (distkeras_tpu/sharding): which shard of an
  // N-shard center this server holds. num_shards == 0 means unsharded
  // (the default — SHARD_INFO then reports "no shard record", exactly
  // like the Python server's shard_info = None). Atomics: set once by
  // dkps_server_set_shard before traffic, read per SHARD_INFO request.
  std::atomic<uint32_t> shard_id{0};
  std::atomic<uint32_t> num_shards{0};

  // Elastic-membership accounting (resilience/elastic.py; parity with
  // the Python PS's join_worker/drain_worker): the pool gauge starts at
  // the configured worker count (dkps_server_set_pool_size) and tracks
  // joins minus drains; the other three are lifetime totals. Telemetry,
  // not durable state — like the op counters they restart on recovery.
  std::atomic<int64_t> st_pool{0};
  std::atomic<uint64_t> st_joined{0}, st_preempted{0}, st_drain_to{0};
  // join/drain idempotence (under lease_mu; parity with the Python PS):
  // a lost-ACK replay of the JOIN/DRAIN wire action must not
  // double-count the membership event. A wid's join counts once until
  // it drains, its drain once until it re-joins; eviction clears both.
  std::unordered_set<uint32_t> joined_wids, drained_wids;

  // -- write-ahead log with GROUP COMMIT (ISSUE 7; same frame format as
  // resilience/wal.py, so Python's recover_ps_state replays a native-
  // written log bit-identically). Appends run under the center mutex —
  // fold order IS log order — but only memcpy pre-encoded bytes into the
  // in-memory `pending` buffer; the flusher thread batches a window of
  // commits onto ONE write+fsync and wakes every waiter at once. Commit
  // handlers defer their ACK until their record is durable (wal_wait),
  // so ACK => fsync'd — the strongest durability this file has ever had,
  // at ~1/window the sync cost. window 0 = time-bounded async (no ACK
  // deferral; fsync at least every interval_s — the quiet-period bound).
  struct WalRec {
    char head[kWalHdr + kCmtPrefix];  // header + (for commits) prefix
    uint32_t head_len = 0;
    // commit payloads are logged ZERO-COPY in the deferred-ACK modes:
    // `payload` points into the handler's scratch buffer, which stays
    // alive because the handler blocks in wal_wait until this record is
    // durable (and a crash clears the queue before waking it). Window 0
    // (no wait) copies into `owned` instead.
    const char* payload = nullptr;
    size_t payload_len = 0;
    std::vector<char> owned;
  };
  struct Wal {
    int fd = -1;
    uint64_t window = 8;
    double interval_s = 0.25;
    std::mutex wmu;  // guards the queue/counters; taken AFTER mu, never
                     // the other way (the flusher takes wmu only)
    std::mutex io_mu;  // serializes writers (flusher / close); appenders
                       // never take it — the fold path can't block on I/O
    std::condition_variable cv;
    std::vector<WalRec> queue;
    uint64_t appended = 0, durable = 0;
    uint64_t commits_appended = 0, commits_durable = 0;
    uint64_t queued_bytes = 0;
    uint64_t waiters = 0;
    bool running = false, abandoned = false;
    std::chrono::steady_clock::time_point first_pending{};
    bool has_pending = false;
    std::thread flusher;
    std::atomic<uint64_t> st_records{0}, st_fsyncs{0}, st_group_max{0};
  };
  Wal wal;
  bool wal_on = false;  // set before start(), read-only afterwards

  // queue one encoded record — call under mu (log order == fold order);
  // takes wmu internally. O(1) in the payload when `copy` is false (the
  // deferred-ACK modes): the queue holds a POINTER into the caller's
  // buffer, pinned by the caller's wal_wait. Returns the wait token.
  uint64_t wal_append_locked(const char* head, size_t head_len,
                             const void* payload, size_t payload_len,
                             bool commit, bool copy) {
    std::lock_guard<std::mutex> g(wal.wmu);
    wal.queue.emplace_back();
    WalRec& r = wal.queue.back();
    std::memcpy(r.head, head, head_len);
    r.head_len = static_cast<uint32_t>(head_len);
    if (payload_len) {
      const char* pay = static_cast<const char*>(payload);
      if (copy) {
        r.owned.assign(pay, pay + payload_len);
        r.payload = r.owned.data();
      } else {
        r.payload = pay;
      }
      r.payload_len = payload_len;
    }
    wal.appended += 1;
    wal.queued_bytes += head_len + payload_len;
    wal.st_records += 1;
    if (commit) wal.commits_appended += 1;
    if (!wal.has_pending) {
      wal.has_pending = true;
      wal.first_pending = std::chrono::steady_clock::now();
    }
    wal.cv.notify_all();
    return wal.appended;
  }

  // `staged`: window-0 callers pre-copy the payload bytes OFF the center
  // mutex (they never wal_wait, so the queue can't reference their
  // receive buffer) and hand ownership here; window >= 1 callers pass
  // nullptr and the queue references `payload` zero-copy — the handler
  // blocks in wal_wait before reusing it. Either way the critical
  // section stays O(1) in the payload size.
  uint64_t wal_append_commit_locked(uint32_t wid, int64_t seq, uint64_t pv,
                                    uint64_t version, float scale,
                                    const float* payload, uint64_t count,
                                    uint32_t payload_crc,
                                    std::vector<char>* staged) {
    char head[kWalHdr + kCmtPrefix];
    char* prefix = head + kWalHdr;
    std::memcpy(prefix + 0, &wid, 4);
    std::memcpy(prefix + 4, &seq, 8);
    std::memcpy(prefix + 12, &pv, 8);
    std::memcpy(prefix + 20, &version, 8);
    std::memcpy(prefix + 28, &scale, 4);
    std::memcpy(prefix + 32, &payload_crc, 4);
    put_hdr(head, REC_COMMIT_FLAT, crc32_buf(prefix, kCmtPrefix),
            static_cast<uint32_t>(kCmtPrefix + count * 4));
    if (staged != nullptr)
      return wal_append_owned_locked(head, sizeof(head), staged,
                                     /*commit=*/true);
    return wal_append_locked(head, sizeof(head), payload, count * 4,
                             /*commit=*/true, /*copy=*/false);
  }

  // take ownership of a pre-staged payload vector (O(1) move under mu)
  uint64_t wal_append_owned_locked(const char* head, size_t head_len,
                                   std::vector<char>* staged, bool commit) {
    std::lock_guard<std::mutex> g(wal.wmu);
    wal.queue.emplace_back();
    WalRec& r = wal.queue.back();
    std::memcpy(r.head, head, head_len);
    r.head_len = static_cast<uint32_t>(head_len);
    r.owned = std::move(*staged);
    r.payload = r.owned.data();
    r.payload_len = r.owned.size();
    wal.appended += 1;
    wal.queued_bytes += head_len + r.payload_len;
    wal.st_records += 1;
    if (commit) wal.commits_appended += 1;
    if (!wal.has_pending) {
      wal.has_pending = true;
      wal.first_pending = std::chrono::steady_clock::now();
    }
    wal.cv.notify_all();
    return wal.appended;
  }

  uint64_t wal_append_small_locked(uint8_t type, const char* body,
                                   size_t len) {
    // small control records (pull/dereg/evict/fence) are copied into the
    // queue — their stack bodies die with this call. An evict body can
    // exceed the fixed head buffer, so it rides the owned-payload slot.
    char head[kWalHdr + kCmtPrefix];
    put_hdr(head, type, crc32_buf(body, len), static_cast<uint32_t>(len));
    return wal_append_locked(head, kWalHdr, body, len,
                             /*commit=*/false, /*copy=*/true);
  }

  void wal_append_pull_locked(uint32_t wid, uint64_t version) {
    char body[12];
    std::memcpy(body + 0, &wid, 4);
    std::memcpy(body + 4, &version, 8);
    wal_append_small_locked(REC_PULL_FLAT, body, sizeof(body));
  }

  uint64_t wal_append_fence_locked(uint64_t epoch) {
    char body[8];
    std::memcpy(body, &epoch, 8);
    return wal_append_small_locked(REC_FENCE_FLAT, body, sizeof(body));
  }

  void wal_append_dereg_locked(uint32_t wid) {
    char body[4];
    std::memcpy(body, &wid, 4);
    wal_append_small_locked(REC_DEREG_FLAT, body, sizeof(body));
  }

  void wal_append_evict_locked(const std::vector<uint32_t>& wids) {
    std::vector<char> body(4 + wids.size() * 4);
    uint32_t count = static_cast<uint32_t>(wids.size());
    std::memcpy(body.data(), &count, 4);
    for (size_t i = 0; i < wids.size(); ++i)
      std::memcpy(body.data() + 4 + i * 4, &wids[i], 4);
    wal_append_small_locked(REC_EVICT_FLAT, body.data(), body.size());
  }

  // block until record `token` is fsync'd (the deferred ACK). False =
  // the log was abandoned (crash seam) — the caller skips its ACK; the
  // client never hears back and replays, the dedup table folds it once.
  // A zero-copy record's payload buffer is pinned exactly as long as its
  // appender sits here: the flusher's drain writes it BEFORE durability
  // advances, and a crash clears the queue BEFORE `abandoned` wakes us.
  bool wal_wait(uint64_t token) {
    std::unique_lock<std::mutex> lk(wal.wmu);
    wal.waiters += 1;
    wal.cv.notify_all();  // the flusher syncs eagerly for waiters
    while (wal.durable < token && !wal.abandoned)
      wal.cv.wait_for(lk, std::chrono::milliseconds(100));
    wal.waiters -= 1;
    return wal.durable >= token;
  }

  // drain the queue → write → fsync → publish durability. Writers
  // (flusher, wal_close) serialize on io_mu; appenders never take it.
  bool wal_drain_and_sync() {
    std::lock_guard<std::mutex> io(wal.io_mu);
    std::vector<WalRec> batch;
    uint64_t upto, upto_commits;
    {
      std::lock_guard<std::mutex> g(wal.wmu);
      if (wal.abandoned || wal.fd < 0) return false;
      batch.swap(wal.queue);
      upto = wal.appended;
      upto_commits = wal.commits_appended;
      wal.queued_bytes = 0;
      wal.has_pending = false;
    }
    // the group-fsync span (flusher thread): the segment every
    // deferred-ACK commit's TK_WAL_WAIT span ends on. No worker/seq —
    // one fsync serves a whole window.
    const uint64_t t_sync = trace_t0();
    bool ok = true;
    for (const WalRec& r : batch) {
      const char* parts[2] = {r.head, r.payload};
      const size_t lens[2] = {r.head_len, r.payload_len};
      for (int i = 0; i < 2 && ok; ++i) {
        const char* p = parts[i];
        size_t left = lens[i];
        while (left) {
          ssize_t k = ::write(wal.fd, p, left);
          if (k < 0) {
            if (errno == EINTR) continue;
            ok = false;
            break;
          }
          p += k;
          left -= static_cast<size_t>(k);
        }
      }
      if (!ok) break;
    }
    if (ok && ::fsync(wal.fd) != 0) ok = false;
    {
      std::lock_guard<std::mutex> g(wal.wmu);
      if (ok) {
        uint64_t group = upto_commits - wal.commits_durable;
        uint64_t prev = wal.st_group_max.load();
        if (group > prev) wal.st_group_max = group;
        wal.durable = std::max(wal.durable, upto);
        wal.commits_durable = std::max(wal.commits_durable, upto_commits);
        wal.st_fsyncs += 1;
      } else {
        // a write/fsync that cannot succeed would strand waiters (and
        // their pinned buffers) forever: abandon instead — clients see
        // no ACK and replay against whatever IS durable
        wal.abandoned = true;
      }
      wal.cv.notify_all();
    }
    trace_rec(TK_FSYNC, 0xffffffffull, 0, t_sync);
    return ok;
  }

  void wal_flush_loop() {
    for (;;) {
      {
        std::unique_lock<std::mutex> lk(wal.wmu);
        for (;;) {
          if (!wal.running) return;
          if (!wal.queue.empty() && !wal.abandoned) {
            const double age =
                wal.has_pending
                    ? std::chrono::duration<double>(
                          std::chrono::steady_clock::now() -
                          wal.first_pending)
                          .count()
                    : 0.0;
            const uint64_t pending_commits =
                wal.commits_appended - wal.commits_durable;
            if (wal.waiters > 0 ||
                (wal.window >= 1 && pending_commits >= wal.window) ||
                wal.queued_bytes >= (64u << 20) || age >= wal.interval_s)
              break;
          }
          wal.cv.wait_for(
              lk, std::chrono::duration<double>(wal.interval_s));
        }
      }
      wal_drain_and_sync();
    }
  }

  // clean shutdown: drain + fsync + close (a CRASH uses wal_abandon).
  // Handlers blocked in wal_wait were released by the still-running
  // flusher before the server joined them — only no-waiter records
  // (pulls, window-0 commits) can still sit in the queue here.
  void wal_close() {
    if (!wal_on) return;
    bool was_abandoned;
    {
      std::lock_guard<std::mutex> g(wal.wmu);
      was_abandoned = wal.abandoned;
    }
    if (!was_abandoned) wal_drain_and_sync();
    {
      std::lock_guard<std::mutex> g(wal.wmu);
      wal.running = false;
      wal.cv.notify_all();
    }
    if (wal.flusher.joinable()) wal.flusher.join();
    std::lock_guard<std::mutex> io(wal.io_mu);
    std::lock_guard<std::mutex> g(wal.wmu);
    if (wal.fd >= 0) {
      ::close(wal.fd);
      wal.fd = -1;
    }
    wal.queue.clear();
  }

  // crash seam: lose the queued records (a SIGKILL'd process's user-space
  // bytes) and wake every deferred-ACK waiter to give up. Order matters
  // for the zero-copy payloads: (1) clear the queue and stop the flusher
  // — waiters stay parked, so every buffer a swapped in-flight batch
  // might still reference stays alive; (2) join the flusher; (3) only
  // THEN set `abandoned`, waking waiters whose buffers nothing
  // references anymore; (4) close the fd last, so no write ever lands on
  // a recycled descriptor.
  void wal_abandon() {
    if (!wal_on) return;
    {
      std::lock_guard<std::mutex> g(wal.wmu);
      wal.running = false;  // flusher exits; wal_wait does NOT check this
      wal.queue.clear();
      wal.cv.notify_all();
    }
    if (wal.flusher.joinable()) wal.flusher.join();
    {
      std::lock_guard<std::mutex> g(wal.wmu);
      wal.abandoned = true;
      wal.cv.notify_all();
    }
    std::lock_guard<std::mutex> io(wal.io_mu);
    std::lock_guard<std::mutex> g(wal.wmu);
    if (wal.fd >= 0) {
      ::close(wal.fd);
      wal.fd = -1;
    }
  }

  static uint64_t now_ns() {
    return static_cast<uint64_t>(
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now().time_since_epoch())
            .count());
  }

  // Evict lapsed leases (rate-limited on the hot path; force=true skips
  // the limiter so observability reads never see a lapsed lease as
  // live). Lock order: lease_mu released BEFORE mu is taken for the
  // pull_version cleanup.
  void expire_leases(bool force = false) {
    const uint64_t now = now_ns();
    std::vector<uint32_t> dead;
    {
      std::lock_guard<std::mutex> g(lease_mu);
      if (!force && now < next_expiry_ns) return;
      const uint64_t every = static_cast<uint64_t>(
          std::max(lease_timeout_s / 4.0, 1e-3) * 1e9);
      next_expiry_ns = now + every;
      for (auto it = leases.begin(); it != leases.end();) {
        if (it->second.deadline_ns < now) {
          dead.push_back(it->first);
          it = leases.erase(it);
        } else {
          ++it;
        }
      }
      for (uint32_t wid : dead) {
        // membership hygiene (parity with the Python _on_evict): an
        // evicted wid's join/drain idempotence records retire with it
        joined_wids.erase(wid);
        drained_wids.erase(wid);
      }
      st_evicted += dead.size();
    }
    if (!dead.empty()) {
      std::lock_guard<std::mutex> g(mu);
      for (uint32_t wid : dead) {
        pull_versions.erase(wid);
        prev_pull_versions.erase(wid);
        // retire the commit-dedup entry too (parity with the Python
        // _on_evict): long elastic runs with many worker generations
        // must not grow last_seq without bound
        last_seq.erase(wid);
      }
      if (wal_on) wal_append_evict_locked(dead);
    }
  }

  // returns true when the lease already existed (a renewal)
  bool heartbeat(uint32_t wid, uint32_t retries) {
    const uint64_t deadline =
        now_ns() + static_cast<uint64_t>(lease_timeout_s * 1e9);
    bool known;
    {
      std::lock_guard<std::mutex> g(lease_mu);
      st_heartbeats += 1;
      auto it = leases.find(wid);
      known = it != leases.end();
      Lease& l = known ? it->second : leases[wid];
      l.deadline_ns = deadline;
      l.renewals += 1;
      if (retries) {
        uint32_t& r = retries_by_wid[wid];
        r = std::max(r, retries);
      }
    }
    expire_leases();
    return known;
  }

  void deregister(uint32_t wid) {
    {
      std::lock_guard<std::mutex> g(lease_mu);
      leases.erase(wid);
    }
    // retire the seqno fence too (fresh clients start a new epoch; the
    // fence would only grow the map) — lease_mu released before mu.
    // Pull-version slots (cur AND prev) retire with the clean exit: a
    // same-id successor's first pull must not shift this generation's
    // version into prev, where a lag-priced exchange would read it
    // (parity with the Python deregister_worker).
    std::lock_guard<std::mutex> g(mu);
    last_seq.erase(wid);
    pull_versions.erase(wid);
    prev_pull_versions.erase(wid);
    if (wal_on) wal_append_dereg_locked(wid);
  }

  // elastic live-join (JOIN, action 12): lease the worker QUIETLY (no
  // heartbeat counted — parity with WorkerRegistry.register) and grow
  // the pool gauge. Returns the post-join pool size.
  int64_t join_wid(uint32_t wid) {
    const uint64_t deadline =
        now_ns() + static_cast<uint64_t>(lease_timeout_s * 1e9);
    {
      std::lock_guard<std::mutex> g(lease_mu);
      Lease& l = leases[wid];
      l.deadline_ns = deadline;
      drained_wids.erase(wid);
      if (!joined_wids.insert(wid).second)
        return st_pool.load();  // lost-ACK replay: already counted
    }
    st_joined += 1;
    return st_pool += 1;
  }

  // preemption drain (DRAIN, action 13): a clean deregister plus the
  // elastic counters; timed_out records a deadline-lapsed drain.
  void drain_wid(uint32_t wid, bool timed_out) {
    deregister(wid);
    {
      std::lock_guard<std::mutex> g(lease_mu);
      if (!drained_wids.insert(wid).second)
        return;  // lost-ACK replay: this drain already counted
      joined_wids.erase(wid);
    }
    st_preempted += 1;
    if (timed_out) st_drain_to += 1;
    int64_t pool = st_pool.load();
    while (pool > 0 &&
           !st_pool.compare_exchange_weak(pool, pool - 1)) {
    }
  }

  // Contention/throughput counters (parity with the Python PS's stats():
  // same semantics, read via dkps_server_stats). Atomics: bumped from
  // handler threads, read lock-free by the stats call. Byte counters are
  // PAYLOAD bytes (weights/quantized values + per-segment scale metadata)
  // — the few fixed per-op protocol bytes (action, version, counts) are
  // excluded, matching the Python side's "framing excluded" accounting.
  // Lock wait/hold cover the CENTER mutex's hot-path sections only (pull
  // snapshot, commit fold) — admin reads (get_center etc.) stay
  // unlogged, same as the Python side.
  std::atomic<uint64_t> st_pulls{0}, st_cpulls{0}, st_commits{0};
  std::atomic<uint64_t> st_fused{0};  // fused EXCHANGE ops served
  std::atomic<uint64_t> st_bytes_in{0}, st_bytes_out{0};
  std::atomic<uint64_t> st_lock_acquires{0}, st_lock_wait_ns{0},
      st_lock_hold_ns{0};
  // Delivered-traffic settling (ISSUE 11): handlers bump this around
  // the reply-send → counter-land window of the pull-side paths;
  // dkps_server_stats waits (bounded) for it to reach zero so an
  // end-of-run stats read sees every delivered reply counted — parity
  // with the Python server's _settle_stats barrier.
  std::atomic<int64_t> st_pending{0};
  struct PendingGuard {
    Server* s;
    explicit PendingGuard(Server* srv) : s(srv) { s->st_pending += 1; }
    ~PendingGuard() { s->st_pending -= 1; }
  };

  // Flight-recorder span ring (ISSUE 11): fixed-capacity ring of
  // (kind, wid, seq, t0_ns, dur_ns) span records over CLOCK_MONOTONIC —
  // the SAME clock Python's perf_counter_ns reads on Linux, so scraped
  // spans drop into the Python tracer's timeline with no offset
  // arithmetic. Armed by dkps_server_set_trace, DRAINED by the TRACE
  // wire action (15). Off by default: one relaxed atomic load per
  // traced section, nothing else.
  static constexpr size_t kTraceCap = 8192;
  static constexpr uint64_t TK_FOLD = 1, TK_WAL_WAIT = 2, TK_FSYNC = 3;
  std::atomic<bool> trace_on{false};
  std::mutex trace_mu;
  std::vector<std::array<uint64_t, 5>> trace_ring;
  uint64_t trace_head = 0;  // total recorded; ring slot = head % cap

  static uint64_t mono_ns() {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<uint64_t>(ts.tv_sec) * 1000000000ull +
           static_cast<uint64_t>(ts.tv_nsec);
  }

  // 0 disables recording at the call site (mono_ns is never 0 after
  // boot): `uint64_t t = trace_t0(); ... trace_rec(kind, w, q, t);`
  uint64_t trace_t0() const {
    return trace_on.load(std::memory_order_relaxed) ? mono_ns() : 0;
  }

  void trace_rec(uint64_t kind, uint64_t wid, uint64_t seq, uint64_t t0) {
    if (t0 == 0) return;
    const uint64_t t1 = mono_ns();
    std::lock_guard<std::mutex> g(trace_mu);
    if (trace_ring.size() < kTraceCap)
      trace_ring.push_back({kind, wid, seq, t0, t1 - t0});
    else
      trace_ring[trace_head % kTraceCap] = {kind, wid, seq, t0, t1 - t0};
    trace_head += 1;
  }

  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> running{false};
  std::thread accept_thread;
  std::mutex conn_mu;
  std::vector<int> conn_fds;
  std::vector<std::thread> handlers;

  // RAII center-mutex guard with wait/hold accounting (steady_clock ns)
  // for the hot-path sections feeding dkps_server_stats
  struct StatGuard {
    Server* s;
    std::chrono::steady_clock::time_point t_acq;
    explicit StatGuard(Server* srv) : s(srv) {
      const auto t0 = std::chrono::steady_clock::now();
      s->mu.lock();
      t_acq = std::chrono::steady_clock::now();
      s->st_lock_wait_ns +=
          std::chrono::duration_cast<std::chrono::nanoseconds>(t_acq - t0)
              .count();
      s->st_lock_acquires += 1;
    }
    ~StatGuard() {
      s->st_lock_hold_ns += std::chrono::duration_cast<
                                std::chrono::nanoseconds>(
                                std::chrono::steady_clock::now() - t_acq)
                                .count();
      s->mu.unlock();
    }
  };

  // Block-quantize center snapshot `c` plus the worker's EF residual
  // `err` (updated in place) into qbuf/pscales — the ONE int8 pull
  // encode, shared by PULL_INT8 and the fused EXCHANGE reply so the two
  // wires cannot drift on the tie rule, the subnormal guard, or the
  // residual math. Call under the worker's PullErr mutex.
  void encode_int8_blocks(const float* c, std::vector<float>& err,
                          std::vector<int8_t>& qbuf,
                          std::vector<float>& pscales) {
    const uint64_t nb = pull_blocks(n);
    if (err.size() != n) err.assign(n, 0.0f);
    for (uint64_t b = 0; b < nb; ++b) {
      const uint64_t lo = b * kPullBlock;
      const uint64_t hi = std::min(lo + kPullBlock, n);
      float amax = 0.0f;
      for (uint64_t i = lo; i < hi; ++i) {
        const float v = c[i] + err[i];
        err[i] = v;  // stage v; residual subtracted below
        const float a = v < 0 ? -v : v;
        amax = a > amax ? a : amax;
      }
      const float scale = amax > 0 ? amax / 127.0f : 0.0f;
      pscales[b] = scale;
      // Subnormal-scale guard (parity with the Python encode's
      // degenerate path): for a tiny block, 1/scale overflows to inf
      // and a zero element would make qf = 0·inf = NaN, which the
      // clamp passes through into an undefined int8 cast. Sending
      // zeros keeps the whole block in the residual instead — the EF
      // stream still telescopes, with defined behavior.
      const float inv = scale >= FLT_MIN ? 1.0f / scale : 0.0f;
      for (uint64_t i = lo; i < hi; ++i) {
        const float v = err[i];
        float qf = v * inv;
        qf = qf < -127.0f ? -127.0f : (qf > 127.0f ? 127.0f : qf);
        // branchless round-half-away (std::lround is a per-element
        // libm call that blocks auto-vectorization; EF absorbs the
        // half-ulp tie-rule difference vs rint)
        qf += qf >= 0.0f ? 0.5f : -0.5f;
        const int8_t q = static_cast<int8_t>(qf);
        qbuf[i] = q;
        err[i] = v - scale * static_cast<float>(q);
      }
    }
  }

  // Undo one encode whose reply never reached the client: restore
  // err_old = v − c from err = v − scale·q (qbuf/pscales/c must be
  // exactly what the encode saw). Without this, a reconnecting worker's
  // EF stream would silently absorb one phantom pull — bounded (≤ half
  // a step per element) but avoidable. Same PullErr mutex as the encode.
  void rollback_int8_blocks(const float* c, std::vector<float>& err,
                            const std::vector<int8_t>& qbuf,
                            const std::vector<float>& pscales) {
    const uint64_t nb = pull_blocks(n);
    for (uint64_t b = 0; b < nb; ++b) {
      const uint64_t lo = b * kPullBlock;
      const uint64_t hi = std::min(lo + kPullBlock, n);
      const float scale = pscales[b];
      for (uint64_t i = lo; i < hi; ++i)
        err[i] += scale * static_cast<float>(qbuf[i]) - c[i];
    }
  }

  // EMA fold after a commit landed in the center — call under mu
  void ema_fold_locked() {
    if (ema_decay < 0) return;
    const float d = static_cast<float>(ema_decay);
    const float od = 1.0f - d;
    float* e = ema.data();
    const float* c = center.data();
    for (uint64_t i = 0; i < n; ++i) e[i] = d * e[i] + od * c[i];
  }

  // conn_wid_'s recorded pull version (0 = never pulled) — call under mu
  uint64_t pull_version_locked() {
    auto it = pull_versions.find(conn_wid_);
    return it != pull_versions.end() ? it->second : 0;
  }

  // the pull version one commit from conn_wid_ is priced from — call
  // under mu. `lag` (the pipelined fused exchange) reads the PREVIOUS
  // recorded version, falling back to the current one when no previous
  // record exists yet (a worker's first exchange after its initial pull,
  // or after a recovery that predates its prev record).
  uint64_t priced_pv_locked(bool lag) {
    if (lag) {
      auto it = prev_pull_versions.find(conn_wid_);
      if (it != prev_pull_versions.end()) return it->second;
    }
    auto it = pull_versions.find(conn_wid_);
    return it != pull_versions.end() ? it->second : 0;
  }

  float scale_from_pv_locked(uint64_t pv) {
    if (mode != MODE_INV_STALENESS) return static_cast<float>(fixed_scale);
    uint64_t tau = num_updates - pv;
    return static_cast<float>(1.0 / (static_cast<double>(tau) + 1.0));
  }

  // record conn_wid_'s pull version at the current update count, with
  // the cur -> prev shift every pull-version record performs — call
  // under mu (PULL, PULL_INT8, and the EXCHANGE fused pull half)
  void record_pull_locked() {
    auto it = pull_versions.find(conn_wid_);
    if (it != pull_versions.end()) prev_pull_versions[conn_wid_] = it->second;
    pull_versions[conn_wid_] = num_updates;
  }

  // fold scale for one commit from conn_wid_'s staleness — call under mu
  float fold_scale_locked() { return scale_from_pv_locked(priced_pv_locked(false)); }

  void handle(int fd) {
    std::vector<float> buf(n);
    // int8-commit scratch, sized lazily on first use and reused across
    // commits (the wire hot path must not heap-allocate per message)
    std::vector<int8_t> qbuf;
    std::vector<uint64_t> lens;
    std::vector<float> scales;
    std::vector<float> pscales;  // compressed-pull per-block scales
    std::vector<float> wbuf;     // durable int8 commits: dequantized
                                 // payload staged off-lock for the WAL
    std::vector<float> obuf;     // EXCHANGE reply scratch: the commit
                                 // payload in `buf` stays pinned for the
                                 // zero-copy WAL wait, so the fused pull
                                 // snapshot needs its own buffer
    for (;;) {
      uint8_t action;
      if (!recv_all(fd, &action, 1)) break;
      if (action == 1) {  // PULL
        uint64_t version;
        {
          // copy under the lock, send outside it: a slow client must not
          // serialize every other worker's fold behind its TCP window
          StatGuard g(this);
          version = num_updates;
          // staleness bookkeeping, exactly the Python PS's pull():
          // tau at the next commit = center updates since this pull
          record_pull_locked();
          if (wal_on) wal_append_pull_locked(conn_wid_, num_updates);
          std::memcpy(buf.data(), center.data(), n * sizeof(float));
        }
        {
          PendingGuard pg(this);  // reply-send → counter settling window
          if (!send_all(fd, &version, 8)) break;
          if (!send_all(fd, buf.data(), n * sizeof(float))) break;
          st_pulls += 1;
          st_bytes_out += n * sizeof(float);
        }
      } else if (action == 5) {  // PULL_INT8: block-quantized center + EF
        const uint64_t nb = pull_blocks(n);
        if (qbuf.size() != n) qbuf.resize(n);
        if (pscales.size() != nb) pscales.resize(nb);
        // Only the center SNAPSHOT needs the center mutex; quantization
        // holds the WORKER's own mutex instead, so different workers'
        // pulls overlap while a same-wid reconnect (old handler still
        // mid-quantize) serializes instead of racing on the residual.
        uint64_t version;
        PullErr* pe;
        {
          StatGuard g(this);
          version = num_updates;
          record_pull_locked();                    // same staleness
          if (wal_on) wal_append_pull_locked(conn_wid_, num_updates);
          pe = &pull_errors[conn_wid_];            // bookkeeping as PULL
          std::memcpy(buf.data(), center.data(), n * sizeof(float));
        }
        std::lock_guard<std::mutex> wg(pe->m);
        encode_int8_blocks(buf.data(), pe->err, qbuf, pscales);
        uint32_t nb32 = static_cast<uint32_t>(nb);
        {
          PendingGuard pg(this);  // settling window, see PULL
          if (!send_all(fd, &version, 8) || !send_all(fd, &nb32, 4) ||
              !send_all(fd, pscales.data(), nb * sizeof(float)) ||
              !send_all(fd, qbuf.data(), n)) {
            // dropped reply: the client never received this blob — roll
            // the residual back to its pre-pull state (still under wg)
            rollback_int8_blocks(buf.data(), pe->err, qbuf, pscales);
            break;
          }
          st_cpulls += 1;
          st_bytes_out += nb * sizeof(float) + n;
        }
      } else if (action == 2) {  // COMMIT
        if (!recv_all(fd, buf.data(), n * sizeof(float))) break;
        uint8_t ack = 1;
        // the O(model) payload hash runs OFF the center mutex, in this
        // worker's handler thread — the lock's section stays fold+append
        const uint32_t pcrc =
            wal_on ? adler32_buf(buf.data(), n * sizeof(float)) : 0;
        std::vector<char> staged;  // window 0: payload copy, OFF the mutex
        if (wal_on && wal.window == 0) {
          const char* pb = reinterpret_cast<const char*>(buf.data());
          staged.assign(pb, pb + n * sizeof(float));
        }
        uint64_t tok = 0;
        {
          StatGuard g(this);
          const float s = fold_scale_locked();
          float* c = center.data();
          const float* d = buf.data();
          for (uint64_t i = 0; i < n; ++i) c[i] += d[i] * s;
          ema_fold_locked();
          num_updates += 1;
          if (wal_on)
            tok = wal_append_commit_locked(
                conn_wid_, -1, pull_version_locked(), num_updates, s,
                d, n, pcrc, wal.window == 0 ? &staged : nullptr);
        }
        st_commits += 1;
        st_bytes_in += n * sizeof(float);
        if (tok && wal.window >= 1 && !wal_wait(tok)) break;  // crashed
        if (!send_all(fd, &ack, 1)) break;
      } else if (action == 4) {  // COMMIT_INT8: per-segment scaled int8
        uint32_t segs;
        if (!recv_all(fd, &segs, 4)) break;
        // segment count and lengths are validated against the pinned n
        // BEFORE any allocation beyond n bytes — a hostile header cannot
        // oversize the payload or overflow the fold loop's bounds
        if (segs == 0 || segs > (1u << 20) || segs > n) break;
        lens.resize(segs);
        scales.resize(segs);
        uint64_t total = 0;
        bool bad = false;
        for (uint32_t i = 0; i < segs; ++i) {
          if (!recv_all(fd, &lens[i], 8) || !recv_all(fd, &scales[i], 4)) {
            bad = true;
            break;
          }
          if (lens[i] > n || total + lens[i] > n) {  // no u64 wrap possible
            bad = true;
            break;
          }
          total += lens[i];
        }
        if (bad || total != n) break;
        if (qbuf.size() != n) qbuf.resize(n);
        if (!recv_all(fd, qbuf.data(), n)) break;
        uint8_t ack = 1;
        uint32_t pcrc = 0;
        if (wal_on) {
          // durable int8 commits dequantize OFF the mutex into wbuf and
          // fold `c += s * wbuf` — two rounding steps instead of the
          // no-WAL path's fused `(s*scale_seg)*q`, because the REPLAY
          // must reproduce the fold from the logged dense payload with
          // one multiply; logging q+scales would save bytes but force
          // the replayer to re-implement this segment walk
          if (wbuf.size() != n) wbuf.resize(n);
          uint64_t off = 0;
          for (uint32_t seg = 0; seg < segs; ++seg) {
            const float sc = scales[seg];
            const int8_t* d = qbuf.data() + off;
            for (uint64_t i = 0; i < lens[seg]; ++i)
              wbuf[off + i] = sc * static_cast<float>(d[i]);
            off += lens[seg];
          }
          pcrc = adler32_buf(wbuf.data(), n * sizeof(float));
        }
        std::vector<char> staged;  // window 0: payload copy, OFF the mutex
        if (wal_on && wal.window == 0) {
          const char* pb = reinterpret_cast<const char*>(wbuf.data());
          staged.assign(pb, pb + n * sizeof(float));
        }
        uint64_t tok = 0;
        {
          StatGuard g(this);
          const float s = fold_scale_locked();
          float* c = center.data();
          if (wal_on) {
            const float* d = wbuf.data();
            for (uint64_t i = 0; i < n; ++i) c[i] += d[i] * s;
          } else {
            uint64_t off = 0;
            for (uint32_t seg = 0; seg < segs; ++seg) {
              const float ss = s * scales[seg];
              const int8_t* d = qbuf.data() + off;
              for (uint64_t i = 0; i < lens[seg]; ++i)
                c[off + i] += ss * static_cast<float>(d[i]);
              off += lens[seg];
            }
          }
          ema_fold_locked();
          num_updates += 1;
          if (wal_on)
            tok = wal_append_commit_locked(
                conn_wid_, -1, pull_version_locked(), num_updates, s,
                wbuf.data(), n, pcrc,
                wal.window == 0 ? &staged : nullptr);
        }
        st_commits += 1;
        st_bytes_in += static_cast<uint64_t>(segs) * 12 + n;
        if (tok && wal.window >= 1 && !wal_wait(tok)) break;
        if (!send_all(fd, &ack, 1)) break;
      } else if (action == 7) {  // COMMIT_SEQ: retry-safe seq'd commit
        uint64_t seq;
        if (!recv_all(fd, &seq, 8)) break;
        if (!recv_all(fd, buf.data(), n * sizeof(float))) break;
        const uint32_t pcrc =
            wal_on ? adler32_buf(buf.data(), n * sizeof(float)) : 0;
        std::vector<char> staged;  // window 0: payload copy, OFF the mutex
        if (wal_on && wal.window == 0) {
          const char* pb = reinterpret_cast<const char*>(buf.data());
          staged.assign(pb, pb + n * sizeof(float));
        }
        bool dup;
        uint64_t tok = 0;
        {
          StatGuard g(this);
          uint64_t& last = last_seq[conn_wid_];
          dup = seq <= last;
          if (!dup) {
            last = seq;
            const float s = fold_scale_locked();
            float* c = center.data();
            const float* d = buf.data();
            for (uint64_t i = 0; i < n; ++i) c[i] += d[i] * s;
            ema_fold_locked();
            num_updates += 1;
            if (wal_on)
              tok = wal_append_commit_locked(
                  conn_wid_, static_cast<int64_t>(seq),
                  pull_version_locked(), num_updates, s, d, n, pcrc,
                  wal.window == 0 ? &staged : nullptr);
          }
        }
        if (dup) {
          st_dups += 1;
        } else {
          st_commits += 1;
        }
        st_bytes_in += n * sizeof(float);
        if (tok && wal.window >= 1 && !wal_wait(tok)) break;
        uint8_t ack = dup ? 2 : 1;
        if (!send_all(fd, &ack, 1)) break;
      } else if (action == 10) {  // COMMIT_SEQ_E: fenced + seq'd commit
        uint64_t epoch, seq;
        if (!recv_all(fd, &epoch, 8)) break;
        if (!recv_all(fd, &seq, 8)) break;
        if (!recv_all(fd, buf.data(), n * sizeof(float))) break;
        const uint32_t pcrc =
            wal_on ? adler32_buf(buf.data(), n * sizeof(float)) : 0;
        std::vector<char> staged;  // window 0: payload copy, OFF the mutex
        if (wal_on && wal.window == 0) {
          const char* pb = reinterpret_cast<const char*>(buf.data());
          staged.assign(pb, pb + n * sizeof(float));
        }
        bool dup = false, fenced = false;
        uint64_t server_epoch;
        uint64_t tok = 0;
        const uint64_t t_fold = trace_t0();  // ISSUE 11 fold span
        {
          StatGuard g(this);
          server_epoch = fence_epoch;
          fenced = epoch != fence_epoch;
          if (!fenced) {
            uint64_t& last = last_seq[conn_wid_];
            dup = seq <= last;
            if (!dup) {
              last = seq;
              const float s = fold_scale_locked();
              float* c = center.data();
              const float* d = buf.data();
              for (uint64_t i = 0; i < n; ++i) c[i] += d[i] * s;
              ema_fold_locked();
              num_updates += 1;
              if (wal_on)
                tok = wal_append_commit_locked(
                    conn_wid_, static_cast<int64_t>(seq),
                    pull_version_locked(), num_updates, s, d, n, pcrc,
                    wal.window == 0 ? &staged : nullptr);
            }
          }
        }
        trace_rec(TK_FOLD, conn_wid_, seq, t_fold);
        if (fenced) {
          st_fenced += 1;
        } else if (dup) {
          st_dups += 1;
        } else {
          st_commits += 1;
        }
        st_bytes_in += n * sizeof(float);
        if (tok && wal.window >= 1) {
          const uint64_t t_w = trace_t0();
          const bool durable = wal_wait(tok);
          trace_rec(TK_WAL_WAIT, conn_wid_, seq, t_w);
          if (!durable) break;
        }
        uint8_t ack = fenced ? 3 : (dup ? 2 : 1);
        if (!send_all(fd, &ack, 1)) break;
        if (!send_all(fd, &server_epoch, 8)) break;
      } else if (action == 9) {  // FENCE: raise the fencing epoch
        uint64_t epoch;
        if (!recv_all(fd, &epoch, 8)) break;
        uint64_t now_epoch;
        uint64_t tok = 0;
        {
          StatGuard g(this);
          if (epoch > fence_epoch) fence_epoch = epoch;
          now_epoch = fence_epoch;
          if (wal_on) tok = wal_append_fence_locked(now_epoch);
        }
        // the fence ack implies durability (parity with the Python PS)
        if (tok && !wal_wait(tok)) break;
        uint8_t ack = 1;
        if (!send_all(fd, &ack, 1)) break;
        if (!send_all(fd, &now_epoch, 8)) break;
      } else if (action == 6) {  // HEARTBEAT: lease renewal
        uint32_t retries;
        if (!recv_all(fd, &retries, 4)) break;
        const bool known = heartbeat(conn_wid_, retries);
        uint8_t ack = known ? 1 : 2;
        if (!send_all(fd, &ack, 1)) break;
      } else if (action == 8) {  // DEREGISTER: clean exit, no eviction
        deregister(conn_wid_);
        uint8_t ack = 1;
        if (!send_all(fd, &ack, 1)) break;
      } else if (action == 12) {  // JOIN: elastic live-join admission
        // reply: u8 ack + u64 num_updates + u64 pool_size (parity with
        // the Python "join" action's {pool_size, num_updates} record)
        const int64_t pool = join_wid(conn_wid_);
        uint64_t updates;
        {
          std::lock_guard<std::mutex> g(mu);
          updates = num_updates;
        }
        uint8_t ack = 1;
        uint64_t pool_u = pool < 0 ? 0 : static_cast<uint64_t>(pool);
        if (!send_all(fd, &ack, 1)) break;
        if (!send_all(fd, &updates, 8)) break;
        if (!send_all(fd, &pool_u, 8)) break;
      } else if (action == 13) {  // DRAIN: preemption drain
        uint8_t timed_out;
        if (!recv_all(fd, &timed_out, 1)) break;
        drain_wid(conn_wid_, timed_out != 0);
        uint8_t ack = 1;
        if (!send_all(fd, &ack, 1)) break;
      } else if (action == 14) {  // EXCHANGE: fused commit + pull
        // One round trip folds the commit and answers with the fresh
        // post-fold center (ISSUE 10) — the wire fusion of COMMIT_SEQ_E
        // + PULL(_INT8). flags: bit0 seq, bit1 epoch, bit2 int8 reply,
        // bit3 lag (price tau from the PREVIOUS pull version — the
        // pipelined worker's delta is one exchange stale). A duplicate
        // seq skips the fold but still gets the pull half; a fenced
        // exchange gets neither.
        uint8_t flags;
        if (!recv_all(fd, &flags, 1)) break;
        const bool has_seq = flags & 1, has_epoch = flags & 2;
        const bool want_int8 = flags & 4, lag = flags & 8;
        uint64_t epoch = 0, seq = 0;
        if (has_epoch && !recv_all(fd, &epoch, 8)) break;
        if (has_seq && !recv_all(fd, &seq, 8)) break;
        if (!recv_all(fd, buf.data(), n * sizeof(float))) break;
        const uint32_t pcrc =
            wal_on ? adler32_buf(buf.data(), n * sizeof(float)) : 0;
        std::vector<char> staged;  // window 0: payload copy, OFF the mutex
        if (wal_on && wal.window == 0) {
          const char* pb = reinterpret_cast<const char*>(buf.data());
          staged.assign(pb, pb + n * sizeof(float));
        }
        if (obuf.size() != n) obuf.resize(n);
        const uint64_t nb = pull_blocks(n);
        if (want_int8) {
          if (qbuf.size() != n) qbuf.resize(n);
          if (pscales.size() != nb) pscales.resize(nb);
        }
        bool dup = false, fenced = false;
        uint64_t server_epoch, version = 0, tok = 0;
        PullErr* pe = nullptr;
        const uint64_t t_fold = trace_t0();  // ISSUE 11 fold span
        {
          StatGuard g(this);
          server_epoch = fence_epoch;
          fenced = has_epoch && epoch != fence_epoch;
          if (!fenced) {
            if (has_seq) {
              uint64_t& last = last_seq[conn_wid_];
              dup = seq <= last;
              if (!dup) last = seq;
            }
            if (!dup) {
              const uint64_t pv = priced_pv_locked(lag);
              const float s = scale_from_pv_locked(pv);
              float* c = center.data();
              const float* d = buf.data();
              for (uint64_t i = 0; i < n; ++i) c[i] += d[i] * s;
              ema_fold_locked();
              num_updates += 1;
              if (wal_on)
                tok = wal_append_commit_locked(
                    conn_wid_, has_seq ? static_cast<int64_t>(seq) : -1,
                    pv, num_updates, s, d, n, pcrc,
                    wal.window == 0 ? &staged : nullptr);
            }
            // fused pull half — applied AND duplicate commits get it (a
            // lost-ACK replay still needs a fresh center, and recording
            // its version is exactly what a retried pull would do)
            record_pull_locked();
            version = num_updates;
            if (wal_on) wal_append_pull_locked(conn_wid_, num_updates);
            if (want_int8) pe = &pull_errors[conn_wid_];
            std::memcpy(obuf.data(), center.data(), n * sizeof(float));
          }
        }
        trace_rec(TK_FOLD, conn_wid_, has_seq ? seq : 0, t_fold);
        if (fenced) {
          st_fenced += 1;
        } else if (dup) {
          st_dups += 1;
        } else {
          st_commits += 1;
        }
        st_bytes_in += n * sizeof(float);
        if (tok && wal.window >= 1) {
          const uint64_t t_w = trace_t0();  // deferred-ACK wait span
          const bool durable = wal_wait(tok);
          trace_rec(TK_WAL_WAIT, conn_wid_, has_seq ? seq : 0, t_w);
          if (!durable) break;  // crashed
        }
        uint8_t ack = fenced ? 3 : (dup ? 2 : 1);
        {
          PendingGuard pg(this);  // settling window, see PULL
          if (!send_all(fd, &ack, 1)) break;
          if (!send_all(fd, &server_epoch, 8)) break;
          if (fenced) continue;
          if (!send_all(fd, &version, 8)) break;
          if (!want_int8) {
            if (!send_all(fd, obuf.data(), n * sizeof(float))) break;
            st_pulls += 1;
            st_bytes_out += n * sizeof(float);
            st_fused += 1;
          } else {
            // block-quantize obuf + this worker's EF residual — the SAME
            // encode/rollback helpers as PULL_INT8, so the fused and
            // standalone compressed-pull wires cannot drift
            std::lock_guard<std::mutex> wg(pe->m);
            encode_int8_blocks(obuf.data(), pe->err, qbuf, pscales);
            uint32_t nb32 = static_cast<uint32_t>(nb);
            if (!send_all(fd, &nb32, 4) ||
                !send_all(fd, pscales.data(), nb * sizeof(float)) ||
                !send_all(fd, qbuf.data(), n)) {
              rollback_int8_blocks(obuf.data(), pe->err, qbuf, pscales);
              break;
            }
            st_cpulls += 1;
            st_bytes_out += nb * sizeof(float) + n;
            st_fused += 1;
          }
        }
      } else if (action == 15) {  // TRACE: drain the span ring (ISSUE 11)
        // reply: u64 count, then count * 5 u64 records of
        // (kind, wid, seq, t0_ns, dur_ns). DRAINING read: a scrape
        // empties the ring, so repeated scrapes never duplicate spans.
        std::vector<std::array<uint64_t, 5>> recs;
        {
          std::lock_guard<std::mutex> g(trace_mu);
          const uint64_t have =
              trace_head < kTraceCap ? trace_head : kTraceCap;
          recs.reserve(have);
          for (uint64_t k = trace_head - have; k < trace_head; ++k)
            recs.push_back(trace_ring[k % kTraceCap]);
          trace_ring.clear();
          trace_head = 0;
        }
        uint64_t cnt = recs.size();
        if (!send_all(fd, &cnt, 8)) break;
        if (cnt &&
            !send_all(fd, recs.data(),
                      cnt * sizeof(std::array<uint64_t, 5>)))
          break;
      } else if (action == 11) {  // SHARD_INFO: shard-map handshake
        // reply: u32 shard_id, u32 num_shards (0 = unsharded), u64
        // fence_epoch — the sharded client verifies it is wired to the
        // shard it represents before folding anything (parity with the
        // Python server's "shard_map" action)
        uint32_t info[2] = {shard_id.load(), num_shards.load()};
        uint64_t epoch;
        {
          std::lock_guard<std::mutex> g(mu);
          epoch = fence_epoch;
        }
        if (!send_all(fd, info, 8)) break;
        if (!send_all(fd, &epoch, 8)) break;
      } else {  // BYE or garbage: drop the connection either way
        break;
      }
    }
    {
      // prune BEFORE closing: stop() must never shutdown() a descriptor
      // number the kernel has already reused for something else
      std::lock_guard<std::mutex> g(conn_mu);
      conn_fds.erase(std::remove(conn_fds.begin(), conn_fds.end(), fd),
                     conn_fds.end());
    }
    close_conn_fd(fd);
  }

  // per-handler worker id — set via the thread entry, see serve_conn
  static thread_local uint32_t conn_wid_;

  void serve_conn(int fd, uint32_t wid) {
    conn_wid_ = wid;
    handle(fd);
  }

  void record_pull_version(uint32_t wid) {
    std::lock_guard<std::mutex> g(mu);
    auto it = pull_versions.find(wid);
    if (it != pull_versions.end()) prev_pull_versions[wid] = it->second;
    pull_versions[wid] = num_updates;
  }
};

thread_local uint32_t Server::conn_wid_ = 0;

struct Client {
  int fd = -1;
  uint64_t n = 0;
  uint32_t wid = 0;
};

int connect_to(const char* host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  set_nodelay(fd);
  return fd;
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------- server --

void* dkps_server_create(const float* init, uint64_t n, int mode,
                         double fixed_scale, const char* host, int port,
                         double ema_decay, double lease_timeout) {
  auto* s = new Server();
  s->center.assign(init, init + n);
  s->n = n;
  s->mode = mode;
  s->fixed_scale = fixed_scale;
  s->ema_decay = ema_decay;
  if (ema_decay >= 0) s->ema = s->center;
  // lease_timeout <= 0 keeps the 30 s default (leases only matter once a
  // client heartbeats — a heartbeat-free run never evicts anything)
  if (lease_timeout > 0) s->lease_timeout_s = lease_timeout;

  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  ::setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
      ::bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
          0 ||
      ::listen(s->listen_fd, 64) != 0) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  sockaddr_in bound{};
  socklen_t blen = sizeof(bound);
  ::getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&bound), &blen);
  s->port = ntohs(bound.sin_port);
  return s;
}

int dkps_server_port(void* h) { return static_cast<Server*>(h)->port; }

int dkps_server_start(void* h) {
  auto* s = static_cast<Server*>(h);
  s->running = true;
  s->accept_thread = std::thread([s] {
    while (s->running) {
      int fd = ::accept(s->listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (s->running && (errno == EINTR || errno == ECONNABORTED)) continue;
        break;
      }
      if (!s->running) {
        ::close(fd);
        break;
      }
      set_nodelay(fd);
      // handshake: magic + worker_id + n; reject on any mismatch
      char magic[6];
      uint32_t wid;
      uint64_t cn;
      if (!recv_all(fd, magic, 6) || std::memcmp(magic, kMagic, 6) != 0 ||
          !recv_all(fd, &wid, 4) || !recv_all(fd, &cn, 8)) {
        ::close(fd);
        continue;
      }
      uint8_t ok = (cn == s->n) ? 1 : 0;
      if (!send_all(fd, &ok, 1) || !ok) {
        ::close(fd);
        continue;
      }
      std::lock_guard<std::mutex> g(s->conn_mu);
      s->conn_fds.push_back(fd);
      s->handlers.emplace_back([s, fd, wid] { s->serve_conn(fd, wid); });
    }
  });
  return 0;
}

// Attach one shared-memory ring connection (ISSUE 12 — the shm lane,
// parity with distkeras_tpu/shm.py): `base` is the caller-mapped segment
// (4 KiB header + two SPSC rings; the Python wrapper creates, owns, and
// unlinks it). Spawns a handler thread running the SAME handshake +
// action loop an accepted TCP connection gets, dispatched over the rings
// via the negative pseudo-fd. Returns that pseudo-fd (< 0) or 0 on
// failure. Call after dkps_server_start and BEFORE the peer's
// dkps_client_connect_shm — the client handshake blocks on the ring
// until this handler answers it.
int dkps_server_attach_shm(void* h, void* base, uint64_t bytes) {
  auto* s = static_cast<Server*>(h);
  if (!s->running) return 0;
  const int fd = shm_register(base, bytes, /*server_side=*/true);
  if (fd == 0) return 0;
  std::lock_guard<std::mutex> g(s->conn_mu);
  if (!s->running) {
    // stop() raced the attach: its conn_mu shutdown section has (or
    // will have) run, and its handler-join loop iterates WITHOUT the
    // lock — appending now would race that iteration and leave an
    // unjoined thread outliving the server. Re-checking under conn_mu
    // closes the window: stop() flips running before ITS conn_mu
    // section, so an attach that sees running here is fully registered
    // before stop's shutdown loop (which then closes the new channel).
    close_conn_fd(fd);
    return 0;
  }
  s->conn_fds.push_back(fd);
  s->handlers.emplace_back([s, fd] {
    // the accept loop's handshake, over the ring: magic + worker_id +
    // vector length, answered with the accept byte
    char magic[6];
    uint32_t wid;
    uint64_t cn;
    uint8_t ok = 0;
    if (recv_all(fd, magic, 6) && std::memcmp(magic, kMagic, 6) == 0 &&
        recv_all(fd, &wid, 4) && recv_all(fd, &cn, 8)) {
      ok = (cn == s->n) ? 1 : 0;
      if (send_all(fd, &ok, 1) && ok) {
        s->serve_conn(fd, wid);  // prunes conn_fds + closes at its tail
        return;
      }
    }
    {
      std::lock_guard<std::mutex> g2(s->conn_mu);
      s->conn_fds.erase(
          std::remove(s->conn_fds.begin(), s->conn_fds.end(), fd),
          s->conn_fds.end());
    }
    close_conn_fd(fd);
  });
  return fd;
}

void dkps_server_stop(void* h) {
  auto* s = static_cast<Server*>(h);
  if (!s->running.exchange(false)) {
    s->wal_close();  // idempotent; a crash() already abandoned it
    return;
  }
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  {
    std::lock_guard<std::mutex> g(s->conn_mu);
    for (int fd : s->conn_fds) shutdown_conn_fd(fd);
  }
  for (auto& t : s->handlers)
    if (t.joinable()) t.join();
  s->wal_close();  // clean stop: drain + fsync + close the log
}

// Crash seam (parity with SocketParameterServer._crash): die like a
// SIGKILL'd process — tear the listener and every live connection, and
// abandon the WAL losing its user-space pending buffer WITHOUT a flush
// or fsync. Records an earlier group fsync made durable survive; the
// torn group's commits were never ACKed, so their clients replay them
// against the recovered server and the dedup table folds each once.
void dkps_server_crash(void* h) {
  auto* s = static_cast<Server*>(h);
  if (s->running.exchange(false)) {
    ::shutdown(s->listen_fd, SHUT_RDWR);
    ::close(s->listen_fd);
    std::lock_guard<std::mutex> g(s->conn_mu);
    for (int fd : s->conn_fds) shutdown_conn_fd(fd);
  }
  s->wal_abandon();
  if (s->accept_thread.joinable()) s->accept_thread.join();
  for (auto& t : s->handlers)
    if (t.joinable()) t.join();
}

// Attach the write-ahead log: open `path` for appending and start the
// group-commit flusher (`window` commits per fsync batch, 0 = async
// time-bounded mode; `interval_s` bounds the durability window in
// seconds either way). Call BEFORE dkps_server_start. Returns 0, or -1
// when the file cannot be opened. The Python wrapper owns recovery,
// snapshot publication, and torn-tail truncation — this side only
// appends records to the live segment it is handed.
int dkps_server_wal_open(void* h, const char* path, uint64_t window,
                         double interval_s) {
  auto* s = static_cast<Server*>(h);
  int fd = ::open(path, O_WRONLY | O_APPEND | O_CREAT | O_CLOEXEC, 0644);
  if (fd < 0) return -1;
  s->wal.fd = fd;
  s->wal.window = window;
  s->wal.interval_s = interval_s > 0 ? interval_s : 0.25;
  s->wal.running = true;
  s->wal_on = true;
  s->wal.flusher = std::thread([s] { s->wal_flush_loop(); });
  return 0;
}

void dkps_server_destroy(void* h) {
  auto* s = static_cast<Server*>(h);
  dkps_server_stop(s);
  delete s;
}

uint64_t dkps_server_num_updates(void* h) {
  auto* s = static_cast<Server*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return s->num_updates;
}

void dkps_server_set_num_updates(void* h, uint64_t v) {
  auto* s = static_cast<Server*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  s->num_updates = v;
}

void dkps_server_get_center(void* h, float* out) {
  auto* s = static_cast<Server*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::memcpy(out, s->center.data(), s->n * sizeof(float));
}

void dkps_server_set_center(void* h, const float* in) {
  auto* s = static_cast<Server*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::memcpy(s->center.data(), in, s->n * sizeof(float));
  // a restored center restarts the average from itself (EMA state is not
  // checkpointed — same policy as the Python trainers)
  if (s->ema_decay >= 0) s->ema = s->center;
}

// EMA read: 0 on success, -1 when the server was created without EMA
int dkps_server_get_ema(void* h, float* out) {
  auto* s = static_cast<Server*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if (s->ema_decay < 0) return -1;
  std::memcpy(out, s->ema.data(), s->n * sizeof(float));
  return 0;
}

// record a pull version server-side (used by the in-process owner when it
// folds without the wire; wire pulls record via the PULL action below)
void dkps_server_record_pull(void* h, uint32_t wid) {
  static_cast<Server*>(h)->record_pull_version(wid);
}

// Contention/throughput counters (parity with the Python PS's stats()).
// Fills out[22]: pulls, compressed_pulls, commits, bytes_in, bytes_out,
// center_lock_acquires, center_lock_wait_ns, center_lock_hold_ns,
// dup_commits, active_workers, evicted_workers, heartbeats,
// worker_retries, fenced_commits, wal_records, wal_fsyncs,
// wal_group_max, pool_size, joined_workers, preempted_workers,
// drain_timeouts, fused_exchanges. Runs a FORCED expiry pass first (a stats read must see
// already-lapsed leases as evicted — no rate-limit window); the counter
// reads stay lock-free atomics and may lag in-flight ops by one —
// telemetry semantics, same as the Python side.
void dkps_server_stats(void* h, uint64_t* out) {
  auto* s = static_cast<Server*>(h);
  s->expire_leases(/*force=*/true);
  // settling barrier (ISSUE 11): pull-side counters land after the
  // reply send — wait (bounded) for in-flight reply windows to close so
  // an end-of-run read is exact; under continuous traffic the gauge
  // passes through zero between ops, and a wedged sender degrades to
  // the historical may-lag semantics after the deadline
  const auto deadline =
      std::chrono::steady_clock::now() + std::chrono::seconds(1);
  while (s->st_pending.load() != 0 &&
         std::chrono::steady_clock::now() < deadline)
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  out[0] = s->st_pulls.load();
  out[1] = s->st_cpulls.load();
  out[2] = s->st_commits.load();
  out[3] = s->st_bytes_in.load();
  out[4] = s->st_bytes_out.load();
  out[5] = s->st_lock_acquires.load();
  out[6] = s->st_lock_wait_ns.load();
  out[7] = s->st_lock_hold_ns.load();
  out[8] = s->st_dups.load();
  {
    std::lock_guard<std::mutex> g(s->lease_mu);
    uint64_t retries = 0;
    for (const auto& kv : s->retries_by_wid) retries += kv.second;
    out[9] = s->leases.size();
    out[10] = s->st_evicted.load();
    out[11] = s->st_heartbeats.load();
    out[12] = retries;
  }
  out[13] = s->st_fenced.load();
  out[14] = s->wal.st_records.load();
  out[15] = s->wal.st_fsyncs.load();
  out[16] = s->wal.st_group_max.load();
  const int64_t pool = s->st_pool.load();
  out[17] = pool < 0 ? 0 : static_cast<uint64_t>(pool);
  out[18] = s->st_joined.load();
  out[19] = s->st_preempted.load();
  out[20] = s->st_drain_to.load();
  out[21] = s->st_fused.load();
}

// Elastic pool gauge base (resilience/elastic.py): the wrapper sets the
// configured worker count at initialize() — the C ABI has no num_workers
// of its own (the fold scale is baked into the mode) — and JOIN/DRAIN
// adjust it from there.
void dkps_server_set_pool_size(void* h, int64_t n) {
  static_cast<Server*>(h)->st_pool.store(n);
}

// Flight recorder (ISSUE 11): arm/disarm the server's span ring. Spans
// cover the EXCHANGE/COMMIT_SEQ_E fold sections, the deferred-ACK WAL
// wait, and the flusher's group fsync; drain them with the TRACE wire
// action (dkps_client_trace_scrape).
void dkps_server_set_trace(void* h, int on) {
  static_cast<Server*>(h)->trace_on.store(on != 0);
}

// -- durable-state restore (crash recovery; the Python wrapper replays
// the log with resilience/wal.py and installs the result here) ----------

// EMA restore: 0 on success, -1 when the server was created without EMA.
// Must run after dkps_server_set_center (which resets the EMA to the
// center) and before serving traffic.
int dkps_server_set_ema(void* h, const float* in) {
  auto* s = static_cast<Server*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if (s->ema_decay < 0) return -1;
  std::memcpy(s->ema.data(), in, s->n * sizeof(float));
  return 0;
}

// Per-worker recovered state: last applied commit seqno (-1 = none),
// recorded pull version (-1 = none), and the PREVIOUS pull version
// (-1 = none; the pipelined exchange's lag-pricing base) — the dedup
// fence and the DynSGD staleness bases must survive a restart, or a
// replayed pre-crash commit double-folds / gets mispriced.
void dkps_server_restore_worker(void* h, uint32_t wid, int64_t last_seq,
                                int64_t pull_version,
                                int64_t prev_pull_version) {
  auto* s = static_cast<Server*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if (last_seq >= 0) s->last_seq[wid] = static_cast<uint64_t>(last_seq);
  if (pull_version >= 0)
    s->pull_versions[wid] = static_cast<uint64_t>(pull_version);
  if (prev_pull_version >= 0)
    s->prev_pull_versions[wid] = static_cast<uint64_t>(prev_pull_version);
}

// fencing-epoch admin (parity with ParameterServer.fence / fence_epoch);
// durable before returning when a WAL is attached, like the Python PS
uint64_t dkps_server_fence(void* h, uint64_t epoch) {
  auto* s = static_cast<Server*>(h);
  uint64_t out, tok = 0;
  {
    std::lock_guard<std::mutex> g(s->mu);
    if (epoch > s->fence_epoch) s->fence_epoch = epoch;
    out = s->fence_epoch;
    if (s->wal_on && s->wal.running) tok = s->wal_append_fence_locked(out);
  }
  if (tok) s->wal_wait(tok);
  return out;
}

uint64_t dkps_server_fence_epoch(void* h) {
  auto* s = static_cast<Server*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return s->fence_epoch;
}

// Shard-map record (distkeras_tpu/sharding): this server holds shard
// `sid` of an `n_shards`-way partitioned center. Served to clients via
// SHARD_INFO (action 11); n_shards 0 = unsharded (the default).
void dkps_server_set_shard(void* h, uint32_t sid, uint32_t n_shards) {
  auto* s = static_cast<Server*>(h);
  s->shard_id.store(sid);
  s->num_shards.store(n_shards);
}

// ---------------------------------------------------------------- client --

static void* client_handshake(int fd, uint32_t wid, uint64_t n) {
  char hello[6 + 4 + 8];
  std::memcpy(hello, kMagic, 6);
  std::memcpy(hello + 6, &wid, 4);
  std::memcpy(hello + 10, &n, 8);
  uint8_t ok = 0;
  if (!send_all(fd, hello, sizeof(hello)) || !recv_all(fd, &ok, 1) || !ok) {
    close_conn_fd(fd);
    return nullptr;
  }
  auto* c = new Client();
  c->fd = fd;
  c->n = n;
  c->wid = wid;
  return c;
}

void* dkps_client_connect(const char* host, int port, uint32_t wid,
                          uint64_t n) {
  int fd = connect_to(host, port);
  if (fd < 0) return nullptr;
  return client_handshake(fd, wid, n);
}

// Adopt an already-connected (blocking-mode) socket — DNS resolution,
// IPv6, and connect timeouts stay the caller's (Python's) problem; the
// hot-path framing stays native. Closes fd on handshake failure.
void* dkps_client_from_fd(int fd, uint32_t wid, uint64_t n) {
  set_nodelay(fd);
  return client_handshake(fd, wid, n);
}

// Connect over a shared-memory ring pair (ISSUE 12): `base` is the same
// mapped segment the server side attached with dkps_server_attach_shm.
// Runs the standard handshake through the ring; the returned handle
// speaks every client op unchanged (the pseudo-fd dispatches in
// send_all/recv_all).
void* dkps_client_connect_shm(void* base, uint64_t bytes, uint32_t wid,
                              uint64_t n) {
  const int fd = shm_register(base, bytes, /*server_side=*/false);
  if (fd == 0) return nullptr;
  return client_handshake(fd, wid, n);
}

// Bound every subsequent pull/commit round-trip: a wedged server makes the
// call fail with a transport error instead of hanging the caller forever.
int dkps_client_set_timeout_ms(void* h, int ms) {
  auto* c = static_cast<Client*>(h);
  if (c->fd < 0) {  // ring lane: the channel carries its own deadline
    shm_chan(c->fd)->timeout_ms.store(ms, std::memory_order_relaxed);
    return 0;
  }
  timeval tv{};
  tv.tv_sec = ms / 1000;
  tv.tv_usec = (ms % 1000) * 1000;
  if (::setsockopt(c->fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv)) != 0)
    return -1;
  return ::setsockopt(c->fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
}

// pull: returns the center version (>= 0) or -1 on transport failure
int64_t dkps_client_pull(void* h, float* out) {
  auto* c = static_cast<Client*>(h);
  uint8_t action = 1;
  uint64_t version;
  if (!send_all(c->fd, &action, 1) || !recv_all(c->fd, &version, 8) ||
      !recv_all(c->fd, out, c->n * sizeof(float)))
    return -1;
  return static_cast<int64_t>(version);
}

int dkps_client_commit(void* h, const float* buf) {
  auto* c = static_cast<Client*>(h);
  uint8_t action = 2;
  uint8_t ack = 0;
  if (!send_all(c->fd, &action, 1) ||
      !send_all(c->fd, buf, c->n * sizeof(float)) ||
      !recv_all(c->fd, &ack, 1) || ack != 1)
    return -1;
  return 0;
}

// int8 commit: `q` is the full n-byte quantized vector, segmented into
// `segs` runs of `lens[i]` values sharing `scales[i]` (per-leaf scales on
// the Python side). One gathered header buffer, then the payload.
int dkps_client_commit_int8(void* h, const int8_t* q, const uint64_t* lens,
                            const float* scales, uint32_t segs) {
  auto* c = static_cast<Client*>(h);
  std::vector<char> header(1 + 4 + static_cast<size_t>(segs) * 12);
  header[0] = 4;
  std::memcpy(header.data() + 1, &segs, 4);
  char* p = header.data() + 5;
  for (uint32_t i = 0; i < segs; ++i) {
    std::memcpy(p, &lens[i], 8);
    std::memcpy(p + 8, &scales[i], 4);
    p += 12;
  }
  uint8_t ack = 0;
  if (!send_all(c->fd, header.data(), header.size()) ||
      !send_all(c->fd, q, c->n) || !recv_all(c->fd, &ack, 1) || ack != 1)
    return -1;
  return 0;
}

// seq'd commit (action 7): per-worker seqno dedup server-side — safe to
// replay after a torn connection. Returns 0 = folded, 1 = duplicate
// (already applied; the retry layer treats both as success), -1 =
// transport failure.
int dkps_client_commit_seq(void* h, uint64_t seq, const float* buf) {
  auto* c = static_cast<Client*>(h);
  char header[1 + 8];
  header[0] = 7;
  std::memcpy(header + 1, &seq, 8);
  uint8_t ack = 0;
  if (!send_all(c->fd, header, sizeof(header)) ||
      !send_all(c->fd, buf, c->n * sizeof(float)) ||
      !recv_all(c->fd, &ack, 1) || (ack != 1 && ack != 2))
    return -1;
  return ack == 2 ? 1 : 0;
}

// fenced + seq'd commit (action 10): the failover-safe commit. Returns
// 0 = folded, 1 = duplicate (both success to the retry layer), 2 =
// FENCED (the server's epoch differs — NOT folded; the caller raises a
// typed fatal/re-resolve error), -1 = transport failure. The server's
// current epoch lands in *server_epoch when non-null.
int dkps_client_commit_seq_e(void* h, uint64_t epoch, uint64_t seq,
                             const float* buf, uint64_t* server_epoch) {
  auto* c = static_cast<Client*>(h);
  char header[1 + 8 + 8];
  header[0] = 10;
  std::memcpy(header + 1, &epoch, 8);
  std::memcpy(header + 9, &seq, 8);
  uint8_t ack = 0;
  uint64_t sepoch = 0;
  if (!send_all(c->fd, header, sizeof(header)) ||
      !send_all(c->fd, buf, c->n * sizeof(float)) ||
      !recv_all(c->fd, &ack, 1) || !recv_all(c->fd, &sepoch, 8) ||
      (ack != 1 && ack != 2 && ack != 3))
    return -1;
  if (server_epoch) *server_epoch = sepoch;
  return ack == 3 ? 2 : (ack == 2 ? 1 : 0);
}

// fence (action 9): raise the server's fencing epoch. Returns the
// post-fence epoch (>= the requested one) or -1 on transport failure.
int64_t dkps_client_fence(void* h, uint64_t epoch) {
  auto* c = static_cast<Client*>(h);
  char header[1 + 8];
  header[0] = 9;
  std::memcpy(header + 1, &epoch, 8);
  uint8_t ack = 0;
  uint64_t now_epoch = 0;
  if (!send_all(c->fd, header, sizeof(header)) ||
      !recv_all(c->fd, &ack, 1) || ack != 1 ||
      !recv_all(c->fd, &now_epoch, 8))
    return -1;
  return static_cast<int64_t>(now_epoch);
}

// shard-map handshake (SHARD_INFO, action 11): which shard of which
// partition this server holds. Returns 0 on success (*out_num == 0 means
// the server is unsharded), -1 on transport failure.
int dkps_client_shard_info(void* h, uint32_t* out_shard, uint32_t* out_num,
                           uint64_t* out_epoch) {
  auto* c = static_cast<Client*>(h);
  uint8_t action = 11;
  uint32_t info[2] = {0, 0};
  uint64_t epoch = 0;
  if (!send_all(c->fd, &action, 1) || !recv_all(c->fd, info, 8) ||
      !recv_all(c->fd, &epoch, 8))
    return -1;
  if (out_shard) *out_shard = info[0];
  if (out_num) *out_num = info[1];
  if (out_epoch) *out_epoch = epoch;
  return 0;
}

// heartbeat (action 6): renew this worker's lease, reporting the client's
// cumulative retry count. Returns 1 = renewed, 0 = (re-)registered,
// -1 = transport failure.
int dkps_client_heartbeat(void* h, uint32_t retries) {
  auto* c = static_cast<Client*>(h);
  char header[1 + 4];
  header[0] = 6;
  std::memcpy(header + 1, &retries, 4);
  uint8_t ack = 0;
  if (!send_all(c->fd, header, sizeof(header)) ||
      !recv_all(c->fd, &ack, 1) || (ack != 1 && ack != 2))
    return -1;
  return ack == 1 ? 1 : 0;
}

// elastic live-join (action 12): lease this worker mid-run. Fills
// *out_updates / *out_pool with the server's current fold count and
// post-join pool gauge. Returns 0 on success, -1 on transport failure.
int dkps_client_join(void* h, uint64_t* out_updates, uint64_t* out_pool) {
  auto* c = static_cast<Client*>(h);
  uint8_t action = 12;
  uint8_t ack = 0;
  uint64_t updates = 0, pool = 0;
  if (!send_all(c->fd, &action, 1) || !recv_all(c->fd, &ack, 1) ||
      ack != 1 || !recv_all(c->fd, &updates, 8) ||
      !recv_all(c->fd, &pool, 8))
    return -1;
  if (out_updates) *out_updates = updates;
  if (out_pool) *out_pool = pool;
  return 0;
}

// preemption drain (action 13): clean deregister + elastic counters;
// timed_out != 0 records a deadline-lapsed drain. 0 on success.
int dkps_client_drain(void* h, uint8_t timed_out) {
  auto* c = static_cast<Client*>(h);
  char header[2];
  header[0] = 13;
  header[1] = static_cast<char>(timed_out ? 1 : 0);
  uint8_t ack = 0;
  if (!send_all(c->fd, header, 2) || !recv_all(c->fd, &ack, 1) || ack != 1)
    return -1;
  return 0;
}

// trace scrape (action 15, ISSUE 11): drain the server's span ring into
// `out` (room for max_recs records of 5 u64: kind, wid, seq, t0_ns,
// dur_ns). Returns the record count written (the remainder of an
// overfull ring is read off the wire and discarded so the stream stays
// framed), or -1 on transport failure.
int64_t dkps_client_trace_scrape(void* h, uint64_t* out,
                                 uint64_t max_recs) {
  auto* c = static_cast<Client*>(h);
  uint8_t action = 15;
  uint64_t cnt = 0;
  if (!send_all(c->fd, &action, 1) || !recv_all(c->fd, &cnt, 8))
    return -1;
  const uint64_t keep = cnt < max_recs ? cnt : max_recs;
  if (keep && !recv_all(c->fd, out, keep * 5 * 8)) return -1;
  uint64_t left = (cnt - keep) * 5 * 8;
  char sink[4096];
  while (left) {
    const uint64_t k = left < sizeof(sink) ? left : sizeof(sink);
    if (!recv_all(c->fd, sink, k)) return -1;
    left -= k;
  }
  return static_cast<int64_t>(keep);
}

// deregister (action 8): clean exit — drop the lease, no eviction counted
int dkps_client_deregister(void* h) {
  auto* c = static_cast<Client*>(h);
  uint8_t action = 8;
  uint8_t ack = 0;
  if (!send_all(c->fd, &action, 1) || !recv_all(c->fd, &ack, 1) || ack != 1)
    return -1;
  return 0;
}

// compressed pull (action 5): decodes the block-quantized reply into `out`
// (n floats). Returns the center version (>= 0) or -1 on transport failure
// or a malformed reply. The server holds this worker's quantization
// residual, so repeated compressed pulls telescope to the exact center.
int64_t dkps_client_pull_int8(void* h, float* out) {
  auto* c = static_cast<Client*>(h);
  uint8_t action = 5;
  uint64_t version;
  uint32_t nb;
  const uint64_t expect_nb = pull_blocks(c->n);
  if (!send_all(c->fd, &action, 1) || !recv_all(c->fd, &version, 8) ||
      !recv_all(c->fd, &nb, 4) || nb != expect_nb)
    return -1;
  std::vector<float> scales(nb);
  std::vector<int8_t> q(c->n);
  if (!recv_all(c->fd, scales.data(), nb * sizeof(float)) ||
      !recv_all(c->fd, q.data(), c->n))
    return -1;
  for (uint64_t b = 0; b < nb; ++b) {
    const uint64_t lo = b * kPullBlock;
    const uint64_t hi = std::min(lo + kPullBlock, c->n);
    const float s = scales[b];
    for (uint64_t i = lo; i < hi; ++i)
      out[i] = s * static_cast<float>(q[i]);
  }
  return static_cast<int64_t>(version);
}

// fused exchange (action 14): fold the commit and read the fresh
// post-fold center in ONE round trip. flags: bit0 carry `seq` (dedup),
// bit1 carry `epoch` (fencing), bit2 int8 pull reply, bit3 lag (price
// tau from the previous pull version — the pipelined worker's honest
// staleness). Returns the post-fold center version (>= 0; duplicate
// folds return the fresh center too), -2 = FENCED (not folded; the
// server's epoch lands in *server_epoch), -1 = transport failure.
int64_t dkps_client_exchange(void* h, uint8_t flags, uint64_t epoch,
                             uint64_t seq, const float* commit, float* out,
                             uint64_t* server_epoch) {
  auto* c = static_cast<Client*>(h);
  char header[1 + 1 + 8 + 8];
  size_t hl = 0;
  header[hl++] = 14;
  header[hl++] = static_cast<char>(flags);
  if (flags & 2) {
    std::memcpy(header + hl, &epoch, 8);
    hl += 8;
  }
  if (flags & 1) {
    std::memcpy(header + hl, &seq, 8);
    hl += 8;
  }
  uint8_t ack = 0;
  uint64_t sepoch = 0, version = 0;
  if (!send_all(c->fd, header, hl) ||
      !send_all(c->fd, commit, c->n * sizeof(float)) ||
      !recv_all(c->fd, &ack, 1) || !recv_all(c->fd, &sepoch, 8) ||
      (ack != 1 && ack != 2 && ack != 3))
    return -1;
  if (server_epoch) *server_epoch = sepoch;
  if (ack == 3) return -2;
  if (!recv_all(c->fd, &version, 8)) return -1;
  if (!(flags & 4)) {
    if (!recv_all(c->fd, out, c->n * sizeof(float))) return -1;
    return static_cast<int64_t>(version);
  }
  uint32_t nb;
  const uint64_t expect_nb = pull_blocks(c->n);
  if (!recv_all(c->fd, &nb, 4) || nb != expect_nb) return -1;
  std::vector<float> scales(nb);
  std::vector<int8_t> q(c->n);
  if (!recv_all(c->fd, scales.data(), nb * sizeof(float)) ||
      !recv_all(c->fd, q.data(), c->n))
    return -1;
  for (uint64_t b = 0; b < nb; ++b) {
    const uint64_t lo = b * kPullBlock;
    const uint64_t hi = std::min(lo + kPullBlock, c->n);
    const float s = scales[b];
    for (uint64_t i = lo; i < hi; ++i)
      out[i] = s * static_cast<float>(q[i]);
  }
  return static_cast<int64_t>(version);
}

void dkps_client_close(void* h) {
  auto* c = static_cast<Client*>(h);
  uint8_t action = 3;
  send_all(c->fd, &action, 1);
  close_conn_fd(c->fd);
  delete c;
}

}  // extern "C"
