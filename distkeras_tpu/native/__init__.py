"""Native runtime components — build-on-demand C++ via ctypes.

The reference had no native code of its own (SURVEY.md §2b.4), but its
performance-critical runtime lived in its dependencies' native layers. This
package is the rebuild's native runtime layer: small C++ cores compiled once
per machine with the system ``g++`` (no pybind11 in this image — plain C ABI
+ ctypes) and cached next to the source. Everything degrades gracefully: if
no compiler is present, callers get ``None`` from :func:`load_dkps` and fall
back to the pure-Python paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "dkps.cpp")
_BUILD_DIR = os.environ.get(
    "DISTKERAS_NATIVE_BUILD_DIR", os.path.join(_HERE, "_build")
)
_SO = os.path.join(_BUILD_DIR, "libdkps.so")

_lock = threading.Lock()
_cached: ctypes.CDLL | None = None
_failed: str | None = None


def _build() -> str | None:
    """Compile dkps.cpp → libdkps.so if missing/stale; return error or None."""
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return None
    tmp = _SO + f".tmp.{os.getpid()}"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-o", tmp, _SRC,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=300
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"g++ unavailable: {e}"
    if proc.returncode != 0:
        return f"g++ failed: {proc.stderr[-2000:]}"
    os.replace(tmp, _SO)  # atomic: concurrent builders race benignly
    return None


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.dkps_server_create.restype = ctypes.c_void_p
    lib.dkps_server_create.argtypes = [
        f32p, ctypes.c_uint64, ctypes.c_int, ctypes.c_double,
        ctypes.c_char_p, ctypes.c_int, ctypes.c_double, ctypes.c_double,
    ]
    lib.dkps_server_port.restype = ctypes.c_int
    lib.dkps_server_port.argtypes = [ctypes.c_void_p]
    lib.dkps_server_start.restype = ctypes.c_int
    lib.dkps_server_start.argtypes = [ctypes.c_void_p]
    lib.dkps_server_stop.restype = None
    lib.dkps_server_stop.argtypes = [ctypes.c_void_p]
    lib.dkps_server_crash.restype = None
    lib.dkps_server_crash.argtypes = [ctypes.c_void_p]
    lib.dkps_server_wal_open.restype = ctypes.c_int
    lib.dkps_server_wal_open.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_double,
    ]
    lib.dkps_server_set_ema.restype = ctypes.c_int
    lib.dkps_server_set_ema.argtypes = [ctypes.c_void_p, f32p]
    lib.dkps_server_restore_worker.restype = None
    lib.dkps_server_restore_worker.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64,
    ]
    lib.dkps_server_destroy.restype = None
    lib.dkps_server_destroy.argtypes = [ctypes.c_void_p]
    lib.dkps_server_num_updates.restype = ctypes.c_uint64
    lib.dkps_server_num_updates.argtypes = [ctypes.c_void_p]
    lib.dkps_server_set_num_updates.restype = None
    lib.dkps_server_set_num_updates.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.dkps_server_get_center.restype = None
    lib.dkps_server_get_center.argtypes = [ctypes.c_void_p, f32p]
    lib.dkps_server_set_center.restype = None
    lib.dkps_server_set_center.argtypes = [ctypes.c_void_p, f32p]
    lib.dkps_server_get_ema.restype = ctypes.c_int
    lib.dkps_server_get_ema.argtypes = [ctypes.c_void_p, f32p]
    lib.dkps_server_record_pull.restype = None
    lib.dkps_server_record_pull.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.dkps_server_stats.restype = None
    lib.dkps_server_stats.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.dkps_client_connect.restype = ctypes.c_void_p
    lib.dkps_client_connect.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_uint32, ctypes.c_uint64,
    ]
    lib.dkps_client_from_fd.restype = ctypes.c_void_p
    lib.dkps_client_from_fd.argtypes = [
        ctypes.c_int, ctypes.c_uint32, ctypes.c_uint64,
    ]
    # shm ring lane (ISSUE 12): the segment is mapped by Python
    # (multiprocessing.shared_memory) and both endpoints attach by base
    # pointer — see dkps.cpp "Shared-memory ring lane"
    lib.dkps_server_attach_shm.restype = ctypes.c_int
    lib.dkps_server_attach_shm.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
    ]
    lib.dkps_client_connect_shm.restype = ctypes.c_void_p
    lib.dkps_client_connect_shm.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint64,
    ]
    lib.dkps_client_set_timeout_ms.restype = ctypes.c_int
    lib.dkps_client_set_timeout_ms.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.dkps_client_pull.restype = ctypes.c_int64
    lib.dkps_client_pull.argtypes = [ctypes.c_void_p, f32p]
    lib.dkps_client_pull_int8.restype = ctypes.c_int64
    lib.dkps_client_pull_int8.argtypes = [ctypes.c_void_p, f32p]
    lib.dkps_client_commit.restype = ctypes.c_int
    lib.dkps_client_commit.argtypes = [ctypes.c_void_p, f32p]
    lib.dkps_client_commit_int8.restype = ctypes.c_int
    lib.dkps_client_commit_int8.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int8),
        ctypes.POINTER(ctypes.c_uint64), f32p, ctypes.c_uint32,
    ]
    lib.dkps_client_commit_seq.restype = ctypes.c_int
    lib.dkps_client_commit_seq.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, f32p,
    ]
    lib.dkps_client_commit_seq_e.restype = ctypes.c_int
    lib.dkps_client_commit_seq_e.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, f32p,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.dkps_client_fence.restype = ctypes.c_int64
    lib.dkps_client_fence.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.dkps_client_exchange.restype = ctypes.c_int64
    lib.dkps_client_exchange.argtypes = [
        ctypes.c_void_p, ctypes.c_uint8, ctypes.c_uint64, ctypes.c_uint64,
        f32p, f32p, ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.dkps_server_set_shard.restype = None
    lib.dkps_server_set_shard.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32,
    ]
    lib.dkps_client_shard_info.restype = ctypes.c_int
    lib.dkps_client_shard_info.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.dkps_server_fence.restype = ctypes.c_uint64
    lib.dkps_server_fence.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.dkps_server_fence_epoch.restype = ctypes.c_uint64
    lib.dkps_server_fence_epoch.argtypes = [ctypes.c_void_p]
    lib.dkps_client_heartbeat.restype = ctypes.c_int
    lib.dkps_client_heartbeat.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.dkps_client_deregister.restype = ctypes.c_int
    lib.dkps_client_deregister.argtypes = [ctypes.c_void_p]
    lib.dkps_server_set_pool_size.restype = None
    lib.dkps_server_set_pool_size.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.dkps_server_set_trace.restype = None
    lib.dkps_server_set_trace.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.dkps_client_trace_scrape.restype = ctypes.c_int64
    lib.dkps_client_trace_scrape.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
    ]
    lib.dkps_client_join.restype = ctypes.c_int
    lib.dkps_client_join.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.dkps_client_drain.restype = ctypes.c_int
    lib.dkps_client_drain.argtypes = [ctypes.c_void_p, ctypes.c_uint8]
    lib.dkps_client_close.restype = None
    lib.dkps_client_close.argtypes = [ctypes.c_void_p]
    return lib


def load_dkps(required: bool = False) -> ctypes.CDLL | None:
    """Load (building if needed) the dkps shared library.

    Returns ``None`` when the library cannot be built and ``required`` is
    False; raises ``RuntimeError`` with the compiler output otherwise.
    """
    global _cached, _failed
    with _lock:
        if _cached is not None:
            return _cached
        if _failed is None:
            _failed = _build() or ""
        if _failed:
            if required:
                raise RuntimeError(f"cannot build libdkps: {_failed}")
            return None
        _cached = _bind(ctypes.CDLL(_SO))
        return _cached
