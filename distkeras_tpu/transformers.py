"""Spark-ML-style feature transformers over :class:`distkeras_tpu.data.Dataset`.

Parity: reference ``distkeras/transformers.py`` —
``LabelIndexTransformer, OneHotTransformer, MinMaxTransformer,
ReshapeTransformer, DenseTransformer`` (SURVEY.md §2b #16). The reference
applied these per Spark row with Python UDFs; here each ``transform`` is one
vectorized NumPy pass over a column — the TPU never sees untransformed data,
and the host-side cost is a single array op instead of a per-row closure.

Every transformer keeps the reference's ``transform(dataset) -> dataset``
calling convention and is composable via :class:`TransformerPipeline`.
"""

from __future__ import annotations

import numpy as np

from distkeras_tpu.data import Dataset


class Transformer:
    def transform(self, ds: Dataset) -> Dataset:
        raise NotImplementedError

    def __call__(self, ds: Dataset) -> Dataset:
        return self.transform(ds)


class LabelIndexTransformer(Transformer):
    """One-hot / score column → integer class index column.

    Parity: reference ``distkeras/transformers.py :: LabelIndexTransformer``
    (used to turn prediction vectors into label indices).
    """

    def __init__(self, output_dim: int | None = None,
                 input_col="prediction", output_col="prediction_index"):
        self.output_dim = output_dim
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, ds: Dataset) -> Dataset:
        col = ds[self.input_col]
        if col.ndim == 1:
            idx = np.round(col).astype(np.int32)
        else:
            idx = np.argmax(col, axis=-1).astype(np.int32)
        return ds.with_column(self.output_col, idx)


class OneHotTransformer(Transformer):
    """Integer label column → one-hot float column.

    Parity: reference ``distkeras/transformers.py :: OneHotTransformer``.
    """

    def __init__(self, output_dim: int, input_col="label", output_col="label_onehot"):
        self.output_dim = output_dim
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, ds: Dataset) -> Dataset:
        labels = ds[self.input_col].astype(np.int64).reshape(-1)
        onehot = np.zeros((len(labels), self.output_dim), dtype=np.float32)
        onehot[np.arange(len(labels)), labels] = 1.0
        return ds.with_column(self.output_col, onehot)


class MinMaxTransformer(Transformer):
    """Affine rescale of a feature column to ``[o_min, o_max]``.

    Parity: reference ``distkeras/transformers.py :: MinMaxTransformer``
    (constructor took the current and target ranges).
    """

    def __init__(self, n_min=0.0, n_max=1.0, o_min=0.0, o_max=255.0,
                 input_col="features", output_col=None):
        self.n_min, self.n_max = float(n_min), float(n_max)
        self.o_min, self.o_max = float(o_min), float(o_max)
        self.input_col = input_col
        self.output_col = output_col or input_col

    def transform(self, ds: Dataset) -> Dataset:
        x = ds[self.input_col].astype(np.float32)
        scale = (self.n_max - self.n_min) / (self.o_max - self.o_min)
        return ds.with_column(self.output_col, (x - self.o_min) * scale + self.n_min)


class StandardScaleTransformer(Transformer):
    """Zero-mean unit-variance scaling (extension beyond the reference)."""

    def __init__(self, input_col="features", output_col=None, eps=1e-8):
        self.input_col = input_col
        self.output_col = output_col or input_col
        self.eps = eps

    def transform(self, ds: Dataset) -> Dataset:
        x = ds[self.input_col].astype(np.float32)
        mean = x.mean(axis=0, keepdims=True)
        std = x.std(axis=0, keepdims=True)
        return ds.with_column(self.output_col, (x - mean) / (std + self.eps))


class ReshapeTransformer(Transformer):
    """Reshape each row of a column (e.g. flat 784 → (28, 28, 1) for CNNs).

    Parity: reference ``distkeras/transformers.py :: ReshapeTransformer``.
    """

    def __init__(self, input_col, output_col, shape):
        self.input_col = input_col
        self.output_col = output_col
        self.shape = tuple(shape)

    def transform(self, ds: Dataset) -> Dataset:
        x = ds[self.input_col]
        return ds.with_column(self.output_col, x.reshape((len(ds),) + self.shape))


class DenseTransformer(Transformer):
    """Sparse (indices, values) representation → dense vectors.

    Parity: reference ``distkeras/transformers.py :: DenseTransformer`` (Spark
    sparse vectors → dense). Input column holds ``(idx, val)`` object pairs or
    an already-dense array (then it's a no-op cast).
    """

    def __init__(self, input_col="features", output_col="features_dense", dim=None):
        self.input_col = input_col
        self.output_col = output_col
        self.dim = dim

    def transform(self, ds: Dataset) -> Dataset:
        col = ds[self.input_col]
        if col.dtype != object:
            return ds.with_column(self.output_col, col.astype(np.float32))
        if self.dim is None:
            raise ValueError("dim required to densify sparse rows")
        out = np.zeros((len(col), self.dim), dtype=np.float32)
        for i, (idx, val) in enumerate(col):
            out[i, np.asarray(idx, dtype=np.int64)] = val
        return ds.with_column(self.output_col, out)


class SequencePadTransformer(Transformer):
    """Pad/truncate variable-length int sequences to a static length + mask.

    TPU-specific extension: XLA needs static shapes (SURVEY.md §5.7), so the
    IMDB-LSTM path pads here on the host and carries a mask column for the
    masked loss.
    """

    def __init__(self, maxlen: int, input_col="sequence",
                 output_col="tokens", mask_col="mask", pad_value=0):
        self.maxlen = maxlen
        self.input_col = input_col
        self.output_col = output_col
        self.mask_col = mask_col
        self.pad_value = pad_value

    def transform(self, ds: Dataset) -> Dataset:
        col = ds[self.input_col]
        n = len(col)
        tokens = np.full((n, self.maxlen), self.pad_value, dtype=np.int32)
        mask = np.zeros((n, self.maxlen), dtype=np.float32)
        for i, seq in enumerate(col):
            seq = np.asarray(seq, dtype=np.int32)[: self.maxlen]
            tokens[i, : len(seq)] = seq
            mask[i, : len(seq)] = 1.0
        return ds.with_column(self.output_col, tokens).with_column(self.mask_col, mask)


class TransformerPipeline(Transformer):
    """Apply a list of transformers in order (Spark ``Pipeline`` analogue)."""

    def __init__(self, stages):
        self.stages = list(stages)

    def transform(self, ds: Dataset) -> Dataset:
        for stage in self.stages:
            ds = stage.transform(ds)
        return ds
