"""The watchtower: declarative SLO/anomaly watchdog over the time series.

The failure shapes this codebase already *simulates* (stragglers under
``FaultPlan`` chaos, staleness blowups, fenced/dup commit storms after a
PS kill, WAL fsync tails, shm ring saturation, convergence stalls,
serving SLO misses) become typed, automatically-detected **alerts**:
each :class:`AlertRule` evaluates one condition over the
:class:`~distkeras_tpu.observability.timeseries.TimeSeriesStore`, the
:class:`Watchdog` turns rule verdicts into fire/resolve *transitions*
(an alert log, an active set, optional hooks), and the
:class:`Watchtower` bundles store + scraper + watchdog into the one
object a trainer run or a live server attaches.

The unifying refactor: :func:`rates_from_counts` and
:func:`straggler_workers` are THE definitions of per-worker rounds/s
and straggler-ness — the skew rule evaluates them over the shared
``worker.<wid>.windows`` series, and ``ElasticPolicy``
(resilience/elastic.py) calls the same two functions instead of
computing privately, so the autoscaler and the alerting can never
disagree about who is slow.

Rules are deliberately *pure* over ``(store, now)`` (the only state a
rule keeps is its own persistence counter), so tests drive them
deterministically with hand-built series — chaos integration only has
to prove the SOURCES feed the store faithfully.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable

import numpy as np

from distkeras_tpu.observability.timeseries import (
    Scraper,
    TimeSeriesStore,
    history_source,
    progress_source,
    ps_source,
    serving_source,
)

__all__ = [
    "Alert", "AlertRule", "TauP95Rule", "CommitSkewRule",
    "CommitReplaySpikeRule", "WalFsyncTailRule", "RingOccupancyRule",
    "DeployLagRule", "ServingSLORule", "PrefixHitRateRule",
    "LossStallRule",
    "BottleneckShiftRule", "SLOClass",
    "default_rules", "Watchdog", "Watchtower", "rates_from_counts",
    "worker_rates", "rounds_per_sec", "straggler_workers",
    "watch_endpoint",
]


# -- the ONE definition of rounds/s and straggler-ness ------------------------

def rates_from_counts(t0: float, counts0: dict, t1: float,
                      counts1: dict) -> dict:
    """Per-worker rounds/s between two cumulative window-count
    observations. Workers present only in the newer observation rate
    from zero (a joiner's first interval counts its whole progress)."""
    dt = float(t1) - float(t0)
    if dt <= 0:
        return {}
    return {
        wid: max(0.0, n - counts0.get(wid, 0)) / dt
        for wid, n in counts1.items()
    }


def worker_rates(store: TimeSeriesStore, window_s: float,
                 now: float | None = None,
                 prefix: str = "worker.") -> dict[int, float]:
    """Per-worker rounds/s read off the shared ``worker.<wid>.windows``
    counter series over the trailing window. Workers without two
    in-window points (just joined, just drained) are omitted."""
    rates: dict[int, float] = {}
    for name in store.names(prefix):
        if not name.endswith(".windows"):
            continue
        r = store.rate(name, window_s, now)
        if r is None:
            continue
        wid = name[len(prefix):-len(".windows")]
        try:
            rates[int(wid)] = r
        except ValueError:
            rates[wid] = r  # non-numeric worker labels pass through
    return rates


def rounds_per_sec(store: TimeSeriesStore, window_s: float,
                   now: float | None = None) -> float | None:
    """Pool rounds/s: the sum of per-worker rates (None before any
    worker has two in-window samples)."""
    rates = worker_rates(store, window_s, now)
    if not rates:
        return None
    return float(sum(rates.values()))


def straggler_workers(rates: dict, ratio: float) -> tuple[float, list]:
    """``(median_rate, [straggler ids])``: a straggler is a worker whose
    rate sits below ``ratio × median`` of the pool — DynSGD's τ tail,
    the workers whose commits the center is already down-weighting
    toward nothing. Needs a pool of >= 2 to define a median."""
    if len(rates) < 2:
        return 0.0, []
    med = float(np.median(list(rates.values())))
    if med <= 0:
        return med, []
    return med, sorted(w for w, r in rates.items() if r < ratio * med)


# -- alerts -------------------------------------------------------------------

class Alert(dict):
    """One typed alert transition (a dict, so it is JSON-clean by
    construction): ``rule``/``kind``/``severity``/``state`` ("firing" |
    "resolved")/``t``/``value``/``threshold``/``detail``."""

    @property
    def firing(self) -> bool:
        return self["state"] == "firing"


class AlertRule:
    """Base rule: subclasses implement :meth:`check` returning
    ``(firing, value, detail)`` — ``firing=None`` means "not enough
    data, leave the alert state unchanged". ``persistence`` demands N
    consecutive firing evaluations before the alert transitions (one
    noisy scrape must not page anyone)."""

    kind = "generic"
    severity = "warning"

    def __init__(self, name: str | None = None, persistence: int = 1):
        self.name = name or self.kind
        if persistence < 1:
            raise ValueError(
                f"persistence must be >= 1, got {persistence}"
            )
        self.persistence = int(persistence)
        self._streak = 0
        self.threshold: float | None = None

    def check(self, store: TimeSeriesStore, now: float):
        raise NotImplementedError

    def evaluate(self, store: TimeSeriesStore, now: float):
        """→ ``(firing, value, detail)`` with persistence applied."""
        firing, value, detail = self.check(store, now)
        if firing is None:
            return None, value, detail
        if firing:
            self._streak += 1
            return self._streak >= self.persistence, value, detail
        self._streak = 0
        return False, value, detail


class TauP95Rule(AlertRule):
    """DynSGD staleness tail: the p95 of recent per-commit τ (sampled
    from the fold path into ``ps.tau_p95``) crossed ``bound``. A τ
    blowup means someone's pulls are ancient — a straggler, a stalled
    pipeline, or a zombie — and the center is paying for it."""

    kind = "tau_p95"

    def __init__(self, bound: float = 16.0, **kw):
        super().__init__(**kw)
        self.threshold = float(bound)

    def check(self, store, now):
        v = store.last("ps.tau_p95")
        if v is None:
            return None, None, None
        return v > self.threshold, v, {"tau_p95": v}


class CommitSkewRule(AlertRule):
    """Per-worker commit-rate skew (the straggler alert): some worker's
    windows/s sits below ``ratio × median`` of the pool over the
    trailing window — evaluated with :func:`straggler_workers`, the
    same definition ``ElasticPolicy`` acts on."""

    kind = "commit_skew"

    def __init__(self, ratio: float = 0.25, window_s: float = 5.0,
                 min_rounds: int = 4, **kw):
        kw.setdefault("persistence", 2)
        super().__init__(**kw)
        self.threshold = float(ratio)
        self.window_s = float(window_s)
        self.min_rounds = int(min_rounds)

    def check(self, store, now):
        rates = worker_rates(store, self.window_s, now)
        # warm-up grace: a worker is judged only once (a) it has
        # completed at least one window — before that it is
        # INITIALIZING (first pull, jit warm-up), not straggling — and
        # (b) its series spans a FULL rate window, so the one-core
        # startup scramble (threads taking turns at the GIL while the
        # first windows compile and run) cannot read as skew; an
        # elastic joiner gets the same one-window grace. A worker that
        # progressed and then stalled stays in.
        for wid in list(rates):
            s = store.get(f"worker.{wid}.windows")
            pts = s.points() if s is not None else []
            if (not pts or pts[-1][1] <= 0
                    or now - pts[0][0] < self.window_s):
                rates.pop(wid)
        if len(rates) < 2:
            return None, None, None
        total = sum(rates.values())
        if total * self.window_s < self.min_rounds:
            return None, total, None   # too little progress to judge
        med, lagging = straggler_workers(rates, self.threshold)
        detail = {
            "median_rounds_per_sec": med,
            "stragglers": {str(w): rates[w] for w in lagging},
            "rates": {str(w): round(r, 3) for w, r in rates.items()},
        }
        worst = min(rates.values()) / med if med > 0 else None
        return bool(lagging), worst, detail


class CommitReplaySpikeRule(AlertRule):
    """Dup/fenced-commit spike: the sum of ``ps.dup_commits`` +
    ``ps.fenced_commits`` grew by more than ``max_in_window`` inside the
    trailing window. A handful of dups is the retry layer doing its job;
    a spike is a lost-ACK storm or a fenced old history replaying after
    a failover."""

    kind = "commit_replay_spike"

    def __init__(self, max_in_window: float = 3.0, window_s: float = 5.0,
                 **kw):
        super().__init__(**kw)
        self.threshold = float(max_in_window)
        self.window_s = float(window_s)

    def check(self, store, now):
        # reset-aware increase: a failed-over PS restarts its op
        # counters — the replay storm right after is exactly when this
        # rule must not be blinded by the reset
        dup = store.increase("ps.dup_commits", self.window_s, now)
        fenced = store.increase("ps.fenced_commits", self.window_s, now)
        if dup is None and fenced is None:
            return None, None, None
        v = (dup or 0.0) + (fenced or 0.0)
        return v > self.threshold, v, {
            "dup_commits": dup or 0.0, "fenced_commits": fenced or 0.0,
        }


class WalFsyncTailRule(AlertRule):
    """WAL fsync tail latency: the p95 of recent group-fsync durations
    (``ps.wal_fsync_p95_ms``) crossed ``p95_ms``. A slow log device
    stretches every deferred commit ACK — durable throughput dies here
    first."""

    kind = "wal_fsync_tail"

    def __init__(self, p95_ms: float = 50.0, **kw):
        super().__init__(**kw)
        self.threshold = float(p95_ms)

    def check(self, store, now):
        v = store.last("ps.wal_fsync_p95_ms")
        if v is None:
            return None, None, None
        return v > self.threshold, v, {"wal_fsync_p95_ms": v}


class DeployLagRule(AlertRule):
    """Serving-tier staleness: the deploy lag (``ps.deploy_lag_folds``
    — folds the training center is ahead of the newest snapshot the
    serving tier materialized) crossed ``bound``. A streamer that
    detached, a stalled publisher thread, or a snapshot cadence far
    coarser than the fold rate all land here: training keeps moving
    while served weights quietly age. Silent on training-only runs —
    until a deployer reports a version (``ps.deploy_version`` > 0)
    there is nothing to lag behind."""

    kind = "deploy_lag"

    def __init__(self, bound: float = 500.0, **kw):
        super().__init__(**kw)
        self.threshold = float(bound)

    def check(self, store, now):
        dv = store.last("ps.deploy_version")
        if dv is None or dv <= 0:
            return None, None, None
        v = store.last("ps.deploy_lag_folds")
        if v is None:
            return None, None, None
        return v > self.threshold, v, {
            "deploy_lag_folds": v, "deploy_version": dv,
        }


class RingOccupancyRule(AlertRule):
    """shm ring saturation: the fullest ring's used fraction
    (``shm.ring_occupancy_frac``) crossed ``frac`` — the writer is
    about to block on the reader; either the reader stalled or
    ``ring_bytes`` is undersized for the payload."""

    kind = "ring_occupancy"

    def __init__(self, frac: float = 0.9, **kw):
        super().__init__(**kw)
        self.threshold = float(frac)

    def check(self, store, now):
        v = store.last("shm.ring_occupancy_frac")
        if v is None:
            return None, None, None
        return v > self.threshold, v, {"ring_occupancy_frac": v}


class SLOClass:
    """One serving SLO class: latency bounds in ms (None = unbounded)."""

    __slots__ = ("p50_ms", "p99_ms")

    def __init__(self, p50_ms: float | None = None,
                 p99_ms: float | None = None):
        self.p50_ms = None if p50_ms is None else float(p50_ms)
        self.p99_ms = None if p99_ms is None else float(p99_ms)


class ServingSLORule(AlertRule):
    """Serving p50/p99 vs per-class SLO, with the queue/prefill/decode
    breakdown in the alert detail (the series carry the means the
    engine computed from its retired-request ring — the same numbers
    the request spans record, without needing tracing on). ``slo`` maps
    class name → :class:`SLOClass` (or ``(p50_ms, p99_ms)``)."""

    kind = "serving_slo"

    def __init__(self, slo: dict | None = None, **kw):
        super().__init__(**kw)
        slo = slo or {"default": SLOClass(p99_ms=1000.0)}
        self.slo: dict[str, SLOClass] = {
            str(c): (s if isinstance(s, SLOClass) else SLOClass(*s))
            for c, s in slo.items()
        }

    def check(self, store, now):
        misses = {}
        seen = False
        worst = None
        for cls, slo in self.slo.items():
            rec = {}
            for key in ("p50_ms", "p99_ms", "queue_ms", "prefill_ms",
                        "decode_ms"):
                v = store.last(f"serve.lat.{cls}.{key}")
                if v is not None:
                    rec[key] = v
            if not rec:
                continue
            seen = True
            for pct in ("p50_ms", "p99_ms"):
                bound = getattr(slo, pct)
                v = rec.get(pct)
                if bound is not None and v is not None and v > bound:
                    misses[cls] = {**rec, "missed": pct, "bound": bound}
                    ratio = v / bound
                    worst = ratio if worst is None else max(worst, ratio)
        if not seen:
            return None, None, None
        return bool(misses), worst, {"misses": misses} if misses else None


class PrefixHitRateRule(AlertRule):
    """The front door's reuse health (ISSUE 17): the engine's lifetime
    token-level prefix-cache hit rate sits below ``floor`` after at
    least ``min_admitted`` requests. A cold cache warming up is normal
    (the admission gate); a WARM replica stuck near zero means the
    router is spraying prefixes instead of colocating them (affinity
    off / misconfigured) or eviction is thrashing the tree — either
    way the fleet is paying full prefill for prompts it already
    computed. Engines without a prefix cache publish no
    ``serve.prefix_hit_rate`` series and are never judged."""

    kind = "prefix_hit_rate"

    def __init__(self, floor: float = 0.05, min_admitted: int = 50, **kw):
        super().__init__(**kw)
        self.threshold = float(floor)
        self.min_admitted = int(min_admitted)

    def check(self, store, now):
        rate = store.last("serve.prefix_hit_rate")
        if rate is None:
            return None, None, None     # cache off: nothing to judge
        admitted = store.last("serve.admitted")
        if admitted is None or admitted < self.min_admitted:
            return None, rate, None     # still warming: hold state
        detail = {"hit_rate": rate, "floor": self.threshold,
                  "admitted": admitted,
                  "cached_blocks": store.last("serve.prefix_cached_blocks"),
                  "evictions": store.last("serve.prefix_evictions")}
        return rate < self.threshold, rate, detail


class LossStallRule(AlertRule):
    """Convergence stall: the least-squares slope of ``train.loss``
    over the trailing window is not meaningfully negative even though
    training progressed (``train.records`` grew by at least
    ``min_new_records``). ``slope_eps`` is in loss-units/second —
    slope >= -eps fires. The progress gate keeps an idle/finished run
    from alerting."""

    kind = "loss_stall"

    def __init__(self, window_s: float = 20.0, min_points: int = 8,
                 min_new_records: int = 8, slope_eps: float = 1e-4, **kw):
        kw.setdefault("persistence", 2)
        super().__init__(**kw)
        self.window_s = float(window_s)
        self.min_points = int(min_points)
        self.min_new_records = int(min_new_records)
        self.threshold = float(slope_eps)

    def check(self, store, now):
        s = store.get("train.loss")
        if s is None:
            return None, None, None
        pts = s.window((now if now is not None
                        else (s.last() or (0,))[0]) - self.window_s)
        if len(pts) < self.min_points:
            return None, None, None
        # span gate (the skew rule's warm-up twin): a judgment about a
        # trailing window needs a window's worth of data — the first
        # seconds of a run (loss briefly rising out of init noise) must
        # not read as a stall, and a run shorter than the window is
        # never judged at all (stalls are a sustained phenomenon)
        if pts[-1][0] - pts[0][0] < 0.8 * self.window_s:
            return None, None, None
        grew = store.delta("train.records", self.window_s, now)
        if grew is None or grew < self.min_new_records:
            return None, None, None
        t = np.asarray([p[0] for p in pts])
        v = np.asarray([p[1] for p in pts])
        slope = float(np.polyfit(t - t[0], v, 1)[0])
        return slope >= -self.threshold, slope, {
            "slope_per_sec": slope, "window_points": len(pts),
        }


class BottleneckShiftRule(AlertRule):
    """The analyst's online twin (ISSUE 14): fires when the DOMINANT
    regime changes mid-run — the ``analyze.regime_code`` series (fed by
    :func:`distkeras_tpu.observability.analyze.regime_source` on traced
    watched runs) stops agreeing with the regime that has held for most
    of the run so far. A run that starts compute-bound and turns
    fsync-bound mid-flight is a disk filling its cache, a log device
    degrading, a straggler arriving — exactly the transition an
    operator wants paged on, and one no level-threshold rule can see.
    Resolves when the newest samples return to the run's dominant
    regime."""

    kind = "bottleneck_shift"

    def __init__(self, min_points: int = 4, **kw):
        kw.setdefault("persistence", 2)
        super().__init__(**kw)
        self.min_points = int(min_points)

    def check(self, store, now):
        from distkeras_tpu.observability.analyze import REGIMES

        s = store.get("analyze.regime_code")
        pts = s.points() if s is not None else []
        if len(pts) < self.min_points:
            return None, None, None
        codes = [int(v) for _, v in pts]
        cur = codes[-1]
        # the run's dominant regime: the mode of everything BEFORE the
        # newest sample (so a genuine shift doesn't out-vote itself
        # only after half the run)
        prior = codes[:-1]
        dominant = max(set(prior), key=prior.count)
        firing = cur != dominant

        def name(c):
            return REGIMES[c] if 0 <= c < len(REGIMES) else str(c)

        return firing, float(cur), {
            "from": name(dominant), "to": name(cur),
            "samples": len(codes),
        }


def default_rules(slo: dict | None = None,
                  tau_bound: float = 16.0) -> list[AlertRule]:
    """The standard rule set — what ``watch=True`` installs. Serving
    rules only judge classes with data, PS rules only servers with the
    matching series (the bottleneck-shift rule needs a traced watched
    run to feed its regime series), so one set covers training and
    serving runs."""
    return [
        TauP95Rule(bound=tau_bound),
        CommitSkewRule(),
        CommitReplaySpikeRule(),
        WalFsyncTailRule(),
        RingOccupancyRule(),
        DeployLagRule(),
        ServingSLORule(slo=slo),
        PrefixHitRateRule(),
        LossStallRule(),
        BottleneckShiftRule(),
    ]


# -- the watchdog -------------------------------------------------------------

class Watchdog:
    """Evaluates rules over a store and keeps the alert ledger: the
    ``active`` set (currently firing), the transition ``log`` (every
    fire AND resolve, timestamped), and per-kind counters. ``hooks``
    are called with each transition — the trainer's ``watch_hook=``
    lands here."""

    def __init__(self, store: TimeSeriesStore,
                 rules: Iterable[AlertRule] | None = None,
                 hooks: Iterable[Callable] | None = None):
        self.store = store
        self.rules = list(rules) if rules is not None else default_rules()
        names = [r.name for r in self.rules]
        if len(names) != len(set(names)):
            raise ValueError(f"duplicate rule names: {sorted(names)}")
        self.hooks = list(hooks or [])
        self._lock = threading.Lock()
        self.active: dict[str, Alert] = {}
        self.log: list[Alert] = []
        self.evaluations = 0

    def evaluate(self, now: float | None = None) -> list[Alert]:
        """One evaluation pass; returns this pass's transitions."""
        t = time.monotonic() if now is None else float(now)
        transitions: list[Alert] = []
        for rule in self.rules:
            firing, value, detail = rule.evaluate(self.store, t)
            with self._lock:
                was = rule.name in self.active
                if firing is None or firing == was:
                    continue
                alert = Alert(
                    rule=rule.name, kind=rule.kind,
                    severity=rule.severity,
                    state="firing" if firing else "resolved",
                    t=t, value=value, threshold=rule.threshold,
                    detail=detail,
                )
                if firing:
                    self.active[rule.name] = alert
                else:
                    self.active.pop(rule.name, None)
                self.log.append(alert)
                transitions.append(alert)
        for alert in transitions:
            for hook in self.hooks:
                try:
                    hook(alert)
                except Exception:  # noqa: BLE001 — observer must survive
                    pass
        with self._lock:
            self.evaluations += 1
        return transitions

    def counts(self) -> dict[str, int]:
        """Lifetime FIRE transitions per alert kind."""
        with self._lock:
            out: dict[str, int] = {}
            for a in self.log:
                if a["state"] == "firing":
                    out[a["kind"]] = out.get(a["kind"], 0) + 1
            return out

    def alerts_json(self) -> dict:
        from distkeras_tpu.observability.metrics import _json_clean

        with self._lock:
            doc = {
                "active": sorted(self.active),
                "log": [dict(a) for a in self.log],
            }
        doc["counts"] = self.counts()
        return _json_clean(doc)


# -- the bundle ---------------------------------------------------------------

class Watchtower:
    """Store + scraper + watchdog in one attachable object.

    ``add_ps`` / ``add_progress`` / ``add_history`` / ``add_serving``
    register the standard sources; the watchdog evaluates after every
    scrape tick (rules always see fresh samples). Attach it to a
    serving ``SocketParameterServer`` / ``GenerationServer`` via their
    ``watchtower`` attribute and the ``metrics`` wire action carries
    the alert ledger to remote scrapers."""

    def __init__(self, rules: Iterable[AlertRule] | None = None,
                 interval: float = 1.0, capacity: int = 512,
                 hook: Callable | None = None):
        self.store = TimeSeriesStore(capacity=capacity)
        self.watchdog = Watchdog(self.store, rules=rules,
                                 hooks=[hook] if hook is not None else [])
        self.scraper = Scraper(self.store, interval=interval)
        self.scraper.on_tick(self.watchdog.evaluate)

    # -- source registration -------------------------------------------------

    def add_source(self, name: str, fn: Callable) -> None:
        self.scraper.add_source(name, fn)

    def add_ps(self, ps) -> None:
        self.add_source("ps", ps_source(ps))

    def add_progress(self, get_progress: Callable[[], dict]) -> None:
        self.add_source("progress", progress_source(get_progress))

    def add_history(self, history: list, lock=None) -> None:
        self.add_source("history", history_source(history, lock))

    def add_serving(self, engine) -> None:
        self.add_source("serving", serving_source(engine))

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self.scraper.start()

    def stop(self) -> None:
        self.scraper.stop(final_tick=True)

    def tick(self, now: float | None = None) -> None:
        self.scraper.tick(now)

    # -- results -------------------------------------------------------------

    @property
    def alerts(self) -> list[Alert]:
        return list(self.watchdog.log)

    def alerts_json(self) -> dict:
        return self.watchdog.alerts_json()

    def dump(self, path: str) -> str:
        """One JSON artifact: every series + the alert ledger."""
        return self.store.dump(path, extra={"alerts": self.alerts_json()})


# -- live-endpoint watch mode (the CLI's engine) ------------------------------

def watch_endpoint(scrape: Callable[[], dict],
                   rules: Iterable[AlertRule] | None = None,
                   interval: float = 2.0, count: int = 0,
                   emit: Callable[[dict], None] | None = None,
                   sleep: Callable[[float], None] = time.sleep) -> Watchdog:
    """Poll a live server's ``metrics`` action and run the SAME watchdog
    rules over the scraped series, emitting alert transitions (plus any
    server-side alert ledger riding the reply) through ``emit``. Runs
    ``count`` polls (0 = forever); returns the watchdog for inspection.
    ``scrape`` is any zero-arg callable returning the metrics reply —
    the CLI passes its wire scraper, tests pass a fake. The returned
    watchdog carries ``remote_active`` (the server-side ledger's active
    set from the LAST poll) next to its own ``active`` — the CLI's
    exit code must reflect a firing alert wherever it lives."""
    from distkeras_tpu.observability.metrics import wire_series_samples

    store = TimeSeriesStore()
    dog = Watchdog(store, rules=rules)
    dog.remote_active = []
    n = 0
    seen_remote = 0
    while True:
        now = time.monotonic()
        reply = scrape()
        for name, kind, value in wire_series_samples(
                reply.get("metrics", {})):
            store.sample(name, now, value, kind)
        for alert in dog.evaluate(now):
            if emit is not None:
                emit(dict(alert))
        # relay the SERVER-side ledger too (a watchtower attached to the
        # server sees sources — τ ring, shm occupancy — a remote scrape
        # cannot reconstruct from counters alone)
        ledger = reply.get("alerts") or {}
        remote = ledger.get("log") or []
        for alert in remote[seen_remote:]:
            if emit is not None:
                emit({"remote": True, **alert})
        seen_remote = len(remote)
        dog.remote_active = list(ledger.get("active") or [])
        n += 1
        if count and n >= count:
            return dog
        sleep(max(0.05, interval))
