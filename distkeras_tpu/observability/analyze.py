"""The analyst: critical-path attribution and bottleneck diagnosis (ISSUE 14).

PR 11's flight recorder answers "what happened" and PR 13's watchtower
"is it healthy right now"; this module answers the question every perf
PR in this repo had to answer by hand-reading bench legs: **why is this
run slow, and which knob fixes it**. It is strictly post-hoc: it
consumes the span streams the recorder already captured (in-memory
``trace.events()`` or a saved Chrome-trace file) plus, optionally, the
watchtower's time-series dump — the training/serving hot paths pay
nothing for it, and a no-trace run pays nothing at all.

The machinery, bottom up:

- **Interval algebra.** Spans are ``[t0, t0+dur)`` intervals;
  :func:`union_length` / :func:`intersect_intervals` are the primitives
  everything else uses. Regime fractions are computed over per-bucket
  interval UNIONS across all threads, not sums: four workers waiting on
  the same group fsync cost the run one fsync of wall time, not four —
  summed attribution (who waited how much) is reported separately, per
  worker.
- **Window assembly.** Each worker's ``worker.fetch`` spans anchor its
  windows (one fetch per window in every loop shape — serial,
  pipelined, elastic); the compress/commit/pull/compute spans between
  two fetch anchors belong to the earlier window. The window's commit
  is then decomposed against the PS-side spans that share its
  correlation id (or nest inside it on the same thread — the in-process
  transport): ``ps.decode`` → center-lock wait (the decode→fold gap) →
  ``ps.fold`` → ``ps.wal_append`` → ``ps.wal_wait``/``wal.fsync``, and
  whatever remains is wire time. A window missing its anchor or commit
  is SKIPPED and counted — dropped spans never become invented time.
- **Overlap.** ``worker.compute`` spans run dispatch → fetch-return, so
  ``|exchange ∩ compute| / |exchange|`` is the fraction of exchange
  hidden under the window's outstanding device work — ~0.0 for the
  serial loop, ~1.0 for ``ps_pipeline_depth=1`` (PR 10's claim, now
  measured per run). The fraction is an upper bound: a device that
  finishes mid-exchange is indistinguishable from one that ran through
  it without device-side events, so per-window CRITICAL attribution
  additionally checks the fetch residue — a pipelined window whose
  fetch still waited was compute-critical (its hidden exchange charged
  to compute), one whose fetch returned immediately was
  exchange-critical.
- **Verdict.** :func:`classify` turns the bucket fractions into one of
  :data:`REGIMES` (``host-core-bound`` refines ``compute-bound`` when
  the worker pool oversubscribes the host's cores and their busy
  intervals saturate them) and keys up to three recommendations to
  existing knobs. ``trace_dropped_spans > 0`` marks the whole verdict
  ``degraded``.

Surfaces: ``python -m distkeras_tpu.observability analyze <trace.json>
[--series <dump.json>] [--json]`` (both files may be gzipped), the
trainer knob ``analyze=True`` (→ ``trainer.analysis_``), ``bench.py
--trace-dir`` legs stamping the verdict into their records, and
:func:`regime_source` feeding ``analyze.regime_code`` into the
watchtower store so ``watch.BottleneckShiftRule`` can fire when the
dominant regime changes mid-run.
"""

from __future__ import annotations

import bisect
import os
from typing import Any, Callable

from distkeras_tpu.observability.trace import load_json_maybe_gz

__all__ = [
    "REGIMES", "load_trace", "analyze_events", "analyze_trace",
    "bucket_totals", "classify", "format_report", "union_length",
    "merge_intervals", "intersect_intervals", "regime_source",
    "RegimeTracker", "regime_code",
]

#: the typed regime vocabulary (index == the ``analyze.regime_code``
#: series value the watchtower's shift rule reads). ``queue-bound`` is
#: the serving tier's admission-wait regime; ``idle`` means the trace
#: carried no attributable work.
REGIMES = (
    "compute-bound",        # 0: device/window compute dominates
    "wire-bound",           # 1: exchange transport (incl. decode) dominates
    "fsync-bound",          # 2: durable logging (append/flush/fsync/wait)
    "fold-lock-bound",      # 3: center-lock queueing + fold dominates
    "host-core-bound",      # 4: compute-bound AND the host's cores are
    #                            oversubscribed by the worker pool
    "queue-bound",          # 5: serving admission queue dominates
    "idle",                 # 6: nothing attributable recorded
)

#: bucket → regime mapping for the training-side classifier
_TRAIN_BUCKET_REGIME = {
    "compute": "compute-bound",
    "wire": "wire-bound",
    "decode": "wire-bound",
    "wal": "fsync-bound",
    "lock_wait": "fold-lock-bound",
    "fold": "fold-lock-bound",
}

#: span names claimed by a window's commit decomposition (matched by
#: corr, or by same-thread nesting for the in-process transport)
_SERVER_SPAN_NAMES = frozenset((
    "ps.decode", "ps.fold", "ps.wal_append", "ps.wal_wait", "wal.fsync",
))

_EPS_NS = 50_000          # 50 µs: "the fetch returned immediately"


def regime_code(name: str) -> int:
    """Regime name → its :data:`REGIMES` index (the series encoding)."""
    return REGIMES.index(name)


# -- interval algebra ---------------------------------------------------------

def merge_intervals(ivs: list[tuple[int, int]]) -> list[tuple[int, int]]:
    """Sorted, non-overlapping union of ``[a, b)`` intervals."""
    out: list[tuple[int, int]] = []
    for a, b in sorted(iv for iv in ivs if iv[1] > iv[0]):
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1] = (out[-1][0], b)
        else:
            out.append((a, b))
    return out


def union_length(ivs: list[tuple[int, int]]) -> int:
    """Total covered length of a set of intervals (overlaps once)."""
    return sum(b - a for a, b in merge_intervals(ivs))


def intersect_intervals(xs: list[tuple[int, int]],
                        ys: list[tuple[int, int]]) -> list[tuple[int, int]]:
    """Intersection of two interval unions (both merged first)."""
    xs, ys = merge_intervals(xs), merge_intervals(ys)
    out = []
    i = j = 0
    while i < len(xs) and j < len(ys):
        a = max(xs[i][0], ys[j][0])
        b = min(xs[i][1], ys[j][1])
        if a < b:
            out.append((a, b))
        if xs[i][1] < ys[j][1]:
            i += 1
        else:
            j += 1
    return out


def _iv(e: dict) -> tuple[int, int]:
    return (e["t0_ns"], e["t0_ns"] + e["dur_ns"])


# -- trace loading ------------------------------------------------------------


def load_trace(path: str) -> tuple[list[dict], dict]:
    """Read a Chrome trace-event file (``trace.save()``'s output, plain
    or gzipped) back into tracer-shaped event dicts. Returns
    ``(events, meta)`` where ``meta`` carries ``otherData`` —
    ``dropped_events`` and ``host_cores`` when the writer stamped them.
    Counter records (``ph: "C"``) come back with the tracer's
    ``__counter__`` category and their value as ``args``."""
    doc = load_json_maybe_gz(path)
    tnames: dict[int, str] = {}
    events: list[dict] = []
    for rec in doc.get("traceEvents", []):
        ph = rec.get("ph")
        if ph == "M":
            if rec.get("name") == "thread_name":
                tnames[rec.get("tid", 0)] = rec.get("args", {}).get(
                    "name", "")
            continue
        if ph == "C":
            events.append({
                "name": rec["name"], "cat": "__counter__", "corr": None,
                "t0_ns": int(rec["ts"] * 1e3), "dur_ns": 0,
                "tid": rec.get("tid", 0), "tname": "",
                "args": rec.get("args", {}).get("value"),
            })
            continue
        if ph != "X":
            continue
        args = dict(rec.get("args") or {})
        corr = args.pop("corr", None)
        events.append({
            "name": rec["name"], "cat": rec.get("cat", ""), "corr": corr,
            "t0_ns": int(rec["ts"] * 1e3),
            "dur_ns": int(rec.get("dur", 0) * 1e3),
            "tid": rec.get("tid", 0), "tname": "", "args": args or None,
        })
    for e in events:
        e["tname"] = tnames.get(e["tid"], e["tname"])
    events.sort(key=lambda e: e["t0_ns"])
    return events, dict(doc.get("otherData") or {})


# -- window assembly ----------------------------------------------------------

def _worker_of(corr) -> str | None:
    """``w3:s17`` / ``w3:x5`` / ``w3`` → ``"3"``; None otherwise."""
    if not isinstance(corr, str) or not corr.startswith("w"):
        return None
    head = corr.split(":", 1)[0][1:]
    return head if head.isdigit() else None


def _assemble_windows(events: list[dict]) -> tuple[dict, int]:
    """→ ``({wid: [window dicts]}, skipped)``. A window anchors on one
    ``worker.fetch``; sibling worker spans between two anchors attach to
    the earlier one (``worker.compute`` attaches by its END, which
    coincides with its window's fetch-return). Server-side spans are
    claimed by corr match or same-thread nesting inside the commit.
    Windows without a commit (dropped spans, the not-yet-flushed tail of
    a pipelined run) are skipped, never guessed at."""
    per_worker: dict[str, list[dict]] = {}
    server_by_corr: dict[str, list[dict]] = {}
    for e in events:
        name = e["name"]
        if name.startswith("worker."):
            wid = _worker_of(e["corr"])
            if wid is not None:
                per_worker.setdefault(wid, []).append(e)
        elif name in _SERVER_SPAN_NAMES and e["corr"] is not None:
            server_by_corr.setdefault(e["corr"], []).append(e)

    out: dict[str, list[dict]] = {}
    skipped = 0
    for wid, evs in per_worker.items():
        fetches = sorted((e for e in evs if e["name"] == "worker.fetch"),
                         key=lambda e: e["t0_ns"])
        if not fetches:
            skipped += sum(1 for e in evs if e["name"] == "worker.commit")
            continue
        bounds = [f["t0_ns"] for f in fetches]
        wins: list[dict] = [
            {"fetch": f, "compress": None, "commit": None, "pull": None,
             "compute": None} for f in fetches
        ]
        for e in sorted(evs, key=lambda ev: ev["t0_ns"]):
            name = e["name"]
            if name == "worker.fetch":
                continue
            # compute spans START before their window's anchor (the
            # dispatch precedes the fetch) — place them by their end,
            # which IS the fetch-return of their window
            t = (e["t0_ns"] + e["dur_ns"] if name == "worker.compute"
                 else e["t0_ns"])
            if t < bounds[0]:
                skipped += 1 if name == "worker.commit" else 0
                continue
            key = name.split(".", 1)[1]
            # the window whose anchor interval contains t
            w = wins[bisect.bisect_right(bounds, t) - 1]
            if key in w and w[key] is None:
                w[key] = e
        kept = []
        for w in wins:
            if w["commit"] is None:
                skipped += 1
                continue
            kept.append(_decompose_window(w, server_by_corr))
        if kept:
            _mark_hidden(kept)
            out[wid] = kept
    return out, skipped


def _mark_hidden(wins: list[dict]) -> None:
    """Post-pass over one worker's decomposed windows: a commit is
    HIDDEN when it lies inside the worker's compute union — in the
    pipelined loop window N's commit runs under window N+1's
    dispatch→fetch-return span, so containment is checked against the
    union, not the commit's own window. ``residue_fetch_ns`` is the
    duration of the first fetch that starts after the commit ends (the
    pipelined loop's post-exchange device wait): a positive residue
    means the device outlasted the hidden exchange — the compute was
    the window's critical path."""
    wins.sort(key=lambda w: w["t0_ns"])
    comp = merge_intervals([w["compute_iv"] for w in wins
                            if w["compute_iv"] is not None])
    fetches = sorted(w["fetch_iv"] for w in wins)
    starts = [f[0] for f in fetches]
    for w in wins:
        c0, c1 = w["commit_iv"]
        w["hidden_exchange"] = any(a <= c0 and c1 <= b for a, b in comp)
        k = bisect.bisect_left(starts, c1)
        w["residue_fetch_ns"] = (fetches[k][1] - fetches[k][0]
                                 if k < len(fetches) else 0)
        # the elastic (EASGD) loop pulls BEFORE its window's fetch, so
        # the pull attaches to the previous window AND runs inside the
        # next one's dispatch→fetch-return span — hidden under compute,
        # charged nothing (same rule as hidden commits; an unfused
        # serial pull sits outside every compute span and stays charged)
        if w["pull_iv"] is not None:
            p0, p1 = w["pull_iv"]
            w["pull_hidden"] = any(a <= p0 and p1 <= b for a, b in comp)


def _decompose_window(w: dict, server_by_corr: dict) -> dict:
    """One window's waterfall: worker phases + the commit's server-side
    decomposition (decode → lock wait → fold → wal append/wait → wire
    residue), all in ns."""
    fetch, commit = w["fetch"], w["commit"]
    c0, c1 = _iv(commit)
    # corr matching covers every transport: the socket/shm handler
    # adopts the frame's corr, the in-process server section runs on
    # the worker's own thread under its corr, and the batched-fold
    # drain stamps each fold with the COMMIT's corr (PR 12). The group
    # flusher's fsync carries no corr — its cost reaches the window
    # through ps.wal_wait, never double-counted here.
    claimed: list[dict] = list(server_by_corr.get(commit["corr"], []))
    named = {n: [e for e in claimed if e["name"] == n]
             for n in _SERVER_SPAN_NAMES}
    decode = sum(e["dur_ns"] for e in named["ps.decode"])
    fold = sum(e["dur_ns"] for e in named["ps.fold"])
    wal = (sum(e["dur_ns"] for e in named["ps.wal_append"])
           + sum(e["dur_ns"] for e in named["ps.wal_wait"])
           + sum(e["dur_ns"] for e in named["wal.fsync"]))
    # center-lock wait: decode-end → fold-start where both sides were
    # recorded (socket/shm); commit-start → fold-start for the
    # in-process transport (no decode span; the client call does
    # nothing else before contending)
    lock_wait = 0
    lock_iv = None
    if named["ps.fold"]:
        fold0 = min(e["t0_ns"] for e in named["ps.fold"])
        if named["ps.decode"]:
            dec1 = max(_iv(e)[1] for e in named["ps.decode"])
            lock_wait = max(0, fold0 - dec1)
            if lock_wait:
                lock_iv = (dec1, fold0)
        elif fold0 >= c0:
            lock_wait = max(0, fold0 - c0)
            if lock_wait:
                lock_iv = (c0, fold0)
    server = decode + fold + wal + lock_wait
    commit_dur = commit["dur_ns"]
    wire = max(0, commit_dur - server)
    pull = w["pull"]["dur_ns"] if w["pull"] else 0
    compute = w["compute"]
    start = compute["t0_ns"] if compute is not None else fetch["t0_ns"]
    end = max(_iv(commit)[1], _iv(fetch)[1],
              _iv(w["pull"])[1] if w["pull"] else 0)
    return {
        "corr": commit["corr"], "t0_ns": start, "t1_ns": end,
        "tid": commit["tid"],
        "fetch_ns": fetch["dur_ns"],
        "compress_ns": w["compress"]["dur_ns"] if w["compress"] else 0,
        "commit_ns": commit_dur, "pull_ns": pull,
        "compute_ns": compute["dur_ns"] if compute is not None else None,
        "compute_iv": _iv(compute) if compute is not None else None,
        "fetch_iv": _iv(fetch), "commit_iv": (c0, c1),
        "pull_iv": _iv(w["pull"]) if w["pull"] else None,
        "decode_ns": decode, "lock_wait_ns": lock_wait,
        "lock_iv": lock_iv,
        "fold_ns": fold, "wal_ns": wal, "wire_ns": wire,
        # filled by _mark_hidden (needs the whole worker's windows)
        "hidden_exchange": False, "pull_hidden": False,
        "residue_fetch_ns": 0,
    }


def _exchange_free(win: dict) -> bool:
    """A window's exchange cost the critical path nothing: it ran
    hidden under outstanding compute AND the device still had work left
    when it finished (the following fetch genuinely waited)."""
    return win["hidden_exchange"] and win["residue_fetch_ns"] > _EPS_NS


def _critical_buckets(win: dict, prev: dict | None) -> dict[str, int]:
    """One window's CRITICAL-path attribution (ns per bucket; the values
    sum to roughly what the window cost the worker's timeline).

    Serial window: the dispatch→fetch-return stretch is compute (it
    holds the jit dispatch, any compile, and the blocking wait; the
    exchange lies entirely outside it) and each exchange phase is
    exposed. Pipelined window: its commit runs under the NEXT window's
    compute span — if the fetch after it still waited, the device was
    the constraint and the hidden exchange is charged nothing; if the
    fetch returned immediately, the exchange was the constraint and its
    decomposition is charged. Symmetrically, a window whose compute
    span envelops the PREVIOUS window's non-free commit only counts its
    observable fetch residue as compute — the enveloped stretch was
    already charged to that exchange."""
    if _exchange_free(win):
        exch = {"wire": 0, "decode": 0, "lock_wait": 0, "fold": 0,
                "wal": 0}
    else:
        exch = {"wire": win["wire_ns"], "decode": win["decode_ns"],
                "lock_wait": win["lock_wait_ns"], "fold": win["fold_ns"],
                "wal": win["wal_ns"]}
    if prev is not None and prev["hidden_exchange"] \
            and not _exchange_free(prev):
        compute = win["fetch_ns"]
    else:
        compute = (win["compute_ns"] if win["compute_ns"] is not None
                   else win["fetch_ns"])
    return {
        "compute": compute,
        "compress": win["compress_ns"],
        "pull": 0 if win["pull_hidden"] else win["pull_ns"],
        **exch,
    }


# -- bucket totals (union-based, the classifier's input) ----------------------

def bucket_totals(events: list[dict]) -> dict[str, float]:
    """Per-bucket wall coverage in ms — interval UNIONS across all
    threads, so N workers waiting on one fsync count it once. This is
    the classifier's input; the per-worker sums (who waited how much)
    live in the full report. Works on any event slice, which is what
    :class:`RegimeTracker` feeds it."""
    ivs: dict[str, list] = {
        "compute": [], "compress": [], "wire": [], "decode": [],
        "lock_wait": [], "fold": [], "wal": [],
        "serve_queue": [], "serve_prefill": [], "serve_decode": [],
    }
    exchange: list[tuple[int, int]] = []
    compute: list[tuple[int, int]] = []
    fetch: list[tuple[int, int]] = []
    wal_wait: list[tuple[int, int]] = []
    for e in events:
        name, iv = e["name"], _iv(e)
        if e["cat"] == "__counter__" or e["dur_ns"] <= 0:
            continue
        if name == "worker.compute":
            compute.append(iv)
        elif name == "worker.fetch":
            fetch.append(iv)
        elif name == "worker.compress":
            ivs["compress"].append(iv)
        elif name in ("worker.commit", "worker.pull"):
            exchange.append(iv)
        elif name == "ps.decode":
            ivs["decode"].append(iv)
        elif name == "ps.fold":
            ivs["fold"].append(iv)
        elif name in ("ps.wal_append", "wal.fsync"):
            ivs["wal"].append(iv)
        elif name == "ps.wal_wait":
            # deferred-ACK waits count per WINDOW (who waited how long —
            # the sums) but not in the wall-union bucket: N workers
            # convoyed behind one flusher would otherwise read as N
            # bands of "disk time" when the disk did one fsync — the
            # union's wal bucket is what the log device actually DID
            # (appends + fsyncs)
            wal_wait.append(iv)
        elif name == "serve.queued":
            ivs["serve_queue"].append(iv)
        elif name == "serve.prefill":
            ivs["serve_prefill"].append(iv)
        elif name == "serve.decode_step":
            ivs["serve_decode"].append(iv)
    # compute evidence: real dispatch→fetch-return spans where present,
    # else the blocking fetch (older traces / foreign scrape)
    ivs["compute"] = compute if compute else fetch
    # wire = exchange wall not covered by any server-side section and
    # not hidden under outstanding compute (wal waits ARE covered —
    # they must not resurface as wire)
    server = (ivs["decode"] + ivs["fold"] + ivs["wal"] + wal_wait
              + (compute if compute else []))
    exch_u = merge_intervals(exchange)
    covered = intersect_intervals(exch_u, server)
    ivs["wire"] = _subtract(exch_u, covered)
    # lock wait needs pairing, which a flat slice cannot do — it is
    # folded into the per-window report; here the fold bucket carries
    # the locked section itself
    out = {k: union_length(v) / 1e6 for k, v in ivs.items()}
    out["lock_wait"] = 0.0
    return out


def _subtract(xs: list[tuple[int, int]],
              ys: list[tuple[int, int]]) -> list[tuple[int, int]]:
    """Interval union difference ``xs \\ ys`` (both merged)."""
    out = []
    ys = merge_intervals(ys)
    for a, b in merge_intervals(xs):
        cur = a
        for c, d in ys:
            if d <= cur or c >= b:
                continue
            if c > cur:
                out.append((cur, c))
            cur = max(cur, d)
            if cur >= b:
                break
        if cur < b:
            out.append((cur, b))
    return out


# -- the classifier -----------------------------------------------------------

def classify(totals_ms: dict[str, float], *, host_cores: int | None = None,
             n_workers: int = 0, wall_ms: float = 0.0,
             busy_ms: float = 0.0,
             serving_only: bool = False) -> tuple[str, dict]:
    """→ ``(regime, fractions)``. Training buckets win when present;
    a serving-only trace classifies over queue/prefill/decode.
    ``host-core-bound`` refines ``compute-bound`` when the pool
    oversubscribes the host and the threads' busy unions saturate it."""
    train_keys = ("compute", "compress", "wire", "decode", "lock_wait",
                  "fold", "wal")
    serve_keys = ("serve_queue", "serve_prefill", "serve_decode")
    keys = serve_keys if serving_only else train_keys
    total = sum(totals_ms.get(k, 0.0) for k in keys)
    if total <= 0.0:
        return "idle", {}
    fr = {k: totals_ms.get(k, 0.0) / total for k in keys}
    if serving_only:
        top = max(serve_keys, key=lambda k: fr[k])
        regime = {"serve_queue": "queue-bound",
                  "serve_prefill": "compute-bound",
                  "serve_decode": "compute-bound"}[top]
        return regime, fr
    grouped = {
        "compute-bound": fr["compute"] + fr["compress"],
        "wire-bound": fr["wire"] + fr["decode"],
        "fsync-bound": fr["wal"],
        "fold-lock-bound": fr["lock_wait"] + fr["fold"],
    }
    regime = max(grouped, key=lambda k: grouped[k])
    # duty-cycle override: when the log device was doing durable work
    # (appends + fsyncs, overlaps counted once) for more than half the
    # run's wall, the run is fsync-bound even if compute spans cover a
    # comparable stretch — compute parallelizes across workers and
    # devices, the log is the serial resource, and the group-commit
    # knob is what moves it
    if (wall_ms > 0 and totals_ms.get("wal", 0.0) / wall_ms > 0.5
            and grouped["fsync-bound"]
            >= max(grouped["wire-bound"], grouped["fold-lock-bound"])):
        regime = "fsync-bound"
    if (regime == "compute-bound" and host_cores
            and n_workers > host_cores and wall_ms > 0
            and busy_ms / (wall_ms * host_cores) > 0.85):
        regime = "host-core-bound"
    fr["_grouped"] = grouped
    return regime, fr


def _recommend(report: dict) -> list[str]:
    """Up to three knob-keyed recommendations, most load-bearing first."""
    recs: list[str] = []
    verdict = report["verdict"]
    regime = verdict["regime"]
    tr = report.get("training") or {}
    counters = report.get("counters") or {}
    if report.get("degraded"):
        recs.append(
            "trace dropped spans (ring overflow) — attribution is a "
            "lower bound; raise trace ring_size or trace_sample down "
            "before trusting marginal calls"
        )
    straggler = tr.get("dominant_wait_worker")
    if straggler is not None:
        recs.append(
            f"worker {straggler} dominates wait time "
            f"({tr['workers'][str(straggler)]['stall_ms']:.0f} ms "
            f"stalled) — a straggler host: drain it (elastic=True with "
            f"autoscale_target; DynSGD is already down-weighting its "
            f"commits)"
        )
    if regime == "fsync-bound":
        recs.append(
            "durable logging dominates — raise ps_wal_group_window "
            "(one fsync per group amortizes the tail) and/or move "
            "ps_wal_dir to a faster filesystem"
        )
    if regime == "wire-bound":
        overlap = tr.get("overlap", {}).get("fraction")
        if overlap is not None and overlap > 0.5:
            recs.append(
                "exchange outlasts compute even at pipeline depth 1 — "
                "the wire itself dominates: try ps_transport='shm' "
                "(colocated) or compression='int8' to shrink the bytes"
            )
        else:
            recs.append(
                "exchange RTT is exposed — enable ps_pipeline_depth=1 "
                "(overlap it with the next window's compute), keep "
                "ps_fused_exchange=True, or move colocated workers to "
                "ps_transport='shm'"
            )
    if regime == "fold-lock-bound":
        recs.append(
            "center-lock queueing/fold dominates — raise ps_num_shards "
            "(leaf-sharded centers fold in parallel); batched folds "
            "already amortize the lock for colocated workers"
        )
    if regime == "host-core-bound":
        recs.append(
            "the worker pool oversubscribes this host's cores — fewer "
            "colocated workers (or more cores) before any transport "
            "knob will show"
        )
    ring = counters.get("shm.ring_occupancy_frac", {}).get("max")
    if ring is not None and ring > 0.9:
        recs.append(
            "shm ring occupancy peaked above 0.9 — the writer is about "
            "to block on the reader: raise the shm ring capacity "
            "(ring_bytes)"
        )
    tau = counters.get("ps.tau_p95", {}).get("last")
    if tau is not None and tau > 16:
        recs.append(
            f"DynSGD τ p95 ended at {tau:.0f} — staleness is pricing "
            f"commits toward nothing; look at the straggler table "
            f"before adding workers"
        )
    sv = report.get("serving") or {}
    if sv and sv.get("dominant") == "queue":
        recs.append(
            "serving requests wait in admission — raise max_batch / "
            "block budget, or add replicas; occupancy says whether the "
            "batch is already full"
        )
    if not recs:
        if regime == "idle":
            recs.append(
                "nothing attributable was recorded — enable tracing "
                "around the workload (trainer trace=True / analyze=True,"
                " bench --trace-dir) before diagnosing"
            )
        else:
            recs.append(
                "no single bottleneck — the run is balanced; scale the "
                "knob matching the regime fractions if throughput must "
                "rise"
            )
    return recs[:3]


# -- the full analysis --------------------------------------------------------

def analyze_events(events: list[dict], *, dropped: int = 0,
                   host_cores: int | None = None,
                   store=None, series: dict | None = None) -> dict:
    """Analyze a full event stream → the report dict (see module doc).
    ``store`` is an optional live ``TimeSeriesStore``; ``series`` an
    already-loaded dump document — either contributes the counters
    section (τ tail, ring occupancy, alert names)."""
    if host_cores is None:
        host_cores = os.cpu_count() or 1
    spans = [e for e in events
             if e.get("cat") != "__counter__" and e.get("dur_ns", 0) >= 0]
    counters = _counter_summary(events, store=store, series=series)
    wall_ns = 0
    if spans:
        t0 = min(e["t0_ns"] for e in spans)
        t1 = max(e["t0_ns"] + e["dur_ns"] for e in spans)
        wall_ns = max(0, t1 - t0)
    busy_ns = _busy_ns(spans)
    totals = bucket_totals(spans)
    windows, skipped = _assemble_windows(spans)
    training = _training_report(windows, totals) if windows else None
    serving = _serving_report(spans)
    serving_only = training is None and serving is not None
    n_workers = len(windows)
    # fold the lock-wait UNION into the classifier's totals (the flat
    # slice cannot pair decode→fold gaps; the windows can) — and carve
    # it out of the wire residue, which covered the same wall stretch.
    # Union, not the per-worker sum: convoyed waits would otherwise
    # zero out genuinely wire-dominated runs.
    if training is not None:
        lw = training["union_ms"]["lock_wait"]
        totals["lock_wait"] = lw
        totals["wire"] = max(0.0, totals["wire"] - lw)
    regime, fractions = classify(
        totals, host_cores=host_cores, n_workers=n_workers,
        wall_ms=wall_ns / 1e6, busy_ms=busy_ns / 1e6,
        serving_only=serving_only,
    )
    report = {
        "ok": True,
        "degraded": dropped > 0,
        "dropped_spans": int(dropped),
        "skipped_windows": int(skipped),
        "host_cores": int(host_cores),
        "wall_s": wall_ns / 1e9,
        "host_busy_fraction": (busy_ns / (wall_ns * host_cores)
                               if wall_ns else 0.0),
        "training": training,
        "serving": serving,
        "counters": counters,
        "verdict": {
            "regime": regime,
            "regime_code": regime_code(regime),
            "degraded": dropped > 0,
            "fractions": {k: round(v, 4) for k, v in fractions.items()
                          if not k.startswith("_")},
        },
    }
    report["verdict"]["recommendations"] = _recommend(report)
    return report


def _busy_ns(spans: list[dict]) -> int:
    """Σ over threads of each thread's busy union — the host-saturation
    numerator (nested spans count once per thread)."""
    per_tid: dict[int, list] = {}
    for e in spans:
        if e["dur_ns"] > 0:
            per_tid.setdefault(e["tid"], []).append(_iv(e))
    return sum(union_length(v) for v in per_tid.values())


def _training_report(windows: dict[str, list[dict]],
                     totals: dict[str, float]) -> dict:
    workers: dict[str, dict] = {}
    crit_totals = {k: 0.0 for k in ("compute", "compress", "wire",
                                    "decode", "lock_wait", "fold", "wal",
                                    "pull")}
    for wid, wins in windows.items():
        sums = {k: 0.0 for k in crit_totals}
        stall = 0
        prev_end = None
        prev = None
        for w in sorted(wins, key=lambda x: x["t0_ns"]):
            for k, v in _critical_buckets(w, prev).items():
                sums[k] += v
            # stall: time between this worker's consecutive windows no
            # span accounts for — batch staging plus anything injected
            # at the boundary (a straggler's sleep lands exactly here)
            if prev_end is not None:
                stall += max(0, w["t0_ns"] - prev_end)
            # the previous loop's true end: commit/fetch end, plus the
            # pull only when it genuinely finished before the next
            # window began — the elastic loop's pull attaches to the
            # previous window yet runs inside the NEXT one's compute
            # span (pull_hidden), and letting it extend prev_end would
            # erase the boundary gap the straggler attribution reads
            prev_end = max(w["commit_iv"][1], w["fetch_iv"][1])
            if w["pull_iv"] is not None and not w["pull_hidden"]:
                prev_end = max(prev_end, w["pull_iv"][1])
            prev = w
        periods = sorted(w["t1_ns"] - w["t0_ns"] for w in wins)
        workers[wid] = {
            **{f"{k}_ms": round(v / 1e6, 3) for k, v in sums.items()},
            "windows": len(wins),
            "stall_ms": round(stall / 1e6, 3),
            "mean_window_ms": round(
                sum(periods) / len(periods) / 1e6, 3),
            "p50_window_ms": round(periods[len(periods) // 2] / 1e6, 3),
            # cadence = window + the stall before the next one: the
            # straggler test — a boundary sleep never shows inside the
            # window span itself
            "mean_cycle_ms": round(
                (sum(periods) + stall) / len(wins) / 1e6, 3),
        }
        for k, v in sums.items():
            crit_totals[k] += v
    overlap_exch, overlap_hidden = _overlap_from_windows(windows)
    med, stragglers, dominant = _stragglers(workers)
    # lock-wait UNION across all windows/threads: workers convoyed on
    # the center lock for the same wall stretch cost the run that
    # stretch once — the classifier's number (the per-worker SUMS above
    # answer who waited how much)
    lock_union = union_length([
        w["lock_iv"] for wins in windows.values() for w in wins
        if w["lock_iv"] is not None
    ])
    return {
        "windows": sum(len(v) for v in windows.values()),
        "workers": workers,
        "totals_ms": {k: round(v / 1e6, 3) for k, v in crit_totals.items()},
        "union_ms": {
            **{k: round(totals.get(k, 0.0), 3)
               for k in ("compute", "compress", "wire", "decode",
                         "fold", "wal")},
            "lock_wait": round(lock_union / 1e6, 3),
        },
        "overlap": {
            "exchange_ms": round(overlap_exch / 1e6, 3),
            "hidden_ms": round(overlap_hidden / 1e6, 3),
            "fraction": (round(overlap_hidden / overlap_exch, 4)
                         if overlap_exch else None),
        },
        "median_cycle_ms": med,
        "stragglers": stragglers,
        "dominant_wait_worker": dominant,
    }


def _overlap_from_windows(windows: dict) -> tuple[int, int]:
    """(total exchange ns, exchange ns hidden under outstanding
    compute) across all workers — the per-run overlap-efficiency
    numerator/denominator."""
    exch_total = hidden_total = 0
    for wins in windows.values():
        for w in wins:
            exch_total += w["commit_ns"] + w["pull_ns"]
            if w["hidden_exchange"]:
                hidden_total += w["commit_ns"]
            # a pull can hide independently of its commit (the elastic
            # loop's pull rides the next window's dispatch while its
            # commit stays exposed) — count each on its own flag, the
            # same rule _critical_buckets charges by
            if w["pull_hidden"]:
                hidden_total += w["pull_ns"]
    return exch_total, hidden_total


def _stragglers(workers: dict) -> tuple[float, list, Any]:
    """Median window cadence, stragglers (mean cycle > 2× the pool
    median), and the dominant wait source (the worker whose stall —
    time between its windows no span accounts for — exceeds 2× the
    median stall AND a tenth of its own timeline)."""
    if not workers:
        return 0.0, [], None
    # LOWER median: with an even pool the upper median is the slower
    # middle worker — at n=2 that is the straggler itself, which could
    # then never exceed 2× "the median" (its own value)
    periods = sorted(w["mean_cycle_ms"] for w in workers.values())
    med = periods[(len(periods) - 1) // 2]
    stragglers = sorted(
        (wid for wid, w in workers.items()
         if med > 0 and w["mean_cycle_ms"] > 2.0 * med),
        key=lambda x: (len(x), x),
    )
    dominant = None
    if len(workers) >= 2:
        stalls = sorted(w["stall_ms"] for w in workers.values())
        med_stall = stalls[(len(stalls) - 1) // 2]
        best = max(workers.items(), key=lambda kv: kv[1]["stall_ms"])
        wid, w = best
        span_ms = w["mean_window_ms"] * w["windows"] + w["stall_ms"]
        if (w["stall_ms"] > max(1.0, 2.0 * med_stall)
                and span_ms > 0 and w["stall_ms"] / span_ms > 0.1):
            dominant = int(wid) if wid.isdigit() else wid
    return med, [int(s) if s.isdigit() else s for s in stragglers], dominant


def _serving_report(spans: list[dict]) -> dict | None:
    reqs: dict[str, dict] = {}
    decode_steps = []
    for e in spans:
        name = e["name"]
        if name == "serve.decode_step":
            decode_steps.append(e)
            continue
        if not name.startswith("serve.") or e["corr"] is None:
            continue
        r = reqs.setdefault(e["corr"], {})
        if name == "serve.request":
            r["total_ns"] = e["dur_ns"]
            args = e.get("args") or {}
            r["state"] = args.get("state")
        elif name == "serve.queued":
            r["queue_ns"] = e["dur_ns"]
        elif name == "serve.prefill":
            r["prefill_ns"] = e["dur_ns"]
    done = {k: r for k, r in reqs.items() if "total_ns" in r}
    if not done and not decode_steps:
        return None
    tot = sum(r["total_ns"] for r in done.values())
    queue = sum(r.get("queue_ns", 0) for r in done.values())
    prefill = sum(r.get("prefill_ns", 0) for r in done.values())
    decode = max(0, tot - queue - prefill)
    buckets = {"queue": queue, "prefill": prefill, "decode": decode}
    dominant = (max(buckets, key=lambda k: buckets[k])
                if tot else "decode")
    # batch occupancy: duration-weighted mean rows in flight over the
    # decode-step spans (the satellite's rows arg; "batch" is the
    # PR 11-era name of the same number)
    wsum = rsum = 0.0
    for e in decode_steps:
        args = e.get("args") or {}
        rows = args.get("rows", args.get("batch"))
        if rows is None or e["dur_ns"] <= 0:
            continue
        wsum += e["dur_ns"]
        rsum += float(rows) * e["dur_ns"]
    return {
        "requests": len(done),
        "totals_ms": {k: round(v / 1e6, 3) for k, v in buckets.items()},
        "dominant": dominant,
        "decode_steps": len(decode_steps),
        "mean_rows_in_flight": (round(rsum / wsum, 3) if wsum else None),
    }


def _counter_summary(events: list[dict], *, store=None,
                     series: dict | None = None) -> dict:
    """last/max per counter name — from the trace's own counter records,
    a live store, or a loaded dump (later sources win)."""
    out: dict[str, dict] = {}

    def _feed(name, values):
        vals = [float(v) for v in values if v is not None]
        if vals:
            out[name] = {"last": vals[-1], "max": max(vals)}

    by_name: dict[str, list] = {}
    for e in events:
        if e.get("cat") == "__counter__" and e.get("args") is not None:
            by_name.setdefault(e["name"], []).append(e["args"])
    for name, vals in by_name.items():
        _feed(name, vals)
    doc = series
    if store is not None:
        doc = store.to_json()
    if doc:
        for name, s in (doc.get("series") or {}).items():
            if name.startswith(("ps.tau", "shm.ring", "serve.active",
                                "analyze.")):
                _feed(name, s.get("v", []))
        alerts = (doc.get("alerts") or {}).get("counts")
        if alerts:
            out["alerts"] = alerts
    return out


def analyze_trace(path: str, series_path: str | None = None,
                  host_cores: int | None = None) -> dict:
    """Analyze a saved trace file (plain or gzipped) — the CLI's and
    CI's entry point. ``series_path`` points at a watchtower/timeseries
    dump; the trace's own ``otherData`` supplies the dropped-span count
    and, when stamped, the recording host's core count (a trace is
    analyzed on whatever machine is handy — the recording host's cores
    are the honest denominator)."""
    events, meta = load_trace(path)
    series = load_json_maybe_gz(series_path) if series_path else None
    if host_cores is None:
        host_cores = meta.get("host_cores")
    report = analyze_events(
        events, dropped=int(meta.get("dropped_events", 0) or 0),
        host_cores=host_cores, series=series,
    )
    report["trace_path"] = path
    return report


# -- human-readable rendering -------------------------------------------------

def format_report(report: dict) -> str:
    lines = []
    v = report["verdict"]
    flag = " [DEGRADED: dropped spans]" if report["degraded"] else ""
    lines.append(f"regime: {v['regime']}{flag}")
    lines.append(
        f"wall {report['wall_s']:.2f}s · host_cores "
        f"{report['host_cores']} · busy {report['host_busy_fraction']:.2f}"
    )
    tr = report.get("training")
    if tr:
        t = tr["totals_ms"]
        lines.append(
            f"training: {tr['windows']} windows · critical-path ms — "
            + " ".join(f"{k}={t[k]:.0f}" for k in (
                "compute", "compress", "wire", "decode", "lock_wait",
                "fold", "wal"))
        )
        ov = tr["overlap"]
        if ov["fraction"] is not None:
            lines.append(
                f"overlap: {ov['hidden_ms']:.0f}/{ov['exchange_ms']:.0f}"
                f" ms hidden ({ov['fraction']:.2f})"
            )
        for wid in sorted(tr["workers"], key=lambda x: (len(x), x)):
            w = tr["workers"][wid]
            lines.append(
                f"  w{wid}: {w['windows']} windows · "
                f"{w['mean_window_ms']:.1f} ms/window · "
                f"stall {w['stall_ms']:.0f} ms · "
                f"lock {w['lock_wait_ms']:.0f} ms · "
                f"wal {w['wal_ms']:.0f} ms"
            )
        if tr["stragglers"]:
            lines.append(f"stragglers: {tr['stragglers']}")
        if tr["dominant_wait_worker"] is not None:
            lines.append(
                f"dominant wait source: worker "
                f"{tr['dominant_wait_worker']}")
    sv = report.get("serving")
    if sv:
        t = sv["totals_ms"]
        occ = sv["mean_rows_in_flight"]
        lines.append(
            f"serving: {sv['requests']} requests · queue "
            f"{t['queue']:.0f} / prefill {t['prefill']:.0f} / decode "
            f"{t['decode']:.0f} ms · dominant {sv['dominant']}"
            + (f" · {occ:.1f} rows in flight" if occ is not None else "")
        )
    for i, rec in enumerate(v["recommendations"], 1):
        lines.append(f"  {i}. {rec}")
    return "\n".join(lines)


# -- the watchtower bridge ----------------------------------------------------

class RegimeTracker:
    """Incremental regime classification over the live recorder: each
    call classifies only the spans recorded since the previous one and
    samples the verdict into ``analyze.regime_code`` (plus per-bucket
    fraction gauges) — the series ``watch.BottleneckShiftRule`` fires
    on. Post-hoc analysis stays the source of truth; this is the cheap
    online shadow of it (one ring scan per scrape tick).

    The cursor is an END-time watermark: spans land in the ring when
    they CLOSE, so filtering by start time would permanently drop a
    long span (a whole pipelined compute window) whose dispatch
    predates shorter spans an earlier tick already consumed."""

    def __init__(self, min_span_ms: float = 1.0):
        self._cursor = 0
        self.min_span_ms = float(min_span_ms)

    def observe(self, events: list[dict], store, now: float) -> None:
        fresh = [e for e in events
                 if e["t0_ns"] + e["dur_ns"] > self._cursor
                 and e.get("cat") != "__counter__"]
        if not fresh:
            return
        totals = bucket_totals(fresh)
        train_ms = sum(totals.get(k, 0.0) for k in (
            "compute", "compress", "wire", "decode", "fold", "wal"))
        serve_ms = sum(totals.get(k, 0.0) for k in (
            "serve_queue", "serve_prefill", "serve_decode"))
        if max(train_ms, serve_ms) < self.min_span_ms:
            # too little evidence: no sample beats a noisy one — and
            # the cursor must NOT advance past unconsumed sub-threshold
            # spans, or sparse runs would shed their evidence tick by
            # tick and never sample at all. Spans with no attributable
            # mass whatsoever ARE consumed (nothing will ever accrue).
            if train_ms == 0.0 and serve_ms == 0.0:
                self._cursor = max(e["t0_ns"] + e["dur_ns"]
                                   for e in fresh)
            return
        self._cursor = max(e["t0_ns"] + e["dur_ns"] for e in fresh)
        regime, fractions = classify(totals,
                                     serving_only=serve_ms > train_ms)
        if regime == "idle":
            return
        # kind="counter" for the CODE series: it is categorical, and
        # the ring's gauge downsampling AVERAGES merged pairs — a run
        # alternating compute-bound(0)/fsync-bound(2) would downsample
        # to 1.0 = wire-bound, a regime never observed. Counter pairs
        # keep a true later sample, so every surviving point is a
        # genuinely classified code.
        store.sample("analyze.regime_code", now, regime_code(regime),
                     kind="counter")
        for k, v in fractions.items():
            if not k.startswith("_"):
                store.sample(f"analyze.frac.{k}", now, v)


def regime_source(tracker: RegimeTracker | None = None) -> Callable:
    """A :class:`~distkeras_tpu.observability.timeseries.Scraper`
    source sampling the live recorder's recent spans into the regime
    series (no-op while tracing is off). The cursor rides into the
    recorder's ``events(min_end_ns=...)`` filter, so stale ring entries
    are skipped as raw tuples — no per-tick materialization of the
    whole ring."""
    from distkeras_tpu.observability import trace as _trace

    tracker = tracker or RegimeTracker()

    def sample(store, now: float) -> None:
        if not _trace.enabled():
            return
        tracker.observe(_trace.events(min_end_ns=tracker._cursor),
                        store, now)

    return sample
