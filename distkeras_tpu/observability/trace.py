"""Flight-recorder tracing: zero-cost-when-off spans → Chrome trace JSON.

The reference's only instrumentation was the trainers' wall-clock
bookkeeping (SURVEY.md: ``distkeras.trainers`` ``training_time``); this
module is the rebuild's real timeline: every interesting section of the
PS exchange, WAL, elastic-membership, and serving stacks opens a *span*
here, and a run with tracing enabled writes one Chrome-trace-event JSON
file loadable in Perfetto (https://ui.perfetto.dev) where a single fused
EXCHANGE stitches across the worker thread, the PS handler, the WAL
flusher, the chain replica, and the C++ native server into one timeline.

Design constraints, in order:

1. **Zero cost when off.** Tracing is off by default and the hot paths
   (worker window loop, PS fold, serving decode step) call into this
   module unconditionally — so the off path must be one module-global
   read plus a no-op. ``span()`` returns a shared no-op context manager
   singleton, ``record``/``set_corr``/``instant`` return immediately:
   no allocation, no locks, no clock reads (the off-mode
   allocation-freeness is pinned by test).
2. **Cheap when on.** Events land in per-thread ring buffers (no lock on
   the record path; the only lock is one registration per thread) as
   plain tuples; ring overflow drops the OLDEST events (a flight
   recorder keeps the recent past). Timestamps are
   ``time.perf_counter_ns()`` — CLOCK_MONOTONIC on Linux, the SAME clock
   the native ``dkps.cpp`` span ring uses (``clock_gettime(
   CLOCK_MONOTONIC)``), so scraped C++ spans and Python spans share one
   timebase within a host without any offset arithmetic.
3. **Correlation.** A span records the *correlation id* in effect on its
   thread when it CLOSES (or an explicit ``corr=``). The worker loop
   sets ``w<id>:x<n>`` per window, the resilient client overrides with
   ``w<id>:s<seq>`` when it assigns the commit seqno (the id the wire
   actually carries), the socket client stamps the current corr into the
   request frame, and the PS handler adopts the frame's corr — so the
   worker-side exchange span and the PS-side fold/WAL-append spans share
   one id across threads, processes, and (via the seqno) the C++ wire.

Sampling: ``enable(sample=0.1)`` keeps a deterministic ~10% of spans
(counter-based, per thread — no RNG on the hot path). ``corr``
propagation is never sampled out, only span recording is.
"""

from __future__ import annotations

import gzip
import json
import os
import threading
import time
from typing import Any

__all__ = [
    "enable", "disable", "enabled", "span", "record", "instant",
    "counter", "set_corr", "current_corr", "add_events", "events",
    "save", "rotate_files", "dropped_spans", "live_dropped",
]

#: category marking a ring entry as a sampled counter value rather than
#: a span — ``save()`` renders these as Chrome ``ph: "C"`` counter
#: tracks (Perfetto draws them as graphs alongside the spans)
COUNTER_CAT = "__counter__"

#: module-global tracer; ``None`` = disabled (the one read every
#: call-site pays when tracing is off)
_tracer = None

#: spans lost to ring overflow by recorders that have since been
#: disabled — ``dropped_spans()`` stays a process-lifetime counter so
#: the metrics surface never un-counts an overflow by turning tracing
#: off (the overflow being SILENT was the bug)
_dropped_retired = 0


class _NoopSpan:
    """Shared do-nothing context manager returned while tracing is off
    (and for sampled-out spans): entering/exiting allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP_SPAN = _NoopSpan()


class _Span:
    """One live span: records ``(t_enter, t_exit)`` into the thread's
    ring on exit. Corr resolution: an explicit ``corr=`` wins; otherwise
    the thread's corr at CLOSE time — a span that wraps a wire call
    inherits the id the client assigned inside it (see module doc)."""

    __slots__ = ("_tr", "name", "cat", "corr", "args", "t0")

    def __init__(self, tr, name, cat, corr, args):
        self._tr = tr
        self.name = name
        self.cat = cat
        self.corr = corr
        self.args = args

    def __enter__(self):
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter_ns()
        tr = self._tr
        st = tr._state()
        corr = self.corr if self.corr is not None else st.corr
        tr._record(st, self.name, self.cat, corr, self.t0, t1 - self.t0,
                   self.args)
        return False


class _ThreadState:
    """Per-thread recorder state (ring + corr + sampling counter)."""

    __slots__ = ("ring", "idx", "corr", "n_seen", "tid", "tname")

    def __init__(self, cap: int):
        self.ring: list = [None] * cap
        self.idx = 0          # total events recorded (ring head = idx-1)
        self.corr: str | None = None
        self.n_seen = 0       # sampling counter (spans offered)
        self.tid = threading.get_native_id()
        self.tname = threading.current_thread().name


class Tracer:
    """The enabled-state recorder. Use the module functions; this class
    is public only so tests can poke at ring internals."""

    def __init__(self, ring_size: int = 65536, sample: float = 1.0):
        if ring_size < 16:
            raise ValueError(f"ring_size must be >= 16, got {ring_size}")
        if not 0.0 < sample <= 1.0:
            raise ValueError(f"sample must be in (0, 1], got {sample}")
        self.ring_size = int(ring_size)
        self.sample = float(sample)
        self._tls = threading.local()
        self._states: list[_ThreadState] = []
        self._reg_lock = threading.Lock()
        # foreign events merged in by scrapers (the native dkps ring, a
        # peer process's snapshot): already-shaped dicts, see add_events
        self._foreign: list[dict] = []

    def _state(self) -> _ThreadState:
        st = getattr(self._tls, "st", None)
        if st is None:
            st = self._tls.st = _ThreadState(self.ring_size)
            with self._reg_lock:
                self._states.append(st)
        return st

    def _record(self, st: _ThreadState, name, cat, corr, t0, dur, args,
                sampled: bool = True):
        if sampled and self.sample < 1.0:
            st.n_seen += 1
            # deterministic counter sampling: record iff the scaled
            # counter crossed an integer — exactly ~sample of spans,
            # no RNG, no per-thread drift
            if int(st.n_seen * self.sample) == int(
                    (st.n_seen - 1) * self.sample):
                return
        st.ring[st.idx % self.ring_size] = (name, cat, corr, t0, dur, args)
        st.idx += 1

    def add_events(self, evs: list[dict]) -> None:
        with self._reg_lock:
            self._foreign.extend(evs)

    def events(self, min_end_ns: int | None = None) -> list[dict]:
        """Every recorded event as a list of dicts (oldest first per
        thread), merged across threads + foreign sources and sorted by
        start time. Keys: name, cat, corr, t0_ns, dur_ns, tid, tname,
        args. ``min_end_ns`` keeps only events that END after it — the
        incremental-consumer filter (RegimeTracker): entries land in
        the ring at span CLOSE, so an end-time cursor never permanently
        misses a long span whose START predates shorter spans already
        observed, and stale entries are skipped as raw tuples (no dict
        built, nothing sorted for them)."""
        out = []
        with self._reg_lock:
            states = list(self._states)
            foreign = list(self._foreign)
        for st in states:
            n = min(st.idx, self.ring_size)
            start = st.idx - n
            for k in range(start, st.idx):
                ev = st.ring[k % self.ring_size]
                if ev is None:
                    continue
                name, cat, corr, t0, dur, args = ev
                if min_end_ns is not None and t0 + dur <= min_end_ns:
                    continue
                out.append({
                    "name": name, "cat": cat, "corr": corr,
                    "t0_ns": t0, "dur_ns": dur,
                    "tid": st.tid, "tname": st.tname, "args": args,
                })
        if min_end_ns is not None:
            out.extend(e for e in foreign
                       if e["t0_ns"] + e["dur_ns"] > min_end_ns)
        else:
            out.extend(foreign)
        out.sort(key=lambda e: e["t0_ns"])
        return out

    def dropped(self) -> int:
        """Events lost to ring overflow (flight-recorder semantics:
        oldest dropped first), totalled across threads."""
        with self._reg_lock:
            states = list(self._states)
        return sum(max(0, st.idx - self.ring_size) for st in states)


def enabled() -> bool:
    return _tracer is not None


def enable(ring_size: int = 65536, sample: float = 1.0) -> Tracer:
    """Turn tracing on (idempotent: an already-enabled tracer is kept —
    nested enables from a bench leg inside a traced trainer must not
    discard the outer recorder's rings)."""
    global _tracer
    if _tracer is None:
        _tracer = Tracer(ring_size=ring_size, sample=sample)
    return _tracer


def disable() -> None:
    """Turn tracing off and discard the recorder (hot paths return to
    the one-global-read no-op). The recorder's overflow count retires
    into the process-lifetime ``dropped_spans()`` counter first."""
    global _tracer, _dropped_retired
    if _tracer is not None:
        _dropped_retired += _tracer.dropped()
    _tracer = None


def dropped_spans() -> int:
    """Process-lifetime spans lost to ring overflow (drop-oldest),
    across every recorder this process has run — the
    ``trace_dropped_spans`` counter on the metrics surface. 0 while
    nothing ever overflowed; monotone otherwise."""
    tr = _tracer
    live = tr.dropped() if tr is not None else 0
    return _dropped_retired + live


def live_dropped() -> int:
    """Spans the CURRENT recorder lost to overflow (0 when off) — the
    analyzer's degraded-verdict input: a past run's retired overflow
    must not degrade this run's analysis."""
    tr = _tracer
    return tr.dropped() if tr is not None else 0


def span(name: str, cat: str = "", corr: str | None = None,
         args: dict | None = None):
    """Open a span: ``with trace.span("ps.fold"): ...``. Returns the
    shared no-op singleton when tracing is off — the off-mode call is
    allocation-free."""
    tr = _tracer
    if tr is None:
        return _NOOP_SPAN
    return _Span(tr, name, cat, corr, args)


def record(name: str, t0_ns: int, t1_ns: int, cat: str = "",
           corr: str | None = None, args: dict | None = None) -> None:
    """Record a completed span retroactively from two timestamps the
    caller already took (the worker phase histograms' path: they clock
    with ``perf_counter`` anyway, so tracing adds no extra clock reads).
    No-op when off."""
    tr = _tracer
    if tr is None:
        return
    st = tr._state()
    tr._record(st, name, cat, corr if corr is not None else st.corr,
               t0_ns, t1_ns - t0_ns, args)


def instant(name: str, cat: str = "", corr: str | None = None,
            args: dict | None = None) -> None:
    """Record a point event (zero-duration span). No-op when off."""
    tr = _tracer
    if tr is None:
        return
    st = tr._state()
    t = time.perf_counter_ns()
    tr._record(st, name, cat, corr if corr is not None else st.corr,
               t, 0, args)


def counter(name: str, value, t_ns: int | None = None) -> None:
    """Record one counter sample (ISSUE 14 satellite): ``save()`` emits
    these as Chrome ``ph: "C"`` counter-track records so sampled gauges
    — DynSGD τ p95, shm ring occupancy, serving rows in flight — render
    as graphs alongside the spans in Perfetto. Never sampled out
    (a decimated counter track lies about its own shape); no-op when
    tracing is off."""
    tr = _tracer
    if tr is None:
        return
    st = tr._state()
    t = time.perf_counter_ns() if t_ns is None else int(t_ns)
    tr._record(st, name, COUNTER_CAT, None, t, 0, float(value),
               sampled=False)


def set_corr(corr: str | None) -> None:
    """Set this thread's correlation id; spans without an explicit
    ``corr=`` record whatever is in effect when they close. No-op when
    off (corr is only consumed by recording)."""
    tr = _tracer
    if tr is None:
        return
    tr._state().corr = corr


def current_corr() -> str | None:
    """This thread's correlation id (None when off/unset) — the socket
    client reads it to stamp outgoing commit/exchange frames."""
    tr = _tracer
    if tr is None:
        return None
    return tr._state().corr


def add_events(evs: list[dict]) -> None:
    """Merge foreign pre-shaped events (the native dkps span ring, a
    peer process's ``events()`` snapshot). Each dict needs ``name``,
    ``t0_ns``, ``dur_ns``; ``cat``/``corr``/``tid``/``tname``/``args``
    are optional. No-op when off."""
    tr = _tracer
    if tr is None:
        return
    shaped = []
    for e in evs:
        shaped.append({
            "name": e["name"], "cat": e.get("cat", ""),
            "corr": e.get("corr"), "t0_ns": int(e["t0_ns"]),
            "dur_ns": int(e.get("dur_ns", 0)),
            "tid": e.get("tid", 0),
            "tname": e.get("tname", "foreign"), "args": e.get("args"),
        })
    tr.add_events(shaped)


def events(min_end_ns: int | None = None) -> list[dict]:
    """All recorded events (see :meth:`Tracer.events`); ``[]`` when
    off. ``min_end_ns`` is the incremental consumer's cursor filter."""
    tr = _tracer
    if tr is None:
        return []
    return tr.events(min_end_ns)


def open_maybe_gz(path: str):
    """Open a JSON document that may be gzipped — sniffed by magic
    bytes, not suffix, so rotated/renamed files read transparently.
    Shared by every observability reader (trace analysis, the
    timeseries store, the CLI)."""
    with open(path, "rb") as f:
        magic = f.read(2)
    if magic == b"\x1f\x8b":
        return gzip.open(path, "rt")
    return open(path)


def load_json_maybe_gz(path: str) -> dict:
    with open_maybe_gz(path) as f:
        return json.load(f)


def rotate_files(path: str, max_bytes: int, keep: int = 3) -> None:
    """Size-capped rotation (ISSUE 14 satellite): when ``path`` already
    holds ``max_bytes`` or more, shift ``path`` → ``path.1`` →
    ``path.2`` … keeping at most ``keep`` rotated generations — a long
    watched run re-saving its timeline keeps bounded history instead of
    growing one file forever (or silently overwriting it)."""
    if keep < 1 or not os.path.exists(path) \
            or os.path.getsize(path) < max_bytes:
        return
    oldest = f"{path}.{keep}"
    if os.path.exists(oldest):
        os.remove(oldest)
    for k in range(keep - 1, 0, -1):
        src = f"{path}.{k}"
        if os.path.exists(src):
            os.replace(src, f"{path}.{k + 1}")
    os.replace(path, f"{path}.1")


def save(path: str, max_bytes: int | None = None, keep: int = 3) -> str:
    """Write everything recorded so far as Chrome trace-event JSON
    (``{"traceEvents": [...]}``, complete-event ``ph: "X"`` records with
    µs timestamps, counter samples as ``ph: "C"`` tracks) — drag the
    file into https://ui.perfetto.dev or ``chrome://tracing``. A path
    ending in ``.gz`` is gzip-compressed (the long-run growth fix;
    ``dump``/``analyze`` read both formats transparently), and
    ``max_bytes`` rotates an existing file first (see
    :func:`rotate_files`). ``otherData`` carries the dropped-span count
    and this host's core count — the analyzer's host-honest
    denominator. Parent directories are created. Returns ``path``.
    Raises RuntimeError when tracing is off (nothing to save — a silent
    empty file would read as "traced, nothing happened")."""
    tr = _tracer
    if tr is None:
        raise RuntimeError("tracing is not enabled: nothing to save")
    evs = tr.events()
    pid = os.getpid()
    out: list[dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "distkeras_tpu"},
    }]
    seen_tids: set = set()
    for e in evs:
        if e["tid"] not in seen_tids:
            seen_tids.add(e["tid"])
            out.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": e["tid"], "args": {"name": e["tname"]},
            })
        if e["cat"] == COUNTER_CAT:
            out.append({
                "name": e["name"], "ph": "C", "ts": e["t0_ns"] / 1e3,
                "pid": pid, "tid": e["tid"],
                "args": {"value": e["args"]},
            })
            continue
        args = dict(e["args"]) if e["args"] else {}
        if e["corr"] is not None:
            args["corr"] = e["corr"]
        out.append({
            "name": e["name"], "cat": e["cat"] or "dk", "ph": "X",
            "ts": e["t0_ns"] / 1e3, "dur": e["dur_ns"] / 1e3,
            "pid": pid, "tid": e["tid"], "args": args,
        })
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    if max_bytes is not None:
        rotate_files(path, int(max_bytes), keep=keep)
    doc = {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "dropped_events": tr.dropped(),
            "host_cores": os.cpu_count() or 1,
        },
    }
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wt") as f:
        json.dump(doc, f)
    return path
