"""Unified metrics surface: typed registry + Prometheus/JSON exporters.

Every subsystem in the rebuild already counts — ``ps.stats()`` /
``aggregate_ps_stats`` (PS contention, WAL, elastic membership),
``GenerationServer.stats()`` (serving), the worker phase histograms —
but each with its own ad-hoc dict shape. This module normalizes them
into ONE registry of *typed* metrics (counter / gauge / histogram) with
two exporters:

- :meth:`MetricsRegistry.to_prometheus` — the text exposition format
  (``# HELP`` / ``# TYPE`` + samples; version 0.0.4), served live from
  ``SocketParameterServer`` and ``GenerationServer`` via the ``metrics``
  wire action and scraped by ``python -m distkeras_tpu.observability``;
- :meth:`MetricsRegistry.to_json` — a JSON-clean snapshot (the shape the
  health snapshot and CI artifacts embed).

The normalizers (:func:`ps_metrics`, :func:`serving_metrics`,
:func:`wal_metrics`, :func:`phase_metrics`) own the stat-key → metric
mapping, so a new counter lands on the wire by adding ONE schema row —
not another bespoke dump. :func:`health_snapshot` folds WAL health
(``resilience.wal.verify_tree``), metrics, and membership into one JSON
document — the single health artifact that replaces the separate
wal-verify / ps-stats / membership dumps.
"""

from __future__ import annotations

import time
from typing import Any, Iterable

__all__ = [
    "Metric", "MetricsRegistry", "ps_metrics", "serving_metrics",
    "wal_metrics", "phase_metrics", "trace_metrics", "health_snapshot",
    "wire_series_samples", "metrics_reply",
]

_KINDS = ("counter", "gauge", "histogram")


class Metric:
    """One named metric: a kind, help text, and labeled samples.

    ``samples`` is a list of ``(labels, value)`` where ``labels`` is a
    (possibly empty) tuple of ``(key, value)`` pairs — tuples, not
    dicts, so a (name, labels) series is hashable and re-observing it
    overwrites rather than duplicates. Histogram values are dicts
    ``{"buckets": [(le, cumulative_count), ...], "sum": s, "count": n}``
    with ``le`` ascending and an implicit ``+Inf`` == ``count``.
    """

    __slots__ = ("name", "kind", "help", "_samples")

    def __init__(self, name: str, kind: str, help_: str = ""):
        if kind not in _KINDS:
            raise ValueError(f"kind must be one of {_KINDS}, got {kind!r}")
        self.name = name
        self.kind = kind
        self.help = help_
        self._samples: dict[tuple, Any] = {}

    def observe(self, value, labels: dict | None = None) -> None:
        key = tuple(sorted((labels or {}).items()))
        self._samples[key] = value

    @property
    def samples(self) -> list[tuple[tuple, Any]]:
        return list(self._samples.items())


class MetricsRegistry:
    """Insertion-ordered collection of :class:`Metric` (one per name;
    re-declaring with a different kind is a programming error and raises
    — the registry is what keeps the surface *typed*)."""

    def __init__(self):
        self._metrics: dict[str, Metric] = {}

    def declare(self, name: str, kind: str, help_: str = "") -> Metric:
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = Metric(name, kind, help_)
        elif m.kind != kind:
            raise ValueError(
                f"metric {name!r} already declared as {m.kind}, "
                f"cannot re-declare as {kind}"
            )
        return m

    def counter(self, name: str, value, labels: dict | None = None,
                help_: str = "") -> None:
        self.declare(name, "counter", help_).observe(value, labels)

    def gauge(self, name: str, value, labels: dict | None = None,
              help_: str = "") -> None:
        self.declare(name, "gauge", help_).observe(value, labels)

    def histogram(self, name: str, buckets: list[tuple[float, int]],
                  sum_: float, count: int, labels: dict | None = None,
                  help_: str = "") -> None:
        self.declare(name, "histogram", help_).observe(
            {"buckets": list(buckets), "sum": float(sum_),
             "count": int(count)}, labels,
        )

    def __iter__(self) -> Iterable[Metric]:
        return iter(self._metrics.values())

    def __len__(self) -> int:
        return len(self._metrics)

    # -- exporters -----------------------------------------------------------

    def to_json(self) -> dict:
        """JSON-clean snapshot: ``{name: {"kind", "help", "samples":
        [{"labels": {...}, "value": ...}]}}``."""
        out = {}
        for m in self:
            out[m.name] = {
                "kind": m.kind, "help": m.help,
                "samples": [
                    {"labels": dict(lbl), "value": val}
                    for lbl, val in m.samples
                ],
            }
        return out

    def to_prometheus(self) -> str:
        """Text exposition (0.0.4): HELP/TYPE headers + one line per
        sample; counters get the ``_total`` suffix convention from their
        declared name (the schemas below already carry it); histograms
        expand to ``_bucket{le=...}`` / ``_sum`` / ``_count``."""
        lines = []
        for m in self:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for lbl, val in m.samples:
                if m.kind == "histogram":
                    for le, c in val["buckets"]:
                        lines.append(_sample_line(
                            m.name + "_bucket",
                            lbl + (("le", _fmt_le(le)),), c))
                    lines.append(_sample_line(
                        m.name + "_bucket", lbl + (("le", "+Inf"),),
                        val["count"]))
                    lines.append(_sample_line(m.name + "_sum", lbl,
                                              val["sum"]))
                    lines.append(_sample_line(m.name + "_count", lbl,
                                              val["count"]))
                else:
                    lines.append(_sample_line(m.name, lbl, val))
        return "\n".join(lines) + "\n"


def _fmt_le(le) -> str:
    return "+Inf" if le in (None, float("inf")) else repr(float(le))


def _sample_line(name: str, labels: tuple, value) -> str:
    if labels:
        body = ",".join(
            f'{k}="{_escape(str(v))}"' for k, v in labels
        )
        name = f"{name}{{{body}}}"
    if isinstance(value, float):
        return f"{name} {value!r}"
    return f"{name} {value}"


def _escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


# -- normalizers: stats dicts → typed metrics --------------------------------

#: ``ps.stats()`` key → (metric name, kind, help). Rates and derived
#: means are EXCLUDED by design: Prometheus derives rates from counters
#: (``rate()``), and re-exporting ours would double-encode them; the
#: JSON snapshot keeps the raw stats dict next to the metrics anyway.
_PS_SCHEMA: tuple[tuple[str, str, str, str], ...] = (
    ("pulls", "dk_ps_pulls_total", "counter", "raw center pulls served"),
    ("compressed_pulls", "dk_ps_compressed_pulls_total", "counter",
     "int8 error-feedback pulls served"),
    ("commits", "dk_ps_commits_total", "counter", "commits folded"),
    ("dup_commits", "dk_ps_dup_commits_total", "counter",
     "replayed commits the seqno dedup refused to double-fold"),
    ("fused_exchanges", "dk_ps_fused_exchanges_total", "counter",
     "single-RTT fused commit+pull exchanges served"),
    ("batched_folds", "dk_ps_batched_folds_total", "counter",
     "folds applied inside a multi-fold center-lock section "
     "(batched local exchange)"),
    ("exchange_rtts", "dk_ps_exchange_rtts_total", "counter",
     "wire round trips spent on exchange traffic"),
    ("fenced_commits", "dk_ps_fenced_commits_total", "counter",
     "commits rejected by the fencing epoch"),
    ("bytes_in", "dk_ps_bytes_in_total", "counter",
     "payload bytes received (commit direction, wire size)"),
    ("bytes_out", "dk_ps_bytes_out_total", "counter",
     "payload bytes sent (pull direction, wire size)"),
    ("center_lock_acquires", "dk_ps_center_lock_acquires_total",
     "counter", "center-lock acquisitions"),
    ("center_lock_wait_ns", "dk_ps_center_lock_wait_ns_total", "counter",
     "total ns spent waiting on the center lock"),
    ("center_lock_hold_ns", "dk_ps_center_lock_hold_ns_total", "counter",
     "total ns the center lock was held"),
    ("num_updates", "dk_ps_num_updates", "gauge",
     "lifetime fold count (durable across failover)"),
    ("active_workers", "dk_ps_active_workers", "gauge",
     "workers holding a live lease"),
    ("evicted_workers", "dk_ps_evicted_workers_total", "counter",
     "lease-lapse evictions"),
    ("heartbeats", "dk_ps_heartbeats_total", "counter",
     "lease renewals received"),
    ("worker_retries", "dk_ps_worker_retries_total", "counter",
     "cumulative client retry count (as reported by heartbeats)"),
    ("wal_records", "dk_ps_wal_records_total", "counter",
     "WAL records appended"),
    ("wal_fsyncs", "dk_ps_wal_fsyncs_total", "counter",
     "real fsync syscalls issued by the WAL"),
    ("wal_group_max", "dk_ps_wal_group_max", "gauge",
     "largest commit window one fsync ever released"),
    ("pool_size", "dk_ps_pool_size", "gauge",
     "elastic worker pool gauge (configured + joins - drains)"),
    ("joined_workers", "dk_ps_joined_workers_total", "counter",
     "lifetime elastic live-joins"),
    ("preempted_workers", "dk_ps_preempted_workers_total", "counter",
     "lifetime preemption drains"),
    ("drain_timeouts", "dk_ps_drain_timeouts_total", "counter",
     "drains whose deadline lapsed into force-drain"),
    ("elapsed_s", "dk_ps_uptime_seconds", "gauge",
     "seconds since server construction"),
    ("deploy_version", "dk_ps_deploy_version", "gauge",
     "newest fold-count version the serving tier reported materialized"),
    ("deploy_lag_folds", "dk_ps_deploy_lag_folds", "gauge",
     "folds the center is ahead of the newest served snapshot "
     "(0 until a deployer reports a version)"),
)

_SERVING_SCHEMA: tuple[tuple[str, str, str, str], ...] = (
    ("submitted", "dk_serve_submitted_total", "counter",
     "requests accepted into the admission queue"),
    ("admitted", "dk_serve_admitted_total", "counter",
     "requests admitted into the running batch"),
    ("completed", "dk_serve_completed_total", "counter",
     "requests finished successfully"),
    ("cancelled", "dk_serve_cancelled_total", "counter",
     "requests cancelled (client death / explicit cancel)"),
    ("rejected", "dk_serve_rejected_total", "counter",
     "requests rejected by queue backpressure"),
    ("failed", "dk_serve_failed_total", "counter", "requests failed"),
    ("steps", "dk_serve_decode_steps_total", "counter",
     "batched decode iterations executed"),
    ("prefills", "dk_serve_prefills_total", "counter",
     "per-request prefills executed"),
    ("tokens_generated", "dk_serve_tokens_generated_total", "counter",
     "new tokens emitted by completed requests"),
    ("occupancy_sum", "dk_serve_occupancy_sum_total", "counter",
     "sum over steps of active batch rows (mean = /steps)"),
    ("spec_rounds", "dk_serve_spec_rounds_total", "counter",
     "speculative verify rounds"),
    ("spec_proposed", "dk_serve_spec_proposed_total", "counter",
     "draft tokens proposed"),
    ("spec_accepted", "dk_serve_spec_accepted_total", "counter",
     "draft tokens accepted"),
    ("connections", "dk_serve_connections_total", "counter",
     "client connections accepted"),
    ("open_connections", "dk_serve_open_connections", "gauge",
     "currently open client connections"),
    ("dead_connections", "dk_serve_dead_connections_total", "counter",
     "clients detected dead mid-generation"),
    ("queued", "dk_serve_queue_depth", "gauge",
     "requests waiting in the admission queue"),
    ("active", "dk_serve_active_requests", "gauge",
     "requests currently occupying batch rows"),
    ("blocks_in_use", "dk_serve_blocks_in_use", "gauge",
     "KV-cache blocks allocated to live requests"),
    ("blocks_free", "dk_serve_blocks_free", "gauge",
     "KV-cache blocks free in the pool"),
    ("blocks_high_water", "dk_serve_blocks_high_water", "gauge",
     "peak concurrent KV-cache block allocation"),
    # the serving front door (ISSUE 17): prefix-cache reuse, COW, and
    # SLO-admission preemption counters — absent keys simply don't emit,
    # so engines without the front door keep their exact legacy surface
    ("prefix_hit_tokens", "dk_serve_prefix_hit_tokens_total", "counter",
     "prompt tokens served from the radix prefix cache"),
    ("prefix_prompt_tokens", "dk_serve_prefix_prompt_tokens_total",
     "counter", "prompt tokens admitted (hit-rate denominator)"),
    ("prefix_hit_rate", "dk_serve_prefix_hit_rate", "gauge",
     "lifetime token-level prefix-cache hit rate"),
    ("prefix_cached_blocks", "dk_serve_prefix_cached_blocks", "gauge",
     "KV blocks currently owned by the radix prefix cache"),
    ("prefix_evictions", "dk_serve_prefix_evictions_total", "counter",
     "cached blocks evicted (LRU refcount-0 leaves)"),
    ("cow_copies", "dk_serve_prefix_cow_copies_total", "counter",
     "copy-on-write block copies (partial-block divergence)"),
    ("preemptions", "dk_serve_preemptions_total", "counter",
     "running rows preempted for higher-SLO admissions"),
)


def _apply_schema(reg: MetricsRegistry, schema, stats: dict,
                  labels: dict | None) -> None:
    for key, name, kind, help_ in schema:
        if key not in stats:
            continue
        val = stats[key]
        if kind == "counter":
            reg.counter(name, val, labels, help_)
        else:
            reg.gauge(name, val, labels, help_)


def ps_metrics(stats: dict, labels: dict | None = None,
               registry: MetricsRegistry | None = None) -> MetricsRegistry:
    """Normalize one ``ps.stats()`` dict — or an ``aggregate_ps_stats``
    roll-up, whose ``per_shard`` list fans out into ``shard``-labeled
    series next to the aggregate — into the registry."""
    reg = registry if registry is not None else MetricsRegistry()
    _apply_schema(reg, _PS_SCHEMA, stats, labels)
    for shard in stats.get("per_shard", ()):
        lbl = dict(labels or {})
        lbl["shard"] = str(shard.get("shard_id", "?"))
        _apply_schema(reg, _PS_SCHEMA, shard, lbl)
    phases = stats.get("exchange_phases")
    if phases:
        phase_metrics(phases, labels=labels, registry=reg)
    return reg


#: serving latency-summary keys (per SLO class, from the engine's
#: retired-request ring) → gauge names; the class rides as a label
_SERVE_LATENCY_KEYS: tuple[tuple[str, str, str], ...] = (
    ("p50_ms", "dk_serve_latency_p50_ms",
     "median end-to-end request latency (ms)"),
    ("p99_ms", "dk_serve_latency_p99_ms",
     "p99 end-to-end request latency (ms)"),
    ("queue_ms", "dk_serve_latency_queue_ms",
     "mean admission-queue wait (ms)"),
    ("prefill_ms", "dk_serve_latency_prefill_ms",
     "mean prefill time (ms)"),
    ("decode_ms", "dk_serve_latency_decode_ms",
     "mean decode time (ms)"),
)


def serving_metrics(stats: dict, labels: dict | None = None,
                    registry: MetricsRegistry | None = None,
                    ) -> MetricsRegistry:
    """Normalize a ``GenerationServer.stats()`` /
    ``GenerationEngine.stats()`` dict — including the per-SLO-class
    latency summary (``stats["latency"]``), which fans out into
    ``class``-labeled gauges."""
    reg = registry if registry is not None else MetricsRegistry()
    _apply_schema(reg, _SERVING_SCHEMA, stats, labels)
    for cls, rec in (stats.get("latency") or {}).items():
        lbl = dict(labels or {})
        lbl["class"] = str(cls)
        for key, name, help_ in _SERVE_LATENCY_KEYS:
            if key in rec:
                reg.gauge(name, rec[key], lbl, help_)
        if "count" in rec:
            # a gauge, not a counter: the count is of records currently
            # inside a bounded ring — eviction can shrink a class's
            # count, and Prometheus rate() over a "counter" would read
            # that dip as a reset spike
            reg.gauge("dk_serve_latency_observations",
                      rec["count"], lbl,
                      "retired requests behind the latency summary "
                      "(bounded-ring occupancy, not a lifetime total)")
    return reg


def trace_metrics(registry: MetricsRegistry | None = None,
                  labels: dict | None = None) -> MetricsRegistry:
    """The flight recorder's own health as metrics: whether tracing is
    on and — the previously-silent signal — how many spans the
    drop-oldest ring overflow discarded (``trace_dropped_spans``). Zero
    dropped means the timeline is complete; anything else says which
    runs need a bigger ``ring_size``."""
    from distkeras_tpu.observability import trace

    reg = registry if registry is not None else MetricsRegistry()
    enabled = trace.enabled()
    reg.gauge("dk_trace_enabled", int(enabled), labels,
              "flight recorder on (1) / off (0)")
    reg.counter("dk_trace_dropped_spans_total",
                trace.dropped_spans(), labels,
                "spans lost to ring-buffer overflow (drop-oldest)")
    return reg


def metrics_reply(registry: MetricsRegistry, watchtower=None) -> dict:
    """Build THE ``metrics`` wire-action reply — the one shape every
    server (socket PS, shm PS, generation server) sends, so the wire
    surfaces cannot drift: the registry (with the flight recorder's
    overflow counter folded in) as JSON + Prometheus text, plus the
    alert ledger when a watchtower is attached."""
    trace_metrics(registry=registry)
    reply = {
        "ok": True, "metrics": registry.to_json(),
        "prom": registry.to_prometheus(),
    }
    if watchtower is not None:
        reply["alerts"] = watchtower.alerts_json()
    return reply


#: wire metric name → (series name, series kind): the inverse of the
#: schemas above, so a REMOTE scrape of the ``metrics`` action feeds
#: the same series names the in-process sources use and the watchdog
#: rules run unchanged (observability/watch.py ``watch_endpoint``).
_WIRE_TO_SERIES: dict[str, tuple[str, str]] = {
    name: (f"ps.{key}", "counter" if kind == "counter" else "gauge")
    for key, name, kind, _ in _PS_SCHEMA
}
_WIRE_TO_SERIES.update({
    name: (f"serve.{key}", "counter" if kind == "counter" else "gauge")
    for key, name, kind, _ in _SERVING_SCHEMA
})
_WIRE_LATENCY_TO_SERIES: dict[str, str] = {
    name: key for key, name, _ in _SERVE_LATENCY_KEYS
}


def wire_series_samples(metrics_json: dict):
    """Yield ``(series_name, kind, value)`` for every recognizable
    sample in a ``metrics`` wire reply's JSON snapshot. Shard-labeled
    PS samples land under ``ps.shard<id>.<key>``; class-labeled serving
    latency gauges under ``serve.lat.<class>.<key>`` — the exact names
    the in-process sources write."""
    for name, doc in (metrics_json or {}).items():
        for s in doc.get("samples", ()):
            value = s.get("value")
            if not isinstance(value, (int, float)):
                continue
            lbl = s.get("labels") or {}
            if name in _WIRE_LATENCY_TO_SERIES and "class" in lbl:
                yield (f"serve.lat.{lbl['class']}."
                       f"{_WIRE_LATENCY_TO_SERIES[name]}",
                       "gauge", value)
                continue
            mapped = _WIRE_TO_SERIES.get(name)
            if mapped is None:
                continue
            series, kind = mapped
            if "shard" in lbl:
                base = series[len("ps."):]
                yield f"ps.shard{lbl['shard']}.{base}", kind, value
            elif not lbl:
                yield series, kind, value


def phase_metrics(phases: dict, labels: dict | None = None,
                  registry: MetricsRegistry | None = None,
                  ) -> MetricsRegistry:
    """Normalize the worker exchange-phase histograms
    (``trainer.ps_stats_["exchange_phases"]`` — per-phase count/total/
    max + log2 ms buckets) into ONE Prometheus histogram labeled by
    phase."""
    reg = registry if registry is not None else MetricsRegistry()
    for phase, rec in phases.items():
        lbl = dict(labels or {})
        lbl["phase"] = phase
        edges = [e for e in rec.get("hist_ms_le", []) if e != "inf"]
        counts = rec.get("hist", [])
        cum, buckets = 0, []
        for le, c in zip(edges, counts):
            cum += c
            buckets.append((float(le), cum))
        reg.histogram(
            "dk_worker_exchange_phase_ms", buckets,
            rec.get("total_ms", 0.0), rec.get("count", 0), lbl,
            "per-window exchange phase latency (ms) by phase",
        )
        reg.gauge("dk_worker_exchange_phase_max_ms", rec.get("max_ms", 0.0),
                  lbl, "worst single phase sample (ms)")
    return reg


# -- the one health document -------------------------------------------------

_MEMBERSHIP_KEYS = (
    "pool_size", "active_workers", "joined_workers", "preempted_workers",
    "drain_timeouts", "evicted_workers", "num_updates",
)


def health_snapshot(wal_root: str | None = None,
                    ps_stats: dict | None = None,
                    serving_stats: dict | None = None,
                    watchtower=None, directory=None) -> dict:
    """ONE JSON health document: WAL health (``verify_tree`` — CRC-valid
    prefixes, torn tails, record totals), the normalized metrics
    snapshot, the membership gauges, the flight recorder's overflow
    counter, the live shm segment inventory, and — when a
    :class:`~distkeras_tpu.observability.watch.Watchtower` (or a
    watchdog / pre-built alert ledger) is passed — the alert ledger.
    Replaces the separate ad-hoc dumps CI used to collect
    independently. Every section is optional; ``ok`` is the AND of the
    sections that can fail (an ACTIVE alert fails it — that is what an
    alert is for)."""
    out: dict = {"ok": True, "generated_unix_s": time.time()}
    if wal_root is not None:
        from distkeras_tpu.resilience.wal import verify_tree

        wal = verify_tree(wal_root)
        out["wal"] = wal
        out["ok"] = out["ok"] and bool(wal.get("ok"))
    reg = MetricsRegistry()
    if ps_stats is not None:
        ps_metrics(ps_stats, registry=reg)
        out["membership"] = {
            k: ps_stats[k] for k in _MEMBERSHIP_KEYS if k in ps_stats
        }
        out["ps_stats"] = _json_clean(ps_stats)
    if serving_stats is not None:
        serving_metrics(serving_stats, registry=reg)
        out["serving_stats"] = _json_clean(serving_stats)
    # the flight recorder's overflow is otherwise silent (satellite):
    # a truncated timeline must be visible as a number, not a surprise
    from distkeras_tpu.observability import trace

    out["trace"] = {"enabled": trace.enabled(),
                    "dropped_spans": trace.dropped_spans()}
    trace_metrics(registry=reg)
    # live /dev/shm segment inventory (satellite): the no-leak property
    # operator-visible — an empty list after a run IS the proof
    from distkeras_tpu import shm as _shm

    out["shm"] = _shm.segment_inventory()
    if directory is not None:
        # membership-directory view (ISSUE 15): per-entry endpoint,
        # fence epoch, and lease age — an out-of-date registration or a
        # lapsing lease is operator-visible, not silent. Accepts the
        # membership dict itself or anything with .membership()
        # (DirectoryServer, DirectoryClient, HostedDirectory).
        view = (directory.membership()
                if hasattr(directory, "membership") else directory)
        out["directory"] = _json_clean(view)
    if watchtower is not None:
        alerts = (watchtower.alerts_json()
                  if hasattr(watchtower, "alerts_json") else watchtower)
        out["alerts"] = _json_clean(alerts)
        out["ok"] = out["ok"] and not alerts.get("active")
    if len(reg):
        out["metrics"] = reg.to_json()
    return out


def _json_clean(obj):
    """Best-effort JSON coercion for stats dicts (numpy scalars etc.)."""
    import numpy as np

    if isinstance(obj, dict):
        return {str(k): _json_clean(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_clean(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    return obj
