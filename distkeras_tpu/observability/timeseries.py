"""Embedded time-series telemetry: ring-buffered series + a background scraper.

PR 11 gave the rebuild a *point-in-time* metrics surface (``ps.stats()``
roll-ups, the typed registry, one health snapshot). This module is the
*continuous* half: a lightweight in-process store of ``(t, value)``
series sampled on an interval by a background :class:`Scraper`, so "is
this run healthy right now" has data behind it — rounds/s over time,
per-worker progress skew, DynSGD τ percentiles, WAL fsync tails, shm
ring occupancy, serving latency percentiles, the training loss curve.
The watchdog (:mod:`distkeras_tpu.observability.watch`) evaluates its
alert rules over exactly these series, and ``ElasticPolicy`` reads its
rounds/s + straggler observations from the same store — ONE definition
of progress, not three private ones.

Design constraints:

- **Bounded memory, whole-run coverage.** Every series is a fixed-
  capacity buffer; when it fills it *downsamples* (adjacent pairs merge:
  gauges average, counters keep the later cumulative value) and doubles
  its implicit resolution — RRD-style. A series therefore always spans
  the whole run at degrading resolution instead of forgetting the start
  (the loss-slope stall rule needs the early history; the skew rule only
  the recent past — both are served).
- **Cheap.** One sample is a float append under one store lock; the
  scraper thread touches the run only through the read-only stat
  surfaces that already exist (``ps.stats()`` without the settling
  barrier, worker ``_windows_done`` counters, bounded deques). A source
  that raises is disabled loudly (one warning), never killing the
  scrape loop.
- **Dumpable.** ``TimeSeriesStore.dump()`` writes one JSON document
  (series + metadata) — the CI chaos artifact, and the operator's
  offline view; :meth:`TimeSeriesStore.load` round-trips it.
"""

from __future__ import annotations

import gzip
import json
import os
import threading
import time
import warnings
from typing import Callable

import numpy as np

from distkeras_tpu.observability import trace as _trace

__all__ = [
    "Series", "TimeSeriesStore", "Scraper",
    "ps_source", "progress_source", "history_source", "serving_source",
    "wire_metrics_source", "snapshot_deque",
]


class Series:
    """One named time series: a bounded list of ``(t, value)`` points.

    ``kind`` controls downsampling semantics when the buffer fills:
    ``"gauge"`` merges adjacent pairs by averaging under the earlier
    timestamp (the point labels the span it summarizes; a queue depth's
    coarse history is its mean), ``"counter"`` keeps the LATER sample of
    each pair (every surviving point stays a true cumulative
    observation — averaging would invent values the counter never
    held). ``resolution`` doubles per fill, so the series always covers
    its whole lifetime in at most ``capacity`` points.

    Concurrency: writers serialize on the store lock; READERS are
    lock-free. Points therefore live in ONE list of ``(t, v)`` tuples —
    appends are atomic under the GIL, downsampling builds a fresh list
    and REBINDS it in one assignment — so a racing reader snapshots
    ``self._pts`` once and sees either the old or the new list, never a
    torn mix of pre- and post-downsample timestamps/values.
    """

    __slots__ = ("name", "kind", "capacity", "resolution", "_pts")

    def __init__(self, name: str, kind: str = "gauge", capacity: int = 512):
        if kind not in ("gauge", "counter"):
            raise ValueError(f"kind must be 'gauge' or 'counter', got {kind!r}")
        if capacity < 8 or capacity % 2:
            raise ValueError(
                f"capacity must be an even number >= 8, got {capacity}"
            )
        self.name = name
        self.kind = kind
        self.capacity = int(capacity)
        self.resolution = 1      # raw samples merged into one point
        self._pts: list[tuple[float, float]] = []

    def __len__(self) -> int:
        return len(self._pts)

    def append(self, t: float, value: float) -> None:
        self._pts.append((float(t), float(value)))
        if len(self._pts) >= self.capacity:
            self._downsample()

    def _downsample(self) -> None:
        # A merged COUNTER pair keeps its later (t, value) sample: every
        # surviving point remains a true "cumulative count as of t"
        # observation, so any two points still give an exact rate. A
        # merged GAUGE pair keeps the earlier timestamp with the pair
        # mean (the point labels the span it summarizes — the head of
        # the series stays anchored at the run start).
        pts = self._pts
        n = len(pts) // 2 * 2
        if self.kind == "counter":
            merged = [pts[i + 1] for i in range(0, n, 2)]
        else:
            merged = [(pts[i][0], (pts[i][1] + pts[i + 1][1]) / 2.0)
                      for i in range(0, n, 2)]
        self._pts = merged + pts[n:]   # one rebind: readers never tear
        self.resolution *= 2

    def points(self) -> list[tuple[float, float]]:
        return list(self._pts)

    def last(self) -> tuple[float, float] | None:
        pts = self._pts
        if not pts:
            return None
        return pts[-1]

    def window(self, since_t: float) -> list[tuple[float, float]]:
        """Points with ``t >= since_t`` (trailing window reads)."""
        pts = self._pts                    # one snapshot (see class doc)
        lo = 0
        hi = len(pts)
        while lo < hi:                     # bisect on the sorted times
            mid = (lo + hi) // 2
            if pts[mid][0] < since_t:
                lo = mid + 1
            else:
                hi = mid
        return pts[lo:]

    def rate(self, window_s: float, now: float | None = None) -> float | None:
        """Per-second rate of change over the trailing window — THE
        rounds/s primitive (meaningful for counter series). None with
        fewer than two in-window points."""
        pts = self._pts
        if not pts:
            return None
        t_end = pts[-1][0] if now is None else float(now)
        w = self.window(t_end - float(window_s))
        if len(w) < 2:
            return None
        (t0, v0), (t1, v1) = w[0], w[-1]
        if t1 <= t0:
            return None
        return (v1 - v0) / (t1 - t0)

    def to_json(self) -> dict:
        pts = list(self._pts)
        return {
            "name": self.name, "kind": self.kind,
            "capacity": self.capacity, "resolution": self.resolution,
            "t": [p[0] for p in pts], "v": [p[1] for p in pts],
        }

    @classmethod
    def from_json(cls, d: dict) -> "Series":
        s = cls(d["name"], d.get("kind", "gauge"),
                d.get("capacity", 512))
        s.resolution = int(d.get("resolution", 1))
        s._pts = [(float(t), float(v)) for t, v in zip(d["t"], d["v"])]
        return s


class TimeSeriesStore:
    """Thread-safe named collection of :class:`Series`.

    ``sample`` lazily declares the series on first touch (kind is fixed
    at declaration — re-sampling with a different kind raises, same
    typed-surface discipline as the metrics registry). The clock is the
    caller's: every producer in this codebase samples ``time.monotonic()``
    so series timestamps, worker progress, and request latencies share
    one timebase.
    """

    def __init__(self, capacity: int = 512):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._series: dict[str, Series] = {}

    def sample(self, name: str, t: float, value,
               kind: str = "gauge") -> None:
        v = float(value)
        with self._lock:
            s = self._series.get(name)
            if s is None:
                s = self._series[name] = Series(name, kind, self.capacity)
            elif s.kind != kind:
                raise ValueError(
                    f"series {name!r} is a {s.kind}, cannot sample as {kind}"
                )
            s.append(t, v)

    def get(self, name: str) -> Series | None:
        with self._lock:
            return self._series.get(name)

    def names(self, prefix: str = "") -> list[str]:
        with self._lock:
            return sorted(n for n in self._series if n.startswith(prefix))

    def last(self, name: str) -> float | None:
        s = self.get(name)
        if s is None:
            return None
        p = s.last()
        return None if p is None else p[1]

    def rate(self, name: str, window_s: float,
             now: float | None = None) -> float | None:
        s = self.get(name)
        return None if s is None else s.rate(window_s, now)

    def delta(self, name: str, window_s: float,
              now: float | None = None) -> float | None:
        """Counter increase over the trailing window (spike rules)."""
        s = self.get(name)
        if s is None or not len(s):
            return None
        t_end = s._pts[-1][0] if now is None else float(now)
        pts = s.window(t_end - float(window_s))
        if len(pts) < 2:
            return None
        return pts[-1][1] - pts[0][1]

    def increase(self, name: str, window_s: float,
                 now: float | None = None) -> float | None:
        """Reset-aware counter increase over the trailing window: the
        sum of positive increments (Prometheus ``increase()``
        semantics). A counter that RESETS mid-window — a failed-over PS
        restarting its op counters — must not report a negative (or
        masked) spike."""
        s = self.get(name)
        if s is None or not len(s):
            return None
        t_end = s._pts[-1][0] if now is None else float(now)
        pts = s.window(t_end - float(window_s))
        if len(pts) < 2:
            return None
        return float(sum(
            max(0.0, pts[i + 1][1] - pts[i][1])
            for i in range(len(pts) - 1)
        ))

    def __len__(self) -> int:
        with self._lock:
            return len(self._series)

    def to_json(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "series": {n: s.to_json()
                           for n, s in sorted(self._series.items())},
            }

    def dump(self, path: str, extra: dict | None = None) -> str:
        """Write the store (plus optional extra sections — the watchdog
        attaches its alert log here) as one JSON document. A ``.gz``
        path is gzip-compressed (long watched runs; ISSUE 14) — ``load``
        and the ``analyze`` CLI sniff the format, so both read back
        transparently."""
        doc = self.to_json()
        if extra:
            doc.update(extra)
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "wt") as f:
            json.dump(doc, f)
        return path

    @classmethod
    def load(cls, path: str) -> "TimeSeriesStore":
        doc = _trace.load_json_maybe_gz(path)
        store = cls(doc.get("capacity", 512))
        for n, s in doc.get("series", {}).items():
            store._series[n] = Series.from_json(s)
        return store


class Scraper:
    """Background sampler: every ``interval`` seconds it runs each
    registered source against the store, then fires ``on_tick`` (the
    watchdog evaluation rides here, so rules see freshly sampled data).

    A **source** is ``fn(store, now) -> None``. One that raises is
    disabled after a single warning naming it — telemetry must never
    take down the run it is observing. ``tick()`` runs one synchronous
    pass (tests drive scraping deterministically through it; the thread
    is just ``tick`` on a timer)."""

    def __init__(self, store: TimeSeriesStore, interval: float = 1.0):
        if interval <= 0:
            raise ValueError(f"interval must be positive, got {interval}")
        self.store = store
        self.interval = float(interval)
        self._sources: list[tuple[str, Callable]] = []
        self._dead: set[str] = set()
        self._on_tick: list[Callable[[float], None]] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.ticks = 0

    def add_source(self, name: str, fn: Callable) -> None:
        self._sources.append((str(name), fn))

    def on_tick(self, fn: Callable[[float], None]) -> None:
        self._on_tick.append(fn)

    def tick(self, now: float | None = None) -> None:
        t = time.monotonic() if now is None else float(now)
        for name, fn in self._sources:
            if name in self._dead:
                continue
            try:
                fn(self.store, t)
            except Exception as e:  # noqa: BLE001 — observer must survive
                self._dead.add(name)
                warnings.warn(
                    f"timeseries source {name!r} failed and was disabled "
                    f"({type(e).__name__}: {e})", stacklevel=2,
                )
        for fn in self._on_tick:
            try:
                fn(t)
            except Exception as e:  # noqa: BLE001
                warnings.warn(
                    f"timeseries on_tick hook failed "
                    f"({type(e).__name__}: {e})", stacklevel=2,
                )
        self.ticks += 1

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval):
                self.tick()

        self._thread = threading.Thread(
            target=loop, daemon=True, name="dk-watch-scraper"
        )
        self._thread.start()

    def stop(self, final_tick: bool = True) -> None:
        self._stop.set()
        t = self._thread
        self._thread = None
        if t is not None:
            t.join(timeout=5)
        if final_tick:
            self.tick()  # end-of-run state always lands in the series


# -- sources -----------------------------------------------------------------

#: scalar ps.stats() keys worth a series, with their kind (rates and
#: derived means are skipped — the store derives rates itself)
_PS_SERIES: tuple[tuple[str, str], ...] = (
    ("pulls", "counter"), ("compressed_pulls", "counter"),
    ("commits", "counter"), ("dup_commits", "counter"),
    ("fenced_commits", "counter"), ("fused_exchanges", "counter"),
    ("batched_folds", "counter"), ("exchange_rtts", "counter"),
    ("bytes_in", "counter"), ("bytes_out", "counter"),
    ("num_updates", "counter"), ("wal_records", "counter"),
    ("wal_fsyncs", "counter"), ("evicted_workers", "counter"),
    ("heartbeats", "counter"), ("worker_retries", "counter"),
    ("joined_workers", "counter"), ("preempted_workers", "counter"),
    ("drain_timeouts", "counter"),
    ("active_workers", "gauge"), ("pool_size", "gauge"),
    ("center_lock_mean_hold_ns", "gauge"), ("wal_group_max", "gauge"),
    ("deploy_version", "gauge"), ("deploy_lag_folds", "gauge"),
)


def ps_source(ps) -> Callable:
    """Sample a parameter server (any transport that quacks ``stats()``:
    single PS, socket/native/shm server, ``ShardedPSGroup`` aggregate —
    or a zero-arg callable resolving the CURRENT server, so a failover's
    promoted primary is scraped, not the corpse) into ``ps.<key>``
    series — plus, where the server exposes them, the DynSGD τ p95
    (``ps.tau_p95``, from the fold path's recent-staleness ring), the
    WAL fsync tail (``ps.wal_fsync_p95_ms`` / ``ps.wal_fsync_max_ms``),
    and shm ring occupancy (``shm.ring_occupancy_frac``, the fullest
    ring's used fraction, plus ``shm.segments``). The stats read skips
    the settling barrier where supported (``settle=False``): a scrape
    must observe the run, not synchronize with it."""
    resolve = ps if callable(ps) else (lambda: ps)

    def sample(store: TimeSeriesStore, now: float) -> None:
        target = resolve()
        if target is None:
            return
        try:
            stats = target.stats(settle=False)
        except TypeError:           # native/group stats() take no kwarg
            stats = target.stats()
        for key, kind in _PS_SERIES:
            v = stats.get(key)
            if v is not None:
                store.sample(f"ps.{key}", now, v, kind)
        taus = getattr(target, "recent_staleness", None)
        if taus is not None:
            vals = taus()
            if vals:
                arr = np.asarray(vals, np.float64)
                p95 = float(np.percentile(arr, 95))
                store.sample("ps.tau_p95", now, p95)
                store.sample("ps.tau_max", now, float(arr.max()))
                # Perfetto counter track (ISSUE 14): the sampled τ tail
                # renders as a graph alongside the spans (no-op untraced)
                _trace.counter("ps.tau_p95", p95)
        wal = getattr(target, "_wal", None)
        recent = getattr(wal, "fsync_ms_recent", None)
        if recent:
            vals = snapshot_deque(recent)
            if vals:
                arr = np.asarray(vals, np.float64)
                store.sample("ps.wal_fsync_p95_ms", now,
                             float(np.percentile(arr, 95)))
                store.sample("ps.wal_fsync_max_ms", now, float(arr.max()))
        occ = getattr(target, "ring_occupancy", None)
        if occ is not None:
            segs = occ()
            if segs:
                frac = max(s["frac"] for s in segs)
                store.sample("shm.ring_occupancy_frac", now, frac)
                _trace.counter("shm.ring_occupancy_frac", frac)
            store.sample("shm.segments", now, len(segs))

    return sample


def progress_source(get_progress: Callable[[], dict]) -> Callable:
    """Sample per-worker cumulative window counts (``{wid: count}``)
    into ``worker.<wid>.windows`` counter series — the ONE progress
    record the skew rule and ``ElasticPolicy`` both read."""

    def sample(store: TimeSeriesStore, now: float) -> None:
        for wid, n in get_progress().items():
            store.sample(f"worker.{wid}.windows", now, n, "counter")

    return sample


def history_source(history: list, lock=None, tail: int = 16) -> Callable:
    """Sample the training history (per-window loss rows appended by the
    hogwild workers) into ``train.records`` (counter) and ``train.loss``
    (gauge: mean of the last ``tail`` losses — one worker's noisy window
    loss is not a signal; their recent mean is)."""

    def sample(store: TimeSeriesStore, now: float) -> None:
        if lock is not None:
            with lock:
                n = len(history)
                recent = [r.get("loss") for r in history[-tail:]]
        else:
            n = len(history)
            recent = [r.get("loss") for r in history[-tail:]]
        store.sample("train.records", now, n, "counter")
        losses = [x for x in recent if x is not None and np.isfinite(x)]
        if losses:
            store.sample("train.loss", now, float(np.mean(losses)))

    return sample


#: scalar GenerationEngine/GenerationServer stats keys worth a series
_SERVE_SERIES: tuple[tuple[str, str], ...] = (
    ("submitted", "counter"), ("admitted", "counter"),
    ("completed", "counter"), ("cancelled", "counter"),
    ("rejected", "counter"), ("failed", "counter"),
    ("steps", "counter"), ("prefills", "counter"),
    ("tokens_generated", "counter"), ("dead_connections", "counter"),
    ("queued", "gauge"), ("active", "gauge"),
    ("blocks_in_use", "gauge"), ("blocks_free", "gauge"),
    ("open_connections", "gauge"),
    # the serving front door (ISSUE 17) — keys absent on engines
    # without it, so legacy series sets are unchanged
    ("prefix_hit_rate", "gauge"), ("prefix_cached_blocks", "gauge"),
    ("prefix_evictions", "counter"), ("cow_copies", "counter"),
    ("preemptions", "counter"),
)


def serving_source(engine) -> Callable:
    """Sample a ``GenerationEngine`` / ``GenerationServer`` into
    ``serve.<key>`` series plus per-SLO-class latency percentiles
    (``serve.lat.<class>.p50_ms`` / ``.p99_ms`` / ``.queue_ms`` /
    ``.prefill_ms`` / ``.decode_ms``) from the engine's retired-request
    ring — the series the per-class SLO rule evaluates."""

    def sample(store: TimeSeriesStore, now: float) -> None:
        stats = engine.stats()
        for key, kind in _SERVE_SERIES:
            v = stats.get(key)
            if v is not None:
                store.sample(f"serve.{key}", now, v, kind)
        if stats.get("active") is not None:
            # rows in flight as a Perfetto counter track (ISSUE 14):
            # batch occupancy over time next to the decode_step spans
            _trace.counter("serve.rows_in_flight", stats["active"])
        lat = stats.get("latency") or {}
        for cls, rec in lat.items():
            for key in ("p50_ms", "p99_ms", "queue_ms", "prefill_ms",
                        "decode_ms"):
                v = rec.get(key)
                if v is not None:
                    store.sample(f"serve.lat.{cls}.{key}", now, v)

    return sample


def wire_metrics_source(scrape: Callable[[], dict]) -> Callable:
    """Feed the store from a live server's ``metrics`` wire reply (the
    ``health --watch`` CLI path): ``scrape()`` returns the reply dict
    and every ``dk_ps_*`` / ``dk_serve_*`` sample lands under the SAME
    series names the in-process sources use, so the watchdog rules run
    unchanged against a remote server."""
    from distkeras_tpu.observability.metrics import wire_series_samples

    def sample(store: TimeSeriesStore, now: float) -> None:
        reply = scrape()
        for name, kind, value in wire_series_samples(
                reply.get("metrics", {})):
            store.sample(name, now, value, kind)

    return sample


def snapshot_deque(d) -> list:
    """Copy a bounded deque another thread is appending to: ``list()``
    over a mutating deque can raise RuntimeError — retry, then settle
    for empty (a telemetry read must never fail the scrape)."""
    for _ in range(4):
        try:
            return list(d)
        except RuntimeError:
            continue
    return []
