"""CLI: scrape live metrics / emit the one-document health snapshot.

Usage::

    python -m distkeras_tpu.observability dump --host H --port P [--prom]
    python -m distkeras_tpu.observability tail --host H --port P \\
        [--interval 2] [--count 0]
    python -m distkeras_tpu.observability health [--wal-dir DIR] \\
        [--host H --port P] [--watch [--interval 2] [--count 0]]
    python -m distkeras_tpu.observability analyze <trace.json[.gz]> \\
        [--series <dump.json[.gz]>] [--json]

``dump``/``tail`` speak the ``metrics`` wire action both the
``SocketParameterServer`` and the ``GenerationServer`` serve (the framed
restricted-pickle protocol — ``networking.py``), printing the JSON
snapshot by default or the Prometheus text exposition with ``--prom``.
``health`` folds WAL health (``resilience.wal.verify_tree``), metrics,
membership, the trace-overflow counter, and the live shm segment
inventory into ONE JSON document (exit code 1 when unhealthy) — the
artifact CI uploads instead of three separate ad-hoc dumps.

``analyze`` (ISSUE 14) runs the post-hoc critical-path analyzer
(observability/analyze.py) over a saved flight-recorder trace — plain
or gzipped — optionally joined with a watchtower time-series dump:
per-worker waterfalls, overlap efficiency, lock/fsync/straggler
attribution, and the typed regime verdict with knob-keyed
recommendations. ``--json`` prints the full report document (the CI
artifact); the default is the human-readable summary. Exit code 2 when
the verdict is degraded (the trace dropped spans), 0 otherwise.

``health --watch`` (ISSUE 13) polls a live server's ``metrics`` action
on ``--interval`` and prints alert TRANSITIONS as JSON lines: the
scraped counters feed the same time-series store and watchdog rules the
in-process watchtower runs (observability/watch.py), and any alert
ledger the server itself carries (a trainer-attached watchtower) is
relayed with ``"remote": true``. ``--count N`` stops after N polls
(0 = forever); the exit code is 1 when any alert is still firing.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _scrape(host: str, port: int, timeout: float = 10.0) -> dict:
    from distkeras_tpu import networking

    sock = networking.connect(host, port, timeout=timeout)
    sock.settimeout(timeout)
    try:
        networking.send_data(sock, {"action": "metrics"})
        reply = networking.recv_data(sock)
    finally:
        try:
            networking.send_data(sock, {"action": "bye"})
        except OSError:
            pass
        sock.close()
    if not isinstance(reply, dict) or not reply.get("ok"):
        raise ConnectionError(f"metrics scrape refused: {reply!r}")
    return reply


def _cmd_dump(args) -> int:
    reply = _scrape(args.host, args.port)
    if args.prom:
        sys.stdout.write(reply.get("prom", ""))
    else:
        print(json.dumps(reply.get("metrics", {}), indent=2,
                         sort_keys=True))
    return 0


def _cmd_tail(args) -> int:
    n = 0
    while True:
        reply = _scrape(args.host, args.port)
        if args.prom:
            sys.stdout.write(reply.get("prom", ""))
        else:
            print(json.dumps({"t_unix_s": time.time(),
                              "metrics": reply.get("metrics", {})}))
        sys.stdout.flush()
        n += 1
        if args.count and n >= args.count:
            return 0
        time.sleep(max(0.05, args.interval))


def _cmd_health(args) -> int:
    from distkeras_tpu.observability.metrics import health_snapshot

    if args.watch:
        if args.host is None or args.port is None:
            raise SystemExit("health --watch needs --host/--port")
        from distkeras_tpu.observability.watch import watch_endpoint

        def emit(alert: dict) -> None:
            print(json.dumps({"t_unix_s": time.time(), **alert}))
            sys.stdout.flush()

        dog = watch_endpoint(
            lambda: _scrape(args.host, args.port),
            interval=args.interval, count=args.count, emit=emit,
        )
        # a firing alert counts wherever it lives: locally derived from
        # the scraped counters, OR in the server-side ledger (rules the
        # remote scrape cannot reconstruct — τ ring, shm occupancy)
        return 1 if dog.active or dog.remote_active else 0

    stats = None
    if args.host is not None:
        from distkeras_tpu import networking

        sock = networking.connect(args.host, args.port, timeout=10.0)
        sock.settimeout(10.0)
        try:
            networking.send_data(sock, {"action": "stats"})
            reply = networking.recv_data(sock)
        finally:
            try:
                networking.send_data(sock, {"action": "bye"})
            except OSError:
                pass
            sock.close()
        if not isinstance(reply, dict) or "stats" not in reply:
            raise ConnectionError(f"stats scrape refused: {reply!r}")
        stats = reply["stats"]
    # a serving server's stats dict carries "submitted"; a PS's carries
    # "pulls" — route to the matching normalizer
    serving = stats is not None and "submitted" in stats \
        and "pulls" not in stats
    report = health_snapshot(
        wal_root=args.wal_dir,
        ps_stats=None if serving else stats,
        serving_stats=stats if serving else None,
    )
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0 if report["ok"] else 1


def _cmd_analyze(args) -> int:
    from distkeras_tpu.observability.analyze import (
        analyze_trace,
        format_report,
    )
    from distkeras_tpu.observability.metrics import _json_clean

    try:
        report = analyze_trace(args.trace, series_path=args.series)
    except (OSError, ValueError, KeyError) as e:
        raise SystemExit(
            f"analyze: cannot read {args.trace!r}: "
            f"{type(e).__name__}: {e}"
        ) from e
    if args.json:
        print(json.dumps(_json_clean(report), indent=2, sort_keys=True))
    else:
        print(format_report(report))
    return 2 if report["degraded"] else 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distkeras_tpu.observability",
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    def _net(p, required=True):
        p.add_argument("--host", default="127.0.0.1" if required else None)
        p.add_argument("--port", type=int, required=required)

    p = sub.add_parser("dump", help="scrape a live server's metrics once")
    _net(p)
    p.add_argument("--prom", action="store_true",
                   help="Prometheus text exposition instead of JSON")
    p.set_defaults(fn=_cmd_dump)

    p = sub.add_parser("tail", help="scrape on an interval")
    _net(p)
    p.add_argument("--prom", action="store_true")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--count", type=int, default=0,
                   help="stop after N scrapes (0 = forever)")
    p.set_defaults(fn=_cmd_tail)

    p = sub.add_parser(
        "health",
        help="one JSON health document: WAL + metrics + membership "
             "(+ --watch: live alert-transition tail)",
    )
    p.add_argument("--wal-dir", default=None,
                   help="WAL directory or sharded root to verify")
    _net(p, required=False)
    p.add_argument("--watch", action="store_true",
                   help="poll the server's metrics action and print "
                        "alert transitions (same watchdog rules as the "
                        "in-process watchtower)")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--count", type=int, default=0,
                   help="stop after N polls (0 = forever)")
    p.set_defaults(fn=_cmd_health)

    p = sub.add_parser(
        "analyze",
        help="post-hoc critical-path attribution + bottleneck verdict "
             "over a saved flight-recorder trace (.json or .json.gz)",
    )
    p.add_argument("trace", help="Chrome trace file from trace.save()")
    p.add_argument("--series", default=None,
                   help="watchtower/timeseries dump to join (counters, "
                        "alert history; .json or .json.gz)")
    p.add_argument("--json", action="store_true",
                   help="full report document instead of the summary")
    p.set_defaults(fn=_cmd_analyze)

    args = ap.parse_args(argv)
    if args.cmd == "health" and args.wal_dir is None \
            and args.host is None:
        ap.error("health needs --wal-dir and/or --host/--port")
    if args.cmd == "health" and args.host is not None \
            and args.port is None:
        ap.error("--host needs --port")
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
