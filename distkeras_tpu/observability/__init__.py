"""Observability: end-to-end tracing + the unified metrics surface.

The flight recorder the ROADMAP directions (train→serve streaming,
SLO-aware scheduling, shm transport) are debugged against:

- :mod:`distkeras_tpu.observability.trace` — zero-cost-when-off spans
  (thread-local ring buffers, monotonic clocks) emitting Chrome
  trace-event JSON loadable in Perfetto, with a correlation id
  (worker id + seqno, or serving request id) stitching one EXCHANGE
  across the worker thread, the PS handler, the WAL flusher, chain
  replicas, and the native C++ server ring.
- :mod:`distkeras_tpu.observability.metrics` — a typed registry
  normalizing ``ps.stats()`` / serving / WAL counters into named
  metrics with Prometheus text + JSON snapshot exporters, served live
  via the ``metrics`` wire action on ``SocketParameterServer`` and
  ``GenerationServer``, plus the single-document
  :func:`~distkeras_tpu.observability.metrics.health_snapshot`.
- :mod:`distkeras_tpu.observability.timeseries` — the embedded
  time-series store (fixed-capacity downsampling ring series) and the
  background :class:`~distkeras_tpu.observability.timeseries.Scraper`
  sampling the PR 11 metrics surface into series over time.
- :mod:`distkeras_tpu.observability.watch` — the watchtower (ISSUE 13):
  declarative typed alert rules (τ p95, commit-rate skew, dup/fenced
  spikes, WAL fsync tails, shm ring occupancy, per-class serving SLO,
  loss-slope convergence stall) evaluated over those series, plus the
  ONE shared definition of rounds/s + straggler ratio that
  ``ElasticPolicy`` reads too.
- :mod:`distkeras_tpu.observability.analyze` — the analyst (ISSUE 14):
  post-hoc critical-path attribution over the recorded spans (per-worker
  waterfalls, pipelining overlap efficiency, center-lock/fsync/straggler
  wait attribution) ending in a typed regime verdict
  (compute/wire/fsync/fold-lock/host-core-bound) with knob-keyed
  recommendations; ``analyze=True`` on a trainer runs it post-run into
  ``trainer.analysis_``, and ``regime_source`` feeds the live regime
  series the watchtower's ``BottleneckShiftRule`` fires on.
- ``python -m distkeras_tpu.observability`` — ``dump`` / ``tail`` a
  live server's metrics, emit the ``health`` snapshot, ``health
  --watch`` a live server's alert transitions, or ``analyze`` a saved
  trace into the bottleneck report.

Trainer knobs: ``trace=True`` (enable), ``trace_dir=`` (write the
timeline file, path lands in ``trainer.trace_path_``),
``trace_sample=`` (deterministic span sampling); ``watch=True`` /
``watch_rules=`` / ``watch_dir=`` / ``scrape_interval=`` /
``watch_hook=`` run the watchtower over a training run (alerts land in
``trainer.watch_alerts_``, the dump path in ``trainer.watch_path_``).
``bench.py`` legs take ``--trace-dir`` and record ``trace_path`` in
their stdout JSON; ``bench.py --regress`` is the trajectory-enforcing
perf-regression guard.
"""

from distkeras_tpu.observability import analyze, timeseries, trace, watch
from distkeras_tpu.observability.metrics import (
    MetricsRegistry,
    health_snapshot,
    phase_metrics,
    ps_metrics,
    serving_metrics,
    trace_metrics,
)
from distkeras_tpu.observability.timeseries import Scraper, TimeSeriesStore
from distkeras_tpu.observability.watch import (
    Watchdog,
    Watchtower,
    default_rules,
)

__all__ = [
    "trace", "timeseries", "watch", "analyze", "MetricsRegistry", "ps_metrics",
    "serving_metrics", "phase_metrics", "trace_metrics",
    "health_snapshot", "TimeSeriesStore", "Scraper", "Watchdog",
    "Watchtower", "default_rules",
]
