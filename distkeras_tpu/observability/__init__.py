"""Observability: end-to-end tracing + the unified metrics surface.

The flight recorder the ROADMAP directions (train→serve streaming,
SLO-aware scheduling, shm transport) are debugged against:

- :mod:`distkeras_tpu.observability.trace` — zero-cost-when-off spans
  (thread-local ring buffers, monotonic clocks) emitting Chrome
  trace-event JSON loadable in Perfetto, with a correlation id
  (worker id + seqno, or serving request id) stitching one EXCHANGE
  across the worker thread, the PS handler, the WAL flusher, chain
  replicas, and the native C++ server ring.
- :mod:`distkeras_tpu.observability.metrics` — a typed registry
  normalizing ``ps.stats()`` / serving / WAL counters into named
  metrics with Prometheus text + JSON snapshot exporters, served live
  via the ``metrics`` wire action on ``SocketParameterServer`` and
  ``GenerationServer``, plus the single-document
  :func:`~distkeras_tpu.observability.metrics.health_snapshot`.
- ``python -m distkeras_tpu.observability`` — ``dump`` / ``tail`` a
  live server's metrics, or emit the ``health`` snapshot.

Trainer knobs: ``trace=True`` (enable), ``trace_dir=`` (write the
timeline file, path lands in ``trainer.trace_path_``),
``trace_sample=`` (deterministic span sampling). ``bench.py`` legs take
``--trace-dir`` and record ``trace_path`` in their stdout JSON.
"""

from distkeras_tpu.observability import trace
from distkeras_tpu.observability.metrics import (
    MetricsRegistry,
    health_snapshot,
    phase_metrics,
    ps_metrics,
    serving_metrics,
)

__all__ = [
    "trace", "MetricsRegistry", "ps_metrics", "serving_metrics",
    "phase_metrics", "health_snapshot",
]
