"""Durable PS state: write-ahead commit log, snapshots, and replay.

The reference never needed PS durability — the center lived in the Spark
driver and a driver death was a rerun. PR 4 made the *workers* restartable;
this module makes the CENTER restartable: every state-changing event on the
parameter server (deduplicated commit folds, pull-version records, clean
deregisters, evictions, fencing-epoch bumps) is appended to a write-ahead
log BEFORE the client sees an ACK, and the full state (center, EMA,
``num_updates``, per-worker pull versions, the commit-dedup table, the
fencing epoch) is periodically written as an fsync'd snapshot that
truncates the log. A restarted PS loads ``(snapshot, wal)`` and replays —
reconstructing exactly the state a never-crashed server would hold after
the same prefix of events (the bit-identical oracle the durability tests
pin).

Why full payloads and not just digests: a digest can *verify* a fold but
cannot *reproduce* it — replay must re-run ``rule.fold`` on the decoded
commit tree to land on the same bits. Each record therefore carries the
payload plus a CRC32 over the framed body; the CRC is the torn-write
detector (a crash mid-append leaves a tail record that fails its CRC and
replay stops cleanly at the last durable prefix).

Crash-consistency contract:

- Appends happen in fold order (the PS appends under its center lock) and
  ``flush()`` per record — an in-process crash (or a SIGKILL'd process)
  loses nothing already handed to the OS. ``fsync`` runs periodically
  (``fsync_every`` records) and always under a snapshot, bounding what a
  *machine* crash can lose; the commit path never waits on fsync.
- A commit folded in memory but torn in the log is a commit whose ACK
  never went out (append-before-ACK): the client replays it with the same
  seqno against the recovered server, whose replayed dedup table does not
  contain it — it folds exactly once. The exactly-once oracle
  (``commits == logical``) survives the crash.
- Snapshots are written to a temp name, fsync'd, atomically renamed, and
  only then do older segments/snapshots get deleted — there is never a
  moment without a recoverable (snapshot, wal) pair.

The same record stream doubles as the hot-standby replication wire: the
primary sends each appended record (prefixed by the same framing) to the
replica before ACKing, and the standby applies records through the same
``replay_record`` path recovery uses — one definition of "apply an event",
whether from disk or from the stream.
"""

from __future__ import annotations

import io
import os
import pickle
import struct
import zlib
from typing import Any, Iterator

import numpy as np

Pytree = Any

# record types
REC_COMMIT = 1    # (worker_id, seq|None, pull_version, version, payload)
REC_PULL = 2      # (worker_id, version)
REC_DEREG = 3     # (worker_id,)          clean exit: clear dedup entry
REC_EVICT = 4     # (worker_ids,)         lease lapse: clear pulls + dedup
REC_FENCE = 5     # (epoch,)              fencing-epoch bump

_HDR = struct.Struct(">BII")  # type, crc32(body), len(body)

_SNAP_PREFIX = "snap-"
_SNAP_SUFFIX = ".dkw"
_SEG_PREFIX = "wal-"
_SEG_SUFFIX = ".log"


def _restricted_loads(data: bytes):
    """Decode a record/snapshot body with the same primitives+numpy-only
    unpickler the wire uses (networking._RestrictedUnpickler): WAL files
    live on shared filesystems, so they get the same defense the frames
    do — a tampered log can corrupt training state, not execute code."""
    from distkeras_tpu.networking import _RestrictedUnpickler

    return _RestrictedUnpickler(io.BytesIO(data)).load()


def encode_record(rec_type: int, body_obj: Any) -> bytes:
    """Frame one record: header(type, crc32, len) + pickled body."""
    body = pickle.dumps(body_obj, protocol=pickle.HIGHEST_PROTOCOL)
    return _HDR.pack(rec_type, zlib.crc32(body), len(body)) + body


def durable_prefix_len(data: bytes) -> int:
    """Byte length of the valid record prefix (where a torn/corrupt tail
    starts, if any)."""
    off = 0
    n = len(data)
    while off + _HDR.size <= n:
        _, crc, ln = _HDR.unpack_from(data, off)
        body_off = off + _HDR.size
        if body_off + ln > n or zlib.crc32(data[body_off:body_off + ln]) != crc:
            return off
        off = body_off + ln
    return off


def iter_records(data: bytes) -> Iterator[tuple[int, Any]]:
    """Yield (type, body) records from a segment's bytes, stopping at the
    first torn or corrupt frame (the durable prefix ends there)."""
    off = 0
    n = len(data)
    while off + _HDR.size <= n:
        rec_type, crc, ln = _HDR.unpack_from(data, off)
        body_off = off + _HDR.size
        if body_off + ln > n:
            return  # torn tail: the append died mid-write
        body = data[body_off:body_off + ln]
        if zlib.crc32(body) != crc:
            return  # corrupt tail (or bit rot): stop at the durable prefix
        try:
            yield rec_type, _restricted_loads(body)
        except Exception:
            return  # undecodable body: same treatment as a bad CRC
        off = body_off + ln


class CommitLog:
    """Append-only WAL + snapshot manager for one parameter server.

    Files in ``directory``:

    - ``wal-<version>.log`` — records appended since the state was at
      ``version`` (the segment's base). Exactly one live segment.
    - ``snap-<version>.dkw`` — fsync'd full-state snapshot at ``version``.

    Appends are NOT thread-safe by themselves — the PS calls them under
    its center lock, which is also what guarantees the log order equals
    the fold order (replay depends on it).
    """

    def __init__(self, directory: str, snapshot_every: int = 100,
                 fsync_every: int = 64):
        self.dir = str(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.snapshot_every = int(snapshot_every)
        self.fsync_every = max(1, int(fsync_every))
        self._fh = None
        self._since_fsync = 0
        self.commits_since_snapshot = 0
        self._segment_base = 0

    # -- append side ---------------------------------------------------------

    def open_segment(self, base_version: int) -> None:
        """Open (appending) the live segment based at ``base_version``.
        An existing file (restart-in-place) is first truncated to its
        durable prefix — appending after a torn tail record would bury
        every new record behind an unreadable frame."""
        self.close()
        self._segment_base = int(base_version)
        path = os.path.join(
            self.dir, f"{_SEG_PREFIX}{base_version:012d}{_SEG_SUFFIX}"
        )
        if os.path.exists(path):
            with open(path, "rb") as f:
                data = f.read()
            good = durable_prefix_len(data)
            if good != len(data):
                with open(path, "r+b") as f:
                    f.truncate(good)
        self._fh = open(path, "ab")

    def append(self, record: bytes) -> None:
        """Append one pre-framed record; flush to the OS (crash-of-process
        safe). Never fsyncs — the PS appends under its center lock, and a
        disk sync must not ride the fold's critical section; callers run
        ``maybe_fsync()`` after releasing it."""
        self._fh.write(record)
        self._fh.flush()
        self._since_fsync += 1

    def maybe_fsync(self) -> None:
        """Periodic machine-crash durability — call OFF the center lock
        (every ``fsync_every`` records trips a real fsync)."""
        if self._since_fsync >= self.fsync_every:
            self.sync()

    def append_commit(self, worker_id: int, seq: int | None,
                      pull_version: int, version: int,
                      payload_bytes: bytes) -> None:
        """``payload_bytes`` is the pre-pickled decoded commit tree
        (pickled OUTSIDE the center lock by the PS — the O(model) encode
        must not ride the fold's critical section)."""
        self.append(encode_record(
            REC_COMMIT,
            (int(worker_id), None if seq is None else int(seq),
             int(pull_version), int(version), payload_bytes),
        ))
        self.commits_since_snapshot += 1

    def append_pull(self, worker_id: int, version: int) -> None:
        self.append(encode_record(REC_PULL, (int(worker_id), int(version))))

    def append_dereg(self, worker_id: int) -> None:
        self.append(encode_record(REC_DEREG, (int(worker_id),)))

    def append_evict(self, worker_ids: list[int]) -> None:
        self.append(encode_record(REC_EVICT, ([int(w) for w in worker_ids],)))

    def append_fence(self, epoch: int) -> None:
        # the PS fsyncs right after releasing its lock: a fence must be
        # durable by the time the fencing caller gets its ack
        self.append(encode_record(REC_FENCE, (int(epoch),)))

    def sync(self) -> None:
        fh = self._fh
        if fh is None:
            return
        try:
            fh.flush()
            os.fsync(fh.fileno())
        except (OSError, ValueError):
            # racing a rotation's close (maybe_fsync runs OFF the center
            # lock by design): the rotation's own open/append path keeps
            # the new segment consistent; skipping one periodic fsync
            # only widens the machine-crash window by < fsync_every
            # records, never corrupts the log
            return
        self._since_fsync = 0

    def should_snapshot(self) -> bool:
        return (self.snapshot_every > 0
                and self.commits_since_snapshot >= self.snapshot_every)

    def rotate(self, version: int) -> None:
        """Phase 1 of a snapshot — MUST run under the PS center lock, at
        the moment the state is captured at ``version``: open a fresh
        segment so every later record lands post-snapshot. Cheap (one
        ``open``); the old segment stays on disk until the snapshot is
        durable — a crash between rotate and publish recovers from the
        previous snapshot plus BOTH segments, losing nothing. Without
        this split, commits folded while the snapshot file was being
        written would sit in a segment the truncation then deletes —
        ACKed work silently lost."""
        self.open_segment(int(version))
        self.commits_since_snapshot = 0

    def publish_snapshot(self, state: dict) -> None:
        """Phase 2 — runs OUTSIDE the center lock (O(model) serialize +
        fsync must not stall the fold path): durably write ``state`` at
        its ``num_updates`` version (tmp + fsync + atomic rename), then
        delete snapshots and segments strictly below it. Only after the
        rename is the old history unreferenced."""
        version = int(state["num_updates"])
        path = os.path.join(
            self.dir, f"{_SNAP_PREFIX}{version:012d}{_SNAP_SUFFIX}"
        )
        tmp = path + f".tmp.{os.getpid()}"
        blob = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
        with open(tmp, "wb") as f:
            f.write(struct.pack(">I", zlib.crc32(blob)))
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        for name in os.listdir(self.dir):
            base = None
            if name.startswith(_SNAP_PREFIX) and name.endswith(_SNAP_SUFFIX):
                base = name[len(_SNAP_PREFIX):-len(_SNAP_SUFFIX)]
            elif name.startswith(_SEG_PREFIX) and name.endswith(_SEG_SUFFIX):
                base = name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)]
            if base is None or not base.isdigit() or int(base) >= version:
                continue
            try:
                os.remove(os.path.join(self.dir, name))
            except OSError:
                pass

    def close(self) -> None:
        if self._fh is not None:
            try:
                self.sync()
            except (OSError, ValueError):
                pass
            self._fh.close()
            self._fh = None


# -- state <-> snapshot ------------------------------------------------------


def ps_state_dict(center: Pytree, num_updates: int,
                  pull_versions: dict, last_seq: dict,
                  ema: Pytree | None, ema_version: int,
                  fence_epoch: int) -> dict:
    """The full recoverable PS state (plain containers + numpy only, so
    the restricted unpickler can load it back)."""
    return {
        "center": center,
        "num_updates": int(num_updates),
        "pull_versions": dict(pull_versions),
        "last_seq": dict(last_seq),
        "ema": ema,
        "ema_version": int(ema_version),
        "fence_epoch": int(fence_epoch),
    }


def _load_snapshot(path: str) -> dict | None:
    try:
        with open(path, "rb") as f:
            data = f.read()
        (crc,) = struct.unpack_from(">I", data, 0)
        blob = data[4:]
        if zlib.crc32(blob) != crc:
            return None
        return _restricted_loads(blob)
    except Exception:
        return None


def replay_record(state: dict, rec_type: int, body: Any, rule,
                  num_workers: int, ema_decay: float | None) -> None:
    """Apply ONE record to ``state`` (the dict ``ps_state_dict`` shapes).

    This is the single definition of "apply an event": crash recovery
    replays disk records through it and the hot standby applies streamed
    records through it — the two consumers cannot diverge. The fold and
    EMA arithmetic are the PS's own (same ``rule.fold`` → ``tree_to_numpy``
    → fma sequence), so a replayed state is bit-identical to the
    sequential no-crash server's.
    """
    from distkeras_tpu import utils

    if rec_type == REC_COMMIT:
        worker_id, seq, pull_version, version, payload_bytes = body
        if version != state["num_updates"] + 1:
            raise ValueError(
                f"WAL sequence gap: record folds to version {version} but "
                f"state is at {state['num_updates']} (segments replayed out "
                f"of order, or mixed logs in one directory)"
            )
        # no dup-skip needed here: only DEDUPLICATED folds are ever logged
        # or streamed, so every COMMIT record is a real, distinct fold
        payload = _restricted_loads(payload_bytes)
        staleness = state["num_updates"] - pull_version
        state["center"] = utils.tree_to_numpy(
            rule.fold(state["center"], payload, num_workers, staleness)
        )
        state["num_updates"] += 1
        if seq is not None:
            state["last_seq"][worker_id] = seq
        if ema_decay is not None and state.get("ema") is not None \
                and state["num_updates"] > state["ema_version"]:
            # the snapshot's EMA may run AHEAD of its center version (the
            # EMA folds on its own lock after the commit's critical
            # section); folds at or below ema_version are already in it
            _ema_fma_inplace(state["ema"], state["center"], ema_decay)
            state["ema_version"] = state["num_updates"]
    elif rec_type == REC_PULL:
        worker_id, version = body
        state["pull_versions"][worker_id] = version
    elif rec_type == REC_DEREG:
        (worker_id,) = body
        state["last_seq"].pop(worker_id, None)
    elif rec_type == REC_EVICT:
        (worker_ids,) = body
        for wid in worker_ids:
            state["pull_versions"].pop(wid, None)
            state["last_seq"].pop(wid, None)
    elif rec_type == REC_FENCE:
        (epoch,) = body
        state["fence_epoch"] = max(state["fence_epoch"], epoch)
    # unknown types: forward-compat skip


def _ema_fma_inplace(ema: Pytree, center: Pytree, d: float) -> None:
    """e = d·e + (1−d)·c with the PS's exact operation order (multiply
    into scratch, scale e, add) so replay matches the live fold bitwise."""
    import jax

    def fma(e, c):
        s = np.multiply(np.asarray(c, dtype=e.dtype), 1.0 - d)
        e *= d
        e += s

    jax.tree.map(fma, ema, center)


def recover_ps_state(directory: str, rule, num_workers: int,
                     ema_decay: float | None,
                     template: Pytree | None = None) -> dict | None:
    """Reconstruct the PS state from ``(newest valid snapshot, wal)``.

    Returns the state dict (plus ``state["replayed"]`` = records applied
    after the snapshot) or None when the directory holds no durable state
    (fresh start). A snapshot that fails its CRC falls back to the next
    older one; WAL segments BELOW the chosen snapshot version are ignored
    (already folded into it), the segment AT it is replayed to its
    durable prefix.
    """
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    snaps = sorted(
        (n for n in names
         if n.startswith(_SNAP_PREFIX) and n.endswith(_SNAP_SUFFIX)),
        reverse=True,
    )
    segs = sorted(
        n for n in names
        if n.startswith(_SEG_PREFIX) and n.endswith(_SEG_SUFFIX)
    )
    state = None
    snap_version = 0
    for name in snaps:
        state = _load_snapshot(os.path.join(directory, name))
        if state is not None:
            snap_version = int(state["num_updates"])
            break
    if state is None:
        if not segs:
            return None
        if template is None:
            raise ValueError(
                f"WAL at {directory} has segments but no snapshot and no "
                f"template center to replay onto"
            )
        from distkeras_tpu import utils

        state = ps_state_dict(
            utils.tree_to_numpy(template), 0, {}, {},
            None, 0, 0,
        )
        if ema_decay is not None:
            import jax

            state["ema"] = jax.tree.map(np.copy, state["center"])
    replayed = 0
    for name in segs:
        base = int(name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)])
        if base < snap_version:
            continue  # pre-snapshot history, already folded in
        with open(os.path.join(directory, name), "rb") as f:
            data = f.read()
        for rec_type, body in iter_records(data):
            replay_record(state, rec_type, body, rule, num_workers, ema_decay)
            replayed += 1
    state["replayed"] = replayed
    return state
