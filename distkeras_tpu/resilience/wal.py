"""Durable PS state: write-ahead commit log, snapshots, and replay.

The reference never needed PS durability — the center lived in the Spark
driver and a driver death was a rerun. PR 4 made the *workers* restartable;
this module makes the CENTER restartable: every state-changing event on the
parameter server (deduplicated commit folds, pull-version records, clean
deregisters, evictions, fencing-epoch bumps) is appended to a write-ahead
log BEFORE the client sees an ACK, and the full state (center, EMA,
``num_updates``, per-worker pull versions, the commit-dedup table, the
fencing epoch) is periodically written as an fsync'd snapshot that
truncates the log. A restarted PS loads ``(snapshot, wal)`` and replays —
reconstructing exactly the state a never-crashed server would hold after
the same prefix of events (the bit-identical oracle the durability tests
pin).

Why full payloads and not just digests: a digest can *verify* a fold but
cannot *reproduce* it — replay must re-run ``rule.fold`` on the decoded
commit tree to land on the same bits. Each record therefore carries the
payload plus a CRC32 over the framed body; the CRC is the torn-write
detector (a crash mid-append leaves a tail record that fails its CRC and
replay stops cleanly at the last durable prefix).

Crash-consistency contract:

- Appends happen in fold order (the PS appends under its center lock).
  Durability is mode-dependent (``group_window``): mode 1 flushes per
  record before the immediate ACK and fsyncs periodically; group mode
  (>1) defers the ACK until a flusher thread has batched a window of
  commits onto ONE ``fsync`` — an ACK then implies *fsynced*, and the
  fold's critical section never waits on (or runs) any disk sync. In
  every mode the flusher bounds the durability window in SECONDS
  (``group_interval``), so pull-heavy quiet periods cannot leave
  records unsynced indefinitely.
- A commit folded in memory but torn in the log is a commit whose ACK
  never went out (append-before-ACK): the client replays it with the same
  seqno against the recovered server, whose replayed dedup table does not
  contain it — it folds exactly once. The exactly-once oracle
  (``commits == logical``) survives the crash.
- Snapshots are written to a temp name, fsync'd, atomically renamed, and
  only then do older segments/snapshots get deleted — there is never a
  moment without a recoverable (snapshot, wal) pair.

The same record stream doubles as the hot-standby replication wire: the
primary sends each appended record (prefixed by the same framing) to the
replica before ACKing, and the standby applies records through the same
``replay_record`` path recovery uses — one definition of "apply an event",
whether from disk or from the stream.
"""

from __future__ import annotations

import collections
import io
import os
import pickle
import struct
import threading
import time
import zlib
from typing import Any, Iterator

import numpy as np

Pytree = Any

# record types — pickle-bodied (the Python PS's original set)
REC_COMMIT = 1    # (worker_id, seq|None, pull_version, version, payload)
REC_PULL = 2      # (worker_id, version)
REC_DEREG = 3     # (worker_id,)          clean exit: clear dedup entry
REC_EVICT = 4     # (worker_ids,)         lease lapse: clear pulls + dedup
REC_FENCE = 5     # (epoch,)              fencing-epoch bump
# split-checksum commit (the off-lock encode, ISSUE 7): body = 32-byte
# binary prefix (worker, seq, pull_version, version, adler32(payload)) +
# the pickled payload bytes. The frame header's CRC covers ONLY the
# prefix, so the O(model) payload checksum is computed BEFORE the center
# lock and the lock's critical section appends pre-encoded chunks — it
# never hashes or copies the payload. (adler32, not crc32, for the bulk
# payload: ~3x faster in CPython and ~10x with the native SSSE3 kernel —
# on the durable hot path the hash IS the cost; its weaker mixing is fine
# for the job here, detecting torn/partial tails.) Replay semantics are
# identical to REC_COMMIT.
REC_COMMIT2 = 6
# flat native records (written by native/dkps.cpp — no pickle anywhere):
# binary little-endian bodies the C++ server can frame with memcpy.
REC_COMMIT_FLAT = 7   # prefix(worker, seq, pull_version, version, scale,
#                       adler32(payload)) + raw f32 LE payload; replay
#                       folds center += payload * f32(scale) — the exact
#                       saxpy the C++ fold ran, so replay is bit-identical
REC_PULL_FLAT = 8     # u32 worker, u64 version
REC_DEREG_FLAT = 9    # u32 worker
REC_EVICT_FLAT = 10   # u32 count + count * u32 workers
REC_FENCE_FLAT = 11   # u64 epoch
# wire-frame commit: the payload bytes are the commit's ENTIRE pickled
# request frame exactly as it crossed the socket — the server logs the
# bytes it already has instead of re-serializing the tree (a whole
# O(model) pickle pass saved per durable commit). Replay re-runs the
# live path's exact pipeline: restricted-unpickle -> ["payload"] ->
# maybe_decode -> tree_to_numpy -> rule.fold.
REC_COMMIT_WIRE = 12
# membership-directory records (distkeras_tpu/directory): the replicated
# (role, key) -> (endpoint, epoch, lease) map logs its state changes
# through the SAME record framing — pickle-bodied tuples, each carrying
# the post-apply version so replay detects gaps exactly like the PS log.
# Lease RENEWALS are deliberately NOT logged (liveness is runtime state,
# like PS heartbeats); expirations ARE (they change the map).
REC_DIR_PUT = 20       # (role, key, host, port, epoch, meta, ttl, version)
REC_DIR_DEL = 21       # (role, key, epoch, version)
REC_DIR_EXPIRE = 22    # ([(role, key), ...], version)
REC_DIR_FENCE = 23     # (epoch, version)
# training-epoch boundary marker (distkeras_tpu/deploy): logged by the PS
# when the trainer's epoch barrier completes, so downstream read replicas
# see epoch edges IN the replication stream (ordered against the folds)
# instead of guessing from fold counts. Does not mutate recoverable PS
# state beyond an advisory mark — old logs without it replay unchanged.
REC_EPOCH = 24         # (epoch,)

_HDR = struct.Struct(">BII")  # type, crc32(body or prefix), len(body)
# split-checksum prefixes (little-endian: the native writer memcpy's
# x86 fields); the trailing u32 is adler32(payload)
_CMT2 = struct.Struct("<IqQQI")    # wid, seq(-1=None), pull_v, v, adler
_CMTF = struct.Struct("<IqQQfI")   # + f32 fold scale before the adler
_PULLF = struct.Struct("<IQ")
_DEREGF = struct.Struct("<I")
_FENCEF = struct.Struct("<Q")

_SNAP_PREFIX = "snap-"
_SNAP_SUFFIX = ".dkw"
_SEG_PREFIX = "wal-"
_SEG_SUFFIX = ".log"


def _restricted_loads(data: bytes):
    """Decode a record/snapshot body with the same primitives+numpy-only
    unpickler the wire uses (networking._RestrictedUnpickler): WAL files
    live on shared filesystems, so they get the same defense the frames
    do — a tampered log can corrupt training state, not execute code."""
    from distkeras_tpu.networking import _RestrictedUnpickler

    return _RestrictedUnpickler(io.BytesIO(data)).load()


def encode_record(rec_type: int, body_obj: Any) -> bytes:
    """Frame one record: header(type, crc32, len) + pickled body."""
    body = pickle.dumps(body_obj, protocol=pickle.HIGHEST_PROTOCOL)
    return _HDR.pack(rec_type, zlib.crc32(body), len(body)) + body


def encode_commit_chunks(worker_id: int, seq: int | None, pull_version: int,
                         version: int, payload_bytes: bytes,
                         payload_sum: int,
                         rec_type: int = REC_COMMIT2) -> tuple[bytes, bytes]:
    """Frame a commit (REC_COMMIT2 / REC_COMMIT_WIRE) as
    ``(header+prefix, payload_bytes)`` chunks.

    The caller computed ``payload_sum = zlib.adler32(payload_bytes)`` OFF
    the center lock; this function is O(1) and safe to call inside the
    fold's critical section (pull_version/version are lock-determined).
    The two chunks are written back-to-back — kept separate so the append
    never copies the O(model) payload into a joined buffer.
    """
    prefix = _CMT2.pack(int(worker_id), -1 if seq is None else int(seq),
                        int(pull_version), int(version),
                        payload_sum & 0xFFFFFFFF)
    hdr = _HDR.pack(rec_type, zlib.crc32(prefix),
                    _CMT2.size + len(payload_bytes))
    return hdr + prefix, payload_bytes


def _validate_body(rec_type: int, body, crc: int) -> bool:
    """Is this frame's body intact? Split-checksum commits (types 6/7/12)
    carry the O(model) payload adler32 inside their fixed-size prefix —
    the header CRC covers only the prefix — so both halves are checked."""
    if rec_type in (REC_COMMIT2, REC_COMMIT_WIRE):
        if len(body) < _CMT2.size or zlib.crc32(body[:_CMT2.size]) != crc:
            return False
        psum = _CMT2.unpack_from(body)[4]
        return zlib.adler32(body[_CMT2.size:]) == psum
    if rec_type == REC_COMMIT_FLAT:
        if len(body) < _CMTF.size or zlib.crc32(body[:_CMTF.size]) != crc:
            return False
        psum = _CMTF.unpack_from(body)[5]
        return zlib.adler32(body[_CMTF.size:]) == psum
    return zlib.crc32(body) == crc


def _decode_body(rec_type: int, body: bytes) -> Any:
    """Decode a validated body into the replay tuple for its type."""
    if rec_type in (REC_COMMIT2, REC_COMMIT_WIRE):
        wid, seq, pull_v, v, _ = _CMT2.unpack_from(body)
        return (wid, None if seq < 0 else seq, pull_v, v,
                body[_CMT2.size:])
    if rec_type == REC_COMMIT_FLAT:
        wid, seq, pull_v, v, scale, _ = _CMTF.unpack_from(body)
        payload = np.frombuffer(body, dtype="<f4", offset=_CMTF.size)
        return (wid, None if seq < 0 else seq, pull_v, v,
                np.float32(scale), payload)
    if rec_type == REC_PULL_FLAT:
        return _PULLF.unpack(body)
    if rec_type == REC_DEREG_FLAT:
        return _DEREGF.unpack(body)
    if rec_type == REC_EVICT_FLAT:
        (count,) = struct.unpack_from("<I", body)
        return (list(struct.unpack_from(f"<{count}I", body, 4)),)
    if rec_type == REC_FENCE_FLAT:
        return _FENCEF.unpack(body)
    return _restricted_loads(body)


def durable_prefix_len(data: bytes) -> int:
    """Byte length of the valid record prefix (where a torn/corrupt tail
    starts, if any)."""
    off = 0
    n = len(data)
    while off + _HDR.size <= n:
        rec_type, crc, ln = _HDR.unpack_from(data, off)
        body_off = off + _HDR.size
        if body_off + ln > n or not _validate_body(
                rec_type, data[body_off:body_off + ln], crc):
            return off
        off = body_off + ln
    return off


def iter_records(data: bytes) -> Iterator[tuple[int, Any]]:
    """Yield (type, body) records from a segment's bytes, stopping at the
    first torn or corrupt frame (the durable prefix ends there)."""
    off = 0
    n = len(data)
    while off + _HDR.size <= n:
        rec_type, crc, ln = _HDR.unpack_from(data, off)
        body_off = off + _HDR.size
        if body_off + ln > n:
            return  # torn tail: the append died mid-write
        body = data[body_off:body_off + ln]
        if not _validate_body(rec_type, body, crc):
            return  # corrupt tail (or bit rot): stop at the durable prefix
        try:
            yield rec_type, _decode_body(rec_type, body)
        except Exception:
            return  # undecodable body: same treatment as a bad CRC
        off = body_off + ln


class CommitLog:
    """Append-only WAL + snapshot manager for one parameter server.

    Files in ``directory``:

    - ``wal-<version>.log`` — records appended since the state was at
      ``version`` (the segment's base). Exactly one live segment.
    - ``snap-<version>.dkw`` — fsync'd full-state snapshot at ``version``.

    Appends are NOT thread-safe by themselves — the PS calls them under
    its center lock, which is also what guarantees the log order equals
    the fold order (replay depends on it).

    Durability modes (``group_window``, ISSUE 7 group commit):

    - ``1`` (the PR 5 behavior): every append flushes to the OS before
      the caller ACKs (process-kill safe) and fsync runs periodically
      (``fsync_every`` records — machine-crash bound).
    - ``> 1``: **group commit** — appends stay buffered and commit
      callers block in :meth:`wait_durable` until the flusher thread has
      batched their records (up to ``group_window`` commits, released
      eagerly whenever a waiter exists) onto ONE ``fsync``. An ACK now
      implies *fsynced*, strictly stronger than mode 1, at ~1/group the
      sync cost.
    - ``0``: time-bounded async — appends stay buffered, callers never
      wait, and the flusher fsyncs at least every ``group_interval``
      seconds. The weakest mode: a crash can lose up to ``interval``
      seconds of ACKed commits (the dedup layer makes *replayed* tails
      safe, but an ACKed-and-lost commit is never replayed). For
      benchmarking the durability/latency frontier.

    In every mode the flusher thread enforces the time deadline: records
    appended by a pull-/heartbeat-heavy quiet period (which never trips
    the commit-count heuristics) are fsync'd within ``group_interval``
    seconds — the durability window is bounded in seconds, not commits.
    """

    def __init__(self, directory: str, snapshot_every: int = 100,
                 fsync_every: int = 64, group_window: int = 1,
                 group_interval: float = 0.25):
        self.dir = str(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.snapshot_every = int(snapshot_every)
        self.fsync_every = max(1, int(fsync_every))
        self.group_window = max(0, int(group_window))
        self.group_interval = float(group_interval)
        if self.group_interval <= 0:
            raise ValueError(
                f"group_interval must be positive, got {group_interval}"
            )
        self._fh = None
        self._since_fsync = 0
        self.commits_since_snapshot = 0
        self._segment_base = 0
        # -- group-commit state (all guarded by _cond's lock) --------------
        self._cond = threading.Condition()
        self._appended = 0          # records accepted (queued or written)
        self._durable = 0           # records known fsync'd
        self._commits_appended = 0  # commit records among _appended
        self._commits_durable = 0
        self._waiters = 0           # commit callers blocked in wait_durable
        self._first_pending_t: float | None = None
        self._seg_written = 0       # bytes accepted for the live segment
        self._seg_durable = 0       # bytes of it known fsync'd
        self._abandoned = False     # crash seam: wake waiters, stop syncing
        self._running = True
        # group modes queue CHUNK REFS here (bytes are immutable — the
        # fold path's "append" is an O(1) list append, no copy, no I/O);
        # the flusher drains, writes, and fsyncs. Writers (flusher /
        # sync / rotate / close) serialize on _io_lock, which appenders
        # NEVER take — the fold path cannot block behind an fsync.
        self._queue: list[tuple[bytes, ...]] = []
        self._io_lock = threading.Lock()
        # write-behind cap: with no waiters (window 0) the queue must not
        # grow past this many unsynced bytes before the flusher kicks in
        self._max_queued_bytes = 64 * 1024 * 1024
        # observability (stats() parity keys on both transports)
        self.wal_records = 0
        self.wal_fsyncs = 0
        self.wal_group_max = 0      # most commits ever released by one fsync
        # recent write+fsync durations in ms (bounded ring, appended by
        # the flusher thread only): the watchtower samples its p95 into
        # ps.wal_fsync_p95_ms — the fsync-tail alert's series. A deque
        # append is O(1) and the flusher already owns the timestamps.
        self.fsync_ms_recent: collections.deque = collections.deque(
            maxlen=256
        )
        self._flusher = threading.Thread(
            target=self._flush_loop, daemon=True,
            name="dk-wal-flusher",
        )
        self._flusher.start()

    @property
    def group_mode(self) -> bool:
        """True when commit ACKs are deferred to the group fsync."""
        return self.group_window > 1

    @property
    def durable_offset(self) -> int:
        """Bytes of the LIVE segment known fsync'd — everything past this
        offset could vanish in a machine crash (the chaos tests truncate
        here to simulate exactly that)."""
        with self._cond:
            return self._seg_durable

    # -- append side ---------------------------------------------------------

    def open_segment(self, base_version: int) -> None:
        """Open (appending) the live segment based at ``base_version``.
        An existing file (restart-in-place) is first truncated to its
        durable prefix — appending after a torn tail record would bury
        every new record behind an unreadable frame."""
        self._close_segment()
        self._segment_base = int(base_version)
        path = os.path.join(
            self.dir, f"{_SEG_PREFIX}{base_version:012d}{_SEG_SUFFIX}"
        )
        if os.path.exists(path):
            with open(path, "rb") as f:
                data = f.read()
            good = durable_prefix_len(data)
            if good != len(data):
                with open(path, "r+b") as f:
                    f.truncate(good)
        self._fh = open(path, "ab")
        with self._cond:
            self._seg_written = 0
            self._seg_durable = 0

    def append(self, record: bytes, commit: bool = False) -> int:
        """Append one pre-framed record; returns a token for
        :meth:`wait_durable`. Mode 1 writes+flushes to the OS here
        (crash-of-process safe before the immediate ACK); group modes
        only queue the immutable bytes for the flusher — O(1), no copy,
        no I/O, because this runs under the PS center lock."""
        return self.append_chunks((record,), commit=commit)

    def append_chunks(self, chunks: tuple[bytes, ...],
                      commit: bool = True) -> int:
        """Append one record supplied as pre-encoded chunks (header+prefix,
        payload) WITHOUT joining or copying them — the center lock's
        append must stay O(1) in the payload size. Same return/flush
        semantics as :meth:`append`."""
        nbytes = 0
        if self.group_window == 1:
            # PR 5 behavior: hand the bytes to the OS before the caller
            # ACKs; fsync stays periodic (maybe_fsync / the flusher's
            # time deadline)
            for chunk in chunks:
                self._fh.write(chunk)
                nbytes += len(chunk)
            self._fh.flush()
            self._since_fsync += 1
            queued = None
        else:
            for chunk in chunks:
                nbytes += len(chunk)
            queued = tuple(chunks)
        with self._cond:
            if queued is not None:
                self._queue.append(queued)
            self._appended += 1
            self.wal_records += 1
            self._seg_written += nbytes
            if commit:
                self._commits_appended += 1
            if self._first_pending_t is None:
                self._first_pending_t = time.monotonic()
            token = self._appended
            self._cond.notify_all()
        return token

    def wait_durable(self, token: int, timeout: float = 30.0) -> bool:
        """Block until record ``token`` is fsync'd (group mode's deferred
        ACK). Returns False when the log was abandoned/closed first (the
        crash seam) or the timeout lapsed — the caller's connection is
        torn either way, so there is nothing meaningful to ACK."""
        deadline = time.monotonic() + timeout
        with self._cond:
            self._waiters += 1
            self._cond.notify_all()  # an eager flusher syncs for waiters
            try:
                while (self._durable < token and self._running
                       and not self._abandoned and self._fh is not None):
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return False
                    self._cond.wait(min(left, 0.1))
                return self._durable >= token
            finally:
                self._waiters -= 1

    def maybe_fsync(self) -> None:
        """Periodic machine-crash durability — call OFF the center lock
        (every ``fsync_every`` records trips a real fsync). Mode-1 path;
        the group flusher owns fsync scheduling otherwise."""
        if not self.group_mode and self._since_fsync >= self.fsync_every:
            self.sync()

    def append_commit(self, worker_id: int, seq: int | None,
                      pull_version: int, version: int,
                      payload_bytes: bytes,
                      payload_sum: int | None = None) -> int:
        """``payload_bytes`` is the pre-pickled decoded commit tree and
        ``payload_sum`` its ``zlib.adler32`` (the checksum the reader
        validates) — BOTH computed OUTSIDE the center lock by the PS
        (the O(model) encode+hash must not ride the fold's critical
        section). This call is O(1) + the queue/buffer append. Returns
        the :meth:`wait_durable` token."""
        if payload_sum is None:
            payload_sum = zlib.adler32(payload_bytes)
        token = self.append_chunks(encode_commit_chunks(
            worker_id, seq, pull_version, version, payload_bytes,
            payload_sum,
        ))
        self.commits_since_snapshot += 1
        return token

    def append_pull(self, worker_id: int, version: int) -> None:
        self.append(encode_record(REC_PULL, (int(worker_id), int(version))))

    def append_dereg(self, worker_id: int) -> None:
        self.append(encode_record(REC_DEREG, (int(worker_id),)))

    def append_evict(self, worker_ids: list[int]) -> None:
        self.append(encode_record(REC_EVICT, ([int(w) for w in worker_ids],)))

    def append_fence(self, epoch: int) -> None:
        # the PS syncs right after releasing its lock: a fence must be
        # durable by the time the fencing caller gets its ack
        self.append(encode_record(REC_FENCE, (int(epoch),)))

    def _flush_loop(self) -> None:
        """The group-commit flusher: batch appended records onto one
        ``fsync`` and release every waiter at once. Sync triggers:

        - a waiter exists (eager — the first committer "leads" the group
          and everyone who appended meanwhile rides its fsync, the classic
          leader/follower group commit);
        - ``group_window`` commits are pending (batch cap);
        - the oldest pending record is ``group_interval`` old (the
          time-based durability bound — covers commit-free quiet periods
          in EVERY mode, including 0 and 1).
        """
        while True:
            with self._cond:
                while self._running:
                    if self._appended > self._durable and not self._abandoned:
                        pending_commits = (self._commits_appended
                                           - self._commits_durable)
                        age = (time.monotonic() - self._first_pending_t
                               if self._first_pending_t is not None else 0.0)
                        if (self._waiters > 0
                                or (self.group_mode
                                    and pending_commits >= self.group_window)
                                or (self._seg_written - self._seg_durable
                                    >= self._max_queued_bytes)
                                or age >= self.group_interval):
                            break
                        self._cond.wait(
                            max(0.001, self.group_interval - age))
                    else:
                        self._cond.wait(self.group_interval)
                if not self._running:
                    return
            if not self._drain_and_sync():
                time.sleep(0.005)  # rotation/crash race: re-evaluate

    def _drain_and_sync(self) -> bool:
        """Write every queued record to the live segment and fsync it;
        publish durability (waking deferred-ACK waiters). Writers —
        flusher, :meth:`sync`, segment close — serialize on ``_io_lock``,
        so a drained batch is always fully written and fsync'd before
        any segment swap; appenders never touch ``_io_lock``."""
        with self._io_lock:
            return self._write_queue_io_locked()

    def _write_queue_io_locked(self) -> bool:
        """The drain body — call with ``_io_lock`` held. A write/fsync
        failure ABANDONS the log (same as the C++ twin): the swapped
        batch is already out of the queue, so carrying on would let a
        later successful drain publish durability past the lost records
        — phantom-durable ACKed commits missing from the log. Abandoning
        instead means no ACK ever goes out for them and their clients
        replay against whatever IS durable."""
        with self._cond:
            if self._abandoned:
                return False
            batch = self._queue
            self._queue = []
            n = self._appended
            n_commits = self._commits_appended
            seg_bytes = self._seg_written
            fh = self._fh
        if fh is None:
            return False
        try:
            # the group-fsync span: in a stitched timeline this is the
            # flusher-thread segment a deferred-ACK commit waits on
            # (ps.wal_wait on the handler thread ends when this closes)
            from distkeras_tpu.observability import trace as _trace

            t_sync = time.perf_counter()
            with _trace.span("wal.fsync", args={"batch": len(batch)}):
                for chunks in batch:
                    for chunk in chunks:
                        fh.write(chunk)
                fh.flush()
                os.fsync(fh.fileno())
            self.fsync_ms_recent.append(
                (time.perf_counter() - t_sync) * 1e3
            )
        except (OSError, ValueError):
            # _io_lock is held, so this is not a close/rotate race — the
            # device genuinely failed the write: abandon (see docstring)
            with self._cond:
                self._abandoned = True
                self._running = False
                self._cond.notify_all()
            return False
        self._since_fsync = 0
        self._publish_durable(n, n_commits, seg_bytes)
        return True

    def sync(self) -> None:
        """Drain + flush + fsync now (fence durability, shutdown, the
        mode-1 periodic fsync) — runs OFF the center lock by design."""
        self._drain_and_sync()

    def _publish_durable(self, n: int, n_commits: int,
                         seg_bytes: int) -> None:
        with self._cond:
            if n > self._durable:
                self.wal_group_max = max(
                    self.wal_group_max, n_commits - self._commits_durable
                )
                self._durable = n
                self._commits_durable = max(self._commits_durable, n_commits)
                self._seg_durable = max(self._seg_durable, seg_bytes)
            self.wal_fsyncs += 1
            if self._durable == self._appended:
                self._first_pending_t = None
            self._cond.notify_all()

    def should_snapshot(self) -> bool:
        return (self.snapshot_every > 0
                and self.commits_since_snapshot >= self.snapshot_every)

    def rotate(self, version: int) -> None:
        """Phase 1 of a snapshot — MUST run under the PS center lock, at
        the moment the state is captured at ``version``: open a fresh
        segment so every later record lands post-snapshot. The old
        segment is flushed+fsync'd by the close (releasing any deferred
        ACKs riding it) and stays on disk until the snapshot is durable —
        a crash between rotate and publish recovers from the previous
        snapshot plus BOTH segments, losing nothing. Without this split,
        commits folded while the snapshot file was being written would
        sit in a segment the truncation then deletes — ACKed work
        silently lost."""
        self.open_segment(int(version))
        self.commits_since_snapshot = 0

    def publish_snapshot(self, state: dict) -> None:
        """Phase 2 — runs OUTSIDE the center lock (O(model) serialize +
        fsync must not stall the fold path): durably write ``state`` at
        its ``num_updates`` version (tmp + fsync + atomic rename), then
        delete snapshots and segments strictly below it. Only after the
        rename is the old history unreferenced."""
        version = int(state["num_updates"])
        path = os.path.join(
            self.dir, f"{_SNAP_PREFIX}{version:012d}{_SNAP_SUFFIX}"
        )
        tmp = path + f".tmp.{os.getpid()}"
        blob = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
        with open(tmp, "wb") as f:
            f.write(struct.pack(">I", zlib.crc32(blob)))
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        for name in os.listdir(self.dir):
            base = None
            if name.startswith(_SNAP_PREFIX) and name.endswith(_SNAP_SUFFIX):
                base = name[len(_SNAP_PREFIX):-len(_SNAP_SUFFIX)]
            elif name.startswith(_SEG_PREFIX) and name.endswith(_SEG_SUFFIX):
                base = name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)]
            if base is None or not base.isdigit() or int(base) >= version:
                continue
            try:
                os.remove(os.path.join(self.dir, name))
            except OSError:
                pass

    def _close_segment(self) -> None:
        """Drain+fsync+close the live segment (rotation path — the flusher
        keeps running). Queued records belong to THIS segment, so the
        drain must complete under ``_io_lock`` before the file swaps;
        publishing durability releases deferred ACKs riding it."""
        if self._fh is None:
            return
        with self._io_lock:
            fh = self._fh
            if fh is None:
                return
            self._write_queue_io_locked()
            try:
                fh.close()
            except (OSError, ValueError):
                pass
            self._fh = None

    def close(self) -> None:
        """Clean shutdown: stop the flusher, fsync the tail, close."""
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._flusher.is_alive() \
                and self._flusher is not threading.current_thread():
            self._flusher.join(timeout=5.0)
        self._close_segment()

    def abandon(self) -> None:
        """Crash seam: die like a SIGKILL'd process. The underlying fd is
        closed WITHOUT flushing the user-space buffer (whatever earlier
        flushes handed the OS is durable, buffered bytes are lost — and
        their commits were never ACKed, so their clients replay them) and
        every deferred-ACK waiter is woken to give up."""
        with self._cond:
            self._abandoned = True
            self._running = False
            self._queue = []  # the lost user-space buffer
            self._cond.notify_all()
        with self._io_lock:  # let an in-flight flusher write land first
            fh, self._fh = self._fh, None
            if fh is not None:
                try:
                    # repoint the descriptor at /dev/null BEFORE closing:
                    # anything still buffered in the file object (the
                    # dying process's user-space bytes) is discarded, and
                    # the close itself stays safe — a raw os.close here
                    # would leave the object's finalizer closing a
                    # recycled fd number out from under its new owner
                    null_fd = os.open(os.devnull, os.O_WRONLY)
                    try:
                        os.dup2(null_fd, fh.fileno())
                    finally:
                        os.close(null_fd)
                    fh.close()
                except (OSError, ValueError):
                    pass


# -- state <-> snapshot ------------------------------------------------------


def ps_state_dict(center: Pytree, num_updates: int,
                  pull_versions: dict, last_seq: dict,
                  ema: Pytree | None, ema_version: int,
                  fence_epoch: int,
                  prev_pull_versions: dict | None = None) -> dict:
    """The full recoverable PS state (plain containers + numpy only, so
    the restricted unpickler can load it back). ``prev_pull_versions``
    (ISSUE 10) is each worker's previous recorded pull version — the base
    a pipelined fused exchange prices its deliberately-stale commit from;
    old snapshots without the key recover with an empty map and the next
    pull record per worker rebuilds it exactly (the shift rule below)."""
    return {
        "center": center,
        "num_updates": int(num_updates),
        "pull_versions": dict(pull_versions),
        "prev_pull_versions": dict(prev_pull_versions or {}),
        "last_seq": dict(last_seq),
        "ema": ema,
        "ema_version": int(ema_version),
        "fence_epoch": int(fence_epoch),
    }


def _load_snapshot(path: str) -> dict | None:
    try:
        with open(path, "rb") as f:
            data = f.read()
        (crc,) = struct.unpack_from(">I", data, 0)
        blob = data[4:]
        if zlib.crc32(blob) != crc:
            return None
        return _restricted_loads(blob)
    except Exception:
        return None


def replay_record(state: dict, rec_type: int, body: Any, rule,
                  num_workers: int, ema_decay: float | None) -> None:
    """Apply ONE record to ``state`` (the dict ``ps_state_dict`` shapes).

    This is the single definition of "apply an event": crash recovery
    replays disk records through it and the hot standby applies streamed
    records through it — the two consumers cannot diverge. The fold and
    EMA arithmetic are the PS's own (same ``rule.fold`` → ``tree_to_numpy``
    → fma sequence), so a replayed state is bit-identical to the
    sequential no-crash server's.
    """
    from distkeras_tpu import utils

    if rec_type in (REC_COMMIT, REC_COMMIT2, REC_COMMIT_WIRE):
        worker_id, seq, pull_version, version, payload_bytes = body
        if version != state["num_updates"] + 1:
            raise ValueError(
                f"WAL sequence gap: record folds to version {version} but "
                f"state is at {state['num_updates']} (segments replayed out "
                f"of order, or mixed logs in one directory)"
            )
        if "_flat" in state:
            # a pickle commit following native flat records (transport
            # switch mid-log): materialize the flat folds into the tree
            # before tree-folding on top of them
            _finish_flat_replay(state)
        # no dup-skip needed here: only DEDUPLICATED folds are ever logged
        # or streamed, so every COMMIT record is a real, distinct fold
        payload = _restricted_loads(payload_bytes)
        if rec_type == REC_COMMIT_WIRE:
            # the logged bytes are the whole wire request frame: re-run
            # the live commit path's exact decode pipeline, so the fold
            # input (and therefore the folded center) is bit-identical
            from distkeras_tpu.parallel.compression import maybe_decode

            payload = utils.tree_to_numpy(maybe_decode(payload["payload"]))
        staleness = state["num_updates"] - pull_version
        state["center"] = utils.tree_to_numpy(
            rule.fold(state["center"], payload, num_workers, staleness)
        )
        state["num_updates"] += 1
        if seq is not None:
            state["last_seq"][worker_id] = seq
        if ema_decay is not None and state.get("ema") is not None \
                and state["num_updates"] > state["ema_version"]:
            # the snapshot's EMA may run AHEAD of its center version (the
            # EMA folds on its own lock after the commit's critical
            # section); folds at or below ema_version are already in it
            _ema_fma_inplace(state["ema"], state["center"], ema_decay)
            state["ema_version"] = state["num_updates"]
    elif rec_type == REC_COMMIT_FLAT:
        # native commit: the C++ fold was `center[i] += payload[i] * scale`
        # (one mul, one add per element, no FMA contraction on baseline
        # x86-64) on a flat f32 vector — replay runs the SAME saxpy on a
        # flat view of the state, so the recovered center is bit-identical
        # to the native server's. The record is self-contained (the fold
        # scale rides it), so replay needs no merge-rule arithmetic.
        worker_id, seq, pull_version, version, scale, payload = body
        if version != state["num_updates"] + 1:
            raise ValueError(
                f"WAL sequence gap: native record folds to version "
                f"{version} but state is at {state['num_updates']}"
            )
        flat = _flat_replay_state(state)
        if payload.shape[0] != flat["c"].shape[0]:
            raise ValueError(
                f"native WAL record carries {payload.shape[0]} floats but "
                f"the center holds {flat['c'].shape[0]}"
            )
        flat["c"] += payload * scale
        state["num_updates"] += 1
        if seq is not None:
            state["last_seq"][worker_id] = seq
        if ema_decay is not None and flat["e"] is not None:
            # dkps.cpp: e[i] = d*e[i] + (1.0f - d)*c[i], d cast to f32 —
            # mirror the f32 `1 - d` (NOT f64 `1 - d` rounded later)
            d32 = np.float32(ema_decay)
            od32 = np.float32(1.0) - d32
            flat["e"] *= d32
            flat["e"] += flat["c"] * od32
            state["ema_version"] = state["num_updates"]
    elif rec_type in (REC_PULL, REC_PULL_FLAT):
        worker_id, version = body
        # the live servers shift cur → prev on EVERY pull-version record
        # (plain pull or fused exchange); replay runs the identical rule,
        # so a recovered pipelined worker's lag pricing is bit-exact
        prev = state["pull_versions"].get(worker_id)
        if prev is not None:
            state.setdefault("prev_pull_versions", {})[worker_id] = prev
        state["pull_versions"][worker_id] = version
    elif rec_type in (REC_DEREG, REC_DEREG_FLAT):
        (worker_id,) = body
        state["last_seq"].pop(worker_id, None)
        # pull-version slots retire with the clean exit (the live
        # servers' deregister rule — see ParameterServer.deregister_worker)
        state["pull_versions"].pop(worker_id, None)
        state.get("prev_pull_versions", {}).pop(worker_id, None)
    elif rec_type in (REC_EVICT, REC_EVICT_FLAT):
        (worker_ids,) = body
        for wid in worker_ids:
            state["pull_versions"].pop(wid, None)
            state.get("prev_pull_versions", {}).pop(wid, None)
            state["last_seq"].pop(wid, None)
    elif rec_type in (REC_FENCE, REC_FENCE_FLAT):
        (epoch,) = body
        state["fence_epoch"] = max(state["fence_epoch"], epoch)
    elif rec_type == REC_EPOCH:
        # advisory training-epoch mark: stored OUTSIDE ps_state_dict's
        # fixed shape (lazily, only when present) so snapshots from
        # before the record type existed round-trip byte-identically
        (epoch,) = body
        state["epoch_mark"] = max(int(state.get("epoch_mark", -1)),
                                  int(epoch))
    # unknown types: forward-compat skip


def _flat_replay_state(state: dict) -> dict:
    """Lazy flat f32 view of the state for native-record replay: the
    center (and EMA) are flattened once on the first flat record and
    written back by :func:`_finish_flat_replay`. Mixing flat records into
    a log whose pickle commits already advanced the tree would desync the
    two views — one server type per directory, enforced here."""
    flat = state.get("_flat")
    if flat is None:
        from distkeras_tpu.native_ps import FlatSpec

        spec = FlatSpec(state["center"])
        flat = {
            "spec": spec,
            "c": spec.flatten(state["center"]),
            "e": (spec.flatten(state["ema"])
                  if state.get("ema") is not None else None),
        }
        state["_flat"] = flat
    return flat


def _finish_flat_replay(state: dict) -> None:
    flat = state.pop("_flat", None)
    if flat is None:
        return
    state["center"] = flat["spec"].unflatten(flat["c"])
    if flat["e"] is not None:
        state["ema"] = flat["spec"].unflatten(flat["e"])


def _ema_fma_inplace(ema: Pytree, center: Pytree, d: float) -> None:
    """e = d·e + (1−d)·c with the PS's exact operation order (multiply
    into scratch, scale e, add) so replay matches the live fold bitwise."""
    import jax

    def fma(e, c):
        s = np.multiply(np.asarray(c, dtype=e.dtype), 1.0 - d)
        e *= d
        e += s

    jax.tree.map(fma, ema, center)


def recover_ps_state(directory: str, rule, num_workers: int,
                     ema_decay: float | None,
                     template: Pytree | None = None) -> dict | None:
    """Reconstruct the PS state from ``(newest valid snapshot, wal)``.

    Returns the state dict (plus ``state["replayed"]`` = records applied
    after the snapshot) or None when the directory holds no durable state
    (fresh start). A snapshot that fails its CRC falls back to the next
    older one; WAL segments BELOW the chosen snapshot version are ignored
    (already folded into it), the segment AT it is replayed to its
    durable prefix.
    """
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    snaps = sorted(
        (n for n in names
         if n.startswith(_SNAP_PREFIX) and n.endswith(_SNAP_SUFFIX)),
        reverse=True,
    )
    segs = sorted(
        n for n in names
        if n.startswith(_SEG_PREFIX) and n.endswith(_SEG_SUFFIX)
    )
    state = None
    snap_version = 0
    for name in snaps:
        state = _load_snapshot(os.path.join(directory, name))
        if state is not None:
            snap_version = int(state["num_updates"])
            break
    if state is None:
        if not segs:
            return None
        if template is None:
            raise ValueError(
                f"WAL at {directory} has segments but no snapshot and no "
                f"template center to replay onto"
            )
        from distkeras_tpu import utils

        state = ps_state_dict(
            utils.tree_to_numpy(template), 0, {}, {},
            None, 0, 0,
        )
        if ema_decay is not None:
            import jax

            state["ema"] = jax.tree.map(np.copy, state["center"])
    replayed = 0
    for name in segs:
        base = int(name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)])
        if base < snap_version:
            continue  # pre-snapshot history, already folded in
        with open(os.path.join(directory, name), "rb") as f:
            data = f.read()
        for rec_type, body in iter_records(data):
            replay_record(state, rec_type, body, rule, num_workers, ema_decay)
            replayed += 1
    _finish_flat_replay(state)  # native flat folds back into the tree
    state["replayed"] = replayed
    return state


# -- offline inspection (`python -m distkeras_tpu.resilience.wal verify`) ----


_REC_NAMES = {
    REC_COMMIT: "commit", REC_COMMIT2: "commit", REC_COMMIT_FLAT: "commit",
    REC_COMMIT_WIRE: "commit",
    REC_PULL: "pull", REC_PULL_FLAT: "pull",
    REC_DEREG: "dereg", REC_DEREG_FLAT: "dereg",
    REC_EVICT: "evict", REC_EVICT_FLAT: "evict",
    REC_FENCE: "fence", REC_FENCE_FLAT: "fence",
    REC_DIR_PUT: "dir_put", REC_DIR_DEL: "dir_del",
    REC_DIR_EXPIRE: "dir_expire", REC_DIR_FENCE: "dir_fence",
    REC_EPOCH: "epoch",
}

#: record-name prefix marking a membership-directory log — ``verify``
#: flags such directories so an operator reading the aggregate report
#: can tell the coordination log from the per-shard commit logs
_DIR_REC_PREFIX = "dir_"


def verify_dir(directory: str) -> dict:
    """Walk a WAL directory's ``(snapshot, wal)`` files and report their
    health — CRC-valid prefix length, torn-tail bytes, and record-type
    counts per segment, snapshot CRC validity — WITHOUT replaying any
    state (no rule/model needed; cheap enough for CI artifacts). The
    chaos tests use this instead of ad-hoc segment parsing."""
    try:
        names = sorted(os.listdir(directory))
    except OSError as e:
        return {"dir": str(directory), "ok": False, "error": str(e),
                "snapshots": [], "segments": []}
    report: dict = {"dir": str(directory), "ok": True,
                    "snapshots": [], "segments": []}
    totals: dict[str, int] = {}
    for name in names:
        path = os.path.join(directory, name)
        if name.startswith(_SNAP_PREFIX) and name.endswith(_SNAP_SUFFIX):
            state = _load_snapshot(path)
            rec = {
                "file": name,
                "bytes": os.path.getsize(path),
                "crc_ok": state is not None,
                "version": (None if state is None
                            else int(state["num_updates"])),
            }
            report["snapshots"].append(rec)
            if state is None:
                report["ok"] = False
        elif name.startswith(_SEG_PREFIX) and name.endswith(_SEG_SUFFIX):
            with open(path, "rb") as f:
                data = f.read()
            good = durable_prefix_len(data)
            counts: dict[str, int] = {}
            for rec_type, _ in iter_records(data):
                key = _REC_NAMES.get(rec_type, f"type{rec_type}")
                counts[key] = counts.get(key, 0) + 1
                totals[key] = totals.get(key, 0) + 1
            rec = {
                "file": name,
                "base": int(name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)]),
                "bytes": len(data),
                "valid_prefix_bytes": good,
                "torn_tail_bytes": len(data) - good,
                "records": counts,
            }
            report["segments"].append(rec)
    report["record_totals"] = totals
    # a membership-directory log (distkeras_tpu/directory) walks the same
    # framing; flag it so the aggregate report names which directory under
    # a shared root is the coordination log vs a shard's commit log
    report["directory"] = any(
        k.startswith(_DIR_REC_PREFIX) for k in totals
    )
    report["torn_tail_bytes"] = sum(
        s["torn_tail_bytes"] for s in report["segments"]
    )
    # a torn tail on the LIVE (newest) segment is expected after a crash;
    # a snapshot that fails its CRC, or a torn NON-live segment, is not
    for s in report["segments"][:-1]:
        if s["torn_tail_bytes"]:
            report["ok"] = False
    return report


def _holds_wal_files(directory: str) -> bool:
    try:
        names = os.listdir(directory)
    except OSError:
        return False
    return any(
        (n.startswith(_SNAP_PREFIX) and n.endswith(_SNAP_SUFFIX))
        or (n.startswith(_SEG_PREFIX) and n.endswith(_SEG_SUFFIX))
        for n in names
    )


def find_wal_dirs(root: str) -> list[str]:
    """Every directory under ``root`` (inclusive) holding WAL/snapshot
    files, sorted — a sharded center's root fans out into per-shard
    subdirectories (``shard-00``, …) each possibly with chain-replica
    subdirectories (``chain-1``, …); see ``sharding.group``."""
    out = []
    for dirpath, dirnames, _ in os.walk(root):
        dirnames.sort()
        if _holds_wal_files(dirpath):
            out.append(dirpath)
    return sorted(out)


def verify_tree(root: str) -> dict:
    """Verify a WAL location that may be a single directory OR a sharded
    root (per-shard subdirectories, each verified like any other WAL dir,
    rolled into ONE aggregate report — the shape the chaos tests and the
    CI artifact consume). A plain directory returns ``verify_dir``'s
    report unchanged."""
    dirs = find_wal_dirs(root)
    if dirs == [root] or not dirs:
        return verify_dir(root)
    reports = []
    totals: dict[str, int] = {}
    ok = True
    for d in dirs:
        rep = verify_dir(d)
        rep["dir"] = os.path.relpath(d, root)
        reports.append(rep)
        ok = ok and rep["ok"]
        for key, n in rep.get("record_totals", {}).items():
            totals[key] = totals.get(key, 0) + n
    return {
        "dir": str(root),
        "sharded": True,
        "ok": ok,
        "dirs": reports,
        "num_wal_dirs": len(reports),
        "num_directory_dirs": sum(
            1 for r in reports if r.get("directory")
        ),
        "record_totals": totals,
        "torn_tail_bytes": sum(r["torn_tail_bytes"] for r in reports),
    }


def main(argv: list[str] | None = None) -> int:
    """CLI: ``python -m distkeras_tpu.resilience.wal verify <dir>``.

    ``<dir>`` may be one server's WAL directory or a sharded root — the
    latter prints one aggregate report over every shard (and chain
    replica) directory beneath it.
    """
    import json
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 2 or argv[0] != "verify":
        print("usage: python -m distkeras_tpu.resilience.wal verify <dir>",
              file=sys.stderr)
        return 2
    report = verify_tree(argv[1])
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0 if report["ok"] else 1


if __name__ == "__main__":  # pragma: no cover - exercised via main()
    raise SystemExit(main())
