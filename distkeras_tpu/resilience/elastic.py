"""Elastic membership: live worker join, preemption-aware drain, autoscaling.

PRs 4–5 made the PS stack survive workers *leaving* (leases + eviction,
restart-up-to-K, exactly-once dedup under churn). This module is the other
half of production elasticity — the half the classic PS literature (Li et
al., OSDI'14) treats as a first-class server feature: the pool can GROW
mid-run, and a preempted worker leaves *cleanly* instead of dying into a
restart budget.

Three pieces, all trainer-side (the servers only gained join/drain
accounting — see ``ParameterServer.join_worker`` / ``drain_worker``):

- :class:`ShardAssigner` — dynamic data-shard assignment. The fixed-pool
  loop splits the dataset into W static shards at launch; under elastic
  membership that would either starve joiners or double-feed leavers.
  Instead the epoch is a pool of window-sized **blocks** (one block = one
  ``window × batch`` training window over a seeded per-epoch permutation);
  workers lease blocks one at a time and confirm completion after the
  window's commit. A drained worker hands its unfinished blocks back; a
  joiner simply starts claiming. Every example is trained exactly once
  per epoch across any sequence of clean joins/drains — the oracle
  ``tests/test_elastic.py`` pins.

- the **live-join protocol** (driven by :class:`ElasticCoordinator`, run
  by the joining worker itself): register with the PS (``join`` wire
  action — lease admitted, ``pool_size``/``joined_workers`` counters),
  pull the current center (which initializes the joiner's pull-version
  server-side, so its first DynSGD commit is priced at the true small τ —
  never the "full history" price a version-less worker would get), start
  a FRESH commit-seqno stream (a new resilient client's epoch-based
  seqnos can never collide with any prior worker's dedup fence), and
  claim blocks from the assigner. On the sharded center the joiner's
  fan-out client runs ``verify_shard_map`` against every shard before
  its first fold, like any other worker.

- the **preemption-notice path**: ``preempt(worker_id)`` sets the
  worker's drain event and arms a deadline. The worker finishes its
  in-flight window, commits it (the ACK already implies WAL durability —
  group commit ACK⇒fsync), returns its remaining blocks to the assigner,
  sends the ``drain`` wire action (which retires its dedup seqno through
  the PR 5 bounded-table path and decrements ``pool_size``), and exits.
  A worker that misses the deadline is force-drained: its blocks are
  released on its behalf, the drain is reported with ``timeout=True``
  (the ``drain_timeouts`` counter), and the lease-eviction machinery
  remains the backstop for whatever the wedged thread does next.

- :class:`ElasticPolicy` — the trainer-side autoscaler. Grows/shrinks
  the pool against a rounds/s target, and releases **persistent
  stragglers**: a worker whose commit rate sits in the τ tail (DynSGD is
  already down-weighting its folds toward nothing) is drained so its
  data share goes back to workers whose commits still count. Scale-up
  goes through the live-join path, scale-down through the drain path.
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import Any, Callable

import numpy as np

__all__ = ["ShardAssigner", "ElasticPolicy", "ElasticCoordinator",
           "WOULD_BLOCK"]

#: Sentinel ``ShardAssigner.claim(wait=False)`` returns when every
#: remaining block is in flight (possibly with the CALLER — the pipelined
#: worker claims its next block while its previous one is still awaiting
#: its deferred exchange). The pipelined loop flushes that exchange and
#: re-claims blocking; waiting here instead would deadlock on the
#: worker's own unconfirmed block.
WOULD_BLOCK = object()


class ShardAssigner:
    """Dynamic per-epoch block pool with exactly-once accounting.

    One **block** is one training window: ``window × batch_size`` rows of
    a seeded per-epoch permutation (shuffle) or of ``arange(n_rows)``.
    Rows past the last whole block are dropped per epoch, matching the
    fixed-pool loop's drop-tail semantics (under shuffle a different tail
    is dropped each epoch).

    Thread-safety: every method is safe to call from any worker or
    coordinator thread. ``claim`` blocks while all remaining blocks are
    in flight with other workers — a drained/dead worker's release wakes
    the waiters — and returns ``None`` only when every block of every
    epoch is complete (or ``stop()`` goes true).
    """

    def __init__(self, n_rows: int, window: int, batch_size: int,
                 num_epoch: int, seed: int = 0, shuffle: bool = False,
                 start_epoch: int = 0,
                 on_epoch_complete: Callable[[int], None] | None = None):
        self.n_rows = int(n_rows)
        self.window = int(window)
        self.batch_size = int(batch_size)
        self.win_rows = self.window * self.batch_size
        self.blocks_per_epoch = self.n_rows // self.win_rows
        if self.blocks_per_epoch == 0:
            raise ValueError(
                f"dataset of {n_rows} rows too small for one window of "
                f"{self.win_rows} rows (window={window} × "
                f"batch={batch_size})"
            )
        self.seed = int(seed)
        self.shuffle = bool(shuffle)
        self.epochs = list(range(int(start_epoch), int(num_epoch)))
        self._cv = threading.Condition()
        B = self.blocks_per_epoch
        self._avail: dict[int, set[int]] = {e: set(range(B))
                                            for e in self.epochs}
        self._done: dict[int, set[int]] = {e: set() for e in self.epochs}
        self._inflight: dict[tuple[int, int], int] = {}
        self._by_worker: dict[int, set[tuple[int, int]]] = {}
        self._perms: dict[int, np.ndarray] = {}
        self._claims = 0
        self._released_blocks = 0
        self._stale_completions = 0
        #: fired (outside the lock) when the LAST block of an epoch
        #: confirms — the one membership-independent epoch boundary an
        #: elastic run has; run_async_training points it at
        #: ``ps.mark_epoch`` so the deployer's epoch-cut snapshots (and
        #: the elastic epoch-barrier checkpoint that falls out of them)
        #: exist without a fixed-pool rendezvous
        self.on_epoch_complete = on_epoch_complete

    def _perm(self, epoch: int) -> np.ndarray:
        """The epoch's row order (cached while the epoch is live). Seeded
        on (seed, epoch) only — membership changes cannot alter which
        rows belong to which block, which is what makes the exactly-once
        guarantee a *data* property, not a scheduling accident."""
        p = self._perms.get(epoch)
        if p is None:
            p = (np.random.default_rng((self.seed, epoch))
                 .permutation(self.n_rows)
                 if self.shuffle else np.arange(self.n_rows))
            self._perms[epoch] = p
        return p

    def epoch_rows(self, epoch: int) -> np.ndarray:
        """All rows the epoch trains (the first ``blocks × win_rows`` of
        its permutation) — the coverage side of the oracle."""
        return self._perm(epoch)[: self.blocks_per_epoch * self.win_rows]

    def claim(self, worker_id: int,
              stop: Callable[[], bool] | None = None, wait: bool = True):
        """Lease the next block: ``(epoch, block, row_indices)``, or
        ``None`` when all work is complete / ``stop()`` goes true.
        Earlier epochs are served first; a worker may run ahead into the
        next epoch while a peer still holds blocks of the previous one
        (hogwild epochs, like the fixed-pool loop's free-running
        workers). ``wait=False`` returns :data:`WOULD_BLOCK` instead of
        waiting when the pool is empty but blocks remain in flight — the
        pipelined worker's probe (its own deferred block may be what the
        pool is waiting on)."""
        while True:
            with self._cv:
                for e in self.epochs:
                    avail = self._avail[e]
                    if avail:
                        b = min(avail)
                        avail.remove(b)
                        self._inflight[(e, b)] = worker_id
                        self._by_worker.setdefault(worker_id, set()).add(
                            (e, b)
                        )
                        self._claims += 1
                        idx = self._perm(e)[
                            b * self.win_rows: (b + 1) * self.win_rows
                        ]
                        return e, b, idx
                if not self._inflight:
                    return None  # every block of every epoch is complete
                if not wait:
                    return WOULD_BLOCK
                # all remaining blocks are in flight with other workers —
                # a drain/death may hand some back; wait, bounded, so a
                # draining waiter can notice its stop flag
                self._cv.wait(0.05)
            if stop is not None and stop():
                return None

    def complete(self, worker_id: int, epoch: int, block: int) -> bool:
        """Confirm a block trained-and-committed. Returns False (a
        **stale completion**) when the block no longer belongs to this
        worker — it was force-released after a drain deadline and may
        already be reassigned; the caller's work stands (its commit
        folded) but the accounting belongs to the new owner."""
        key = (int(epoch), int(block))
        retired = False
        with self._cv:
            owner = self._inflight.get(key)
            if owner != worker_id:
                self._stale_completions += 1
                return False
            self._inflight.pop(key)
            self._by_worker.get(worker_id, set()).discard(key)
            self._done[epoch].add(block)
            if len(self._done[epoch]) == self.blocks_per_epoch:
                self._perms.pop(epoch, None)  # epoch retired: free the perm
                retired = True
            self._cv.notify_all()
        if retired and self.on_epoch_complete is not None:
            try:
                self.on_epoch_complete(int(epoch))
            except Exception:  # noqa: BLE001
                pass  # the mark is advisory: never fail a completion
        return True

    def release(self, worker_id: int) -> int:
        """Hand the worker's in-flight blocks back to the pool (the
        drain/death path). Returns how many went back. Idempotent."""
        n = 0
        with self._cv:
            for key in self._by_worker.pop(worker_id, set()):
                if self._inflight.get(key) == worker_id:
                    self._inflight.pop(key)
                    self._avail[key[0]].add(key[1])
                    n += 1
            self._released_blocks += n
            if n:
                self._cv.notify_all()
        return n

    def oracle(self) -> dict:
        """The exactly-once ledger: ``exactly_once`` is True iff every
        block of every epoch completed exactly once with nothing left in
        flight and no stale completions (a stale completion means a
        timeout-drained worker's window was retrained — at-least-once,
        the honest price of a missed drain deadline)."""
        with self._cv:
            total = len(self.epochs) * self.blocks_per_epoch
            done = sum(len(s) for s in self._done.values())
            return {
                "epochs": len(self.epochs),
                "blocks_per_epoch": self.blocks_per_epoch,
                "blocks_total": total,
                "blocks_done": done,
                "blocks_in_flight": len(self._inflight),
                "claims": self._claims,
                "released_blocks": self._released_blocks,
                "stale_completions": self._stale_completions,
                "exactly_once": (done == total and not self._inflight
                                 and self._stale_completions == 0),
            }


class ElasticPolicy:
    """Deterministic autoscaling decisions from progress observations.

    ``observe(now, per_worker_windows)`` is fed the pool's cumulative
    per-worker window counts; it differentiates against the previous
    observation and returns at most one action per call. Since ISSUE 13
    the rounds/s and straggler math is NOT private: differentiation is
    :func:`observability.watch.rates_from_counts` and the straggler
    verdict :func:`observability.watch.straggler_workers` — the same two
    definitions the watchtower's commit-skew alert evaluates over the
    shared ``worker.<wid>.windows`` series, and
    :meth:`observe_series` reads its rates straight off that store (the
    path the :class:`ElasticCoordinator` drives), so the autoscaler and
    the alerting can never disagree about who is slow. Actions:

    - ``("join", None)`` — total rounds/s fell below
      ``grow_margin × target`` with headroom under ``max_workers``;
    - ``("release", worker_id)`` — either the pool overshoots
      ``shrink_margin × target``, or the worker is a **persistent
      straggler**: its rate sat below ``straggler_ratio × median`` for
      ``patience`` consecutive observations. A straggler's commits are
      the DynSGD τ tail — the center is already down-weighting them
      toward nothing, so releasing the worker returns its data share to
      workers whose commits still move the model.

    ``target_rounds_per_sec=None`` disables the throughput rules and
    keeps only the straggler release. ``cooldown_s`` spaces membership
    changes so one slow observation cannot thrash the pool. Pure state
    machine over the values it is fed — no clocks, no threads — so tests
    drive it synthetically.
    """

    def __init__(self, target_rounds_per_sec: float | None = None,
                 min_workers: int = 1, max_workers: int | None = None,
                 grow_margin: float = 0.85, shrink_margin: float = 1.3,
                 straggler_ratio: float = 0.25, patience: int = 3,
                 cooldown_s: float = 2.0, window_s: float = 1.0):
        if target_rounds_per_sec is not None and target_rounds_per_sec <= 0:
            raise ValueError(
                f"target_rounds_per_sec must be positive, got "
                f"{target_rounds_per_sec}"
            )
        if min_workers < 1:
            raise ValueError(f"min_workers must be >= 1, got {min_workers}")
        if max_workers is not None and max_workers < min_workers:
            raise ValueError(
                f"max_workers ({max_workers}) < min_workers ({min_workers})"
            )
        if patience < 1:
            raise ValueError(f"patience must be >= 1, got {patience}")
        self.target = (None if target_rounds_per_sec is None
                       else float(target_rounds_per_sec))
        self.min_workers = int(min_workers)
        self.max_workers = None if max_workers is None else int(max_workers)
        self.grow_margin = float(grow_margin)
        self.shrink_margin = float(shrink_margin)
        self.straggler_ratio = float(straggler_ratio)
        self.patience = int(patience)
        self.cooldown_s = float(cooldown_s)
        # trailing-window length for the shared-timeseries observation
        # path (observe_series): long enough for >= 2 scrape samples at
        # the coordinator's poll cadence, short enough to track churn
        if window_s <= 0:
            raise ValueError(f"window_s must be positive, got {window_s}")
        self.window_s = float(window_s)
        self._last: tuple[float, dict[int, int]] | None = None
        self._lag: dict[int, int] = {}
        self._last_action_t = -float("inf")
        self.decisions: list[dict] = []

    def observe(self, now: float,
                per_worker_windows: dict[int, int]) -> list[tuple]:
        from distkeras_tpu.observability.watch import rates_from_counts

        if self._last is None:
            self._last = (float(now), dict(per_worker_windows))
            return []
        t0, prev = self._last
        self._last = (float(now), dict(per_worker_windows))
        rates = rates_from_counts(t0, prev, now, per_worker_windows)
        if not rates:
            return []
        return self._decide(now, rates)

    def observe_series(self, store, now: float,
                       window_s: float | None = None,
                       wids=None) -> list[tuple]:
        """Observe off the SHARED timeseries: per-worker rounds/s read
        from the ``worker.<wid>.windows`` counter series (the store the
        coordinator's progress sampling feeds and the watchtower's skew
        rule evaluates) over the trailing window — the single-definition
        path ``ElasticCoordinator.run`` drives. ``wids`` restricts to
        the currently-live pool (a drained worker's series lingers for
        one window; it must not be re-released)."""
        from distkeras_tpu.observability.watch import worker_rates

        if window_s is None:
            window_s = self.window_s
        rates = worker_rates(store, window_s, float(now))
        if wids is not None:
            live = set(wids)
            rates = {w: r for w, r in rates.items() if w in live}
        if not rates:
            return []
        return self._decide(now, rates)

    def _decide(self, now: float, rates: dict) -> list[tuple]:
        """The decision body, shared by both observation paths."""
        from distkeras_tpu.observability.watch import straggler_workers

        pool = len(rates)
        total = sum(rates.values())
        # straggler bookkeeping runs every observation (cooldown or not):
        # patience counts consecutive slow WINDOWS of observation
        if pool >= 2:
            _med, lagging = straggler_workers(rates,
                                              self.straggler_ratio)
            lag_set = set(lagging)
            for wid in rates:
                if wid in lag_set:
                    self._lag[wid] = self._lag.get(wid, 0) + 1
                else:
                    self._lag.pop(wid, None)
            for wid in list(self._lag):
                if wid not in rates:
                    self._lag.pop(wid)
        else:
            self._lag.clear()
        if float(now) - self._last_action_t < self.cooldown_s:
            return []
        lagged = sorted(w for w, n in self._lag.items()
                        if n >= self.patience)
        if lagged and pool > self.min_workers:
            wid = min(lagged, key=lambda w: (rates.get(w, 0.0), w))
            self._lag.pop(wid, None)
            self._last_action_t = float(now)
            self.decisions.append({"action": "release", "worker": wid,
                                   "reason": "straggler",
                                   "rate": rates.get(wid, 0.0)})
            return [("release", wid)]
        if self.target is not None:
            if total < self.grow_margin * self.target and (
                    self.max_workers is None or pool < self.max_workers):
                self._last_action_t = float(now)
                self.decisions.append({"action": "join",
                                       "reason": "under_target",
                                       "rounds_per_sec": total})
                return [("join", None)]
            if total > self.shrink_margin * self.target \
                    and pool > self.min_workers:
                wid = min(rates, key=lambda w: (rates[w], w))
                self._last_action_t = float(now)
                self.decisions.append({"action": "release", "worker": wid,
                                       "reason": "over_target",
                                       "rounds_per_sec": total})
                return [("release", wid)]
        return []


class ElasticCoordinator:
    """Trainer-side membership manager: spawns joiners, drains preempted
    workers against a deadline, runs the autoscaling policy, and carries
    the run to completion across any membership schedule.

    ``spawn(worker_id, joiner)`` (supplied by ``run_async_training``)
    builds a fully-wired worker — transport client (socket / native /
    sharded fan-out, resilient wrapping included), device binding, jitted
    window fn — and returns ``(worker, client, started_thread)``.
    ``make_drain_client(worker_id)`` builds a throwaway client for the
    force-drain RPC when the worker itself missed the deadline.
    """

    def __init__(self, assigner: ShardAssigner,
                 spawn: Callable[[int, bool], tuple],
                 make_drain_client: Callable[[int], Any] | None = None,
                 fault_plan=None, policy: ElasticPolicy | None = None,
                 drain_timeout: float = 5.0, poll_interval: float = 0.1,
                 max_pool_size: int | None = None, store=None):
        self.assigner = assigner
        # the SHARED progress timeseries (ISSUE 13): every poll samples
        # live workers' cumulative window counts into
        # ``worker.<wid>.windows``, and the policy observes rates off
        # those series — the same store/series the watchtower's
        # commit-skew rule reads when the trainer runs with watch=True
        # (pass its store in), so there is ONE definition of rounds/s.
        if store is None and policy is not None:
            from distkeras_tpu.observability.timeseries import (
                TimeSeriesStore,
            )

            store = TimeSeriesStore()
        self.store = store
        self._spawn = spawn
        self._make_drain_client = make_drain_client
        self.fault_plan = fault_plan
        self.policy = policy
        self.drain_timeout = float(drain_timeout)
        self.poll_interval = float(poll_interval)
        self.max_pool_size = (
            None if max_pool_size is None else int(max_pool_size)
        )
        self._lock = threading.Lock()
        self.workers: dict[int, Any] = {}
        self.clients: dict[int, Any] = {}
        self._threads: dict[int, threading.Thread] = {}
        self._drainers: list[threading.Thread] = []
        self._draining: set[int] = set()
        self._drained: set[int] = set()
        self.timeout_drained: set[int] = set()
        self._next_id = 0
        self.joined = 0
        self.preempted = 0
        self.drain_timeouts = 0
        self.join_log: list[dict] = []

    # -- membership ----------------------------------------------------------

    def start(self, initial_ids: list[int]) -> None:
        with self._lock:
            self._next_id = (max(initial_ids) + 1) if initial_ids else 0
        for wid in initial_ids:
            self._admit(wid, joiner=False)

    def _admit(self, worker_id: int, joiner: bool) -> None:
        worker, client, thread = self._spawn(worker_id, joiner)
        with self._lock:
            self.workers[worker_id] = worker
            self.clients[worker_id] = client
            self._threads[worker_id] = thread

    def request_join(self, reason: str = "fault_plan") -> int | None:
        """Live-join one worker (fresh id). Returns the new id, or None
        when the pool is at ``max_pool_size``."""
        with self._lock:
            # same liveness rule as _live_progress/stats: an abandoned
            # timeout-drained thread is not pool capacity — counting it
            # would block the refill its force-drain was meant to allow
            live = [w for w, t in self._threads.items()
                    if t.is_alive() and w not in self._draining
                    and w not in self.timeout_drained]
            if (self.max_pool_size is not None
                    and len(live) >= self.max_pool_size):
                return None
            wid = self._next_id
            self._next_id += 1
            self.joined += 1
            self.join_log.append({"worker": wid, "reason": reason})
        from distkeras_tpu.observability import trace as _trace

        with _trace.span("elastic.join", corr=f"w{wid}",
                         args={"reason": reason}):
            self._admit(wid, joiner=True)
        return wid

    def request_preempt(self, worker_id: int,
                        reason: str = "fault_plan") -> bool:
        """Deliver a preemption notice: the worker drains — finish the
        in-flight window, flush its commit, hand blocks back, clean
        ``drain`` deregistration — within ``drain_timeout`` seconds, or
        is force-drained (blocks released on its behalf, the drain
        reported with ``timeout=True``, lease eviction as backstop)."""
        with self._lock:
            w = self.workers.get(worker_id)
            t = self._threads.get(worker_id)
            if w is None or t is None or worker_id in self._draining \
                    or worker_id in self._drained:
                return False
            self._draining.add(worker_id)
            self.preempted += 1
        w.drain_event.set()
        drainer = threading.Thread(
            target=self._drain, args=(worker_id, reason), daemon=True,
            name=f"distkeras-drain-{worker_id}",
        )
        drainer.start()
        with self._lock:
            self._drainers.append(drainer)
        return True

    def _drain(self, worker_id: int, reason: str) -> None:
        from distkeras_tpu.observability import trace as _trace

        with _trace.span("elastic.drain", corr=f"w{worker_id}",
                         args={"reason": reason}):
            self._drain_impl(worker_id)

    def _drain_impl(self, worker_id: int) -> None:
        t = self._threads[worker_id]
        t.join(self.drain_timeout)
        timed_out = t.is_alive()
        client = self.clients.get(worker_id)
        if timed_out:
            # deadline lapsed: release the worker's shard range on its
            # behalf, close its client out from under it (tears any
            # blocked wire op, so the wedged thread dies fast), and
            # report the timeout drain on a throwaway admin client —
            # eviction remains the backstop for whatever is left
            with self._lock:
                self.timeout_drained.add(worker_id)
                self.drain_timeouts += 1
            self.assigner.release(worker_id)
            try:
                if client is not None:
                    client.close()
            except Exception:
                pass
            admin = None
            try:
                if self._make_drain_client is not None:
                    admin = self._make_drain_client(worker_id)
                    self._report_drain(admin, timeout=True)
            except Exception as e:
                warnings.warn(
                    f"force-drain of worker {worker_id} could not reach "
                    f"the PS ({type(e).__name__}: {e}); lease eviction "
                    f"will retire it", stacklevel=2,
                )
            finally:
                if admin is not None:
                    try:
                        admin.close()
                    except Exception:
                        pass
        else:
            # clean drain: the worker already released its blocks on
            # exit; report the drain on its own (now idle) client, which
            # also retires the dedup seqno via the deregister path. The
            # client stays open — the common shutdown path closes every
            # client exactly once.
            try:
                if client is not None:
                    self._report_drain(client, timeout=False)
            except Exception as e:
                # same degradation as the timeout path, named: the pool
                # gauge stays over-counted and the dedup/lease entries
                # linger until eviction retires them — never silently
                warnings.warn(
                    f"drain of worker {worker_id} could not reach the PS "
                    f"({type(e).__name__}: {e}); lease eviction will "
                    f"retire it", stacklevel=2,
                )
        with self._lock:
            self._drained.add(worker_id)
            self._draining.discard(worker_id)

    @staticmethod
    def _report_drain(client, timeout: bool) -> None:
        drain = getattr(client, "drain", None)
        if drain is not None:
            drain(timeout=timeout)
        else:  # transport without a drain channel: fall back to deregister
            dereg = getattr(client, "deregister", None)
            if dereg is not None:
                dereg()

    # -- the deterministic fault seam (called by workers per window) ---------

    def on_window(self, worker_id: int, window_index: int) -> None:
        """Worker window-boundary hook: fires the fault plan's seeded
        join/preempt events — the same (worker_id, window_index) seam as
        ``kill_at``, so elastic chaos is exactly reproducible."""
        plan = self.fault_plan
        if plan is None:
            return
        if plan.take_join(worker_id, window_index):
            self.request_join(reason="fault_plan")
        if plan.take_preempt(worker_id, window_index):
            self.request_preempt(worker_id, reason="fault_plan")

    # -- the run loop --------------------------------------------------------

    def _live_progress(self) -> dict[int, int]:
        with self._lock:
            return {
                wid: int(getattr(w, "_windows_done", 0))
                for wid, w in self.workers.items()
                if self._threads[wid].is_alive()
                and wid not in self._draining
                and wid not in self.timeout_drained
            }

    def run(self) -> None:
        """Supervise to completion: all worker threads done (abandoned
        timeout-drained threads excluded) and every drain settled."""
        while True:
            with self._lock:
                threads = dict(self._threads)
                draining = set(self._draining)
                abandoned = set(self.timeout_drained)
            alive = [wid for wid, t in threads.items()
                     if t.is_alive() and wid not in abandoned]
            if not alive and not draining:
                break
            now = time.monotonic()
            progress = (self._live_progress()
                        if self.store is not None or self.policy is not None
                        else None)
            if self.store is not None and progress:
                for wid, n in progress.items():
                    self.store.sample(f"worker.{wid}.windows", now, n,
                                      "counter")
            if self.policy is not None and progress:
                # the single-definition path: rates come off the shared
                # series, not a private differentiation
                actions = (
                    self.policy.observe_series(
                        self.store, now,
                        window_s=max(self.policy.window_s,
                                     3 * self.poll_interval),
                        wids=progress.keys())
                    if self.store is not None
                    else self.policy.observe(now, progress)
                )
                for action, wid in actions:
                    if action == "join":
                        self.request_join(reason="autoscaler")
                    elif action == "release":
                        self.request_preempt(wid, reason="autoscaler")
            time.sleep(self.poll_interval)
        with self._lock:
            drainers = list(self._drainers)
        for d in drainers:
            d.join(timeout=self.drain_timeout + 5.0)

    # -- results -------------------------------------------------------------

    def all_workers(self) -> list:
        with self._lock:
            return [self.workers[w] for w in sorted(self.workers)]

    def all_clients(self) -> list:
        with self._lock:
            return [self.clients[w] for w in sorted(self.clients)]

    def worker_error(self, worker) -> BaseException | None:
        """The worker's error, unless it was timeout-drained (we gave up
        on it — whatever its abandoned thread raised afterward is
        expected fallout, recorded in stats, not a run failure)."""
        with self._lock:
            for wid, w in self.workers.items():
                if w is worker and wid in self.timeout_drained:
                    return None
        return worker.error

    def stats(self) -> dict:
        with self._lock:
            return {
                "joined": self.joined,
                "preempted": self.preempted,
                "drain_timeouts": self.drain_timeouts,
                "pool_size_final": sum(
                    1 for wid, t in self._threads.items()
                    if t.is_alive() and wid not in self.timeout_drained
                ),
                "workers_total": len(self.workers),
                "join_log": list(self.join_log),
                "policy_decisions": (
                    list(self.policy.decisions)
                    if self.policy is not None else []
                ),
                "assigner": self.assigner.oracle(),
            }
