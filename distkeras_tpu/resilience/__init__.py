"""Resilience subsystem for the TPU-native parameter-server stack.

The reference delegated its whole fault story to Spark (RDD lineage +
task retry); this package is the rebuild's own robustness layer, built on
PR 3's decontended PS hot path:

- :mod:`~distkeras_tpu.resilience.faults` — seeded deterministic fault
  injection (:class:`FaultPlan`) for the wire and the worker threads.
- :mod:`~distkeras_tpu.resilience.heartbeat` — worker leases +
  heartbeats (:class:`WorkerRegistry`), stale-worker eviction surfaced in
  ``ps.stats()`` and fed into DynSGD staleness.
- :mod:`~distkeras_tpu.resilience.retry` — :class:`RetryPolicy`
  (exponential backoff + deterministic jitter + deadline) and
  :class:`ResilientPSClient`, a reconnecting client whose commits carry
  per-worker seqnos deduplicated server-side (exactly-once folds).
- :mod:`~distkeras_tpu.resilience.recovery` — :class:`WorkerSupervisor`,
  upgrading ``tolerate_worker_failures`` to restart-with-budget from the
  latest checkpoint snapshot + a fresh center pull; and
  :class:`PSFailoverSupervisor`, the trainer-side lease on the PRIMARY
  PS that promotes the hot standby (or restarts in place from the WAL)
  and repoints every worker's :class:`PSEndpoint` resolver.
- :mod:`~distkeras_tpu.resilience.wal` — PS durability:
  :class:`CommitLog` write-ahead log + fsync'd snapshots, crash-restart
  replay (``recover_ps_state``), and the record stream the hot standby
  applies.
- :mod:`~distkeras_tpu.resilience.elastic` — elastic membership:
  :class:`ShardAssigner` (dynamic window-block data assignment,
  exactly-once per epoch across joins/drains),
  :class:`ElasticCoordinator` (live worker join, preemption-aware
  bounded-deadline drain), and :class:`ElasticPolicy` (the rounds/s +
  τ-tail-straggler autoscaler).

Trainer-level knobs: ``retry_policy``, ``heartbeat_interval``,
``lease_timeout``, ``worker_restart_budget``, ``fault_plan``,
``ps_wal_dir``, ``ps_snapshot_every``, ``ps_standby``, ``elastic``,
``autoscale_target``, ``preempt_drain_timeout``, ``max_pool_size`` (see
``DistributedTrainer``).
"""

from distkeras_tpu.resilience.elastic import (  # noqa: F401
    ElasticCoordinator,
    ElasticPolicy,
    ShardAssigner,
)
from distkeras_tpu.resilience.faults import (  # noqa: F401
    FaultInjectedError,
    FaultPlan,
    WorkerKilled,
)
from distkeras_tpu.resilience.heartbeat import Lease, WorkerRegistry  # noqa: F401
from distkeras_tpu.resilience.recovery import (  # noqa: F401
    PSFailoverSupervisor,
    RestartBudgetExceeded,
    WorkerSupervisor,
)
from distkeras_tpu.resilience.retry import (  # noqa: F401
    PSEndpoint,
    ResilientPSClient,
    RetryDeadlineExceeded,
    RetryPolicy,
    is_retryable,
)
from distkeras_tpu.resilience.wal import (  # noqa: F401
    CommitLog,
    recover_ps_state,
)

__all__ = [
    "ElasticCoordinator",
    "ElasticPolicy",
    "ShardAssigner",
    "FaultInjectedError",
    "FaultPlan",
    "WorkerKilled",
    "Lease",
    "WorkerRegistry",
    "PSFailoverSupervisor",
    "RestartBudgetExceeded",
    "WorkerSupervisor",
    "PSEndpoint",
    "ResilientPSClient",
    "RetryDeadlineExceeded",
    "RetryPolicy",
    "is_retryable",
    "CommitLog",
    "recover_ps_state",
]
