"""Resilience subsystem for the TPU-native parameter-server stack.

The reference delegated its whole fault story to Spark (RDD lineage +
task retry); this package is the rebuild's own robustness layer, built on
PR 3's decontended PS hot path:

- :mod:`~distkeras_tpu.resilience.faults` — seeded deterministic fault
  injection (:class:`FaultPlan`) for the wire and the worker threads.
- :mod:`~distkeras_tpu.resilience.heartbeat` — worker leases +
  heartbeats (:class:`WorkerRegistry`), stale-worker eviction surfaced in
  ``ps.stats()`` and fed into DynSGD staleness.
- :mod:`~distkeras_tpu.resilience.retry` — :class:`RetryPolicy`
  (exponential backoff + deterministic jitter + deadline) and
  :class:`ResilientPSClient`, a reconnecting client whose commits carry
  per-worker seqnos deduplicated server-side (exactly-once folds).
- :mod:`~distkeras_tpu.resilience.recovery` — :class:`WorkerSupervisor`,
  upgrading ``tolerate_worker_failures`` to restart-with-budget from the
  latest checkpoint snapshot + a fresh center pull.

Trainer-level knobs: ``retry_policy``, ``heartbeat_interval``,
``lease_timeout``, ``worker_restart_budget``, ``fault_plan`` (see
``DistributedTrainer``).
"""

from distkeras_tpu.resilience.faults import (  # noqa: F401
    FaultInjectedError,
    FaultPlan,
    WorkerKilled,
)
from distkeras_tpu.resilience.heartbeat import Lease, WorkerRegistry  # noqa: F401
from distkeras_tpu.resilience.recovery import (  # noqa: F401
    RestartBudgetExceeded,
    WorkerSupervisor,
)
from distkeras_tpu.resilience.retry import (  # noqa: F401
    ResilientPSClient,
    RetryDeadlineExceeded,
    RetryPolicy,
    is_retryable,
)

__all__ = [
    "FaultInjectedError",
    "FaultPlan",
    "WorkerKilled",
    "Lease",
    "WorkerRegistry",
    "RestartBudgetExceeded",
    "WorkerSupervisor",
    "ResilientPSClient",
    "RetryDeadlineExceeded",
    "RetryPolicy",
    "is_retryable",
]
