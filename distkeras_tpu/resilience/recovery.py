"""Automatic worker recovery: restart dead hogwild workers from snapshots.

``tolerate_worker_failures`` (PR era of `workers.py`) was "ignore the
dead": survivors finish the run at reduced parallelism. This module
upgrades it to "restart the dead": a :class:`WorkerSupervisor` watches the
worker threads and, when one dies with a tolerable error, relaunches it —
up to ``max_restarts`` times per worker — from the best state available:

1. the worker's latest in-memory epoch snapshot (the same per-worker
   ``{opt, nt[, params]}`` dict the checkpoint barrier persists through
   ``AsyncCheckpointer``/``save_checkpoint``), resuming at the epoch after
   the snapshot; else
2. the newest on-disk checkpoint's entry for that worker; else
3. fresh per-worker state re-initialized from a **fresh center pull** —
   the center kept training while the worker was down, so the restart
   re-bases onto the survivors' progress instead of rewinding it.

Either way the restarted worker re-pulls the center before training
(non-elastic workers always do; elastic ones restore their own variable),
renews its heartbeat lease on the first window, and its replayed commits
start from its client's seqno stream — the server's dedup keeps
exactly-once folds across the death/restart boundary.

Checkpoint barriers don't survive a death (the dying worker aborts the
rendezvous and tolerant peers drop to checkpoint-free training — the
pre-existing semantics); a restarted worker therefore runs barrier-free
too. ``restart_delay`` inserts a cooldown before each relaunch: it
backstops crash loops and deliberately exceeds the lease timeout in chaos
tests so eviction-then-readmission is observable in ``ps.stats()``.
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import Any, Callable


class RestartBudgetExceeded(RuntimeError):
    """A supervised worker died past its ``max_restarts`` budget and the
    failure was fatal (not tolerated, or no survivors). Raised by
    ``run_async_training``; carries the worker's last error as
    ``__cause__``."""


class WorkerSupervisor:
    """Run worker threads to completion, restarting tolerable deaths.

    ``workers`` are ``AsyncWorker``-shaped objects (``error``,
    ``snapshot``, ``restore``, ``start_epoch``, ``barrier`` attributes and
    a ``train`` entry point); ``args_of(i)`` returns the positional args
    for worker ``i``'s ``train``. ``fallback_restore(i)`` supplies a
    restore dict from outside (the on-disk checkpoint) when the worker
    died before its first in-memory snapshot.
    """

    def __init__(self, workers: list, args_of: Callable[[int], tuple],
                 max_restarts: int = 0, restart_delay: float = 0.0,
                 fallback_restore: Callable[[int], dict | None] | None = None,
                 poll_interval: float = 0.05):
        self.workers = workers
        self.args_of = args_of
        self.max_restarts = int(max_restarts)
        self.restart_delay = float(restart_delay)
        self.fallback_restore = fallback_restore
        self.poll_interval = float(poll_interval)
        self.restarts = [0] * len(workers)
        self.restart_log: list[dict] = []

    def _spawn(self, i: int) -> threading.Thread:
        t = threading.Thread(
            target=self.workers[i].train, args=self.args_of(i), daemon=True,
            name=f"distkeras-worker-{i}",
        )
        t.start()
        return t

    def _relaunch(self, i: int, err: BaseException) -> threading.Thread:
        w = self.workers[i]
        self.restarts[i] += 1
        # Latest snapshot wins; else the newest on-disk checkpoint's state
        # for this worker; else None -> the worker re-initializes from a
        # fresh center pull inside _train.
        restore = w.snapshot
        source = "snapshot"
        if restore is None and self.fallback_restore is not None:
            restore = self.fallback_restore(i)
            source = "checkpoint"
        if restore is None:
            source = "center-pull"
        epoch = getattr(w, "_epoch_done", None)
        w.restore = restore
        if restore is not None and epoch is not None:
            w.start_epoch = epoch + 1
        w.error = None
        # a death broke the rendezvous for everyone; the restartee (like
        # its tolerant peers) trains on barrier-free — see module docstring
        w.barrier = None
        self.restart_log.append({
            "worker": i, "attempt": self.restarts[i], "from": source,
            "error": f"{type(err).__name__}: {err}",
        })
        warnings.warn(
            f"worker {i} died ({type(err).__name__}: {err}); restart "
            f"{self.restarts[i]}/{self.max_restarts} from {source}",
            stacklevel=2,
        )
        if self.restart_delay > 0:
            time.sleep(self.restart_delay)
        return self._spawn(i)

    def run(self) -> list[BaseException | None]:
        """Start every worker, supervise until all are done (dead workers
        past budget stay dead). Returns the final per-worker errors."""
        threads = [self._spawn(i) for i in range(len(self.workers))]
        pending = set(range(len(self.workers)))
        while pending:
            for i in sorted(pending):
                threads[i].join(timeout=self.poll_interval)
                if threads[i].is_alive():
                    continue
                err = self.workers[i].error
                if err is not None and not isinstance(err, KeyboardInterrupt) \
                        and self.restarts[i] < self.max_restarts:
                    threads[i] = self._relaunch(i, err)
                    continue
                pending.discard(i)
        return [w.error for w in self.workers]

    def stats(self) -> dict:
        return {
            "restarts": int(sum(self.restarts)),
            "restart_log": list(self.restart_log),
        }
