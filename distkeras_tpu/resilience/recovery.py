"""Automatic worker recovery: restart dead hogwild workers from snapshots.

``tolerate_worker_failures`` (PR era of `workers.py`) was "ignore the
dead": survivors finish the run at reduced parallelism. This module
upgrades it to "restart the dead": a :class:`WorkerSupervisor` watches the
worker threads and, when one dies with a tolerable error, relaunches it —
up to ``max_restarts`` times per worker — from the best state available:

1. the worker's latest in-memory epoch snapshot (the same per-worker
   ``{opt, nt[, params]}`` dict the checkpoint barrier persists through
   ``AsyncCheckpointer``/``save_checkpoint``), resuming at the epoch after
   the snapshot; else
2. the newest on-disk checkpoint's entry for that worker; else
3. fresh per-worker state re-initialized from a **fresh center pull** —
   the center kept training while the worker was down, so the restart
   re-bases onto the survivors' progress instead of rewinding it.

Either way the restarted worker re-pulls the center before training
(non-elastic workers always do; elastic ones restore their own variable),
renews its heartbeat lease on the first window, and its replayed commits
start from its client's seqno stream — the server's dedup keeps
exactly-once folds across the death/restart boundary.

Checkpoint barriers don't survive a death (the dying worker aborts the
rendezvous and tolerant peers drop to checkpoint-free training — the
pre-existing semantics); a restarted worker therefore runs barrier-free
too. ``restart_delay`` inserts a cooldown before each relaunch: it
backstops crash loops and deliberately exceeds the lease timeout in chaos
tests so eviction-then-readmission is observable in ``ps.stats()``.
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import Any, Callable


class RestartBudgetExceeded(RuntimeError):
    """A supervised worker died past its ``max_restarts`` budget and the
    failure was fatal (not tolerated, or no survivors). Raised by
    ``run_async_training``; carries the worker's last error as
    ``__cause__``."""


class PSFailoverSupervisor:
    """Trainer-side lease on the PRIMARY parameter server: ping it, and
    when its lease lapses, promote the replacement and repoint every
    worker's endpoint resolver.

    The PS watches its workers (heartbeats/leases, PR 4); this is the
    reverse direction — someone must watch the PS. A daemon thread pings
    the primary over TCP every ``ping_interval``; ``failover_timeout``
    seconds without a successful ping declares it dead and runs the
    failover, in this order (ISSUE 15 — promote, publish, THEN fence):

    1. **promote**: the hot standby (``standby.promote(epoch+1)``) if
       one was attached, else ``restart_factory()`` — a fresh
       ``SocketParameterServer`` recovering (snapshot, wal) in place;
    2. **publish** (the atomic repoint): ``resolver.update(host, port,
       epoch+1)`` writes endpoint and epoch as one lock-guarded triple,
       and the membership-directory entry (when ``publish=`` is wired)
       lands the same triple — every re-resolve from here on names the
       new primary at the new epoch;
    3. **fence** the superseded primary (best effort — usually it is
       simply dead and the connect is refused; unconfirmed fences are
       retried every tick): commits carrying its epoch are rejected
       from here on, so a zombie that wakes up cannot ACK folds into a
       history nobody serves anymore — and the worker it bounces
       re-resolves onto an already-published successor instead of
       spinning against a fenced endpoint.

    Restart-in-place shares the WAL directory with the old primary and
    therefore assumes the old process is really gone (the lease lapse is
    the evidence); a suspected-but-alive primary is what the standby +
    fencing path is for.

    Doubles as the chaos actor: when the installed ``fault_plan`` carries
    ``kill_ps_after_commits``, the supervisor crash-stops the primary
    (``_crash()`` — SIGKILL semantics, no final fsync) once its commit
    count crosses the threshold, then recovers from its own kill.
    """

    #: what this supervisor watches (subclasses rename — the directory
    #: supervisor reuses the whole machinery on its own wire surface)
    _kind = "parameter server"

    def __init__(self, resolver, primary, standby=None,
                 restart_factory: Callable[[], Any] | None = None,
                 failover_timeout: float = 2.0,
                 ping_interval: float | None = None,
                 fault_plan=None, max_failovers: int = 4,
                 publish: Callable[[str, int, int], None] | None = None):
        self.resolver = resolver
        self.active = primary
        # `standby` accepts one replica (the PR 5 hot standby) or a LIST —
        # a replication chain, head first (distkeras_tpu/sharding): each
        # failover promotes the first not-yet-promoted link, so a chain of
        # length k survives k successive primary deaths before falling
        # back to restart_factory.
        if standby is None:
            self.standbys: list = []
        elif isinstance(standby, (list, tuple)):
            self.standbys = [s for s in standby if s is not None]
        else:
            self.standbys = [standby]
        self.standby = self.standbys[0] if self.standbys else None
        self.restart_factory = restart_factory
        self.failover_timeout = float(failover_timeout)
        self.ping_interval = (
            float(ping_interval) if ping_interval is not None
            else max(self.failover_timeout / 4.0, 0.02)
        )
        self.fault_plan = fault_plan
        self.max_failovers = int(max_failovers)
        self.failovers = 0
        self.failover_log: list[dict] = []
        self.failover_latency_s = 0.0
        self.wal_replay_s = 0.0
        self.error: BaseException | None = None
        # fences that could not be CONFIRMED at failover time (the old
        # primary was unreachable — usually dead, but possibly only
        # stalled): retried every watch tick until they land, so an
        # alive-but-slow zombie gets fenced the moment it wakes instead
        # of silently absorbing its still-connected workers' commits
        # into a superseded history forever
        self._pending_fences: list[tuple[str, int, int, dict]] = []
        # Membership-directory publication (distkeras_tpu/directory,
        # ISSUE 15): ``publish(host, port, epoch)`` writes this server's
        # directory entry. Called at failover as part of the atomic
        # repoint (publish-then-fence — see _failover_impl) and on every
        # healthy ping as the entry's lease renewal, so a dead primary's
        # registration ages out while a live one never does. A publish
        # that fails (the directory itself failing over) goes on the
        # pending list and is retried each watch tick — best-effort by
        # design: the directory must never stall the PS failover it
        # exists to advertise.
        self._publish_cb = publish
        self._pending_publish: tuple[str, int, int] | None = None
        self.publishes = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._watch, daemon=True, name="distkeras-ps-supervisor"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    # -- the watch loop ------------------------------------------------------

    def _ping(self) -> dict | None:
        from distkeras_tpu.parameter_servers import ParameterServerClient

        host, port, _ = self.resolver.resolve()
        timeout = max(min(self.failover_timeout / 2.0, 1.0), 0.05)
        try:
            c = ParameterServerClient(host, port, -1,
                                      connect_timeout=timeout)
            try:
                return c.ping(timeout=timeout)
            finally:
                c._sock.close()
        except (OSError, EOFError):
            return None

    def _watch(self) -> None:
        try:
            deadline = time.monotonic() + self.failover_timeout
            while not self._stop.is_set():
                info = self._ping()
                now = time.monotonic()
                if info is not None and info.get("ok"):
                    deadline = now + self.failover_timeout
                    if self._publish_cb is not None \
                            and self._pending_publish is None:
                        # a healthy ping renews the directory lease (an
                        # identical re-publish is a renewal server-side);
                        # best-effort — a directory mid-failover must not
                        # stall this watch loop
                        try:
                            self._publish_cb(*self.resolver.resolve())
                            self.publishes += 1
                        except Exception:
                            pass
                    plan = self.fault_plan
                    if plan is not None and plan.should_kill_ps(
                            int(info.get("num_updates", 0))):
                        # chaos: crash-stop the primary in-process; the
                        # next ping round discovers the corpse
                        crash = getattr(self.active, "_crash", None)
                        if crash is not None:
                            crash()
                            plan.note_ps_kill()
                elif now >= deadline:
                    if self.failovers >= self.max_failovers:
                        raise RuntimeError(
                            f"parameter server unreachable after "
                            f"{self.failovers} failovers"
                        )
                    self._failover()
                    deadline = time.monotonic() + self.failover_timeout
                if self._pending_fences:
                    self._retry_pending_fences()
                if self._pending_publish is not None:
                    self._publish_now(*self._pending_publish)
                self._stop.wait(self.ping_interval)
        except BaseException as e:  # surfaced by run_async_training
            self.error = e

    def _try_fence(self, host: str, port: int, epoch: int) -> bool:
        from distkeras_tpu.parameter_servers import ParameterServerClient

        try:
            c = ParameterServerClient(host, port, -1, connect_timeout=0.5)
            c._sock.settimeout(1.0)
            try:
                c.fence(epoch)
                return True
            finally:
                c._sock.close()
        except (OSError, EOFError):
            return False

    def _retry_pending_fences(self) -> None:
        """Each watch tick: land any fence that could not be confirmed at
        failover time. A stalled-not-dead zombie primary gets fenced the
        moment it answers again; its workers' next commits then raise
        FencedEpochError and re-resolve to the real primary instead of
        feeding a dead history."""
        still = []
        for host, port, epoch, entry in self._pending_fences:
            if self._try_fence(host, port, epoch):
                entry["fence_confirmed"] = True
            else:
                still.append((host, port, epoch, entry))
        self._pending_fences = still

    def _failover(self) -> None:
        from distkeras_tpu.observability import trace as _trace

        with _trace.span("ps.failover"):
            self._failover_impl()

    def _publish_now(self, host: str, port: int, epoch: int) -> bool:
        """Write the directory entry (when wired); a failure parks the
        triple on the pending slot, retried each watch tick — the
        eventually-delivered half of publish-then-fence."""
        if self._publish_cb is None:
            return True
        try:
            self._publish_cb(host, int(port), int(epoch))
            self.publishes += 1
            self._pending_publish = None
            return True
        except Exception:
            self._pending_publish = (host, int(port), int(epoch))
            return False

    def _failover_impl(self) -> None:
        t0 = time.monotonic()
        old_host, old_port, old_epoch = self.resolver.resolve()
        epoch = old_epoch + 1
        # 1. promote: the first LIVE not-yet-promoted link of the chain.
        # A crashed/stopped link is skipped, not promoted — promoting a
        # corpse would burn every worker's retry deadline behind a closed
        # listener before the NEXT failover finds the real successor.
        # (A dead middle link also means its downstream tail stopped
        # receiving records at its death — the primary drops the broken
        # stream and keeps ACKing, the PR 5 degrade semantics — so a
        # later promotion of that tail recovers only the folds it saw;
        # the chain guards against successive HEAD deaths.)
        nxt = next(
            (s for s in self.standbys
             if not s.promoted_ and not getattr(s, "crashed_", False)
             and getattr(s, "_running", True)),
            None,
        )
        if nxt is not None:
            nxt.promote(epoch)
            new = nxt
            via = "standby"
        elif self.restart_factory is not None:
            new = self.restart_factory()
            new.fence(epoch)
            self.wal_replay_s += float(getattr(new, "wal_replay_s", 0.0))
            via = "restart"
        else:
            raise RuntimeError(
                f"primary {self._kind} died with no standby and no "
                f"restart factory (set ps_standby=True or ps_wal_dir)"
            )
        # 2. PUBLISH-THEN-FENCE (ISSUE 15): the epoch bump is atomic
        # with the repoint. resolver.update writes (host, port, epoch)
        # as ONE lock-guarded triple — no reader ever observes the new
        # endpoint at the old epoch or the old endpoint at the new one —
        # and the membership-directory publication (when wired) lands
        # the same triple before any fence is attempted. Ordering
        # matters: a worker the fence bounces off the old primary
        # re-resolves IMMEDIATELY, so the system of record must already
        # name the promoted primary when the first FencedEpochError
        # lands — the old order (fence first) left re-resolvers pinned
        # to a fenced endpoint for the whole promotion window, and a
        # slow worker could still commit to an unfenced old primary
        # AFTER a fast worker had moved on with nothing published to
        # arbitrate. With the publish first, any commit the new primary
        # accepts is at epoch e+1 and every re-resolve — resolver or
        # directory — yields e+1, so the old history can only ever
        # absorb commits from clients that never re-resolved, and the
        # fence (issued right here, retried until confirmed) closes
        # that door too.
        self.resolver.update(new.host, new.port, epoch)
        self.active = new
        published = self._publish_now(new.host, new.port, epoch)
        # 3. fence the superseded history (best effort NOW: it is
        # usually a corpse and the connect is refused instantly; an
        # unconfirmed fence goes on the retry list — see _pending_fences)
        fence_confirmed = self._try_fence(old_host, old_port, epoch)
        latency = time.monotonic() - t0
        self.failovers += 1
        self.failover_latency_s += latency
        entry = {
            "via": via, "epoch": epoch, "latency_s": round(latency, 4),
            "wal_replay_s": round(
                float(getattr(new, "wal_replay_s", 0.0)), 4
            ),
            "fence_confirmed": fence_confirmed,
            "published": published,
        }
        self.failover_log.append(entry)
        if not fence_confirmed:
            self._pending_fences.append((old_host, old_port, epoch, entry))
        warnings.warn(
            f"{self._kind} failed over via {via} to "
            f"{new.host}:{new.port} (epoch {epoch}, "
            f"{latency * 1e3:.0f} ms)",
            stacklevel=2,
        )

    def stats(self) -> dict:
        return {
            "failovers": self.failovers,
            "failover_latency_s": round(self.failover_latency_s, 4),
            "wal_replay_s": round(self.wal_replay_s, 4),
            "publishes": self.publishes,
            "failover_log": list(self.failover_log),
        }


class DirectoryFailoverSupervisor(PSFailoverSupervisor):
    """The same lease-watch/promote/repoint machinery pointed at a
    :class:`~distkeras_tpu.directory.DirectoryServer`: the directory
    speaks the PS admin surface (``ping`` / ``fence`` / promotion on
    its standby), so watching the watcher costs one subclass and zero
    new protocol. Clients need no repoint call at all — they re-probe
    the seed list and prefer the highest fence epoch, which the
    promotion just bumped."""

    _kind = "membership directory"


class WorkerSupervisor:
    """Run worker threads to completion, restarting tolerable deaths.

    ``workers`` are ``AsyncWorker``-shaped objects (``error``,
    ``snapshot``, ``restore``, ``start_epoch``, ``barrier`` attributes and
    a ``train`` entry point); ``args_of(i)`` returns the positional args
    for worker ``i``'s ``train``. ``fallback_restore(i)`` supplies a
    restore dict from outside (the on-disk checkpoint) when the worker
    died before its first in-memory snapshot.
    """

    def __init__(self, workers: list, args_of: Callable[[int], tuple],
                 max_restarts: int = 0, restart_delay: float = 0.0,
                 fallback_restore: Callable[[int], dict | None] | None = None,
                 poll_interval: float = 0.05):
        self.workers = workers
        self.args_of = args_of
        self.max_restarts = int(max_restarts)
        self.restart_delay = float(restart_delay)
        self.fallback_restore = fallback_restore
        self.poll_interval = float(poll_interval)
        self.restarts = [0] * len(workers)
        self.restart_log: list[dict] = []

    def _spawn(self, i: int) -> threading.Thread:
        t = threading.Thread(
            target=self.workers[i].train, args=self.args_of(i), daemon=True,
            name=f"distkeras-worker-{i}",
        )
        t.start()
        return t

    def _relaunch(self, i: int, err: BaseException) -> threading.Thread:
        w = self.workers[i]
        self.restarts[i] += 1
        # Latest snapshot wins; else the newest on-disk checkpoint's state
        # for this worker; else None -> the worker re-initializes from a
        # fresh center pull inside _train.
        restore = w.snapshot
        source = "snapshot"
        if restore is None and self.fallback_restore is not None:
            restore = self.fallback_restore(i)
            source = "checkpoint"
        if restore is None:
            source = "center-pull"
        epoch = getattr(w, "_epoch_done", None)
        w.restore = restore
        if restore is not None and epoch is not None:
            w.start_epoch = epoch + 1
        w.error = None
        # a death broke the rendezvous for everyone; the restartee (like
        # its tolerant peers) trains on barrier-free — see module docstring
        w.barrier = None
        self.restart_log.append({
            "worker": i, "attempt": self.restarts[i], "from": source,
            "error": f"{type(err).__name__}: {err}",
        })
        warnings.warn(
            f"worker {i} died ({type(err).__name__}: {err}); restart "
            f"{self.restarts[i]}/{self.max_restarts} from {source}",
            stacklevel=2,
        )
        if self.restart_delay > 0:
            time.sleep(self.restart_delay)
        return self._spawn(i)

    def run(self) -> list[BaseException | None]:
        """Start every worker, supervise until all are done (dead workers
        past budget stay dead). Returns the final per-worker errors."""
        threads = [self._spawn(i) for i in range(len(self.workers))]
        pending = set(range(len(self.workers)))
        while pending:
            for i in sorted(pending):
                threads[i].join(timeout=self.poll_interval)
                if threads[i].is_alive():
                    continue
                err = self.workers[i].error
                if err is not None and not isinstance(err, KeyboardInterrupt) \
                        and self.restarts[i] < self.max_restarts:
                    threads[i] = self._relaunch(i, err)
                    continue
                pending.discard(i)
        return [w.error for w in self.workers]

    def stats(self) -> dict:
        return {
            "restarts": int(sum(self.restarts)),
            "restart_log": list(self.restart_log),
        }
