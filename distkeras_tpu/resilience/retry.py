"""Retry policy and the reconnecting, commit-deduplicated PS client.

The reference's answer to a dropped socket was Spark re-running the whole
task (reference ``distkeras/workers.py`` placement inside
``mapPartitionsWithIndex``); this port's PS path previously had NO answer —
one torn connection killed the worker thread. This module is the answer:

- :class:`RetryPolicy` — exponential backoff with deterministic seeded
  jitter and a wall-clock deadline, plus the retryable/fatal triage
  (``ProtocolError.retryable`` wins; plain connection/socket errors are
  retryable; everything else — assertion failures, shape errors — is a
  bug, not weather, and propagates immediately).
- :class:`ResilientPSClient` — wraps any transport client factory
  (socket, native, in-process) with reconnect-and-retry on pull/commit.
  Every commit carries a per-worker **sequence number**; the server folds
  a given (worker, seq) at most once, so the classic lost-ACK replay (the
  server folded, the reply died, the client retries) is deduplicated
  server-side instead of double-folded into the center — the oracle the
  chaos tests pin.

Heartbeats piggyback on the training loop (``maybe_heartbeat`` at window
boundaries) rather than running on their own thread: no background thread
to leak, no second connection to wedge, and liveness tracks the thing that
actually matters — the worker making progress.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Callable

import numpy as np

from distkeras_tpu.networking import FencedEpochError, ProtocolError
from distkeras_tpu.observability import trace as _trace

Pytree = Any


class RetryDeadlineExceeded(ConnectionError):
    """Retries exhausted (attempt budget or wall-clock deadline); carries
    the last underlying failure as ``__cause__``."""


def is_retryable(exc: BaseException) -> bool:
    """Transient transport weather vs a real bug.

    The failover triage, explicitly:

    - ``ConnectionRefusedError`` (ECONNREFUSED) and mid-handshake EOF ARE
      retryable: they are exactly what a client sees in the window
      between a primary dying and its replacement answering — backing
      off and re-resolving is the correct move, not dying.
    - ``ProtocolError`` carries its own verdict (an oversized frame will
      be oversized on every retry; a mid-frame close is weather).
    - ``FencedEpochError`` is a ProtocolError with ``retryable=False``:
      an epoch mismatch is deterministic against the same server. (The
      resilient client makes ONE exception — when its endpoint resolver
      has already moved to a newer epoch, the reconnect adopts it and
      the retry is legitimate; see ``ResilientPSClient._classify``.)
    - other connection/socket-level failures are retryable; everything
      else (shape errors, assertions) is a bug and propagates.
    """
    if isinstance(exc, ProtocolError):
        return exc.retryable
    return isinstance(exc, (ConnectionError, socket.timeout, BrokenPipeError,
                            EOFError, OSError))


class PSEndpoint:
    """Thread-safe record of where the CURRENT primary lives — host,
    port, and fencing epoch — shared by every worker's client factory
    and updated exactly once per failover by the trainer-side
    :class:`~distkeras_tpu.resilience.recovery.PSFailoverSupervisor`.
    Reconnecting clients read it at connect time, so a reconnect after a
    promotion lands on the new primary carrying the new epoch with no
    per-worker coordination."""

    def __init__(self, host: str, port: int, epoch: int = 0):
        self._lock = threading.Lock()
        self._host = host
        self._port = int(port)
        self._epoch = int(epoch)
        self.updates = 0

    def resolve(self) -> tuple[str, int, int]:
        with self._lock:
            return self._host, self._port, self._epoch

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def update(self, host: str, port: int, epoch: int) -> None:
        with self._lock:
            self._host = host
            self._port = int(port)
            self._epoch = int(epoch)
            self.updates += 1


class RetryPolicy:
    """Exponential backoff + deterministic jitter + deadline.

    Delay for attempt k (0-based) is ``base_delay * 2**k``, capped at
    ``max_delay``, each scaled by a seeded jitter factor drawn uniformly
    from ``[1 - jitter, 1]`` — full determinism given the seed, and
    jitter-down-only so the deadline math stays a guarantee. Retrying
    stops when ``max_attempts`` tries failed or the next sleep would land
    past ``deadline`` seconds from the first attempt.
    """

    def __init__(self, max_attempts: int = 6, base_delay: float = 0.05,
                 max_delay: float = 2.0, deadline: float = 60.0,
                 jitter: float = 0.5, seed: int = 0):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.deadline = float(deadline)
        self.jitter = float(jitter)
        self.seed = int(seed)

    def delays(self, salt: int = 0) -> "_DelaySequence":
        """A fresh deterministic delay sequence (one per retried call).
        ``salt`` decorrelates sequences that share a policy — without it,
        W workers backing off after one server death would retry in
        lockstep, preserving exactly the thundering herd jitter exists to
        break. Determinism holds per (seed, salt)."""
        return _DelaySequence(self, salt)

    def run(self, fn: Callable[[], Any], on_retry=None,
            clock=time.monotonic, sleep=time.sleep, salt: int = 0,
            classify: Callable[[BaseException], bool] | None = None) -> Any:
        """Call ``fn`` under this policy. ``on_retry(attempt, exc)`` fires
        before each re-attempt (the client uses it to reconnect and
        count). Non-retryable failures propagate untouched. ``classify``
        overrides the default :func:`is_retryable` triage (the resilient
        client widens it across failovers)."""
        triage = is_retryable if classify is None else classify
        t0 = clock()
        seq = self.delays(salt)
        attempt = 0
        while True:
            try:
                return fn()
            except BaseException as e:
                attempt += 1
                if not triage(e):
                    raise
                if attempt >= self.max_attempts:
                    raise RetryDeadlineExceeded(
                        f"gave up after {attempt} attempts: {e}"
                    ) from e
                delay = seq.next_delay()
                if clock() - t0 + delay > self.deadline:
                    raise RetryDeadlineExceeded(
                        f"deadline of {self.deadline}s exceeded after "
                        f"{attempt} attempts: {e}"
                    ) from e
                if on_retry is not None:
                    on_retry(attempt, e)
                sleep(delay)


class _DelaySequence:
    """Deterministic jittered exponential-backoff delays for ONE call."""

    def __init__(self, policy: RetryPolicy, salt: int = 0):
        self._policy = policy
        self._rng = np.random.Generator(
            np.random.Philox([policy.seed, salt])
        )
        self._k = 0

    def next_delay(self) -> float:
        p = self._policy
        raw = min(p.base_delay * (2.0 ** self._k), p.max_delay)
        self._k += 1
        factor = 1.0 - p.jitter * float(self._rng.random())
        return raw * factor


class ResilientPSClient:
    """Reconnecting wrapper with seqno'd commits and piggyback heartbeats.

    ``make_client`` builds a fresh transport client (``pull`` / ``commit``
    / ``close``, optionally ``heartbeat``); the wrapper rebuilds it on a
    retryable failure and replays the op. A replayed commit re-sends the
    SAME sequence number, so the server's per-worker dedup keeps the fold
    exactly-once even when the original commit landed and only its ACK
    died. Exposes the same call surface the workers already use, so it
    drops into ``run_async_training`` transparently.
    """

    def __init__(self, make_client: Callable[[], Any], worker_id: int,
                 policy: RetryPolicy | None = None,
                 heartbeat_interval: float | None = None,
                 resolver: PSEndpoint | None = None):
        self._make_client = make_client
        self.worker_id = int(worker_id)
        self.policy = policy if policy is not None else RetryPolicy()
        self.heartbeat_interval = heartbeat_interval
        # Failover awareness: `resolver` names the current primary; the
        # factory is expected to read it, so every reconnect re-resolves
        # the endpoint and adopts the current fencing epoch. With a
        # resolver, a FencedEpochError is retried IFF the resolver has
        # moved past the epoch this client was using (the fence names a
        # failover we haven't caught up with); without one, fenced is
        # fatal — there is no newer endpoint to move to.
        self.resolver = resolver
        self._client = make_client()
        self.seq = 0           # logical commits CONFIRMED by this client
        self._wire_seq = 0     # seqnos issued (incl. abandoned commits)
        # Wire seqnos are epoch + seq: the epoch (wall-clock ns at client
        # birth) makes any new client's seqnos larger than any previous
        # client's for the same worker id — a fresh run against a
        # LONG-LIVED external PS must not have its seq 1..N silently
        # swallowed by the server's dedup fence from the previous run.
        # Dedup only needs per-worker monotonicity, not determinism.
        self._seq_epoch = time.time_ns()
        self.retries = 0       # cumulative reconnect-and-retry count
        self.reconnects = 0
        self._calls = 0        # jitter salt: decorrelates backoff per call
        self._timeout: float | None = None  # sticky across reconnects
        self._next_hb = 0.0    # piggyback rate limiter (monotonic)

    # -- plumbing ------------------------------------------------------------

    def _apply_timeout(self, client) -> None:
        if self._timeout is None:
            return
        if hasattr(client, "set_timeout"):
            client.set_timeout(self._timeout)
        elif hasattr(client, "_sock"):
            client._sock.settimeout(self._timeout)

    def _reconnect(self, attempt: int, exc: BaseException) -> None:
        self.retries += 1
        try:
            self._client.close()
        except Exception:
            pass
        refresh = getattr(self.resolver, "refresh", None)
        if refresh is not None:
            # directory-backed resolver (distkeras_tpu/directory): a
            # connect failure or FencedEpochError re-resolves through
            # the directory before the factory rebuilds — the repoint
            # path for readers with no hand-wired supervisor. Best
            # effort: a directory mid-failover just leaves the cached
            # endpoint for this attempt and the next retry asks again.
            try:
                refresh()
            except Exception:
                pass
        try:
            self._client = self._make_client()
            self.reconnects += 1
            # the bound must survive the swap: transports default to
            # block-forever, which would defeat a caller's deadline
            self._apply_timeout(self._client)
        except Exception:
            # server still down: keep the dead client; the next retry's
            # op fails fast and lands back here after one more backoff
            pass

    def _classify(self, exc: BaseException) -> bool:
        if isinstance(exc, FencedEpochError) and self.resolver is not None:
            # A fence names a failover; with a resolver every reconnect
            # re-resolves and adopts the CURRENT epoch, so retrying is
            # how this client catches up. Deliberately retryable even
            # when the resolver hasn't advanced yet — promotion updates
            # it moments after the fence lands, and racing that window
            # with a fatal would kill workers the failover was built to
            # save. A resolver that never advances ends the loop at the
            # retry deadline instead. Without a resolver there is no
            # newer endpoint to move to: fenced stays fatal.
            return True
        return is_retryable(exc)

    def _run(self, fn: Callable[[], Any]) -> Any:
        self._calls += 1
        salt = (self.worker_id << 32) ^ self._calls
        return self.policy.run(fn, on_retry=self._reconnect, salt=salt,
                               classify=self._classify)

    # -- the worker-facing surface -------------------------------------------

    def pull(self, worker_id: int | None = None) -> Pytree:
        return self._run(lambda: self._client.pull())

    def commit(self, worker_id: int | None, payload: Pytree) -> None:
        # ONE seqno per logical commit, assigned before the first attempt;
        # every replay re-sends it, so the server folds it at most once.
        # `seq` counts only CONFIRMED commits (an ack, fresh or dup, came
        # back): a commit abandoned at the retry deadline must not inflate
        # the exactly-once oracle's logical count. The one residual
        # ambiguity is inherent to at-least-once delivery: an abandoned
        # commit whose very first attempt folded server-side before the
        # ack died leaves commits == logical + 1 — possible only in runs
        # that lost a worker mid-commit, which the oracle's consumers
        # (chaos tests, --chaos bench) don't tolerate silently anyway.
        self._wire_seq += 1
        seq = self._seq_epoch + self._wire_seq
        if _trace.enabled():
            # the seqno IS the wire-carried correlation id: stamp it on
            # this thread so the worker-side exchange span and the
            # server-side fold/WAL spans (Python frame corr, or the
            # native ring's (wid, seq)) close under one id
            _trace.set_corr(f"w{self.worker_id}:s{seq}")
        self._run(lambda: self._client.commit(self.worker_id, payload,
                                              seq=seq))
        self.seq += 1

    def exchange(self, worker_id: int | None, payload: Pytree,
                 lag: bool = False) -> Pytree:
        """Fused commit + pull under the retry policy (ISSUE 10): ONE
        seqno covers the whole exchange — a lost-ACK replay re-sends the
        same seq, the server's dedup skips the re-fold but still answers
        with a fresh center (the pull half retries like any pull), so the
        fused action is exactly-once for the fold and at-least-once for
        the read, which is precisely the ``commit(); pull()`` contract.
        Transports without a fused channel fall back to the 2-RTT pair
        inside one retried op (a replayed pair dedups its commit)."""
        self._wire_seq += 1
        seq = self._seq_epoch + self._wire_seq
        if _trace.enabled():
            _trace.set_corr(f"w{self.worker_id}:s{seq}")  # see commit()

        def op():
            inner = self._client
            ex = getattr(inner, "exchange", None)
            if ex is not None:
                return ex(self.worker_id, payload, seq=seq, lag=lag)
            inner.commit(self.worker_id, payload, seq=seq)
            return inner.pull()

        out = self._run(op)
        self.seq += 1
        return out

    def heartbeat(self, retries: int | None = None) -> None:
        """Renew this worker's lease now (reporting cumulative retries)."""
        n = self.retries if retries is None else int(retries)
        self._run(lambda: self._client.heartbeat(retries=n))

    def maybe_heartbeat(self) -> bool:
        """Piggyback hook for the training loop: renew at most once per
        ``heartbeat_interval`` (no-op when the interval is None). Returns
        whether a heartbeat was sent. Never raises on transport failure —
        liveness reporting must not kill a worker the lease would merely
        have expired."""
        if self.heartbeat_interval is None:
            return False
        now = time.monotonic()
        if now < self._next_hb:
            return False
        self._next_hb = now + float(self.heartbeat_interval)
        try:
            self.heartbeat()
        except Exception:
            return False
        return True

    def join(self) -> dict | None:
        """Elastic live-join admission, under the retry policy (a join
        racing a shard failover reconnects and re-registers). Returns
        the server's admission record, or None when the transport has no
        join channel (plain legacy servers: the lease then starts with
        the first heartbeat instead)."""
        def op():
            inner = self._client
            join = getattr(inner, "join", None)
            return None if join is None else join()

        return self._run(op)

    def drain(self, timeout: bool = False) -> None:
        """Preemption drain (clean deregister + the server's elastic
        counters), under the retry policy. Falls back to a plain
        deregister on transports without a drain channel."""
        def op():
            inner = self._client
            drain = getattr(inner, "drain", None)
            if drain is not None:
                return drain(timeout=timeout)
            dereg = getattr(inner, "deregister", None)
            if dereg is not None:
                dereg()

        self._run(op)

    def shard_map(self) -> dict | None:
        """Forward the shard-map handshake to the wrapped transport
        client (under the retry policy). Without this, a sharded center's
        mis-wiring guard would be silently skipped on exactly the
        resilient path supervised sharded runs always use — `sharding.
        client.verify_shard_map` treats a client with no handshake
        surface as unsharded/legacy. Returns None when the inner
        transport has no shard channel at all."""
        def op():
            # re-resolve per attempt: a retry's reconnect swaps _client
            inner = self._client
            probe = (getattr(inner, "shard_map", None)
                     or getattr(inner, "shard_info", None))
            return None if probe is None else probe()

        return self._run(op)

    def set_timeout(self, seconds: float | None) -> None:
        """Bound the inner client's round-trips (transport-appropriate);
        sticky — re-applied to every replacement client a reconnect
        builds, so the bound survives retries."""
        self._timeout = seconds
        self._apply_timeout(self._client)

    def close(self) -> None:
        try:
            if hasattr(self._client, "deregister"):
                self._client.deregister()
        except Exception:
            pass
        self._client.close()
