"""Deterministic fault injection for the PS wire and hogwild workers.

The original dist-keras never needed a chaos harness of its own — Spark's
task retry WAS the fault story, and faults were whatever the cluster did to
you. The TPU-native PS stack owns its transport, so it owns its chaos too:
:class:`FaultPlan` is a seeded plan of wire faults (drops, delays,
op-count partitions) plus kill-at-window worker faults, installed behind
the ``networking._fault_hook`` seam and the ``AsyncWorker`` window loop.
Tests and ``bench.py --chaos`` drive the same plan, so the chaos an
integration test proves survivable is the chaos the benchmark measures.

Determinism: every wire-fault decision comes from one ``Philox``-seeded
generator consumed under a lock in call order, and worker kills key on
``(worker_id, window_index)`` — no wall clock anywhere. Two runs with the
same seed and the same per-thread call sequences draw the same faults;
kill faults are exactly reproducible regardless of interleaving.

A drop raises :class:`FaultInjectedError` — a ``ConnectionError`` (and
``ProtocolError``) subclass, so the server's handler paths and the client
retry layer treat it exactly like a real torn connection. ``max_faults``
bounds total injected wire faults so a chaotic run always drains to
completion (the chaos-test convergence gate relies on this).
"""

from __future__ import annotations

import threading
import time
from typing import Any

import numpy as np

from distkeras_tpu import networking
from distkeras_tpu.networking import ProtocolError


class FaultInjectedError(ProtocolError):
    """A fault-plan drop: looks like a torn connection to every consumer
    (retryable by policy, connection-dropping for server handlers)."""

    def __init__(self, message: str):
        super().__init__(message, retryable=True)


class WorkerKilled(RuntimeError):
    """A fault-plan worker kill (crash-at-window-N): the supervisor treats
    it like any other worker death — restart budget permitting."""


class FaultPlan:
    """A seeded, deterministic plan of faults to inject into one run.

    Wire faults (consulted by ``networking.send_data``/``recv_data`` while
    installed):

    - ``drop_send`` / ``drop_recv``: per-op probability of raising
      :class:`FaultInjectedError` instead of performing the op. A recv
      drop is the nasty one — the peer already acted on the request, so a
      naive client retry would double-apply it (the commit-seqno dedup in
      the PS exists exactly for this).
    - ``delay`` / ``delay_s``: per-op probability of sleeping ``delay_s``
      before the op (slow-link / GC-pause stand-in).
    - ``partition_after`` / ``partition_ops``: after ``partition_after``
      wire ops, the next ``partition_ops`` ops all drop — a deterministic
      network partition window keyed on op count, not wall time.

    Worker faults (consulted by ``AsyncWorker`` at each window):

    - ``kill_at``: ``{worker_id: window_index}`` — the worker raises
      :class:`WorkerKilled` when it reaches that window (once; a
      restarted worker passing the same index survives).
    - ``straggle``: ``{worker_id: seconds}`` — the worker sleeps that
      long at EVERY window boundary: a deterministic persistent
      straggler (slow host, thermal throttle, noisy neighbor stand-in).
      This is the fault the watchtower's commit-skew alert and the
      autoscaler's τ-tail release exist for — same seam as ``kill_at``,
      no randomness at all.

    Elastic-membership faults (consulted by the ``ElasticCoordinator`` —
    resilience/elastic.py — through the worker window loop, so they ride
    the same deterministic (worker_id, window_index) seam as ``kill_at``):

    - ``join_worker_at_window``: ``{observer_worker_id: window_index}`` —
      at the observer's first window boundary AT OR AFTER that index,
      ONE new worker live-joins the pool (fresh id, live-join
      handshake). Fires once per entry.
    - ``preempt_worker_at_window``: ``{victim_worker_id: window_index}``
      — at the victim's first window boundary at or after that index it
      receives a preemption notice and starts a bounded-deadline drain.
      Fires once per entry.

    Parameter-server faults (consulted by the trainer-side
    ``PSFailoverSupervisor`` — resilience/recovery.py):

    - ``kill_ps_after_commits``: crash-stop the PRIMARY parameter server
      (``_crash()``: connections torn, no final fsync) once its applied
      commit count crosses this threshold — deterministic in commit
      count, not wall time. Fires once per run; the supervisor then
      proves the failover (hot-standby promotion or WAL
      restart-in-place). Requires the supervisor to be active
      (``ps_standby=True``, ``ps_wal_dir``, or ``ps_chain_length > 1``
      on the trainer).
    - ``kill_shard_id``: with a sharded center (``ps_num_shards > 1``),
      WHICH shard's primary the kill targets (default 0) — the
      kill-one-shard chaos: that shard fails over while its siblings
      keep folding, and the exactly-once oracle must hold per shard.

    Membership-directory faults (consulted by the ``DirectoryServer`` —
    distkeras_tpu/directory — once per handled op on the PRIMARY):

    - ``kill_directory_after_ops``: crash-stop the directory primary
      (``_crash()``: connections torn, WAL abandoned) once it has
      handled this many ops — deterministic in op count. Fires once;
      the directory failover supervisor then proves the promotion, and
      every consumer's next lookup re-probes the seeds onto the
      promoted replica. Requires ``directory=True`` on the trainer.
    - ``directory_partition_after`` / ``directory_partition_ops``:
      after N directory ops, the next K all drop (torn connection to
      the caller) — a deterministic directory partition window. The
      training hot path must ride it out untouched: the directory is
      consulted only at build/reconnect time.

    ``max_faults`` caps drops+partition hits (delays excluded) so runs
    terminate; ``stats()`` reports what was actually injected.
    """

    def __init__(self, seed: int = 0, drop_send: float = 0.0,
                 drop_recv: float = 0.0, delay: float = 0.0,
                 delay_s: float = 0.0, partition_after: int | None = None,
                 partition_ops: int = 0,
                 kill_at: dict[int, int] | None = None,
                 straggle: dict[int, float] | None = None,
                 max_faults: int | None = None,
                 kill_ps_after_commits: int | None = None,
                 kill_shard_id: int | None = None,
                 join_worker_at_window: dict[int, int] | None = None,
                 preempt_worker_at_window: dict[int, int] | None = None,
                 kill_directory_after_ops: int | None = None,
                 directory_partition_after: int | None = None,
                 directory_partition_ops: int = 0):
        for name, p in (("drop_send", drop_send), ("drop_recv", drop_recv),
                        ("delay", delay)):
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{name} must be a probability, got {p}")
        self.seed = int(seed)
        self.drop_send = float(drop_send)
        self.drop_recv = float(drop_recv)
        self.delay = float(delay)
        self.delay_s = float(delay_s)
        self.partition_after = partition_after
        self.partition_ops = int(partition_ops)
        self.kill_at = dict(kill_at or {})
        self.straggle = {
            int(w): float(s) for w, s in (straggle or {}).items()
        }
        for w, s in self.straggle.items():
            if s < 0:
                raise ValueError(
                    f"straggle[{w}] must be >= 0 seconds, got {s}"
                )
        self.max_faults = max_faults
        self.kill_ps_after_commits = (
            None if kill_ps_after_commits is None
            else int(kill_ps_after_commits)
        )
        if kill_shard_id is not None and kill_shard_id < 0:
            raise ValueError(
                f"kill_shard_id must be >= 0, got {kill_shard_id}"
            )
        self.kill_shard_id = (
            None if kill_shard_id is None else int(kill_shard_id)
        )
        self.join_worker_at_window = dict(join_worker_at_window or {})
        self.preempt_worker_at_window = dict(preempt_worker_at_window or {})
        self.kill_directory_after_ops = (
            None if kill_directory_after_ops is None
            else int(kill_directory_after_ops)
        )
        self.directory_partition_after = (
            None if directory_partition_after is None
            else int(directory_partition_after)
        )
        self.directory_partition_ops = int(directory_partition_ops)
        self._rng = np.random.Generator(np.random.Philox(self.seed))
        self._lock = threading.Lock()
        self._ops = 0
        self._killed: set[int] = set()
        self._joined: set[int] = set()
        self._preempted: set[int] = set()
        self._ps_killed = False
        self._directory_killed = False
        self._n_drops = 0
        self._n_delays = 0
        self._n_partition_drops = 0
        self._n_kills = 0
        self._n_straggles = 0
        self._n_joins = 0
        self._n_preempts = 0
        self._n_ps_kills = 0
        self._n_directory_ops = 0
        self._n_directory_kills = 0
        self._n_directory_drops = 0

    # -- wire hook (installed into networking._fault_hook) -------------------

    def _wire(self, op: str, sock: Any) -> None:
        """The networking seam: decide this op's fate under the lock (the
        generator is shared state), sleep OUTSIDE it (a delay must stall
        one connection, not serialize every other thread's faults)."""
        sleep_s = 0.0
        with self._lock:
            self._ops += 1
            budget = (self.max_faults is None
                      or (self._n_drops + self._n_partition_drops)
                      < self.max_faults)
            if (budget and self.partition_after is not None
                    and self.partition_after < self._ops
                    <= self.partition_after + self.partition_ops):
                self._n_partition_drops += 1
                raise FaultInjectedError(
                    f"injected partition (op {self._ops})"
                )
            p_drop = self.drop_send if op == "send" else self.drop_recv
            if budget and p_drop and self._rng.random() < p_drop:
                self._n_drops += 1
                raise FaultInjectedError(
                    f"injected {op} drop (op {self._ops})"
                )
            if self.delay and self._rng.random() < self.delay:
                self._n_delays += 1
                sleep_s = self.delay_s
        if sleep_s > 0.0:
            time.sleep(sleep_s)

    # -- worker hook ---------------------------------------------------------

    def maybe_kill(self, worker_id: int, window_index: int) -> None:
        """Raise :class:`WorkerKilled` when ``worker_id`` reaches its
        configured window — once; restarts replay the window unharmed."""
        step = self.kill_at.get(worker_id)
        if step is None or window_index != step:
            return
        with self._lock:
            if worker_id in self._killed:
                return
            self._killed.add(worker_id)
            self._n_kills += 1
        raise WorkerKilled(
            f"injected kill: worker {worker_id} at window {window_index}"
        )

    def maybe_straggle(self, worker_id: int) -> None:
        """Sleep the configured straggler delay at a window boundary
        (no-op for workers without one). Deterministic: every window,
        same duration — the persistent-straggler shape, not jitter."""
        s = self.straggle.get(worker_id)
        if not s:
            return
        with self._lock:
            self._n_straggles += 1
        time.sleep(s)

    # -- elastic-membership hooks (ElasticCoordinator) -----------------------

    def take_join(self, worker_id: int, window_index: int) -> bool:
        """True exactly once, at ``worker_id``'s first window boundary AT
        OR AFTER its configured trigger (``>=``, not ``==``: a worker
        slowed by concurrent wire chaos must still fire the event at its
        next boundary instead of skipping past it): the coordinator
        should live-join one new worker now. Deterministic in the
        worker's own completed-window count — a restarted worker
        replaying windows does not re-trigger."""
        step = self.join_worker_at_window.get(worker_id)
        if step is None or window_index < step:
            return False
        with self._lock:
            if worker_id in self._joined:
                return False
            self._joined.add(worker_id)
            self._n_joins += 1
        return True

    def take_preempt(self, worker_id: int, window_index: int) -> bool:
        """True exactly once, at ``worker_id``'s first window boundary at
        or after its configured preemption point (same ``>=`` semantics
        as :meth:`take_join`): the worker should receive a preemption
        notice and start its bounded-deadline drain."""
        step = self.preempt_worker_at_window.get(worker_id)
        if step is None or window_index < step:
            return False
        with self._lock:
            if worker_id in self._preempted:
                return False
            self._preempted.add(worker_id)
            self._n_preempts += 1
        return True

    # -- parameter-server hook (PSFailoverSupervisor) ------------------------

    def should_kill_ps(self, num_updates: int) -> bool:
        """True exactly until the kill is taken: the primary PS should be
        crash-stopped now (its commit count crossed the threshold)."""
        if self.kill_ps_after_commits is None:
            return False
        with self._lock:
            return (not self._ps_killed
                    and num_updates >= self.kill_ps_after_commits)

    def note_ps_kill(self) -> None:
        with self._lock:
            self._ps_killed = True
            self._n_ps_kills += 1

    # -- membership-directory hook (DirectoryServer) -------------------------

    def take_directory_op(self) -> str:
        """Consulted once per handled op on the directory PRIMARY:
        ``"kill"`` exactly once when the op count crosses the kill
        threshold, ``"drop"`` inside the partition window, else
        ``"ok"``. Deterministic in op count — no wall clock, no rng."""
        with self._lock:
            self._n_directory_ops += 1
            ops = self._n_directory_ops
            if (self.kill_directory_after_ops is not None
                    and not self._directory_killed
                    and ops >= self.kill_directory_after_ops):
                self._directory_killed = True
                self._n_directory_kills += 1
                return "kill"
            if (self.directory_partition_after is not None
                    and self.directory_partition_after < ops
                    <= (self.directory_partition_after
                        + self.directory_partition_ops)):
                self._n_directory_drops += 1
                return "drop"
        return "ok"

    @property
    def has_directory_events(self) -> bool:
        """Whether the plan carries directory faults (they need a hosted
        directory — without ``directory=True`` nothing ever consults
        them, so the chaos would silently test nothing)."""
        return (self.kill_directory_after_ops is not None
                or self.directory_partition_after is not None)

    # -- lifecycle -----------------------------------------------------------

    def install(self) -> None:
        """Install the wire hook; exactly one plan may be active."""
        if networking._fault_hook is not None:
            raise RuntimeError("a FaultPlan is already installed")
        networking._fault_hook = self._wire

    def uninstall(self) -> None:
        # == not `is`: each `self._wire` access builds a fresh bound method
        if networking._fault_hook == self._wire:
            networking._fault_hook = None

    def __enter__(self) -> "FaultPlan":
        self.install()
        return self

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def stats(self) -> dict:
        """What the plan actually injected (for assertions and chaos-bench
        records)."""
        with self._lock:
            return {
                "wire_ops": self._ops,
                "drops": self._n_drops,
                "partition_drops": self._n_partition_drops,
                "delays": self._n_delays,
                "kills": self._n_kills,
                "straggles": self._n_straggles,
                "joins": self._n_joins,
                "preempts": self._n_preempts,
                "ps_kills": self._n_ps_kills,
                "directory_ops": self._n_directory_ops,
                "directory_kills": self._n_directory_kills,
                "directory_drops": self._n_directory_drops,
            }

    @property
    def has_elastic_events(self) -> bool:
        """Whether the plan carries join/preempt membership events (they
        need an elastic trainer — the fixed-pool loop never consults
        them, so running them there would silently test nothing)."""
        return bool(self.join_worker_at_window
                    or self.preempt_worker_at_window)
