"""Worker liveness: leases, heartbeats, and stale-worker eviction.

The reference inherited liveness from Spark — a hung executor was the
cluster manager's problem. The TPU-native PS has no cluster manager between
it and its hogwild workers, so liveness is tracked here: each worker holds
a **lease** on the server, renewed by heartbeats its training loop sends at
window boundaries (piggybacked — no extra threads, no extra connections to
wedge). A worker that stops renewing past ``lease_timeout`` is **evicted**:
its lease is dropped, the eviction is counted into ``ps.stats()``, and the
server's per-worker pull-version entry is cleared via the eviction
callback — so if the worker ever comes back and commits without re-pulling,
DynSGD sees the full center history as its staleness (τ = num_updates) and
down-weights the zombie commit to ~nothing instead of folding it fresh.

The registry is transport-neutral (the in-process and socket PS share one
instance on the base ``ParameterServer``; the C++ server mirrors the same
lease semantics natively) and clock-injectable for deterministic tests.
Expiry scans are O(workers) and rate-limited to a quarter lease, so the
commit hot path stays O(fold).
"""

from __future__ import annotations

import threading
import time
from typing import Callable


class Lease:
    """One worker's liveness record."""

    __slots__ = ("worker_id", "deadline", "renewals")

    def __init__(self, worker_id: int, deadline: float):
        self.worker_id = worker_id
        self.deadline = deadline
        self.renewals = 0


class WorkerRegistry:
    """Lease table with heartbeat renewal and rate-limited expiry.

    ``renew`` auto-registers (a heartbeat from an unknown or evicted
    worker re-admits it — that's what a recovered worker's first
    heartbeat is). ``on_evict`` runs OUTSIDE the registry lock with the
    evicted ids, so callbacks may take other locks (the PS's center lock)
    without ordering hazards.
    """

    def __init__(self, lease_timeout: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 on_evict: Callable[[list[int]], None] | None = None):
        if lease_timeout <= 0:
            raise ValueError(
                f"lease_timeout must be positive, got {lease_timeout}"
            )
        self.lease_timeout = float(lease_timeout)
        self._clock = clock
        self._on_evict = on_evict
        self._lock = threading.Lock()
        self._leases: dict[int, Lease] = {}
        self._evicted_total = 0
        self._heartbeats = 0
        # Latest cumulative client-reported retry count PER WORKER ID,
        # kept across lease lifecycles: clients report running totals, so
        # folding a count into a sum at eviction and accepting the same
        # total again after re-admission would double-count. max() per id,
        # summed at read time, counts each retry exactly once.
        self._retries_by_wid: dict[int, int] = {}
        # expiry scans rate-limit to a quarter lease: liveness detection
        # stays prompt while per-commit overhead stays a clock read
        self._expiry_every = max(self.lease_timeout / 4.0, 1e-3)
        self._next_expiry = self._clock()

    def renew(self, worker_id: int, retries: int = 0) -> bool:
        """Heartbeat: extend (or create) the worker's lease; ``retries``
        is the client's cumulative retry count (monotone — the registry
        stores the latest value per worker and sums across workers).
        Returns True if the lease already existed (a renewal), False if
        this heartbeat (re-)registered the worker."""
        now = self._clock()
        with self._lock:
            self._heartbeats += 1
            lease = self._leases.get(worker_id)
            fresh = lease is None
            if fresh:
                lease = self._leases[worker_id] = Lease(worker_id, 0.0)
            lease.deadline = now + self.lease_timeout
            lease.renewals += 1
            if retries:
                self._retries_by_wid[worker_id] = max(
                    self._retries_by_wid.get(worker_id, 0), int(retries)
                )
        self.expire()
        return not fresh

    def register(self, worker_id: int) -> None:
        """Insert (or refresh) a lease WITHOUT counting a heartbeat — the
        elastic live-join path: the joiner holds a lease from the moment
        it is admitted, but ``heartbeats`` stays a pure count of
        heartbeat ops."""
        now = self._clock()
        with self._lock:
            lease = self._leases.get(worker_id)
            if lease is None:
                lease = self._leases[worker_id] = Lease(worker_id, 0.0)
            lease.deadline = now + self.lease_timeout

    def deregister(self, worker_id: int) -> None:
        """Clean exit: drop the lease without counting an eviction (the
        worker's reported retries stay in the run total)."""
        with self._lock:
            self._leases.pop(worker_id, None)

    def expire(self, force: bool = False) -> list[int]:
        """Evict workers whose leases lapsed; returns the newly evicted
        ids. Rate-limited internally — call freely from hot paths;
        ``force=True`` (observability reads) skips the rate limit so a
        stats consumer never sees an already-lapsed lease as live."""
        now = self._clock()
        with self._lock:
            if not force and now < self._next_expiry:
                return []
            self._next_expiry = now + self._expiry_every
            dead = [wid for wid, l in self._leases.items()
                    if l.deadline < now]
            for wid in dead:
                self._leases.pop(wid)
            self._evicted_total += len(dead)
        if dead and self._on_evict is not None:
            self._on_evict(dead)
        return dead

    def active(self) -> list[int]:
        """Currently-leased worker ids (after a forced expiry pass)."""
        self.expire(force=True)
        with self._lock:
            return sorted(self._leases)

    def stats(self) -> dict:
        """Counters folded into ``ps.stats()``: ``active_workers``,
        ``evicted_workers`` (total evictions, re-admissions included),
        ``heartbeats``, and ``worker_retries`` (sum over worker ids of the
        latest cumulative retry count each reported — eviction and
        re-admission cycles never double-count). Runs a FORCED expiry
        pass first: a lapsed lease is never reported as live."""
        self.expire(force=True)
        with self._lock:
            return {
                "active_workers": len(self._leases),
                "evicted_workers": self._evicted_total,
                "heartbeats": self._heartbeats,
                "worker_retries": sum(self._retries_by_wid.values()),
            }
