"""Benchmark datasets for the five BASELINE configs.

The reference's examples pulled MNIST via Keras downloads and the ATLAS Higgs
CSV from CERN storage (``examples/mnist.py``, ``examples/workflow.ipynb`` —
SURVEY.md §2b #19). This build environment has **zero network egress**, so each
loader:

1. uses a real on-disk copy if present (``$DISTKERAS_DATA/<name>.npz`` or the
   conventional ``~/.keras/datasets`` path), else
2. generates a **deterministic synthetic stand-in with identical shapes,
   dtypes, and class structure** — class-conditional Gaussian templates, so
   models genuinely learn (accuracy is meaningful, not chance) while the
   compute/communication profile matches the real config.

Every loader returns ``(train: Dataset, test: Dataset)`` with columns
``features`` / ``label``.
"""

from __future__ import annotations

import os
from pathlib import Path

import numpy as np

from distkeras_tpu.data import Dataset

def _search_dirs() -> list[str]:
    # read the env at call time, not import time: on a real pod the data dir
    # may be mounted/exported after this module is first imported
    return [
        os.environ.get("DISTKERAS_DATA", ""),
        str(Path.home() / ".keras" / "datasets"),
    ]


def _find(name: str) -> Path | None:
    for d in _search_dirs():
        if d and (p := Path(d) / name).exists():
            return p
    return None


def _class_template_images(
    n: int, num_classes: int, shape: tuple, seed: int, noise: float = 0.35,
    split: int = 0,
):
    """Class-conditional template + noise images in [0, 1].

    Templates are smooth low-frequency patterns per class; a linear probe gets
    well above chance and a CNN separates them almost perfectly — mirroring the
    easy/medium difficulty of MNIST/CIFAR for throughput benchmarking.

    The templates depend only on ``seed`` so train (``split=0``) and test
    (``split=1``) share one distribution; only the sampling noise differs.
    """
    templates = (
        np.random.default_rng(seed)
        .normal(0.5, 0.25, size=(num_classes,) + shape)
        .astype(np.float32)
    )
    rng = np.random.default_rng((seed, split))
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    x = templates[labels] + rng.normal(0.0, noise, size=(n,) + shape).astype(
        np.float32
    )
    return np.clip(x, 0.0, 1.0), labels


def mnist(n_train: int = 60000, n_test: int = 10000, seed: int = 0):
    """MNIST (28×28×1, 10 classes) or its synthetic stand-in."""
    p = _find("mnist.npz")
    if p is not None:
        with np.load(p) as z:
            xtr, ytr = z["x_train"][:n_train], z["y_train"][:n_train]
            xte, yte = z["x_test"][:n_test], z["y_test"][:n_test]
        xtr = (xtr.astype(np.float32) / 255.0)[..., None]
        xte = (xte.astype(np.float32) / 255.0)[..., None]
        ytr, yte = ytr.astype(np.int32), yte.astype(np.int32)
    else:
        xtr, ytr = _class_template_images(n_train, 10, (28, 28, 1), seed, split=0)
        xte, yte = _class_template_images(n_test, 10, (28, 28, 1), seed, split=1)
    return (
        Dataset.from_arrays(xtr, ytr),
        Dataset.from_arrays(xte, yte),
    )


def cifar10(n_train: int = 50000, n_test: int = 10000, seed: int = 10):
    """CIFAR-10 (32×32×3, 10 classes) or its synthetic stand-in."""
    p = _find("cifar10.npz")
    if p is not None:
        with np.load(p) as z:
            xtr = z["x_train"][:n_train].astype(np.float32) / 255.0
            xte = z["x_test"][:n_test].astype(np.float32) / 255.0
            ytr = z["y_train"][:n_train].astype(np.int32).reshape(-1)
            yte = z["y_test"][:n_test].astype(np.int32).reshape(-1)
    else:
        xtr, ytr = _class_template_images(
            n_train, 10, (32, 32, 3), seed, noise=0.45, split=0
        )
        xte, yte = _class_template_images(
            n_test, 10, (32, 32, 3), seed, noise=0.45, split=1
        )
    return Dataset.from_arrays(xtr, ytr), Dataset.from_arrays(xte, yte)


def higgs(n_train: int = 100000, n_test: int = 20000, seed: int = 20):
    """ATLAS-Higgs-style tabular binary classification (28 float features).

    The real dataset (``workflow.ipynb``'s ATLAS challenge CSV) is physics
    kinematics; the stand-in draws features from two overlapping Gaussians
    pushed through a random nonlinear mixing so a deep MLP beats a linear
    model, as on the real data.
    """
    p = _find("higgs.npz")
    rng = np.random.default_rng(seed)
    if p is not None:
        with np.load(p) as z:
            xtr = z["x_train"][:n_train].astype(np.float32)
            ytr = z["y_train"][:n_train].astype(np.int32).reshape(-1)
            xte = z["x_test"][:n_test].astype(np.float32)
            yte = z["y_test"][:n_test].astype(np.int32).reshape(-1)
    else:
        # One mixing matrix and mean-shift direction for both splits — train
        # and test must share the decision boundary; only the samples differ.
        # Signal = linear mean shift (a linear probe works, ~0.75) plus a
        # nonlinear component (a deep MLP does clearly better), mirroring the
        # real Higgs task's structure.
        w1 = rng.normal(0, 1, size=(28, 28)).astype(np.float32)
        u = rng.normal(0, 1, size=(28,)).astype(np.float32)
        u /= np.linalg.norm(u)

        def make(n, r):
            y = r.integers(0, 2, size=n).astype(np.int32)
            base = r.normal(0, 1, size=(n, 28)).astype(np.float32)
            shift = 1.1 * u[None, :] + np.tanh(base @ w1) * 0.7
            x = base + shift * y[:, None]
            return x.astype(np.float32), y

        xtr, ytr = make(n_train, rng)
        xte, yte = make(n_test, rng)
    return Dataset.from_arrays(xtr, ytr), Dataset.from_arrays(xte, yte)


def imdb(
    n_train: int = 25000,
    n_test: int = 25000,
    vocab: int = 20000,
    maxlen: int = 200,
    seed: int = 30,
):
    """IMDB-style variable-length token sequences, binary sentiment.

    Returns already-padded ``features`` int32[maxlen] plus a ``mask`` column —
    variable lengths are handled on the host so XLA sees static shapes
    (SURVEY.md §7.3 hard part 3). Sentiment signal: each class draws tokens
    from a shifted Zipf distribution with a set of class-indicative tokens.
    """
    p = _find("imdb.npz")
    rng = np.random.default_rng(seed)
    if p is not None:
        with np.load(p, allow_pickle=True) as z:
            seqs_tr = z["x_train"][:n_train]
            ytr = z["y_train"][:n_train].astype(np.int32)
            seqs_te = z["x_test"][:n_test]
            yte = z["y_test"][:n_test].astype(np.int32)
    else:
        pos_tokens = rng.choice(np.arange(10, vocab), size=200, replace=False)
        neg_tokens = rng.choice(np.arange(10, vocab), size=200, replace=False)

        def make(n, r):
            y = r.integers(0, 2, size=n).astype(np.int32)
            seqs = []
            for yi in y:
                length = int(r.integers(20, maxlen))
                base = (r.zipf(1.3, size=length) % (vocab - 1) + 1).astype(np.int32)
                marks = pos_tokens if yi else neg_tokens
                n_marks = max(2, length // 8)
                pos = r.integers(0, length, size=n_marks)
                base[pos] = r.choice(marks, size=n_marks)
                seqs.append(base)
            return np.asarray(seqs, dtype=object), y

        seqs_tr, ytr = make(n_train, rng)
        seqs_te, yte = make(n_test, rng)

    def pad(seqs):
        tokens = np.zeros((len(seqs), maxlen), dtype=np.int32)
        mask = np.zeros((len(seqs), maxlen), dtype=np.float32)
        for i, s in enumerate(seqs):
            s = np.asarray(s, dtype=np.int32)[:maxlen]
            tokens[i, : len(s)] = s
            mask[i, : len(s)] = 1.0
        return tokens, mask

    ttr, mtr = pad(seqs_tr)
    tte, mte = pad(seqs_te)
    train = Dataset({"features": ttr, "mask": mtr, "label": ytr})
    test = Dataset({"features": tte, "mask": mte, "label": yte})
    return train, test


def is_synthetic(name: str) -> bool:
    """True when the named dataset will fall back to the synthetic stand-in."""
    return _find(f"{name}.npz") is None
