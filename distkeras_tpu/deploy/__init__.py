"""Live deployment: stream weights from the training PS into the serving
tier, hot-swap them atomically between decode steps, and orchestrate
canary rollout / SLO-gated rollback across the serving fleet.

Three layers (DESIGN.md "Live deployment"):

- :mod:`~distkeras_tpu.deploy.stream` — serving-side *read replicas* of
  the training center. A :class:`ReadReplica` speaks the exact
  chain-replication record protocol the hot standby does (the primary's
  ``attach_standby`` connects to it), applies records through the one
  shared ``replay_record``, and forwards raw frames down-chain so N
  serving hosts share one stream off the trainer. A
  :class:`WeightStreamer` owns one replica per shard, cuts *versioned
  model snapshots* at fold-count/epoch boundaries (never per-commit),
  assembles the sharded consistent cut, and reports the published
  version back into ``ps.stats()['deploy_lag_folds']``.
- the serving engine's swap gate —
  :meth:`~distkeras_tpu.serving.scheduler.GenerationEngine.swap_params`
  stages ``(params, version)`` and applies them BETWEEN decode steps, so
  one ``decode_step`` can never mix two weight sets.
- :mod:`~distkeras_tpu.deploy.rollout` — a pure hysteresis state machine
  (:class:`RolloutPolicy`, the ``ElasticPolicy`` discipline) plus the
  :class:`RolloutController` that pins a canary fraction of
  directory-registered replicas to a candidate version, promotes on
  watchdog-green, and rolls back on a firing ``ServingSLORule``.
"""

from distkeras_tpu.deploy.rollout import (  # noqa: F401
    RolloutController,
    RolloutPolicy,
    watchtower_health,
)
from distkeras_tpu.deploy.stream import (  # noqa: F401
    ModelSnapshot,
    ReadReplica,
    SnapshotStore,
    WeightStreamer,
)

__all__ = [
    "ModelSnapshot",
    "ReadReplica",
    "SnapshotStore",
    "WeightStreamer",
    "RolloutPolicy",
    "RolloutController",
    "watchtower_health",
]
