"""Rollout orchestration: canary → promote / rollback over the serving
fleet, driven by a pure hysteresis state machine.

:class:`RolloutPolicy` follows the ``ElasticPolicy`` discipline
(resilience/elastic.py): no clocks, no threads, no sockets — callers
hand it ``now`` and the observed health signals, it returns a list of
action dicts and journals every decision. The surrounding
:class:`RolloutController` owns the impure half: picking the canary
subset deterministically from the router's directory view, pushing
versions onto replicas, and journaling every transition to the flight
recorder (``deploy.transition`` instants) and to a JSON-clean
``journal`` list CI uploads as an artifact.

The health signals come from the watchtower: ``green`` means the
watchdog currently holds NO active alert (promotion gate), and
``slo_firing`` means a ``ServingSLORule`` alert is active (rollback
trigger). :func:`watchtower_health` adapts a ``Watchtower`` into that
pair.
"""

from __future__ import annotations

import math
from typing import Callable

from distkeras_tpu.observability import trace as _trace

__all__ = ["RolloutPolicy", "RolloutController", "watchtower_health"]


def watchtower_health(tower) -> tuple[bool, bool]:
    """``(green, slo_firing)`` from a Watchtower's active-alert set.

    ``green`` is strict — ANY active alert (a PS rule, a loss stall)
    blocks promotion; a candidate should not be promoted into a sick
    fleet even when serving latency itself looks fine. ``slo_firing``
    is specifically the serving-SLO rule: the one signal that means the
    canary is hurting traffic NOW and must be rolled back."""
    active = getattr(getattr(tower, "watchdog", tower), "active", {})
    green = not active
    slo_firing = any(
        a.get("kind") == "serving_slo" for a in active.values()
    )
    return green, slo_firing


class RolloutPolicy:
    """Pure hysteresis state machine for one candidate at a time.

    States: ``idle`` (baseline serving everywhere) and ``canary`` (the
    candidate pinned to a fraction of the fleet). ``observe`` moves the
    machine and returns the actions the caller must execute:

    - ``{"action": "canary", "version": v, "fraction": f}`` — pin the
      candidate to a ``fraction`` of replicas.
    - ``{"action": "ramp", "version": v, "fraction": f}`` — the current
      ramp step stayed green for ``green_checks`` observations after its
      ``bake_s`` soak: widen the canary to the next fraction (only with
      a progressive ``fractions=`` ladder).
    - ``{"action": "promote", "version": v}`` — the LAST ramp step
      stayed green for ``green_checks`` consecutive observations after a
      ``bake_s`` soak: activate fleet-wide.
    - ``{"action": "rollback", "version": v, "to": baseline}`` — the
      serving SLO fired ``red_checks`` consecutive observations: repin
      the canaries to the baseline.

    ``fractions`` (ISSUE 17) turns the single static canary fraction
    into a progressive ramp — e.g. ``[0.01, 0.1, 0.5]`` exposes 1% of
    the fleet first, and each widening requires a FRESH bake + green
    streak, so a regression that only shows under real traffic volume
    is caught while it still touches a sliver of users. ``fractions=
    None`` (default) is exactly the legacy single-step machine:
    ``[canary_fraction]``.

    Hysteresis on BOTH edges (consecutive-check streaks + the bake
    time) keeps one noisy scrape from promoting a bad model or rolling
    back a good one; ``cooldown_s`` separates consecutive rollouts the
    same way ``ElasticPolicy.cooldown_s`` separates scale actions.
    """

    def __init__(self, canary_fraction: float = 0.25, bake_s: float = 2.0,
                 green_checks: int = 2, red_checks: int = 1,
                 cooldown_s: float = 5.0,
                 fractions: list[float] | None = None):
        if not 0.0 < canary_fraction <= 1.0:
            raise ValueError(
                f"canary_fraction must be in (0, 1], got {canary_fraction}"
            )
        if bake_s < 0 or cooldown_s < 0:
            raise ValueError("bake_s and cooldown_s must be >= 0")
        if green_checks < 1 or red_checks < 1:
            raise ValueError("green_checks and red_checks must be >= 1")
        self.canary_fraction = float(canary_fraction)
        if fractions is None:
            fractions = [self.canary_fraction]
        fractions = [float(f) for f in fractions]
        if not fractions or any(not 0.0 < f <= 1.0 for f in fractions):
            raise ValueError(
                f"fractions must be non-empty, each in (0, 1]: {fractions}"
            )
        if any(b <= a for a, b in zip(fractions, fractions[1:])):
            raise ValueError(
                f"fractions must be strictly increasing: {fractions}"
            )
        self.fractions = fractions
        self._fi = 0              # index of the ACTIVE ramp step
        self.bake_s = float(bake_s)
        self.green_checks = int(green_checks)
        self.red_checks = int(red_checks)
        self.cooldown_s = float(cooldown_s)
        self.state = "idle"
        self.version = 0          # the promoted baseline
        self.candidate: int | None = None
        self._t_canary: float | None = None
        self._t_last_action: float | None = None
        self._green_streak = 0
        self._red_streak = 0
        #: every decision, in order — the rollout journal CI uploads
        self.decisions: list[dict] = []

    def _emit(self, now: float, action: str, **fields) -> dict:
        rec = {"t": float(now), "action": action, "state": self.state,
               **fields}
        self.decisions.append(rec)
        return rec

    def observe(self, now: float, candidate: int | None,
                green: bool, slo_firing: bool) -> list[dict]:
        """Advance the machine one observation; returns actions to run."""
        out: list[dict] = []
        if self.state == "idle":
            if candidate is None or candidate <= self.version:
                return out
            if (self._t_last_action is not None
                    and now - self._t_last_action < self.cooldown_s):
                return out  # cooling down from the previous rollout
            self.state = "canary"
            self.candidate = int(candidate)
            self._fi = 0
            self._t_canary = now
            self._t_last_action = now
            self._green_streak = 0
            self._red_streak = 0
            out.append(self._emit(now, "canary", version=self.candidate,
                                  fraction=self.fractions[0]))
            return out
        # state == "canary"
        if slo_firing:
            self._red_streak += 1
            self._green_streak = 0
            if self._red_streak >= self.red_checks:
                version = self.candidate
                self.state = "idle"
                self.candidate = None
                self._t_last_action = now
                out.append(self._emit(now, "rollback", version=version,
                                      to=self.version))
            return out
        self._red_streak = 0
        if green and now - self._t_canary >= self.bake_s:
            self._green_streak += 1
            if self._green_streak >= self.green_checks:
                if self._fi + 1 < len(self.fractions):
                    # ramp: widen to the next fraction; the new step
                    # re-bakes and needs a FRESH green streak — each
                    # widening earns its own soak
                    self._fi += 1
                    self._t_canary = now
                    self._t_last_action = now
                    self._green_streak = 0
                    out.append(self._emit(
                        now, "ramp", version=self.candidate,
                        fraction=self.fractions[self._fi]))
                    return out
                version = self.candidate
                self.state = "idle"
                self.version = version
                self.candidate = None
                self._t_last_action = now
                out.append(self._emit(now, "promote", version=version))
        else:
            # not green (some alert is up) or still baking: hold, and a
            # non-green observation restarts the green streak — the
            # promotion gate wants CONSECUTIVE clean checks
            if not green:
                self._green_streak = 0
        return out


class RolloutController:
    """Drives a rollout over real replicas: deterministic canary pick,
    version activation, and transition journaling.

    - ``router`` — a ``RoutedGenerationClient`` (or anything with
      ``refresh()`` and ``replica_versions() -> {key: version}``): the
      directory view the canary subset is picked from.
    - ``activate(key, version) -> bool`` — push ``version`` onto the
      replica registered under ``key`` (the serving server's
      ``deploy_activate`` wire action; in-process tests pass a closure).
    - ``health() -> (green, slo_firing)`` — usually
      ``lambda: watchtower_health(tower)``.

    The canary subset is the first ``ceil(fraction·N)`` keys ordered by
    ``stable_hash(key)`` — deterministic across controllers and across
    calls, so a restarted controller repins the SAME replicas.
    """

    def __init__(self, router, activate: Callable[[str, int], bool],
                 health: Callable[[], tuple[bool, bool]],
                 policy: RolloutPolicy | None = None):
        self.router = router
        self.activate = activate
        self.health = health
        self.policy = policy if policy is not None else RolloutPolicy()
        self.candidate: int | None = None
        self.canary_keys: list[str] = []
        #: JSON-clean transition journal (CI artifact)
        self.journal: list[dict] = []

    def begin(self, candidate: int) -> None:
        """Stage a candidate version; the next ``step`` may canary it."""
        self.candidate = int(candidate)

    def _keys(self) -> list[str]:
        from distkeras_tpu.sharding.ring import stable_hash

        try:
            self.router.refresh()
        except Exception:
            pass  # a directory blip: act on the last known fleet
        versions = self.router.replica_versions()
        return sorted(versions, key=lambda k: (stable_hash(k), k))

    def _pick_canaries(self, keys: list[str],
                       fraction: float | None = None) -> list[str]:
        if not keys:
            return []
        if fraction is None:
            fraction = self.policy.canary_fraction
        n = max(1, int(math.ceil(float(fraction) * len(keys))))
        return keys[:n]

    def _journal(self, now: float, action: dict, keys: list[str],
                 ok: int) -> None:
        rec = {**action, "keys": list(keys), "activated": ok}
        self.journal.append(rec)
        _trace.instant("deploy.transition", cat="deploy", args={
            "action": action["action"],
            "version": int(action.get("version") or 0),
            "replicas": len(keys),
        })

    def step(self, now: float) -> list[dict]:
        """One control-loop tick: read health, advance the policy,
        execute whatever it decided. Returns the executed actions."""
        green, slo_firing = self.health()
        actions = self.policy.observe(now, self.candidate, green,
                                      slo_firing)
        executed = []
        for action in actions:
            kind = action["action"]
            if kind == "canary":
                keys = self._pick_canaries(self._keys(),
                                           action.get("fraction"))
                self.canary_keys = keys
            elif kind == "ramp":
                # widen: activate ONLY the newly-added replicas — the
                # existing canaries already run the candidate, and
                # re-activating them would re-stage a no-op swap
                want = self._pick_canaries(self._keys(),
                                           action.get("fraction"))
                have = set(self.canary_keys)
                keys = [k for k in want if k not in have]
                self.canary_keys = list(self.canary_keys) + keys
            elif kind == "promote":
                # the canaries already run the candidate — activate the
                # remainder of the fleet
                keys = [k for k in self._keys()
                        if k not in set(self.canary_keys)]
                self.candidate = None
            else:  # rollback: repin the canaries to the baseline
                keys = list(self.canary_keys)
                self.canary_keys = []
                self.candidate = None
            version = (self.policy.version if kind == "rollback"
                       else action["version"])
            ok = 0
            for key in keys:
                try:
                    if self.activate(key, version):
                        ok += 1
                except Exception:
                    pass  # a dead replica re-registers and catches up
            self._journal(now, action, keys, ok)
            executed.append(action)
        return executed
