"""Weight streaming: serving-tier read replicas of the training center.

The training PS already streams every applied record (commit / pull /
dereg / evict / fence / epoch) to its hot standby BEFORE the client's
ACK, and a standby chain-link forwards the same raw frames to its own
successor (``StandbySocketParameterServer._serve_replication``). A
:class:`ReadReplica` is the serving tier's subscriber to that stream: it
listens like a standby, accepts the primary's ``replicate_stream``
handshake, applies each record through the one shared
``wal.replay_record`` (so its center is bit-identical to the trainer's at
every version), and forwards the raw frames to ITS successor — N serving
hosts chain off one stream without multiplying the trainer's send cost.

Serving must NOT consume the stream per-commit: a model swap costs a
prefill storm (every in-flight sequence either drains or re-prefills) and
at async-SGD fold rates that would swap thousands of times a second.
:class:`WeightStreamer` therefore *materializes versioned snapshots* only
at fold-count boundaries (``snapshot_every``) and at training-epoch marks
(``REC_EPOCH``, logged by the trainer's barrier), and for a sharded
center it assembles the consistent cut — every shard captured at the SAME
version ``F`` — before publishing. Published versions are reported back
to the training PS, which exposes the distance as
``stats()['deploy_lag_folds']`` (the watchtower's ``DeployLagRule``).

Epoch-mark snapshots double as *elastic epoch-barrier checkpoints*: with
``checkpoint_dir`` set, the store writes the exact resume payload
``run_async_training`` consumes (center + epoch, worker list empty → the
``warn_elastic_resume`` center-only path), closing the "elastic runs are
resume-only" gap.
"""

from __future__ import annotations

import pickle
import queue
import socket
import threading
from typing import Callable

from distkeras_tpu import networking
from distkeras_tpu.observability import trace as _trace

__all__ = [
    "ModelSnapshot",
    "ReadReplica",
    "SnapshotStore",
    "WeightStreamer",
]


def _tree_copy(tree):
    import jax
    import numpy as np

    return jax.tree.map(np.copy, tree)


class ModelSnapshot:
    """One materialized serving model: ``(version, epoch, tree)``.

    ``version`` is the training center's fold count at the cut;
    ``epoch`` is the training epoch for epoch-boundary cuts (None for
    plain fold-count cuts). Immutable by convention — the engine swaps
    the tree in whole, never mutates it.
    """

    __slots__ = ("version", "epoch", "tree")

    def __init__(self, version: int, tree, epoch: int | None = None):
        self.version = int(version)
        self.epoch = None if epoch is None else int(epoch)
        self.tree = tree

    def __repr__(self) -> str:  # journal/debug friendliness
        ep = "" if self.epoch is None else f", epoch={self.epoch}"
        return f"ModelSnapshot(version={self.version}{ep})"


class SnapshotStore:
    """Bounded version → :class:`ModelSnapshot` map with subscribers.

    ``publish`` is monotone (an older-or-equal version is dropped — the
    sharded assembler may race a fold-count cut against an epoch cut at
    the same version) and notifies subscribers OUTSIDE the lock.

    With ``checkpoint_dir`` set, every epoch-boundary snapshot also
    lands on disk as a resumable checkpoint in ``run_async_training``'s
    payload shape (``workers=[]`` → the elastic center-only resume path
    with ``warn_elastic_resume``) — the epoch-barrier checkpoint elastic
    runs previously never got.
    """

    def __init__(self, keep: int = 4, checkpoint_dir: str | None = None,
                 checkpoint_keep: int = 3):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self._mu = threading.Lock()
        self._snaps: dict[int, ModelSnapshot] = {}
        self._latest = 0
        self.keep = int(keep)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_keep = int(checkpoint_keep)
        self._subs: list[Callable[[ModelSnapshot], None]] = []
        self.published = 0
        self.checkpoints_written = 0

    def subscribe(self, fn: Callable[[ModelSnapshot], None]) -> None:
        """Call ``fn(snapshot)`` after every accepted publish (outside
        the store lock; exceptions are swallowed per-subscriber)."""
        with self._mu:
            self._subs.append(fn)

    def publish(self, version: int, tree, epoch: int | None = None) -> bool:
        snap = ModelSnapshot(version, tree, epoch=epoch)
        with self._mu:
            if snap.version <= self._latest:
                return False
            self._snaps[snap.version] = snap
            self._latest = snap.version
            while len(self._snaps) > self.keep:
                del self._snaps[min(self._snaps)]
            self.published += 1
            subs = list(self._subs)
        if self.checkpoint_dir is not None and snap.epoch is not None:
            self._write_checkpoint(snap)
        for fn in subs:
            try:
                fn(snap)
            except Exception:  # a broken subscriber must not stall the cut
                pass
        return True

    def _write_checkpoint(self, snap: ModelSnapshot) -> None:
        from distkeras_tpu.checkpoint import save_checkpoint

        payload = {
            # worker state is per-process optimizer slots the serving
            # tier never sees: empty list → the resume path warns
            # (warn_elastic_resume) and restarts workers fresh from the
            # center — exactly elastic resume's defined semantics
            "workers": [],
            "center": snap.tree,
            "num_updates": snap.version,
            "epoch": snap.epoch,
        }
        try:
            save_checkpoint(self.checkpoint_dir, payload, snap.version,
                            keep=self.checkpoint_keep)
            self.checkpoints_written += 1
        except OSError:
            pass  # a full/readonly disk degrades durability, not serving

    def latest(self) -> ModelSnapshot | None:
        with self._mu:
            snap = self._snaps.get(self._latest)
        return snap

    def get(self, version: int) -> ModelSnapshot | None:
        with self._mu:
            return self._snaps.get(int(version))

    def versions(self) -> list[int]:
        with self._mu:
            return sorted(self._snaps)


class ReadReplica:
    """One shard's serving-side subscriber to the replication stream.

    Listens like a hot standby: the TRAINING side connects out to
    ``(host, port)`` (``attach_standby`` on the primary or on a chain
    tail) and sends the ``replicate_stream`` handshake — a full base
    state — then raw header+body record frames. Records are applied
    through ``wal.replay_record`` under one apply lock, so the replica's
    center is bit-identical to the trainer's at every version, and
    forwarded to this replica's own successor (``attach_successor``) so
    several serving hosts share one stream.

    Construct with the TRAINER's merge rule and *configured* worker
    count — the fold arithmetic prices staleness from them, and a
    mismatch silently diverges the replayed center.
    """

    def __init__(self, rule, num_workers: int, *, ema_decay: float | None = None,
                 host: str = "127.0.0.1", shard_id: int = 0,
                 on_apply: Callable | None = None, backlog: int = 4):
        self.rule = rule
        self.num_workers = int(num_workers)
        self.ema_decay = ema_decay
        self.shard_id = int(shard_id)
        self.on_apply = on_apply
        self._lock = threading.Lock()  # state + successor sock + counters
        self._state: dict | None = None
        self._streaming = False
        self._records = 0
        self._successor_sock = None
        self._successor_addr: tuple[str, int] | None = None
        self._n_forward_drops = 0
        self._closed = False
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, 0))
        self._srv.listen(backlog)
        self.host, self.port = self._srv.getsockname()[:2]
        self._threads: list[threading.Thread] = []
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"read-replica-{self.shard_id}")
        t.start()
        self._threads.append(t)

    # -- stream side ---------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return  # listener closed
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _handle(self, conn) -> None:
        try:
            while True:
                msg = networking.recv_data(conn)
                action = msg.get("action")
                if action == "replicate_stream":
                    self._serve_stream(conn, msg)
                    break  # stream EOF/error ends the connection
                elif action == "ping":
                    with self._lock:
                        v = (self._state or {}).get("num_updates", 0)
                    networking.send_data(conn, {
                        "ok": True, "num_updates": v, "read_replica": True,
                        "shard": self.shard_id,
                    })
                elif action in ("stop", "bye"):
                    break
                else:
                    networking.send_data(
                        conn, {"ok": False, "error": "read replica"}
                    )
        except (ConnectionError, EOFError, OSError):
            pass
        except pickle.UnpicklingError:
            pass
        finally:
            conn.close()

    def _serve_stream(self, conn, msg) -> None:
        from distkeras_tpu.resilience import wal as _wal

        with self._lock:
            self._state = dict(msg["state"])
            self._streaming = True
            # a successor registered before the base arrived attaches now,
            # under the same lock — it misses no record
            if self._successor_addr and self._successor_sock is None:
                self._connect_successor_locked()
        networking.send_data(conn, {"ok": True})
        hdr = _wal._HDR
        try:
            while True:
                head = networking._recv_exact(conn, hdr.size)
                _, _, ln = hdr.unpack(head)
                body = networking._recv_exact(conn, ln, expected=ln)
                recs = list(_wal.iter_records(head + body))
                if not recs:
                    raise networking.ProtocolError(
                        "corrupt replication record", retryable=False
                    )
                rec_type = recs[0][0]
                with self._lock:
                    self._records += 1
                    with _trace.span("deploy.apply",
                                     args={"shard": self.shard_id}):
                        _wal.replay_record(
                            self._state, rec_type, recs[0][1],
                            self.rule, self.num_workers, self.ema_decay,
                        )
                    self._forward_locked(head, body)
                    if self.on_apply is not None:
                        self.on_apply(self, rec_type, self._state)
        finally:
            with self._lock:
                self._streaming = False

    # -- chain side ----------------------------------------------------------

    def attach_successor(self, host: str, port: int,
                         timeout: float = 10.0) -> None:
        """Chain another read replica behind this one. Before the base
        state arrives the address is parked and the handshake happens
        inside the base install (gap-free); after it, the successor gets
        this replica's CURRENT state as its base under the apply lock."""
        with self._lock:
            self._successor_addr = (host, int(port))
            self._successor_timeout = float(timeout)
            if self._state is not None:
                self._connect_successor_locked()

    def _connect_successor_locked(self) -> None:
        host, port = self._successor_addr
        timeout = getattr(self, "_successor_timeout", 10.0)
        sock = networking.connect(host, port, timeout=timeout)
        sock.settimeout(timeout)
        base = {k: v for k, v in self._state.items()
                if k not in ("replayed", "_flat")}
        networking.send_data(
            sock, {"action": "replicate_stream", "state": base}
        )
        reply = networking.recv_data(sock)
        if not reply.get("ok"):
            sock.close()
            raise ConnectionError(
                f"read replica at {host}:{port} refused the stream: {reply}"
            )
        sock.settimeout(5.0)  # bounded per-record forward
        self._successor_sock = sock

    def _forward_locked(self, head: bytes, body: bytes) -> None:
        sock = self._successor_sock
        if sock is None:
            return
        try:
            with _trace.span("deploy.forward"):
                sock.sendall(head)
                sock.sendall(body)
        except OSError:
            self._successor_sock = None
            self._n_forward_drops += 1
            try:
                sock.close()
            except OSError:
                pass

    # -- reads ---------------------------------------------------------------

    @property
    def num_updates(self) -> int:
        with self._lock:
            return int((self._state or {}).get("num_updates", 0))

    @property
    def epoch_mark(self) -> int | None:
        with self._lock:
            mark = (self._state or {}).get("epoch_mark")
        return None if mark is None else int(mark)

    def snapshot_center(self):
        """``(version, center copy)`` at a consistent instant (under the
        apply lock — no record lands mid-copy)."""
        with self._lock:
            if self._state is None:
                return 0, None
            return (int(self._state["num_updates"]),
                    _tree_copy(self._state["center"]))

    def stats(self) -> dict:
        with self._lock:
            return {
                "shard_id": self.shard_id,
                "records": self._records,
                "num_updates": int((self._state or {}).get("num_updates", 0)),
                "streaming": self._streaming,
                "forward_drops": self._n_forward_drops,
            }

    def stop(self) -> None:
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            sock = self._successor_sock
            self._successor_sock = None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


class WeightStreamer:
    """One serving host's streaming attachment: per-shard read replicas +
    the snapshot cut policy + the consistent-cut assembler.

    - ``snapshot_every``: cut a snapshot when a shard's fold count
      crosses a multiple of it (0 disables fold-count cuts).
    - training-epoch marks (``REC_EPOCH``) always cut, and carry the
      epoch into the snapshot (and the elastic checkpoint, if a
      ``checkpoint_dir`` is set on the store).
    - a sharded center publishes only when EVERY shard was captured at
      the same version ``F`` (each shard passes through ``F`` exactly
      once, so the captures exist; one slow shard delays the cut, which
      is exactly what ``deploy_lag_folds`` then shows).

    Captures happen under the per-shard apply lock (an O(shard) copy at
    snapshot cadence); assembly/publish/checkpoint run on a background
    publisher thread so the apply loop — and the chain forward behind it
    — never stalls on a join or a disk write.
    """

    def __init__(self, rule, num_workers: int, *, plan=None,
                 ema_decay: float | None = None, snapshot_every: int = 50,
                 keep: int = 4, store: SnapshotStore | None = None,
                 checkpoint_dir: str | None = None,
                 host: str = "127.0.0.1",
                 report: Callable[[int], None] | None = None):
        if snapshot_every < 0:
            raise ValueError(
                f"snapshot_every must be >= 0, got {snapshot_every}"
            )
        self.plan = plan
        self.snapshot_every = int(snapshot_every)
        self.store = store if store is not None else SnapshotStore(
            keep=keep, checkpoint_dir=checkpoint_dir
        )
        self._report = report
        n = 1 if plan is None else int(plan.num_shards)
        self.replicas = [
            ReadReplica(rule, num_workers, ema_decay=ema_decay, host=host,
                        shard_id=sid, on_apply=self._on_apply)
            for sid in range(n)
        ]
        # version → {sid: (tree, epoch|None)} pending shard captures
        self._mu = threading.Lock()
        self._pending: dict[int, dict[int, tuple]] = {}
        self._q: queue.Queue = queue.Queue()
        self._closed = False
        self._publisher = threading.Thread(
            target=self._publish_loop, daemon=True, name="weight-streamer"
        )
        self._publisher.start()

    # -- wiring --------------------------------------------------------------

    def attach_to(self, ps) -> None:
        """Subscribe to ``ps``'s replication stream. ``ps`` is a single
        PS (plain or standby chain tail) or a ``ShardedPSGroup`` — for a
        group, each shard's chain TAIL (or primary, chainless groups)
        attaches its matching replica. Also adopts ``ps`` as the deploy
        report sink unless one was given at construction."""
        chains = getattr(ps, "chains", None)
        servers = getattr(ps, "servers", None)
        if chains is not None and servers is not None:  # sharded group
            if len(self.replicas) != len(servers):
                raise ValueError(
                    f"streamer built for {len(self.replicas)} shard(s) but "
                    f"the group has {len(servers)}"
                )
            for sid, rep in enumerate(self.replicas):
                tail = chains[sid][-1] if chains and chains[sid] \
                    else servers[sid]
                tail.attach_standby(rep.host, rep.port)
        else:
            if len(self.replicas) != 1:
                raise ValueError(
                    "sharded streamer attached to an unsharded server"
                )
            if getattr(ps, "has_standby", False):
                raise ValueError(
                    "the server's replica slot is taken (hot standby) — "
                    "attach the streamer to the chain tail instead"
                )
            ps.attach_standby(self.replicas[0].host, self.replicas[0].port)
        if self._report is None:
            sink = getattr(ps, "report_deploy_version", None)
            if sink is not None:
                self._report = sink

    def chain_to(self, other: "WeightStreamer") -> None:
        """Forward this host's stream to ``other`` (per matching shard)
        — N serving hosts share the trainer's single replica slot."""
        if len(other.replicas) != len(self.replicas):
            raise ValueError("chained streamers must have equal shard counts")
        for rep, succ in zip(self.replicas, other.replicas):
            rep.attach_successor(succ.host, succ.port)
        if other._report is None:
            other._report = self._report

    # -- cut policy ----------------------------------------------------------

    def _on_apply(self, replica: ReadReplica, rec_type: int,
                  state: dict) -> None:
        # called under the replica's apply lock: keep it O(1) except at
        # cut points, where the O(shard) copy is the point
        from distkeras_tpu.resilience import wal as _wal

        v = int(state["num_updates"])
        if rec_type == _wal.REC_EPOCH:
            epoch = state.get("epoch_mark")
            if v > 0:
                self._capture(replica, state, v, epoch)
            return
        if rec_type in (_wal.REC_COMMIT, _wal.REC_COMMIT2,
                        _wal.REC_COMMIT_WIRE, _wal.REC_COMMIT_FLAT):
            if self.snapshot_every and v and v % self.snapshot_every == 0:
                self._capture(replica, state, v, None)

    def _capture(self, replica: ReadReplica, state: dict, version: int,
                 epoch) -> None:
        if "_flat" in state:
            # native flat replay keeps the center as a flat vector until
            # stream end; cutting mid-flat would need a spec unflatten —
            # materialize through the replica's own view instead
            from distkeras_tpu.resilience.wal import _flat_replay_state

            flat = _flat_replay_state(state)
            tree = flat["spec"].unflatten(flat["c"].copy())
        else:
            tree = _tree_copy(state["center"])
        self._q.put((replica.shard_id, version, epoch, tree))

    # -- assembly / publish --------------------------------------------------

    def _publish_loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            sid, version, epoch, tree = item
            ready = None
            with self._mu:
                slot = self._pending.setdefault(version, {})
                slot[sid] = (tree, epoch)
                if len(slot) == len(self.replicas):
                    ready = self._pending.pop(version)
                    # an older cut can never complete once a newer one
                    # has: every shard passes each version exactly once
                    for stale in [x for x in self._pending if x < version]:
                        del self._pending[stale]
            if ready is None:
                continue
            if self.plan is None:
                tree, epoch = ready[0]
            else:
                parts = [ready[sid][0] for sid in range(len(self.replicas))]
                tree = self.plan.join(parts)
                epochs = {e for _, e in ready.values() if e is not None}
                epoch = min(epochs) if epochs else None
            if self.store.publish(version, tree, epoch=epoch):
                _trace.instant("deploy.snapshot", cat="deploy",
                               args={"version": version,
                                     "epoch": -1 if epoch is None else epoch})
                if self._report is not None:
                    try:
                        self._report(version)
                    except Exception:
                        pass  # a dead trainer must not kill publishing

    # -- reads / teardown ----------------------------------------------------

    def stats(self) -> dict:
        latest = self.store.latest()
        return {
            "replicas": [r.stats() for r in self.replicas],
            "published": self.store.published,
            "latest_version": 0 if latest is None else latest.version,
            "checkpoints_written": self.store.checkpoints_written,
        }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for rep in self.replicas:
            rep.stop()
        self._q.put(None)
        self._publisher.join(timeout=5.0)
