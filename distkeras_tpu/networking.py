"""Transport layer: length-prefixed framing over TCP, host discovery.

Parity: reference ``distkeras/networking.py`` — ``determine_host_address()``,
``connect(host, port)``, ``send_data(sock, obj)`` / ``recv_data(sock)`` with
pickled, length-prefixed frames (SURVEY.md §2b #13).

Role in the rebuild: the DEFAULT parameter exchange is XLA collectives over
ICI and never touches this module. TCP framing remains for the genuinely
asynchronous parameter-server backend (``backend="ps"`` with
``ps_transport="socket"``) — the path that generalizes to a PS reachable over
DCN from multiple pod slices, where a compiler-scheduled collective cannot
express true asynchrony.

Framing: 8-byte big-endian length + payload. Payloads are control dicts whose
weight pytrees are plain containers (dict/list/tuple) of numpy arrays, decoded
by a restricted unpickler that resolves no globals beyond numpy array
reconstruction — a forged frame cannot execute code or allocate unboundedly
(length cap). The PS binds loopback by default; as in the reference, expose it
beyond the job's network only deliberately.
"""

from __future__ import annotations

import io
import os
import pickle
import socket
import struct
from typing import Any

_LEN = struct.Struct(">Q")

#: Upper bound on accepted frame size (defense in depth: a malformed or
#: malicious length prefix must not trigger multi-GB allocations). 2 GiB is
#: far above any weight blob this framework ships in one frame.
MAX_FRAME_BYTES = 2 * 1024 * 1024 * 1024

#: Fault-injection seam (resilience/faults.py installs here): a callable
#: ``hook(op, sock)`` with op in {"send", "recv"} invoked at the top of
#: every framed wire operation. It may sleep (delay fault) or raise a
#: ConnectionError subclass (drop/partition fault). None = production path,
#: zero overhead beyond one attribute read.
_fault_hook = None


class ProtocolError(ConnectionError):
    """A framed wire operation failed or produced a malformed frame.

    Subclasses ConnectionError so every pre-existing ``except
    (ConnectionError, ...)`` keeps catching it; the retry layer
    (``resilience.retry``) looks at ``retryable`` to separate transient
    transport failures (peer died mid-frame — reconnect and retry) from
    protocol violations (oversized/garbled frames — a peer speaking a
    different protocol, where retrying the same bytes can only fail again).
    """

    def __init__(self, message: str, *, frame_size: int | None = None,
                 peer: str | None = None, retryable: bool = True):
        ctx = []
        if frame_size is not None:
            ctx.append(f"frame={frame_size}B")
        if peer:
            ctx.append(f"peer={peer}")
        super().__init__(f"{message} [{', '.join(ctx)}]" if ctx else message)
        self.frame_size = frame_size
        self.peer = peer
        self.retryable = retryable


class PeerDeadError(ProtocolError):
    """The other end of a shared-memory ring died or closed mid-operation
    (``distkeras_tpu/shm.py``): its closed flag is set, its pid is gone,
    or a mid-record transfer stalled past the liveness deadline.
    Retryable by design — it is the shm lane's equivalent of a torn TCP
    connection, and the resilient client answers it the same way (tear
    the conn, mint a fresh ring pair, replay the op under the seqno
    dedup). The server-side handler treats it as connection death: the
    handler exits and the segment is unlinked, so a worker that dies
    mid-ring-write can never wedge the server or leak /dev/shm."""

    def __init__(self, message: str, *, peer: str | None = None):
        super().__init__(message, peer=peer, retryable=True)


class FencedEpochError(ProtocolError):
    """A parameter-server rejected an operation carrying a stale fencing
    epoch: a failover promoted a new primary (or a restart bumped the
    epoch) and this client's token predates it. NOT retryable against the
    same server — the epoch mismatch is deterministic, a replay can only
    be fenced again. The resilient client treats it as retryable ONLY
    when its endpoint resolver has already moved to a newer epoch (the
    reconnect adopts the new token); without that, it is the fatal signal
    that this worker belongs to a superseded history.
    """

    def __init__(self, message: str, *, client_epoch: int | None = None,
                 server_epoch: int | None = None, peer: str | None = None):
        ctx = ""
        if client_epoch is not None or server_epoch is not None:
            ctx = f" (client epoch {client_epoch}, server epoch {server_epoch})"
        super().__init__(message + ctx, peer=peer, retryable=False)
        self.client_epoch = client_epoch
        self.server_epoch = server_epoch


class ShardMapMismatchError(ProtocolError):
    """A sharded-PS client is wired to the wrong shard: the endpoint's
    shard-map handshake (shard id, shard count, ring digest — see
    ``distkeras_tpu/sharding``) disagrees with the client's plan. NOT
    retryable: the mismatch is deterministic configuration, and folding
    leaves into the wrong shard's center would silently corrupt training
    — failing fast here is the whole point of the handshake."""

    def __init__(self, message: str, *, peer: str | None = None):
        super().__init__(message, peer=peer, retryable=False)


class ServerBusyError(ProtocolError):
    """The serving tier's bounded admission queue is full — backpressure,
    not failure. Retryable by design: the reconnecting client backs off
    (jittered, via ``resilience.retry``) and resubmits; an open-loop load
    source that ignores it is choosing to drop the request. ``retry_after``
    is the server's hint (seconds) when it has one."""

    def __init__(self, message: str = "server busy: admission queue full",
                 *, retry_after: float | None = None,
                 peer: str | None = None):
        super().__init__(message, peer=peer, retryable=True)
        self.retry_after = retry_after


def _peer_of(sock: socket.socket) -> str | None:
    """Best-effort peer label for error context (never raises)."""
    try:
        peer = sock.getpeername()
    except OSError:
        return None
    if isinstance(peer, tuple) and len(peer) >= 2:
        return f"{peer[0]}:{peer[1]}"
    return str(peer)


class _RestrictedUnpickler(pickle.Unpickler):
    """Unpickler for control frames: primitives + numpy arrays only.

    Frames on this wire are control dicts of primitives (actions, ids,
    serialized-weight ``bytes`` blobs) and occasionally bare numpy arrays;
    no other global may be resolved, closing the arbitrary-code-execution
    hole that ``pickle.loads`` on untrusted bytes opens.
    """

    _ALLOWED = {
        ("numpy", "ndarray"),
        ("numpy", "dtype"),
        ("numpy._core.multiarray", "_reconstruct"),
        ("numpy.core.multiarray", "_reconstruct"),
        ("numpy._core.multiarray", "scalar"),
        ("numpy.core.multiarray", "scalar"),
        ("numpy._core.numeric", "_frombuffer"),
        ("numpy.core.numeric", "_frombuffer"),
    }

    def find_class(self, module, name):
        if (module, name) in self._ALLOWED:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"frame tried to load disallowed global {module}.{name}"
        )


def determine_host_address() -> str:
    """Best-effort routable address of this host.

    Parity: reference ``distkeras/networking.py :: determine_host_address``.
    Prefers the TPU-pod worker address from the metadata env
    (``TPU_WORKER_HOSTNAMES``/``TPU_WORKER_ID``) when present — on an
    airgapped pod the UDP-connect trick below can pick an interface that is
    routable-looking but wrong for DCN. Otherwise uses the UDP-connect trick
    (no packets are sent — 8.8.8.8 only selects the default route's
    interface); falls back to loopback on fully isolated hosts.
    """
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    worker_id = os.environ.get("TPU_WORKER_ID", "")
    if hostnames and worker_id.isdigit():
        # index the RAW split: filtering blanks first would misalign ids
        hosts = hostnames.split(",")
        if int(worker_id) < len(hosts) and hosts[int(worker_id)].strip():
            return hosts[int(worker_id)].strip()
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def connect(host: str, port: int, timeout: float | None = 30.0) -> socket.socket:
    """Open a TCP connection with Nagle disabled (small-frame latency)."""
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def send_data(sock: socket.socket, obj: Any) -> None:
    if _fault_hook is not None:
        _fault_hook("send", sock)
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    prefix = _LEN.pack(len(payload))
    if not hasattr(sock, "sendmsg"):  # e.g. a test double wrapping send
        sock.sendall(prefix + payload)
        return
    # gather-write the 8-byte prefix + payload (zero-copy host staging,
    # ISSUE 10): the historical `prefix + payload` concat copied the whole
    # O(model) weight frame once per send just to prepend 8 bytes
    sent = sock.sendmsg([prefix, payload])
    total = len(prefix) + len(payload)
    if sent < total:
        # partial gather write (huge frame vs socket buffer): finish with
        # sendall over zero-copy memoryviews of the remainder
        if sent < len(prefix):
            sock.sendall(prefix[sent:])
            sent = len(prefix)
        sock.sendall(memoryview(payload)[sent - len(prefix):])


def _recv_exact(sock: socket.socket, n: int, expected: int | None = None) -> bytes:
    """Read exactly ``n`` bytes; a mid-frame close raises a retryable
    ProtocolError carrying the frame size and peer context. ``expected``
    is the full frame length when known (body reads), so the error names
    the frame being lost, not just the remaining bytes."""
    chunks = []
    want = n
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ProtocolError(
                f"socket closed mid-frame ({want - n} of {want} bytes read)",
                frame_size=expected if expected is not None else want,
                peer=_peer_of(sock), retryable=True,
            )
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def decode_frame(raw: bytes) -> Any:
    """Decode one frame's payload bytes through the SAME restricted
    unpickler the socket wire uses. Shared with the shm transport
    (``distkeras_tpu/shm.py``) and WAL wire-frame replay so every lane's
    decode pipeline is literally this one function — a frame logged
    verbatim from any transport replays bit-identically."""
    return _RestrictedUnpickler(io.BytesIO(raw)).load()


def recv_data(sock: socket.socket, max_bytes: int = MAX_FRAME_BYTES) -> Any:
    return recv_data_raw(sock, max_bytes)[0]


def recv_data_raw(sock: socket.socket,
                  max_bytes: int = MAX_FRAME_BYTES) -> tuple[Any, bytes]:
    """Like :func:`recv_data`, but also returns the frame's raw pickled
    bytes. The durable PS logs a commit's wire bytes VERBATIM
    (``resilience/wal.py :: REC_COMMIT_WIRE``) instead of re-serializing
    the decoded tree — one O(model) pickle pass saved per durable commit
    on its hot path."""
    if _fault_hook is not None:
        _fault_hook("recv", sock)
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if length > max_bytes:
        # NOT retryable: the peer is speaking a different (or hostile)
        # protocol — the same frame would bust the cap on every retry
        raise ProtocolError(
            f"frame of {length} bytes exceeds the {max_bytes}-byte cap",
            frame_size=int(length), peer=_peer_of(sock), retryable=False,
        )
    raw = _recv_exact(sock, length, expected=int(length))
    return decode_frame(raw), raw
