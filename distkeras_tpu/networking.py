"""Transport layer: length-prefixed framing over TCP, host discovery.

Parity: reference ``distkeras/networking.py`` — ``determine_host_address()``,
``connect(host, port)``, ``send_data(sock, obj)`` / ``recv_data(sock)`` with
pickled, length-prefixed frames (SURVEY.md §2b #13).

Role in the rebuild: the DEFAULT parameter exchange is XLA collectives over
ICI and never touches this module. TCP framing remains for the genuinely
asynchronous parameter-server backend (``backend="ps"`` with
``ps_transport="socket"``) — the path that generalizes to a PS reachable over
DCN from multiple pod slices, where a compiler-scheduled collective cannot
express true asynchrony.

Framing: 8-byte big-endian length + payload. Payloads are
``utils.serialize_weights`` blobs or small pickled control dicts; as in the
reference, the wire format assumes both ends are the same trusted training
job (do not expose the PS port beyond the job's network).
"""

from __future__ import annotations

import pickle
import socket
import struct
from typing import Any

_LEN = struct.Struct(">Q")


def determine_host_address() -> str:
    """Best-effort routable address of this host.

    Parity: reference ``distkeras/networking.py :: determine_host_address``.
    Uses the UDP-connect trick (no packets sent); falls back to loopback on
    isolated hosts.
    """
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def connect(host: str, port: int, timeout: float | None = 30.0) -> socket.socket:
    """Open a TCP connection with Nagle disabled (small-frame latency)."""
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def send_data(sock: socket.socket, obj: Any) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("socket closed mid-frame")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_data(sock: socket.socket) -> Any:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, length))
