"""Evaluators: score a prediction column against a label column.

Parity: reference ``distkeras/evaluators.py :: AccuracyEvaluator``
(SURVEY.md §2b #17), extended with loss-based evaluation.
"""

from __future__ import annotations

import numpy as np

from distkeras_tpu.data import Dataset
from distkeras_tpu.ops.losses import get_loss


class AccuracyEvaluator:
    """Fraction of rows where prediction matches label.

    Handles prediction columns holding class scores (argmaxed), probabilities,
    or already-integer indices; labels one-hot or integer.
    """

    def __init__(self, prediction_col: str = "prediction", label_col: str = "label"):
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, ds: Dataset) -> float:
        pred = _class_indices(ds[self.prediction_col], len(ds))
        label = _class_indices(ds[self.label_col], len(ds))
        return float(np.mean(pred == label))


class LossEvaluator:
    """Mean loss of a prediction column vs labels (any registered loss)."""

    def __init__(self, loss="mse", prediction_col: str = "prediction",
                 label_col: str = "label"):
        self.loss_fn = get_loss(loss)
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, ds: Dataset) -> float:
        return float(self.loss_fn(ds[self.label_col], ds[self.prediction_col]))


def _class_indices(arr, n_rows: int) -> np.ndarray:
    """Scores [N, C] → argmax; one-hot → argmax; integers pass through."""
    arr = np.asarray(arr)
    if arr.ndim > 1 and arr.shape[-1] > 1:
        return np.argmax(arr, axis=-1).astype(np.int64)
    return np.round(arr.reshape(n_rows, -1)[:, 0]).astype(np.int64)


class FScoreEvaluator:
    """Precision / recall / F1 (beyond the reference's accuracy-only module).

    ``average="binary"`` scores class ``pos_label`` only; ``"macro"``
    averages the per-class scores unweighted over the union of classes
    present in the labels or the predictions (sklearn semantics — a class
    predicted but absent from the eval split still counts, as 0).
    Zero-division cases score 0, sklearn-style.
    """

    def __init__(self, metric: str = "f1", average: str = "binary",
                 pos_label: int = 1, prediction_col: str = "prediction",
                 label_col: str = "label"):
        if metric not in ("f1", "precision", "recall"):
            raise ValueError(
                f"metric={metric!r}: expected 'f1', 'precision', or 'recall'"
            )
        if average not in ("binary", "macro"):
            raise ValueError(
                f"average={average!r}: expected 'binary' or 'macro'"
            )
        self.metric = metric
        self.average = average
        self.pos_label = int(pos_label)
        self.prediction_col = prediction_col
        self.label_col = label_col

    def _score_one(self, pred, label, cls: int) -> float:
        tp = float(np.sum((pred == cls) & (label == cls)))
        fp = float(np.sum((pred == cls) & (label != cls)))
        fn = float(np.sum((pred != cls) & (label == cls)))
        precision = tp / (tp + fp) if tp + fp else 0.0
        recall = tp / (tp + fn) if tp + fn else 0.0
        if self.metric == "precision":
            return precision
        if self.metric == "recall":
            return recall
        if precision + recall == 0.0:
            return 0.0
        return 2 * precision * recall / (precision + recall)

    def evaluate(self, ds: Dataset) -> float:
        pred = _class_indices(ds[self.prediction_col], len(ds))
        label = _class_indices(ds[self.label_col], len(ds))
        if self.average == "binary":
            return self._score_one(pred, label, self.pos_label)
        classes = np.union1d(np.unique(label), np.unique(pred))
        return float(np.mean(
            [self._score_one(pred, label, int(c)) for c in classes]
        ))


class AUCEvaluator:
    """ROC AUC from a score column (rank statistic, ties averaged).

    The prediction column may hold a single score per row or ``[N, C]``
    class scores — the ``pos_label`` column is the score and rows with
    ``label == pos_label`` are the positives (one-vs-rest for C > 2).
    A single score column is the score FOR class ``pos_label``: with
    ``pos_label == 0`` the 1-D scores are negated so "higher score" still
    means "more positive" (mirroring the column-select of the [N, C] path).
    """

    def __init__(self, prediction_col: str = "prediction",
                 label_col: str = "label", pos_label: int = 1):
        self.prediction_col = prediction_col
        self.label_col = label_col
        self.pos_label = int(pos_label)

    def evaluate(self, ds: Dataset) -> float:
        scores = np.asarray(ds[self.prediction_col], np.float64)
        if scores.ndim > 1 and scores.shape[-1] > 1:
            if self.pos_label >= scores.shape[-1]:
                raise ValueError(
                    f"pos_label {self.pos_label} out of range for "
                    f"[N, {scores.shape[-1]}] score matrix"
                )
            scores = scores[:, self.pos_label]
        else:
            scores = scores.reshape(len(ds))
            if self.pos_label == 0:
                scores = -scores
            elif self.pos_label != 1:
                raise ValueError(
                    f"pos_label {self.pos_label} needs [N, C] class scores; "
                    "a single score column only identifies class 0 vs 1"
                )
        label = _class_indices(ds[self.label_col], len(ds))
        pos = label == self.pos_label
        n_pos, n_neg = int(pos.sum()), int((~pos).sum())
        if not n_pos or not n_neg:
            raise ValueError(
                f"AUC needs both classes; got {n_pos} positive / "
                f"{n_neg} negative rows"
            )
        # Mann-Whitney U via tie-averaged ranks, fully vectorized: each tie
        # group gets rank first_index + (count-1)/2 + 1
        order = np.argsort(scores, kind="mergesort")
        s = scores[order]
        uniq_first = np.flatnonzero(np.r_[True, s[1:] != s[:-1]])
        counts = np.diff(np.append(uniq_first, len(s)))
        group_rank = uniq_first + (counts - 1) / 2.0 + 1.0
        ranks = np.empty(len(s), np.float64)
        ranks[order] = np.repeat(group_rank, counts)
        u = ranks[pos].sum() - n_pos * (n_pos + 1) / 2.0
        return float(u / (n_pos * n_neg))
