"""Evaluators: score a prediction column against a label column.

Parity: reference ``distkeras/evaluators.py :: AccuracyEvaluator``
(SURVEY.md §2b #17), extended with loss-based evaluation.
"""

from __future__ import annotations

import numpy as np

from distkeras_tpu.data import Dataset
from distkeras_tpu.ops.losses import get_loss


class AccuracyEvaluator:
    """Fraction of rows where prediction matches label.

    Handles prediction columns holding class scores (argmaxed), probabilities,
    or already-integer indices; labels one-hot or integer.
    """

    def __init__(self, prediction_col: str = "prediction", label_col: str = "label"):
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, ds: Dataset) -> float:
        pred = ds[self.prediction_col]
        if pred.ndim > 1 and pred.shape[-1] > 1:
            pred = np.argmax(pred, axis=-1)
        else:
            pred = np.round(pred.reshape(len(ds), -1)[:, 0])
        label = ds[self.label_col]
        if label.ndim > 1 and label.shape[-1] > 1:
            label = np.argmax(label, axis=-1)
        else:
            label = label.reshape(len(ds), -1)[:, 0]
        return float(np.mean(pred.astype(np.int64) == label.astype(np.int64)))


class LossEvaluator:
    """Mean loss of a prediction column vs labels (any registered loss)."""

    def __init__(self, loss="mse", prediction_col: str = "prediction",
                 label_col: str = "label"):
        self.loss_fn = get_loss(loss)
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, ds: Dataset) -> float:
        return float(self.loss_fn(ds[self.label_col], ds[self.prediction_col]))
