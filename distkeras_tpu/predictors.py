"""Inference path: map a trained model over a Dataset.

Parity: reference ``distkeras/predictors.py :: ModelPredictor`` —
``predict(df)`` appended a ``'prediction'`` column by deserializing the model
once per Spark partition and looping rows (SURVEY.md §3.4). Here prediction is
one jitted batched apply per fixed-size chunk: rows are padded to a static
batch so XLA compiles exactly once, and the pad rows are trimmed on the host.
On a mesh, batches are sharded over ``dp`` so inference scales like training.
"""

from __future__ import annotations

import jax
import numpy as np

from distkeras_tpu.data import Dataset, padded_chunks
from distkeras_tpu.model import ModelSpec, from_keras
from distkeras_tpu.parallel.mesh import put_global


class ModelPredictor:
    """Append a prediction column computed by a trained model.

    Accepts a Keras 3 model (weights already trained — the reference contract)
    or a ``ModelSpec`` plus explicit ``(params, state)`` pytrees, e.g. a
    trainer's ``trained_params_`` / ``trained_nt_``.
    """

    def __init__(self, model, params=None, state=None,
                 features_col="features", output_col: str = "prediction",
                 batch_size: int = 512, mesh=None, dp_axis: str = "dp",
                 quantize: bool = False):
        if isinstance(model, ModelSpec):
            if params is None:
                raise ValueError("ModelSpec predictor needs explicit params")
            self.spec = model
            self.params = params
            self.state = state if state is not None else {}
        else:
            self.spec = from_keras(model)
            self.params, self.state = self.spec.init_np()
        if quantize:
            # int8 weight-only serving (ops/quant.py): every Dense kernel
            # streams int8 from HBM; flax-backed specs only
            from distkeras_tpu.ops.quant import quantize_serving

            self.spec, self.params = quantize_serving(
                self.spec, self.params, state=self.state
            )
        self.features_col = (
            [features_col] if isinstance(features_col, str) else list(features_col)
        )
        self.output_col = output_col
        self.batch_size = int(batch_size)
        # data-parallel inference (the reference mapped prediction over the
        # Spark cluster — SURVEY.md §3.4): rows shard over `dp_axis`, params
        # replicate, one jitted apply per chunk as before
        self._x_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            if dp_axis not in mesh.shape:
                raise ValueError(
                    f"dp_axis {dp_axis!r} not in mesh axes "
                    f"{tuple(mesh.shape.keys())}"
                )
            dp = mesh.shape[dp_axis]
            if self.batch_size % dp:
                raise ValueError(
                    f"batch_size {self.batch_size} not divisible by mesh "
                    f"axis '{dp_axis}' of size {dp}"
                )
            self._x_sharding = NamedSharding(mesh, P(dp_axis))
            rep = NamedSharding(mesh, P())
            self.params = jax.tree.map(lambda p: put_global(p, rep), self.params)
            self.state = jax.tree.map(lambda s: put_global(s, rep), self.state)
        spec = self.spec

        def fwd(params, state, x):
            out, _ = spec.apply(params, state, x, False)
            return out

        self._fwd = jax.jit(fwd)

    def predict(self, ds: Dataset) -> Dataset:
        cols = [ds[c] for c in self.features_col]
        outs = []
        for chunk, real in padded_chunks(cols, self.batch_size):
            if self._x_sharding is not None:
                chunk = [put_global(c, self._x_sharding) for c in chunk]
            x = chunk[0] if len(chunk) == 1 else tuple(chunk)
            out = np.asarray(self._fwd(self.params, self.state, x))
            outs.append(out[:real])
        return ds.with_column(self.output_col, np.concatenate(outs))


class LabelIndexPredictor(ModelPredictor):
    """ModelPredictor that emits argmaxed class indices directly."""

    def predict(self, ds: Dataset) -> Dataset:
        out = super().predict(ds)
        return out.with_column(
            self.output_col, np.argmax(out[self.output_col], axis=-1).astype(np.int32)
        )


class GeneratorPredictor:
    """Map KV-cached autoregressive decoding over a Dataset of prompts.

    Beyond-reference sibling of ``ModelPredictor`` for the causal-LM family
    (``models.transformer_lm``): appends a column of newly generated tokens
    ``[N, max_new_tokens]``. Prompts are processed in fixed-size chunks
    (static shapes — XLA compiles the prefill+scan program once); pad rows
    are generated and discarded. ``beams > 1`` decodes with
    :func:`models.beam_search` instead of sampling and keeps each row's
    best beam (``temperature``/``top_k``/``top_p`` must stay at their
    greedy defaults — beam search is deterministic).

    ``eos_id`` stops rows at end-of-sequence on BOTH paths (sampling rows
    pad with ``eos_id`` after the first hit — the static output shape
    never changes); ``per_row_new_tokens=True`` adds a companion
    ``{output_col}_new_tokens`` int32 column counting each row's real
    tokens up to and including its eos, computed by the serving tier's
    retire rule (:func:`distkeras_tpu.serving.per_row_new_token_counts`)
    rather than a second local eos-scan that could drift from it.
    """

    def __init__(self, model, params, *, features_col: str = "features",
                 output_col: str = "generated", max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int | None = None,
                 top_p: float | None = None,
                 seed: int = 0, batch_size: int = 64, beams: int = 1,
                 length_penalty: float = 0.0, eos_id: int | None = None,
                 per_row_new_tokens: bool = False):
        from distkeras_tpu.models.lm import TransformerLM

        module = model.module if isinstance(model, ModelSpec) else model
        if not isinstance(module, TransformerLM):
            raise TypeError(
                f"GeneratorPredictor needs a TransformerLM (or its "
                f"ModelSpec), got {type(module)}"
            )
        self.model = model
        self.params = params
        self.features_col = features_col
        self.output_col = output_col
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        self.seed = int(seed)
        self.batch_size = int(batch_size)
        self.beams = int(beams)
        self.length_penalty = float(length_penalty)
        self.eos_id = eos_id
        self.per_row_new_tokens = bool(per_row_new_tokens)
        if self.beams < 1:
            raise ValueError(f"beams must be >= 1, got {beams}")
        if self.beams > 1 and (
            self.temperature != 0.0 or top_k is not None or top_p is not None
        ):
            raise ValueError(
                "beam search is deterministic: temperature/top_k/top_p "
                "cannot be combined with beams > 1"
            )
        if self.beams == 1 and self.length_penalty:
            raise ValueError(
                "length_penalty is a beam-search option: sampling decode "
                "(beams=1) would silently ignore it — set beams > 1"
            )

    def predict(self, ds: Dataset) -> Dataset:
        from distkeras_tpu.models.lm import beam_search, generate
        from distkeras_tpu.serving import per_row_new_token_counts

        outs = []
        for i, ((chunk,), real) in enumerate(padded_chunks(
            [np.asarray(ds[self.features_col])], self.batch_size
        )):
            if self.beams > 1:
                toks, _ = beam_search(
                    self.model, self.params, chunk, self.max_new_tokens,
                    beams=self.beams, length_penalty=self.length_penalty,
                    eos_id=self.eos_id,
                )
                full = toks[:, 0]  # best beam per row
            else:
                full = generate(
                    self.model, self.params, chunk, self.max_new_tokens,
                    temperature=self.temperature, top_k=self.top_k,
                    top_p=self.top_p, eos_id=self.eos_id,
                    # distinct stream per chunk — identical prompts in
                    # different chunks must not draw identical samples
                    seed=self.seed + i,
                )
            outs.append(full[:real, chunk.shape[1]:])
        out = ds.with_column(self.output_col, np.concatenate(outs))
        if self.per_row_new_tokens:
            out = out.with_column(
                f"{self.output_col}_new_tokens",
                per_row_new_token_counts(out[self.output_col], self.eos_id),
            )
        return out
