"""distkeras_tpu — TPU-native rebuild of dist-keras (CAOYUE19930616/dist-keras).

The reference framework is data-parallel distributed training of Keras models on
Apache Spark: replicas are placed with ``rdd.mapPartitionsWithIndex`` and exchange
weights with a driver-hosted TCP-socket parameter server
(reference: ``distkeras/trainers.py``, ``distkeras/workers.py``,
``distkeras/parameter_servers.py``, ``distkeras/networking.py`` — cited at
module/symbol granularity throughout this repo because the reference mount was
empty at survey time; see SURVEY.md §0).

This rebuild keeps the trainer API surface
(``SingleTrainer, ADAG, DOWNPOUR, AEASGD, EAMSGD, DynSGD``) but is TPU-first:

- one SPMD replica per chip over a ``jax.sharding.Mesh`` (axis ``'dp'``) instead
  of Spark executors;
- the pull/commit parameter exchange is lowered to XLA collectives
  (``psum``/``pmean`` over ICI) executed as each algorithm's *merge rule* at
  communication-window boundaries (``distkeras_tpu.parallel``);
- an optional genuinely-asynchronous parameter-server backend (host threads +
  TCP, ``distkeras_tpu.parameter_servers``) preserves the reference's async
  semantics for multi-slice/DCN deployments.

``import distkeras`` is provided as a drop-in alias package.
"""

import os

# The reference ran Keras on Theano/TF1; this rebuild runs Keras 3 on JAX.
# Must be set before `import keras` anywhere in the process.
os.environ.setdefault("KERAS_BACKEND", "jax")

__version__ = "0.1.0"

from distkeras_tpu import utils  # noqa: E402
from distkeras_tpu.resilience import (  # noqa: E402
    FaultPlan,
    RetryPolicy,
)
from distkeras_tpu.trainers import (  # noqa: E402
    ADAG,
    AEASGD,
    DOWNPOUR,
    DynSGD,
    EAMSGD,
    MeshTrainer,
    SingleTrainer,
    Trainer,
)

__all__ = [
    "ADAG",
    "AEASGD",
    "DOWNPOUR",
    "DynSGD",
    "EAMSGD",
    "FaultPlan",
    "MeshTrainer",
    "RetryPolicy",
    "SingleTrainer",
    "Trainer",
    "utils",
    "__version__",
]
