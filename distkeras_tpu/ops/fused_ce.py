"""Chunked fused linear + softmax cross-entropy — large-vocab LM training
without the ``[B, L, V]`` logits tensor.

The plain causal-LM loss path materializes the full logits
(``hidden @ lm_head`` → ``[B, L, V]``) and then reduces them to one scalar;
at serious vocab sizes that buffer dominates training memory (B=8, L=2048,
V=64k in f32 is ~4.3 GB — before the backward doubles it with dlogits).
Only three reductions of the logits are ever needed: the per-row
log-sum-exp, the picked label logit, and (in the backward) the softmax
row. So this op computes the loss **in row chunks** inside a ``lax.scan``:
each chunk's ``[chunk, V]`` logits live only for one scan step, XLA fuses
the matmul with the log-sum-exp that consumes it, and the full logits
tensor never exists in HBM — forward *or* backward.

The backward is a :func:`jax.custom_vjp` that recomputes each chunk's
logits from the saved ``hidden`` (the flash-attention trade: FLOPs for
HBM), forms ``dlogits = softmax − onehot`` chunk-locally, and accumulates
``d_kernel`` in an f32 carry. Peak extra memory is
``O(chunk · V)`` activations + one f32 kernel-shaped accumulator, instead
of ``O(N · V)``.

This is a compiler-level fusion, not a Pallas kernel, on purpose: the
chunk matmul ``[chunk, D] · [D, V]`` is exactly MXU-shaped, and XLA already
fuses the elementwise softmax/log-sum-exp chain into its epilogue — a
hand-written kernel would re-derive what the scan structure already
guarantees (the O(chunk·V) ceiling).

Surfaced on the LM family as ``transformer_lm(fused_ce=True)`` (see
``models/lm.py``) via the ``ModelSpec.fused_losses`` seam — consumed by
the six collective/PS trainers, ``MeshTrainer(strategy="spmd")`` (any
``parameter_sharding``), and the ``validation_data`` evaluator. The
pipeline/sequence/expert strategy engines rebuild their forwards
mesh-specialized and train unfused (``MeshTrainer`` warns). The reference has no analogue (its largest head was an IMDB LSTM
classifier, SURVEY.md §5.7); this exists so the rebuild's beyond-parity LM
family trains at real vocab sizes on one chip.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _chunk_rows(n: int, chunk: int) -> tuple[int, int]:
    """Number of scan steps and padded row count."""
    steps = max(1, -(-n // chunk))
    return steps, steps * chunk


def _pad_to(x, rows):
    n = x.shape[0]
    if n == rows:
        return x
    pad = [(0, rows - n)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad)


def _chunk_logits(h_c, kernel, bias):
    """One chunk's logits in f32: ``[chunk, D] @ [D, V] (+ bias)``.

    The matmul runs in the params' dtype (bf16 on TPU → MXU) with f32
    accumulation; the softmax math downstream is all f32.
    """
    logits = jnp.dot(h_c, kernel, preferred_element_type=jnp.float32)
    logits = logits.astype(jnp.float32)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    return logits


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _fused_ce(hidden, kernel, bias, labels, mask, chunk):
    loss, _ = _fused_ce_fwd(hidden, kernel, bias, labels, mask, chunk)
    return loss


def _fused_ce_fwd(hidden, kernel, bias, labels, mask, chunk):
    n = hidden.shape[0]
    steps, rows = _chunk_rows(n, chunk)
    h = _pad_to(hidden, rows).reshape(steps, chunk, hidden.shape[1])
    lab = _pad_to(labels, rows).reshape(steps, chunk)
    m = _pad_to(mask, rows).reshape(steps, chunk)

    def body(total, args):
        h_c, lab_c, m_c = args
        logits = _chunk_logits(h_c, kernel, bias)
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, lab_c[:, None], axis=-1)[:, 0]
        return total + jnp.sum((lse - picked) * m_c), None

    total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (h, lab, m))
    msum = jnp.sum(mask)
    denom = jnp.maximum(msum, 1.0)
    return total / denom, (hidden, kernel, bias, labels, mask, total, msum)


def _fused_ce_bwd(chunk, res, g):
    hidden, kernel, bias, labels, mask, total, msum = res
    n, d = hidden.shape
    steps, rows = _chunk_rows(n, chunk)
    h = _pad_to(hidden, rows).reshape(steps, chunk, d)
    lab = _pad_to(labels, rows).reshape(steps, chunk)
    m = _pad_to(mask, rows).reshape(steps, chunk)
    v = kernel.shape[1]
    denom = jnp.maximum(msum, 1.0)
    scale = g / denom

    def body(carry, args):
        dk, db = carry
        h_c, lab_c, m_c = args
        logits = _chunk_logits(h_c, kernel, bias)
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, lab_c[:, None], axis=-1)[:, 0]
        p = jax.nn.softmax(logits, axis=-1)
        dlogits = p - jax.nn.one_hot(lab_c, v, dtype=p.dtype)
        dlogits = dlogits * (m_c * scale)[:, None]
        # dh in the hidden dtype (bf16 matmul on the MXU), dk accumulated f32
        dh_c = jnp.dot(
            dlogits.astype(hidden.dtype), kernel.T,
            preferred_element_type=jnp.float32,
        ).astype(hidden.dtype)
        dk = dk + jnp.dot(
            h_c.T, dlogits.astype(hidden.dtype),
            preferred_element_type=jnp.float32,
        ).astype(jnp.float32)
        if bias is not None:  # no [V] carry/reduction for bias-free heads
            db = db + jnp.sum(dlogits, axis=0)
        return (dk, db), (dh_c, lse - picked)

    zero_db = (jnp.zeros((), jnp.float32) if bias is None
               else jnp.zeros((v,), jnp.float32))
    zero = (jnp.zeros((d, v), jnp.float32), zero_db)
    (dk, db), (dh, nll) = jax.lax.scan(body, zero, (h, lab, m))
    dh = dh.reshape(rows, d)[:n]
    # loss = T/D with T = Σ nll_i·m_i, D = max(Σm, 1):
    # ∂loss/∂m_i = nll_i/D − T·[Σm > 1]/D² — the same weights a caller
    # differentiating the unfused masked mean would get
    ddenom = jnp.where(msum > 1.0, 1.0, 0.0)
    dmask = g * (nll.reshape(rows)[:n] / denom - total * ddenom / denom**2)
    dbias = None if bias is None else db.astype(bias.dtype)
    return (
        dh,
        dk.astype(kernel.dtype),
        dbias,
        np.zeros(labels.shape, dtype=jax.dtypes.float0),
        dmask.astype(mask.dtype),
    )


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


def chunked_softmax_cross_entropy(hidden, labels, kernel, bias=None, *,
                                  mask=None, chunk: int = 256):
    """Mean sparse softmax cross-entropy of ``hidden @ kernel (+ bias)``
    against integer ``labels``, computed ``chunk`` rows at a time.

    Equivalent to ``sparse_softmax_cross_entropy(labels, logits)`` (or its
    masked form when ``mask`` is given) with the logits accumulated in f32 —
    but the full ``[N, V]`` logits tensor is never materialized in either
    the forward or the backward pass (see module docstring).

    Args:
      hidden: ``[N, D]`` final hidden states (callers flatten ``[B, L, D]``).
      labels: ``[N]`` integer class ids.
      kernel: ``[D, V]`` head weight (any float dtype; bf16 hits the MXU).
      bias: optional ``[V]`` head bias.
      mask: optional ``[N]`` validity weights; loss is
        ``sum(nll · mask) / max(sum(mask), 1)``. Default: all rows valid.
      chunk: rows per scan step — peak logits memory is ``chunk × V`` f32.
    """
    hidden = jnp.asarray(hidden)
    if hidden.ndim != 2:
        raise ValueError(f"hidden must be [rows, dim], got {hidden.shape}")
    labels = jnp.asarray(labels, jnp.int32).reshape(hidden.shape[0])
    if mask is None:
        mask = jnp.ones((hidden.shape[0],), jnp.float32)
    else:
        mask = jnp.asarray(mask, jnp.float32).reshape(hidden.shape[0])
    if int(chunk) < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    return _fused_ce(hidden, kernel, bias, labels, mask, int(chunk))
