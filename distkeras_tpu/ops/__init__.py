"""Numerical ops: losses, metrics, and Pallas TPU kernels for the hot paths."""

from distkeras_tpu.ops import losses, metrics
from distkeras_tpu.ops.losses import get_loss
from distkeras_tpu.ops.metrics import accuracy

__all__ = ["losses", "metrics", "get_loss", "accuracy"]
