"""Numerical ops: losses, metrics, and Pallas TPU kernels
(``ops.pallas_kernels.fused_adam``, selectable as
``worker_optimizer="fused_adam"``)."""

from distkeras_tpu.ops import losses, metrics
from distkeras_tpu.ops.losses import get_loss
from distkeras_tpu.ops.metrics import accuracy


def __getattr__(name):
    # pallas modules import jax.experimental.pallas; keep them lazy so plain
    # loss/metric users never pay for it
    if name == "pallas_kernels":
        from distkeras_tpu.ops import pallas_kernels

        return pallas_kernels
    if name == "quant":
        from distkeras_tpu.ops import quant

        return quant
    raise AttributeError(f"module 'distkeras_tpu.ops' has no attribute {name!r}")


__all__ = ["losses", "metrics", "get_loss", "accuracy"]
