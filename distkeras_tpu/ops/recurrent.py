"""Fused LSTM scan — a Pallas TPU kernel for the recurrent hot loop.

Parity+perf: the reference's newest model was a Keras LSTM trained step-by-
step on CPU executors (reference ``distkeras/examples`` IMDB config —
SURVEY.md §2b #19 / BASELINE config 5). The rebuild's XLA ``lax.scan`` path
(:mod:`distkeras_tpu.models.lstm`) is bounded not by matmul FLOPs but by
per-step overheads: each of the T sequential steps round-trips the h/c
carries through HBM and launches a tiny [B,H]·[H,4H] contraction
(SCALING.md's roofline paragraph for BASELINE config 5). This kernel runs
the WHOLE scan as one Pallas grid:

- grid ``(T/K,)`` with ``K`` timesteps unrolled per grid step — TPU grid
  steps execute sequentially, which is exactly a recurrence: the carries
  (h, c) live in VMEM scratch across grid steps and never touch HBM, and
  the K-unroll amortizes the per-grid-step pipeline overhead that
  dominates at [B,H]-sized blocks;
- the recurrent weight ``wh [H, 4H]`` has a constant index map, so Mosaic
  keeps it resident in VMEM for the whole scan (one HBM fetch total);
- per timestep, one MXU contraction ``h @ wh`` plus the VPU gate math; the
  step's ``h`` and ``c`` tiles (both in the model dtype — the f32 carry
  inside the kernel keeps the recurrence itself full-precision) stream out
  double-buffered while the next chunk computes.

Backward is the reverse-time kernel with the same structure: carries
``dc``/``dh`` and the ``dwh`` accumulator in VMEM scratch, per step one
recompute of the gate pre-activations from the saved ``h`` sequence (no
saved probabilities — same recompute philosophy as
:mod:`distkeras_tpu.ops.flash_attention`), and two MXU contractions
(``dz @ whᵀ`` for the carried gradient, ``h_prevᵀ @ dz`` folded into the
``dwh`` accumulator). The t-1 states come from the saved sequences via a
previous-chunk block view — no shifted HBM copies.

Gate math matches ``models.lstm.LSTMClassifier`` exactly: forget bias +1.0,
cell state f32 in-kernel, gates/hidden in the model dtype. On TPU the
kernel compiles natively; elsewhere it runs in Pallas interpret mode so the
same code path is oracle-tested in CI (tests/test_recurrent.py pins values
AND gradients against the ``lax.scan`` reference).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distkeras_tpu.ops.flash_attention import _interpret_default

#: timesteps unrolled per grid step (largest divisor of T from this ladder)
CHUNK = 8

#: per-core scoped VMEM budget for a kernel's blocks (v5e limit is 16 MiB;
#: leave headroom for scratch, wh, and Mosaic's own allocations)
_VMEM_BUDGET = 10 * 1024 * 1024


def _pick_chunk(T, per_t_bytes):
    """Largest ladder divisor of T whose double-buffered blocks fit VMEM."""
    for k in (CHUNK, 5, 4, 2, 1):
        if T % k == 0 and 2 * k * per_t_bytes <= _VMEM_BUDGET:
            return k
    return 1


def _gates(z):
    """z [B, 4H] f32 → (i_s, f_s, g_t, o_s) activated gates, H-wide each."""
    H = z.shape[-1] // 4
    i, f, g, o = (z[:, k * H:(k + 1) * H] for k in range(4))
    return (jax.nn.sigmoid(i), jax.nn.sigmoid(f + 1.0), jnp.tanh(g),
            jax.nn.sigmoid(o))


def _lstm_fwd_kernel(gx_ref, wh_ref, hs_ref, *rest, K):
    """One grid step = K timesteps: z = gx_t + h @ wh; gate math; stream
    out h_t (and c_t when training needs the residual); carries stay in
    VMEM scratch."""
    if len(rest) == 3:
        cs_ref, h_s, c_s = rest
    else:
        cs_ref, (h_s, c_s) = None, rest
    t0 = pl.program_id(0)

    @pl.when(t0 == 0)
    def _():
        h_s[:] = jnp.zeros_like(h_s)
        c_s[:] = jnp.zeros_like(c_s)

    wh = wh_ref[:].astype(h_s.dtype)
    for k in range(K):
        z = (
            gx_ref[k].astype(jnp.float32)
            + jax.lax.dot_general(
                h_s[:], wh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        )
        i_s, f_s, g_t, o_s = _gates(z)
        c = f_s * c_s[:] + i_s * g_t
        h = (o_s * jnp.tanh(c)).astype(h_s.dtype)
        c_s[:] = c
        h_s[:] = h
        hs_ref[k] = h.astype(hs_ref.dtype)
        if cs_ref is not None:
            cs_ref[k] = c.astype(cs_ref.dtype)


def _lstm_bwd_kernel(gx_ref, wh_ref, hs_ref, hsp_ref, cs_ref, csp_ref,
                     dh_ref, dgx_ref, dwh_ref, dc_s, dhr_s, dwh_s, *, K):
    """One grid step = K reverse timesteps: recompute gates from h_{t-1},
    fold gradients. ``hsp_ref``/``csp_ref`` are the PREVIOUS chunk's saved
    h/c blocks (clamped at chunk 0); the global first timestep's zero
    initial state is imposed in-kernel."""
    s = pl.program_id(0)          # s = 0 … T/K-1, visiting chunks in reverse
    n = pl.num_programs(0)

    @pl.when(s == 0)
    def _():
        dc_s[:] = jnp.zeros_like(dc_s)
        dhr_s[:] = jnp.zeros_like(dhr_s)
        dwh_s[:] = jnp.zeros_like(dwh_s)

    wh = wh_ref[:].astype(hs_ref.dtype)
    for k in range(K - 1, -1, -1):
        if k > 0:
            h_prev = hs_ref[k - 1]
            c_prev = cs_ref[k - 1].astype(jnp.float32)
        else:
            # hsp/csp are single-timestep views of the previous chunk's
            # last step (clamped); zero them at the global first timestep
            first_t = (s == n - 1)   # global t == 0
            h_prev = jnp.where(
                first_t, 0.0, hsp_ref[0].astype(jnp.float32)
            ).astype(hs_ref.dtype)
            c_prev = jnp.where(
                first_t, 0.0, csp_ref[0].astype(jnp.float32)
            )
        z = (
            gx_ref[k].astype(jnp.float32)
            + jax.lax.dot_general(
                h_prev, wh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        )
        i_s, f_s, g_t, o_s = _gates(z)
        c = cs_ref[k].astype(jnp.float32)
        tc = jnp.tanh(c)

        dh_total = dh_ref[k].astype(jnp.float32) + dhr_s[:]
        do_pre = dh_total * tc * o_s * (1.0 - o_s)
        dc_tot = dh_total * o_s * (1.0 - tc * tc) + dc_s[:]
        di_pre = dc_tot * g_t * i_s * (1.0 - i_s)
        df_pre = dc_tot * c_prev * f_s * (1.0 - f_s)
        dg_pre = dc_tot * i_s * (1.0 - g_t * g_t)
        dc_s[:] = dc_tot * f_s

        dz = jnp.concatenate([di_pre, df_pre, dg_pre, do_pre], axis=-1)
        dgx_ref[k] = dz.astype(dgx_ref.dtype)
        dz_c = dz.astype(hs_ref.dtype)
        dhr_s[:] = jax.lax.dot_general(
            dz_c, wh, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dwh_s[:] += jax.lax.dot_general(
            h_prev, dz_c, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(s == n - 1)
    def _():
        dwh_ref[:] = dwh_s[:].astype(dwh_ref.dtype)


def _fwd(gx_t, wh, interpret, save_c: bool = True):
    """gx_t [T, B, 4H] (time-major), wh [H, 4H] → (hs [T, B, H], cs|None).

    ``save_c=False`` (the eval/primal path) skips streaming the c sequence
    to HBM entirely — it is only the backward's residual. When saved, cs is
    stored in the model dtype (halves its HBM traffic for bf16 training);
    the f32 carry inside the kernel keeps the recurrence full-precision.
    """
    T, B, H4 = gx_t.shape
    H = H4 // 4
    # streamed blocks per timestep: gx [B,4H] in, hs(+cs) [B,H] out
    K = _pick_chunk(T, (H4 + (2 if save_c else 1) * H) * B
                    * gx_t.dtype.itemsize)
    seq_spec = pl.BlockSpec((K, B, H), lambda t: (t, 0, 0))
    seq_shape = jax.ShapeDtypeStruct((T, B, H), gx_t.dtype)
    out = pl.pallas_call(
        functools.partial(_lstm_fwd_kernel, K=K), grid=(T // K,),
        in_specs=[
            pl.BlockSpec((K, B, H4), lambda t: (t, 0, 0)),
            pl.BlockSpec((H, H4), lambda t: (0, 0)),
        ],
        out_specs=[seq_spec, seq_spec] if save_c else [seq_spec],
        out_shape=[seq_shape, seq_shape] if save_c else [seq_shape],
        scratch_shapes=[
            pltpu.VMEM((B, H), gx_t.dtype),   # h carry
            pltpu.VMEM((B, H), jnp.float32),  # c carry
        ],
        interpret=interpret,
    )(gx_t, wh)
    return (out[0], out[1]) if save_c else (out[0], None)


def _bwd(gx_t, wh, hs, cs, dhs, interpret):
    """Reverse-time gradients → (dgx_t [T, B, 4H], dwh [H, 4H])."""
    T, B, H4 = gx_t.shape
    H = H4 // 4
    # streamed blocks per timestep: gx+dgx [B,4H], hs/hsp/cs/csp/dh [B,H]
    K = _pick_chunk(T, (2 * H4 + 5 * H) * B * gx_t.dtype.itemsize)
    n = T // K

    rev = lambda t: (n - 1 - t, 0, 0)       # visit chunks in reverse time
    # single-timestep view of the previous chunk's LAST step (clamped;
    # kernel zeroes t==0) — streams 1 row, not a whole spare chunk
    rev_prev = lambda t: (jnp.maximum((n - 1 - t) * K - 1, 0), 0, 0)
    dgx, dwh = pl.pallas_call(
        functools.partial(_lstm_bwd_kernel, K=K), grid=(n,),
        in_specs=[
            pl.BlockSpec((K, B, H4), rev),              # gx
            pl.BlockSpec((H, H4), lambda t: (0, 0)),    # wh
            pl.BlockSpec((K, B, H), rev),               # hs chunk
            pl.BlockSpec((1, B, H), rev_prev),          # h_{chunk-1} view
            pl.BlockSpec((K, B, H), rev),               # cs chunk
            pl.BlockSpec((1, B, H), rev_prev),          # c_{chunk-1} view
            pl.BlockSpec((K, B, H), rev),               # dh
        ],
        out_specs=[
            pl.BlockSpec((K, B, H4), rev),
            pl.BlockSpec((H, H4), lambda t: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H4), gx_t.dtype),
            jax.ShapeDtypeStruct((H, H4), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((B, H), jnp.float32),   # dc carry
            pltpu.VMEM((B, H), jnp.float32),   # dh carried from t+1
            pltpu.VMEM((H, H4), jnp.float32),  # dwh accumulator
        ],
        interpret=interpret,
    )(gx_t, wh, hs, hs, cs, cs, dhs)
    return dgx, dwh


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _lstm_core(gx_t, wh, interpret):
    hs, _ = _fwd(gx_t, wh, interpret, save_c=False)
    return hs


def _lstm_core_fwd(gx_t, wh, interpret):
    hs, cs = _fwd(gx_t, wh, interpret)
    return hs, (gx_t, wh, hs, cs)


def _lstm_core_bwd(interpret, res, dhs):
    gx_t, wh, hs, cs = res
    dgx, dwh = _bwd(gx_t, wh, hs, cs, dhs, interpret)
    return dgx, dwh.astype(wh.dtype)


_lstm_core.defvjp(_lstm_core_fwd, _lstm_core_bwd)


def lstm_scan_reference(gates_x, wh):
    """The XLA ``lax.scan`` oracle (identical math, batch-major I/O).

    ``gates_x`` [B, T, 4H] (model dtype), ``wh`` [H, 4H] → hs [B, T, H].
    """
    H = wh.shape[0]
    dtype = gates_x.dtype

    def step(carry, gx_t):
        c, h = carry
        z = (gx_t + h @ wh.astype(dtype)).astype(jnp.float32)
        i, f, g, o = jnp.split(z, 4, axis=-1)
        c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = (jax.nn.sigmoid(o) * jnp.tanh(c)).astype(dtype)
        return (c, h), h

    B = gates_x.shape[0]
    c0 = jnp.zeros((B, H), jnp.float32)
    h0 = jnp.zeros((B, H), dtype)
    _, outs = jax.lax.scan(step, (c0, h0), jnp.moveaxis(gates_x, 1, 0))
    return jnp.moveaxis(outs, 0, 1)


def lstm_scan(gates_x, wh, impl: str = "auto",
              interpret: bool | None = None):
    """Run the LSTM recurrence over pre-projected gate inputs.

    ``gates_x`` [B, T, 4H] (``x @ W_x + b`` for every step — hoisted out of
    the recurrence as one big matmul), ``wh`` [H, 4H] recurrent weights →
    ``hs`` [B, T, H] in ``gates_x.dtype``. Differentiable in both arguments.

    ``impl``: ``"pallas"`` forces the fused kernel, ``"xla"`` the
    ``lax.scan`` reference, ``"auto"`` uses the kernel only when running
    natively on TPU with tile-friendly shapes (H a multiple of 128, B of 8).
    """
    if impl not in ("pallas", "xla", "auto"):
        raise ValueError(
            f"unknown lstm impl {impl!r}; use 'pallas', 'xla', or 'auto'"
        )
    B, T, H4 = gates_x.shape
    H = H4 // 4
    if impl == "xla" or (
        impl == "auto"
        and (H % 128 or B % 8 or jax.default_backend() != "tpu")
    ):
        return lstm_scan_reference(gates_x, wh)
    hs = _lstm_core(
        jnp.moveaxis(gates_x, 1, 0), wh,
        _interpret_default() if interpret is None else bool(interpret),
    )
    return jnp.moveaxis(hs, 0, 1)
