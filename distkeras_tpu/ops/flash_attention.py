"""Flash attention — a Pallas TPU kernel for the transformer hot path.

The reference has no attention at all (its newest model was an LSTM —
SURVEY.md §5.7), so this is TPU-native surplus: the memory-bound softmax
attention of the transformer/MoE families as a streaming online-softmax
kernel (Dao et al. 2022 construction, TPU grid edition).

Forward: grid ``(batch·head, q-blocks, k-blocks)`` with the k axis innermost.
Each step multiplies one ``[block_q, D]`` query tile against one
``[block_k, D]`` key/value tile on the MXU (f32 accumulation over bf16
inputs) and folds the result into VMEM scratch accumulators ``(m, l, acc)``
via the numerically stable online softmax; the last k step normalizes and
writes the output tile. Peak on-chip memory is ``O(block_q · block_k)`` —
independent of sequence length — where XLA's fused attention materializes
the full ``O(L²)`` score tensor per head in HBM (it OOMs at L=16k on a v5e
where this kernel keeps running). The kernel also emits per-row log-sum-exp,
which makes the backward pass a textbook recompute: ``p = exp(qk − lse)``,
no saved probabilities.

Backward: two Pallas kernels with the same tile-streaming structure, so
training memory is also ``O(block_q · block_k)`` per core instead of the
``O(L²)`` score/probability tensors a plain-XLA backward materializes.
``delta = rowsum(dO · O)`` is precomputed in XLA (one elementwise pass),
then a dq kernel (grid ``(batch·head, q-blocks, k-blocks)``, k innermost,
``dq += ds @ k``) and a dk/dv kernel (grid ``(batch·head, k-blocks,
q-blocks)``, q innermost, ``dk += dsᵀ @ q``, ``dv += pᵀ @ dO``) each
rebuild their probability tile from the saved lse and fold into VMEM
accumulators. Causal tiles that cannot contribute are skipped on both
sides of the diagonal (dq skips above, dk/dv below). ``_attention_bwd_math``
keeps the plain-XLA gradient identities as the small-shape oracle.

On TPU the kernel compiles natively; elsewhere (the 8-device CPU mesh in CI)
it runs in Pallas interpret mode, so the SAME code path is oracle-tested
everywhere (tests/test_flash_attention.py pins it against
``parallel.sequence.attention_reference``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e9  # matches parallel.sequence: finite mask keeps softmax NaN-free

BLOCK_Q = 128   # q rows per grid step
BLOCK_K = 512   # k/v rows per inner grid step


def _first_k_tile(iq, *, block_q, block_k, window):
    """Index of the first k tile inside the attention band of q block
    ``iq`` (0 when unwindowed). Floor division handles the negative
    numerator near the sequence start."""
    if window is None:
        return 0
    return jnp.maximum(0, (iq * block_q - window + 1) // block_k)


def _last_k_tile(iq, nk, *, block_q, block_k, causal, window):
    """Index of the last contributing k tile for q block ``iq``: the causal
    diagonal and/or the upper edge of the window band, else the last tile."""
    last = nk - 1
    if causal:
        last = jnp.minimum(last, (iq * block_q + block_q - 1) // block_k)
    elif window is not None:
        last = jnp.minimum(
            last, (iq * block_q + block_q - 1 + window - 1) // block_k
        )
    return last


def band_predicate(q_pos, k_pos, causal, window):
    """THE causal/sliding-window validity predicate, shared by the kernels
    (both orientations), the XLA backward oracle, and
    ``attention_reference``: query ``i`` sees key ``j`` iff ``j <= i`` when
    causal, ``i - j < window`` (and ``j - i < window`` when bidirectional)
    under a window. ``q_pos``/``k_pos`` broadcast; returns None when
    everything is valid."""
    if not causal and window is None:
        return None
    valid = None
    if causal:
        valid = q_pos >= k_pos
    if window is not None:
        band = q_pos - k_pos < window          # lower edge of the band
        if not causal:
            band &= k_pos - q_pos < window     # symmetric upper edge
        valid = band if valid is None else (valid & band)
    return valid


def _band_valid(iq, kt, *, block_q, block_k, causal, window):
    """[bq, bk] tile of :func:`band_predicate` for q tile ``iq`` × k tile
    ``kt`` (None when everything is valid)."""
    q_pos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = kt * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return band_predicate(q_pos, k_pos, causal, window)


def _num_band_tiles(n_tiles, span, block):
    """Static size of the restricted grid axis: max tiles of width ``block``
    an arbitrarily aligned index range of length ``span`` can touch."""
    return min(n_tiles, (span - 2) // block + 2)


def _restricted_k_axis(nk, bq, bk, causal, window):
    """(nkt, k_tile(iq, j)) for the forward/dq grids: the static size of the
    k axis and the index map from (q tile, band step) → real k tile. With no
    window the axis is the full nk and the map is the identity on j; with a
    window only the tiles the band can touch are visited (and DMA'd), so
    compute and bandwidth are O(L·window) — clamped duplicate tiles at the
    sequence end are guarded off in-kernel by ``kt <= last_k``."""
    if window is None:
        return nk, (lambda i, j: j)
    span = bq + window - 1 if causal else bq + 2 * window - 2

    def k_tile(i, j):
        fk = _first_k_tile(i, block_q=bq, block_k=bk, window=window)
        return jnp.minimum(fk + j, nk - 1)

    return _num_band_tiles(nk, span, bk), k_tile


def _restricted_q_axis(nq, bq, bk, causal, window):
    """(nqt, q_tile(jk, i)) for the dkv grid — the transposed mirror of
    :func:`_restricted_k_axis`."""
    if window is None:
        return nq, (lambda j, i: i)
    span = bk + window - 1 if causal else bk + 2 * window - 2

    def q_tile(j, i):
        fq = _first_q_tile(j, block_q=bq, block_k=bk, causal=causal,
                           window=window)
        return jnp.minimum(fq + i, nq - 1)

    return _num_band_tiles(nq, span, bq), q_tile


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc, *,
               scale, causal, block_q, block_k, window=None, nk=None,
               km_ref=None):
    """One (bh, iq, jk) step: fold a [bq, bk] score tile into the online
    softmax state; finalize on this q block's last contributing k step.

    With ``window`` set the grid's k axis is restricted to the band (the
    BlockSpec index map only loads in-band tiles), so ``jk`` counts tiles
    from the band start: the real k tile is ``first_k + jk``."""
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    if nk is None:
        nk = pl.num_programs(2)

    @pl.when(jk == 0)
    def _():
        m_s[:] = jnp.full_like(m_s, _NEG)
        l_s[:] = jnp.zeros_like(l_s)
        acc[:] = jnp.zeros_like(acc)

    # under causal/window masking, k tiles outside the band contribute
    # nothing — the restricted grid never visits tiles below the band, and
    # the guards below skip tiles past its end (≈2× at long causal context)
    kt = _first_k_tile(iq, block_q=block_q, block_k=block_k,
                       window=window) + jk
    last_k = _last_k_tile(iq, nk, block_q=block_q, block_k=block_k,
                          causal=causal, window=window)

    @pl.when(kt <= last_k)
    def _():
        q = q_ref[0].astype(jnp.float32) * scale        # [bq, D]
        k = k_ref[0].astype(jnp.float32)                # [bk, D]
        v = v_ref[0].astype(jnp.float32)                # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                # [bq, bk]
        valid = _band_valid(iq, kt, block_q=block_q, block_k=block_k,
                            causal=causal, window=window)
        if km_ref is not None:
            km = km_ref[0].astype(jnp.float32) > 0.5     # [1, bk]
            km = jnp.broadcast_to(km, s.shape)
            valid = km if valid is None else (valid & km)
        if valid is not None:
            s = jnp.where(valid, s, _NEG)

        m_prev = m_s[:]                                  # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if valid is not None:
            p = jnp.where(valid, p, 0.0)
        corr = jnp.exp(m_prev - m_new)                   # [bq, 1]
        l_s[:] = l_s[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_s[:] = m_new

    @pl.when(kt == last_k)
    def _():
        l = jnp.maximum(l_s[:], 1e-30)
        o_ref[0] = (acc[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_s[:] + jnp.log(l)


def _interpret_default():
    return jax.default_backend() != "tpu"


def _pick_block_q(L):
    """q tile height: taller q tiles amortize per-grid-step pipeline
    overhead and cut the number of (m, l, acc) rescale passes. Round 5
    re-measured the ladder on a v5e DOWN to L = 1024 (fwd+bwd, causal):
    512-row tiles win 1.5× at L = 2048 for BOTH D=64 (thin heads — the
    VERDICT r4 #4 gap: the per-step overhead, not the 64-wide MXU
    contraction, was the recoverable part) and D=128, matching the
    2.0–2.1× already measured at L ≥ 8192 (SCALING.md flash table).
    Gated at L >= 1024 — exactly the measured range: L = 512 would get a
    single 512-row tile (a config no measurement covered), so it keeps
    the default ladder, as do lengths that aren't 512-multiples
    (tile rule)."""
    return 512 if L >= 1024 and L % 512 == 0 else BLOCK_Q


def _pick_block_k(L):
    """k tile width: largest tile-aligned block that divides L (128 always
    does); 1024 whenever L allows it (same round-5 measurement as
    _pick_block_q — fewer, wider k steps beat the old 512 ladder at every
    L ≥ 1024 tried). Every (bq, bk) combination keeps bk % bq == 0 or
    bq % bk == 0, which the backward's causal tile-skipping index math
    relies on."""
    if L % 1024 == 0:
        return 1024
    return next(c for c in (BLOCK_K, 384, 256, 128) if L % c == 0)


def _gqa_groups(q, k):
    """Validated GQA group size: q heads per shared k/v head (1 = MHA)."""
    H, Hkv = q.shape[2], k.shape[2]
    if H % Hkv:
        raise ValueError(
            f"q heads {H} must be a multiple of kv heads {Hkv}"
        )
    return H // Hkv


def _kv_row(b, H, Hkv):
    """Grid row (over B·H) → k/v array row (over B·Hkv): query head h
    reads shared head h // group — the same [Hkv, group] factoring as the
    LM's cache decode and jnp.repeat expansion."""
    if H == Hkv:
        return b
    return (b // H) * Hkv + (b % H) // (H // Hkv)


def _fa_forward(q, k, v, key_mask, *, scale, causal, interpret,
                window=None):
    """q [B, L, H, D], k/v [B, L, Hkv, D] with Hkv | H (grouped-query
    attention reads shared K/V heads straight from the index maps — no
    repeated-KV materialization), + key_mask [B, L] →
    (out [B, L, H, D], lse)."""
    B, L, H, D = q.shape
    Hkv = k.shape[2]
    _gqa_groups(q, k)
    if L % BLOCK_Q:
        raise ValueError(
            f"sequence length {L} must be a multiple of {BLOCK_Q}"
        )
    bq = _pick_block_q(L)
    bk = _pick_block_k(L)

    def bh(x):  # [B, L, h, D] → [B·h, L, D]
        h = x.shape[2]
        return jnp.moveaxis(x, 2, 1).reshape(B * h, L, D)

    nk = L // bk
    nkt, k_tile = _restricted_k_axis(nk, bq, bk, causal, window)
    grid = (B * H, L // bq, nkt)
    qspec = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))
    kvspec = pl.BlockSpec(
        (1, bk, D), lambda b, i, j: (_kv_row(b, H, Hkv), k_tile(i, j), 0)
    )
    ospec = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))
    # lse carries a trailing singleton so its block obeys the (8, 128)
    # tile rule (last dim equal to the array dim is allowed)
    lspec = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0))
    out_shape = [
        jax.ShapeDtypeStruct((B * H, L, D), q.dtype),
        jax.ShapeDtypeStruct((B * H, L, 1), jnp.float32),
    ]
    scratch = [
        pltpu.VMEM((bq, 1), jnp.float32),   # running max m
        pltpu.VMEM((bq, 1), jnp.float32),   # running denom l
        pltpu.VMEM((bq, D), jnp.float32),   # running numerator acc
    ]
    in_specs = [qspec, kvspec, kvspec]
    args = [bh(q), bh(k), bh(v)]
    if key_mask is None:
        kernel = functools.partial(
            _fa_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
            window=window, nk=nk,
        )
    else:
        H_ = H
        # mask ships as [B, 1, L] so its block obeys the (8, 128) tile rule
        in_specs.append(
            pl.BlockSpec((1, 1, bk), lambda b, i, j: (b // H_, 0,
                                                      k_tile(i, j)))
        )
        args.append(key_mask.astype(jnp.float32)[:, None, :])

        def kernel(q_ref, k_ref, v_ref, km_ref, o_ref, lse_ref,
                   m_s, l_s, acc):
            _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc,
                       scale=scale, causal=causal, block_q=bq, block_k=bk,
                       window=window, nk=nk, km_ref=km_ref)

    o, lse = pl.pallas_call(
        kernel, grid=grid,
        in_specs=in_specs,
        out_specs=[ospec, lspec],
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(*args)
    out = jnp.moveaxis(o.reshape(B, H, L, D), 1, 2)
    return out, lse[..., 0]


def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, d_ref, *rest,
                      scale, causal, block_q, block_k, window=None, nk=None):
    """One (bh, iq, jk) step: rebuild the [bq, bk] probability tile from the
    saved lse and fold ``ds @ k`` into the dq accumulator; write on this q
    block's last contributing k step."""
    if len(rest) == 3:
        km_ref, dq_ref, acc = rest
    else:
        km_ref, (dq_ref, acc) = None, rest
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    if nk is None:
        nk = pl.num_programs(2)

    @pl.when(jk == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)

    kt = _first_k_tile(iq, block_q=block_q, block_k=block_k,
                       window=window) + jk
    last_k = _last_k_tile(iq, nk, block_q=block_q, block_k=block_k,
                          causal=causal, window=window)

    @pl.when(kt <= last_k)
    def _():
        qs = q_ref[0].astype(jnp.float32) * scale       # [bq, D]
        kk = k_ref[0].astype(jnp.float32)               # [bk, D]
        vv = v_ref[0].astype(jnp.float32)               # [bk, D]
        gg = g_ref[0].astype(jnp.float32)               # [bq, D]
        s = jax.lax.dot_general(
            qs, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                # [bq, bk]
        valid = _band_valid(iq, kt, block_q=block_q, block_k=block_k,
                            causal=causal, window=window)
        if km_ref is not None:
            km = km_ref[0].astype(jnp.float32) > 0.5     # [1, bk]
            km = jnp.broadcast_to(km, s.shape)
            valid = km if valid is None else (valid & km)
        if valid is not None:
            s = jnp.where(valid, s, _NEG)
        p = jnp.exp(s - lse_ref[0])                      # lse [bq, 1]
        if valid is not None:
            p = jnp.where(valid, p, 0.0)
        dp = jax.lax.dot_general(
            gg, vv, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                # [bq, bk]
        ds = p * (dp - d_ref[0])                         # delta [bq, 1]
        acc[:] += jax.lax.dot_general(
            ds, kk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(kt == last_k)
    def _():
        dq_ref[0] = acc[:].astype(dq_ref.dtype)


def _first_q_tile(jk, *, block_q, block_k, causal, window):
    """First q tile that can see k tile ``jk``: the causal diagonal and/or
    the lower edge of the window band (0 when unrestricted)."""
    if causal:
        return (jk * block_k) // block_q
    if window is not None:
        return jnp.maximum(0, (jk * block_k - window + 1) // block_q)
    return 0


def _last_q_tile(jk, nq, *, block_q, block_k, window):
    """Last q tile inside k tile ``jk``'s band (``nq - 1`` unwindowed)."""
    if window is None:
        return nq - 1
    return jnp.minimum(
        nq - 1, (jk * block_k + block_k - 1 + window - 1) // block_q
    )


def _band_valid_t(jk, qt, *, block_q, block_k, causal, window):
    """Transposed [bk, bq] tile of :func:`band_predicate` for k tile ``jk``
    × q tile ``qt``."""
    k_pos = jk * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_k, block_q), 0
    )
    q_pos = qt * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_k, block_q), 1
    )
    return band_predicate(q_pos, k_pos, causal, window)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, d_ref, *rest,
                       scale, causal, block_q, block_k, window=None,
                       nq=None, gqa_groups=None):
    """One (bh, jk, iq) step — or (b·hkv, jk, gg, iq) under grouped-query
    attention, where the extra ``gg`` axis walks the q heads sharing this
    k/v head and the dk/dv accumulators run across the whole group:
    rebuild the transposed [bk, bq] probability tile and fold ``pᵀ @ dO``
    / ``dsᵀ @ q`` into the dv/dk accumulators; write on the group's last
    contributing q step."""
    if len(rest) == 5:
        km_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        km_ref = None
        dk_ref, dv_ref, dk_acc, dv_acc = rest
    jk = pl.program_id(1)
    if gqa_groups is None:
        last_g = None
        iq = pl.program_id(2)
        if nq is None:
            nq = pl.num_programs(2)
        first_step = iq == 0
    else:
        grp = pl.program_id(2)  # in-group q head (gg names the dO tile)
        iq = pl.program_id(3)
        assert nq is not None
        first_step = (grp == 0) & (iq == 0)
        last_g = grp == gqa_groups - 1

    @pl.when(first_step)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    first_q = _first_q_tile(jk, block_q=block_q, block_k=block_k,
                            causal=causal, window=window)
    if window is None:
        # full grid: iq is the real q tile, skip those before the band
        qt = iq
        last_q = nq - 1
    else:
        # restricted grid: iq counts tiles from the band start
        qt = first_q + iq
        last_q = _last_q_tile(jk, nq, block_q=block_q, block_k=block_k,
                              window=window)

    @pl.when((qt >= first_q) & (qt <= last_q))
    def _():
        qs = q_ref[0].astype(jnp.float32) * scale       # [bq, D]
        kk = k_ref[0].astype(jnp.float32)               # [bk, D]
        vv = v_ref[0].astype(jnp.float32)               # [bk, D]
        gg = g_ref[0].astype(jnp.float32)               # [bq, D]
        st = jax.lax.dot_general(
            kk, qs, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                # [bk, bq]
        valid = _band_valid_t(jk, qt, block_q=block_q, block_k=block_k,
                              causal=causal, window=window)
        if km_ref is not None:
            km = km_ref[0].astype(jnp.float32) > 0.5     # [bk, 1]
            km = jnp.broadcast_to(km, st.shape)
            valid = km if valid is None else (valid & km)
        if valid is not None:
            st = jnp.where(valid, st, _NEG)
        pt = jnp.exp(st - lse_ref[0])                    # lse [1, bq]
        if valid is not None:
            pt = jnp.where(valid, pt, 0.0)
        dv_acc[:] += jax.lax.dot_general(
            pt, gg, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dpt = jax.lax.dot_general(
            vv, gg, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                # [bk, bq]
        dst = pt * (dpt - d_ref[0])                      # delta [1, bq]
        dk_acc[:] += jax.lax.dot_general(
            dst, qs, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    write = qt == last_q if last_g is None else ((qt == last_q) & last_g)

    @pl.when(write)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _fa_backward(q, k, v, key_mask, out, lse, g, *, scale, causal,
                 interpret, window=None):
    """Blockwise flash-attention backward: (dq, dk, dv) via two Pallas
    kernels, ``O(block_q · block_k)`` on-chip — no [B, H, L, L] tensors.
    Under grouped-query attention (k/v hold Hkv < H heads) dq reads the
    shared heads through the index maps and the dkv grid gains a group
    axis whose accumulators sum the whole group — dk/dv come out
    Hkv-wide, no repeated-KV tensors anywhere."""
    B, L, H, D = q.shape
    Hkv = k.shape[2]
    groups = _gqa_groups(q, k)
    bq = _pick_block_q(L)
    bk = _pick_block_k(L)  # same ladders as the forward — keep in lockstep

    def bh(x):  # [B, L, h, D] → [B·h, L, D]
        h = x.shape[2]
        return jnp.moveaxis(x, 2, 1).reshape(B * h, L, D)

    qb, kb, vb, gb = bh(q), bh(k), bh(v), bh(g)
    # delta = rowsum(dO · O): one elementwise pass, [B·H, L]
    delta = jnp.sum(gb.astype(jnp.float32) * bh(out).astype(jnp.float32),
                    axis=-1)
    lse_col, d_col = lse[..., None], delta[..., None]      # [B·H, L, 1]
    lse_row, d_row = lse[:, None, :], delta[:, None, :]    # [B·H, 1, L]
    H_ = H
    nk, nq = L // bk, L // bq
    # same restricted band axes as the forward (one shared builder, so the
    # forward and backward grids cannot drift apart)
    nkt, k_tile = _restricted_k_axis(nk, bq, bk, causal, window)
    nqt, q_tile = _restricted_q_axis(nq, bq, bk, causal, window)

    qspec = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))
    kvspec_q = pl.BlockSpec(
        (1, bk, D), lambda b, i, j: (_kv_row(b, H, Hkv), k_tile(i, j), 0)
    )
    colspec = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0))

    dq_specs = [qspec, kvspec_q, kvspec_q, qspec, colspec, colspec]
    dq_args = [qb, kb, vb, gb, lse_col, d_col]
    if key_mask is not None:
        dq_specs.append(
            pl.BlockSpec((1, 1, bk), lambda b, i, j: (b // H_, 0,
                                                      k_tile(i, j)))
        )
        dq_args.append(key_mask.astype(jnp.float32)[:, None, :])
    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, window=window, nk=nk),
        grid=(B * H, nq, nkt),
        in_specs=dq_specs,
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((B * H, L, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(*dq_args)

    # dk/dv: k blocks on the parallel axis, q innermost; under GQA the
    # grid is (B·Hkv, nk, group, nqt) with the group axis outside the q
    # walk so the accumulators span every q head sharing the k/v head
    def q_row_of(b, gg):
        # b over B·Hkv, gg the in-group q head → row over B·H
        return (b // Hkv) * H + (b % Hkv) * groups + gg

    if groups == 1:
        grid = (B * H, nk, nqt)
        kvspec = pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0))
        qspec2 = pl.BlockSpec(
            (1, bq, D), lambda b, j, i: (b, q_tile(j, i), 0)
        )
        rowspec = pl.BlockSpec(
            (1, 1, bq), lambda b, j, i: (b, 0, q_tile(j, i))
        )
        kmspec = pl.BlockSpec((1, bk, 1), lambda b, j, i: (b // H_, j, 0))
    else:
        grid = (B * Hkv, nk, groups, nqt)
        kvspec = pl.BlockSpec((1, bk, D), lambda b, j, gg, i: (b, j, 0))
        qspec2 = pl.BlockSpec(
            (1, bq, D),
            lambda b, j, gg, i: (q_row_of(b, gg), q_tile(j, i), 0),
        )
        rowspec = pl.BlockSpec(
            (1, 1, bq),
            lambda b, j, gg, i: (q_row_of(b, gg), 0, q_tile(j, i)),
        )
        kmspec = pl.BlockSpec(
            (1, bk, 1), lambda b, j, gg, i: (b // Hkv, j, 0)
        )
    dkv_specs = [qspec2, kvspec, kvspec, qspec2, rowspec, rowspec]
    dkv_args = [qb, kb, vb, gb, lse_row, d_row]
    if key_mask is not None:
        dkv_specs.append(kmspec)
        dkv_args.append(key_mask.astype(jnp.float32)[..., None])
    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, window=window, nq=nq,
                          gqa_groups=None if groups == 1 else groups),
        grid=grid,
        in_specs=dkv_specs,
        out_specs=[kvspec, kvspec],
        out_shape=[jax.ShapeDtypeStruct((B * Hkv, L, D), k.dtype),
                   jax.ShapeDtypeStruct((B * Hkv, L, D), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        interpret=interpret,
    )(*dkv_args)

    def unbh(x):  # [B·h, L, D] → [B, L, h, D]
        h = x.shape[0] // B
        return jnp.moveaxis(x.reshape(B, h, L, D), 1, 2)

    return unbh(dq), unbh(dk), unbh(dv)


def _attention_bwd_math(q, k, v, key_mask, lse, g, *, scale, causal,
                        window=None):
    """Recompute-based backward (plain XLA): p from saved lse, then the
    standard flash-attention gradient identities. GQA: k/v may hold
    Hkv < H heads — expanded here, with dk/dv group-summed back."""
    B, L, H, D = q.shape
    Hkv = k.shape[2]
    groups = _gqa_groups(q, k)
    if groups > 1:
        k = jnp.repeat(k, groups, axis=2)
        v = jnp.repeat(v, groups, axis=2)
    qf = q.astype(jnp.float32) * scale
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, k.astype(jnp.float32))
    band = band_predicate(jnp.arange(L)[:, None], jnp.arange(L)[None, :],
                          causal, window)
    valid = (None if band is None
             else jnp.broadcast_to(band[None, None], s.shape))
    if key_mask is not None:
        km = key_mask.astype(bool)[:, None, None, :]
        valid = km if valid is None else (valid & km)
    if valid is not None:
        s = jnp.where(valid, s, _NEG)
    lse_b = lse.reshape(B, H, L)                       # [B, H, L]
    p = jnp.exp(s - lse_b[..., None])
    if valid is not None:
        p = jnp.where(valid, p, 0.0)
    gf = g.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, gf)
    dp = jnp.einsum("bqhd,bkhd->bhqk", gf, vf)
    # d(softmax): ds = p * (dp - rowsum(dp * p))
    row = jnp.sum(dp * p, axis=-1, keepdims=True)
    ds = p * (dp - row)
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, k.astype(jnp.float32)) * scale
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf)
    if groups > 1:
        # sum the group's q-head contributions back onto the shared head
        dk = dk.reshape(B, L, Hkv, groups, D).sum(axis=3)
        dv = dv.reshape(B, L, Hkv, groups, D).sum(axis=3)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_core(q, k, v, key_mask, causal, scale, interpret, window):
    out, _ = _fa_forward(
        q, k, v, key_mask, scale=scale, causal=causal, interpret=interpret,
        window=window,
    )
    return out


def _fa_fwd(q, k, v, key_mask, causal, scale, interpret, window):
    out, lse = _fa_forward(
        q, k, v, key_mask, scale=scale, causal=causal, interpret=interpret,
        window=window,
    )
    # saving `out` adds no memory under jit: it aliases the primal output
    return out, (q, k, v, key_mask, out, lse)


def _fa_bwd(causal, scale, interpret, window, res, g):
    q, k, v, key_mask, out, lse = res
    dq, dk, dv = _fa_backward(
        q, k, v, key_mask, out, lse, g,
        scale=scale, causal=causal, interpret=interpret, window=window,
    )
    dmask = None if key_mask is None else jnp.zeros_like(key_mask)
    return dq, dk, dv, dmask


_flash_core.defvjp(_fa_fwd, _fa_bwd)


def _canonical_window(window, L):
    """Validate ``window``; a band covering the whole sequence is None."""
    if window is None:
        return None
    window = int(window)
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    return None if window >= L else window


def flash_attention(q, k, v, causal: bool = False, scale=None, key_mask=None,
                    interpret: bool | None = None, window: int | None = None):
    """Pallas flash attention; same contract as ``attention_reference``.

    ``q/k/v`` [B, L, H, D] → [B, L, H, D]; optional ``key_mask`` [B, L]
    (1 = attend). Gradients flow to q/k/v (the mask gets zero cotangent, as
    with the hard mask in the reference). ``window`` enables sliding-window
    (local) attention: query ``i`` sees keys ``(i-window, i]`` when causal,
    ``|i-j| < window`` otherwise; the kernel grid only visits in-band tiles,
    so compute AND k/v DMA scale as O(L·window).
    """
    return _flash_core(
        q, k, v, key_mask, bool(causal),
        float(scale if scale is not None else q.shape[-1] ** -0.5),
        _interpret_default() if interpret is None else bool(interpret),
        _canonical_window(window, q.shape[1]),
    )


def attention(q, k, v, causal: bool = False, scale=None, key_mask=None,
              impl: str = "auto", window: int | None = None):
    """Dispatch between the Pallas kernel and the XLA reference.

    ``impl``: ``"flash"`` forces the kernel (requires ``L % 128 == 0``),
    ``"reference"`` the XLA path, ``"auto"`` uses the kernel only when
    running natively on TPU AND the shapes are tile-friendly — interpret
    mode off-TPU is for testing, not speed. ``key_mask`` is treated as a
    static-presence argument (its values are traced, its presence is not).
    ``window``: sliding-window (local) attention span — see
    :func:`flash_attention`.
    """
    from distkeras_tpu.parallel.sequence import attention_reference

    if impl not in ("flash", "reference", "auto"):
        raise ValueError(
            f"unknown attention impl {impl!r}; use 'flash', 'reference', "
            f"or 'auto'"
        )
    L = q.shape[1]
    if impl == "reference" or (
        impl == "auto"
        and (L % BLOCK_Q or jax.default_backend() != "tpu")
    ):
        return attention_reference(q, k, v, causal=causal, scale=scale,
                                   key_mask=key_mask, window=window)
    return flash_attention(q, k, v, causal, scale, key_mask, window=window)
