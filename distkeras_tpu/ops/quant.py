"""Int8 weight-only quantization: TPU-native serving for trained models.

Beyond-reference (the Spark-era reference served float32 Keras weights and
nothing else — SURVEY.md §2b #15): symmetric per-output-channel int8
post-training quantization, built for the TPU memory system.

Why weight-only, and why a Pallas kernel:

- **Autoregressive decode is HBM-bandwidth-bound.** Every decode step
  streams every weight matrix once to multiply a tiny ``[B, 1, d]``
  activation. Int8 weights halve the bytes per step, which is directly
  ~2× decode throughput for the weight-dominated regime (small batch,
  cache smaller than the weights).
- **The dequant must happen AFTER the HBM read.** An XLA-level
  ``q.astype(bf16) * scale`` before the matmul is loop-invariant inside
  the decode ``lax.scan`` — the compiler may hoist it and materialize a
  full bf16 copy in HBM, forfeiting the entire win. The Pallas kernel
  makes the schedule explicit: int8 tiles stream HBM→VMEM, are widened to
  bf16 in-register, hit the MXU, and the per-channel scale is applied to
  the f32 accumulator. No bf16 weight tensor ever exists in HBM.
- **Activations stay bf16.** v5e's MXU runs int8×int8 at 2× bf16 peak,
  but decode is nowhere near compute-bound — weight-only takes the
  bandwidth win and keeps activation precision (no calibration needed).

Accuracy: symmetric absmax per output channel; the scale is exact in f32
and applied after the f32 accumulation, so ``q_matmul`` equals the exact
``x @ (q · scale)`` product up to matmul dtype rounding (pinned by
tests/test_quant.py).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANES = 128


class QTensor(NamedTuple):
    """An int8-quantized matrix: ``q [K, N] int8`` with per-output-channel
    ``scale [N] f32``; the represented value is ``q.astype(f32) * scale``."""

    q: jax.Array
    scale: jax.Array


def quantize(w, axis: int = 0) -> QTensor:
    """Symmetric absmax int8 quantization of a 2-D weight.

    ``axis`` is the reduction (input) dimension of the matmul the weight
    feeds — scales are per *output* channel, so dequantization commutes
    with the contraction and can be applied to the accumulator.
    """
    w = jnp.asarray(w)
    if w.ndim != 2:
        raise ValueError(f"quantize expects a 2-D weight, got {w.shape}")
    if axis not in (0, 1):
        raise ValueError(f"axis must be 0 or 1, got {axis}")
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.round(wf / jnp.expand_dims(scale, axis))
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return QTensor(q=q, scale=scale)


def dequantize(qt: QTensor, axis: int = 0, dtype=jnp.float32):
    """Materialize the represented weight (test/debug path — the runtime
    paths never do this in HBM)."""
    return (qt.q.astype(jnp.float32)
            * jnp.expand_dims(qt.scale, axis)).astype(dtype)


def _q_matmul_xla(x, qt: QTensor, out_dtype):
    """Reference lowering: widen-in-graph matmul, scale on the f32 result.

    Matches the kernel bit-for-bit in f32 and is the fallback wherever the
    kernel's tiling constraints don't hold. (Inside a decode scan XLA may
    hoist the widening — that is exactly what the Pallas path prevents.)
    """
    acc = jax.lax.dot_general(
        x, qt.q.astype(x.dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return (acc * qt.scale).astype(out_dtype)


def _q_matmul_kernel(x_ref, q_ref, s_ref, o_ref):
    """One output tile: int8 weight tile → bf16 in-register → MXU → scale."""
    w = q_ref[...].astype(x_ref.dtype)
    acc = jnp.dot(x_ref[...], w, preferred_element_type=jnp.float32)
    o_ref[...] = (acc * s_ref[...]).astype(o_ref.dtype)


def _pad_to(n: int, mult: int) -> int:
    return -(-n // mult) * mult


@functools.partial(jax.jit, static_argnames=("bm", "bn", "out_dtype",
                                             "interpret"))
def _q_matmul_pallas(x2, q, scale, *, bm, bn, out_dtype, interpret):
    m, k = x2.shape
    n = q.shape[1]
    mp = _pad_to(m, bm)
    xp = jnp.pad(x2, ((0, mp - m), (0, 0)))
    out = pl.pallas_call(
        _q_matmul_kernel,
        grid=(mp // bm, n // bn),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, n), out_dtype),
        interpret=interpret,
    )(xp, q, scale.reshape(1, n))
    return out[:m]


def q_matmul(x, qt: QTensor, *, impl: str = "auto", out_dtype=None,
             interpret: bool | None = None):
    """``x [..., K] @ dequant(qt) [K, N] → [..., N]``.

    ``impl``: ``"pallas"`` (fused in-VMEM dequant kernel), ``"xla"``
    (widen-in-graph fallback), or ``"auto"`` — the kernel whenever its
    tiling constraints hold (K and N multiples of 128, K small enough for
    a full-depth VMEM tile). ``interpret`` defaults to "kernel on TPU,
    interpreter elsewhere" so CI exercises the same code path on CPU.
    """
    if impl not in ("auto", "pallas", "xla"):
        raise ValueError(f"impl must be 'auto', 'pallas', or 'xla', "
                         f"got {impl!r}")
    k, n = qt.q.shape
    if x.shape[-1] != k:
        raise ValueError(f"x trailing dim {x.shape[-1]} != weight rows {k}")
    out_dtype = out_dtype or x.dtype
    tileable = (k % _LANES == 0 and n % _LANES == 0 and k <= 8192)
    if impl == "auto":
        impl = "pallas" if tileable else "xla"
    if impl == "xla":
        return _q_matmul_xla(x, qt, out_dtype)
    if not tileable:
        raise ValueError(
            f"impl='pallas' needs K, N multiples of {_LANES} and K <= 8192; "
            f"got K={k}, N={n} (use impl='auto' to fall back)"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lead = x.shape[:-1]
    m = 1
    for d in lead:
        m *= d
    x2 = x.reshape(m, k)
    # one output tile spans the full contraction: K<=8192 bf16 rows fit a
    # [bm, K] + [K, bn] VMEM working set comfortably inside 16 MiB
    bm = min(_pad_to(max(m, 1), 16), 256)
    bn = min(n, 512)
    while n % bn:
        bn //= 2
    out = _q_matmul_pallas(x2, qt.q, qt.scale, bm=bm, bn=bn,
                           out_dtype=out_dtype, interpret=bool(interpret))
    return out.reshape(*lead, n)


def quantize_dense_tree(params):
    """Walk a flax param tree and quantize every Dense-shaped leaf pair.

    A subtree ``{"kernel": [K, N] float, "bias": ...}`` (exactly the param
    set ``nn.Dense`` creates) becomes ``{"kernel_q": int8, "scale": f32,
    "bias": ...}`` — the param set ``models.lm.QDense`` reads. Everything
    else (embeddings, LayerNorm scales/biases, conv kernels) passes through
    unchanged.
    """
    from collections.abc import Mapping

    def rec(node):
        if isinstance(node, Mapping):
            if (set(node) == {"kernel", "bias"}
                    and getattr(node["kernel"], "ndim", 0) == 2):
                qt = quantize(node["kernel"], axis=0)
                return {"kernel_q": qt.q, "scale": qt.scale,
                        "bias": node["bias"]}
            return {k: rec(v) for k, v in node.items()}
        return node

    return rec(params)
