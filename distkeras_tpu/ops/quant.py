"""Int8 weight-only quantization: TPU-native serving for trained models.

Beyond-reference (the Spark-era reference served float32 Keras weights and
nothing else — SURVEY.md §2b #15): symmetric per-output-channel int8
post-training quantization, built for the TPU memory system.

Why weight-only, and why a Pallas kernel:

- **Autoregressive decode is HBM-bandwidth-bound.** Every decode step
  streams every weight matrix once to multiply a tiny ``[B, 1, d]``
  activation. Int8 weights halve the bytes per step, which is directly
  ~2× decode throughput for the weight-dominated regime (small batch,
  cache smaller than the weights).
- **The dequant must happen AFTER the HBM read.** An XLA-level
  ``q.astype(bf16) * scale`` before the matmul is loop-invariant inside
  the decode ``lax.scan`` — the compiler may hoist it and materialize a
  full bf16 copy in HBM, forfeiting the entire win. The Pallas kernel
  makes the schedule explicit: int8 tiles stream HBM→VMEM, are widened to
  bf16 in-register, hit the MXU, and the per-channel scale is applied to
  the f32 accumulator. No bf16 weight tensor ever exists in HBM.
- **Activations stay bf16.** v5e's MXU runs int8×int8 at 2× bf16 peak,
  but decode is nowhere near compute-bound — weight-only takes the
  bandwidth win and keeps activation precision (no calibration needed).

Accuracy: symmetric absmax per output channel; the scale is exact in f32
and applied after the f32 accumulation, so ``q_matmul`` equals the exact
``x @ (q · scale)`` product up to matmul dtype rounding (pinned by
tests/test_quant.py).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANES = 128


class QTensor(NamedTuple):
    """An int8-quantized matrix: ``q [K, N] int8`` with per-output-channel
    ``scale [N] f32``; the represented value is ``q.astype(f32) * scale``."""

    q: jax.Array
    scale: jax.Array


def quantize(w, axis: int = 0) -> QTensor:
    """Symmetric absmax int8 quantization of a 2-D weight.

    ``axis`` is the reduction (input) dimension of the matmul the weight
    feeds — scales are per *output* channel, so dequantization commutes
    with the contraction and can be applied to the accumulator.
    """
    w = jnp.asarray(w)
    if w.ndim != 2:
        raise ValueError(f"quantize expects a 2-D weight, got {w.shape}")
    if axis not in (0, 1):
        raise ValueError(f"axis must be 0 or 1, got {axis}")
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.round(wf / jnp.expand_dims(scale, axis))
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return QTensor(q=q, scale=scale)


def dequantize(qt: QTensor, axis: int = 0, dtype=jnp.float32):
    """Materialize the represented weight (test/debug path — the runtime
    paths never do this in HBM)."""
    return (qt.q.astype(jnp.float32)
            * jnp.expand_dims(qt.scale, axis)).astype(dtype)


def _q_matmul_xla(x, qt: QTensor, out_dtype):
    """Reference lowering: widen-in-graph matmul, scale on the f32 result.

    Matches the kernel bit-for-bit in f32 and is the fallback wherever the
    kernel's tiling constraints don't hold. (Inside a decode scan XLA may
    hoist the widening — that is exactly what the Pallas path prevents.)
    """
    acc = jax.lax.dot_general(
        x, qt.q.astype(x.dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return (acc * qt.scale).astype(out_dtype)


def _q_matmul_kernel(x_ref, q_ref, s_ref, o_ref):
    """One output tile: int8 weight tile → bf16 in-register → MXU → scale."""
    w = q_ref[...].astype(x_ref.dtype)
    acc = jnp.dot(x_ref[...], w, preferred_element_type=jnp.float32)
    o_ref[...] = (acc * s_ref[...]).astype(o_ref.dtype)


def _pad_to(n: int, mult: int) -> int:
    return -(-n // mult) * mult


@functools.partial(jax.jit, static_argnames=("bm", "bn", "out_dtype",
                                             "interpret"))
def _q_matmul_pallas(x2, q, scale, *, bm, bn, out_dtype, interpret):
    m, k = x2.shape
    n = q.shape[1]
    mp = _pad_to(m, bm)
    xp = jnp.pad(x2, ((0, mp - m), (0, 0)))
    out = pl.pallas_call(
        _q_matmul_kernel,
        grid=(mp // bm, n // bn),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, n), out_dtype),
        interpret=interpret,
    )(xp, q, scale.reshape(1, n))
    return out[:m]


def q_matmul(x, qt: QTensor, *, impl: str = "auto", out_dtype=None,
             interpret: bool | None = None):
    """``x [..., K] @ dequant(qt) [K, N] → [..., N]``.

    ``impl``: ``"pallas"`` (fused in-VMEM dequant kernel), ``"xla"``
    (widen-in-graph fallback), or ``"auto"`` — the kernel whenever its
    tiling constraints hold (K and N multiples of 128, K small enough for
    a full-depth VMEM tile). ``interpret`` defaults to "kernel on TPU,
    interpreter elsewhere" so CI exercises the same code path on CPU.
    """
    if impl not in ("auto", "pallas", "xla"):
        raise ValueError(f"impl must be 'auto', 'pallas', or 'xla', "
                         f"got {impl!r}")
    k, n = qt.q.shape
    if x.shape[-1] != k:
        raise ValueError(f"x trailing dim {x.shape[-1]} != weight rows {k}")
    out_dtype = out_dtype or x.dtype
    tileable = (k % _LANES == 0 and n % _LANES == 0 and k <= 8192)
    if impl == "auto":
        impl = "pallas" if tileable else "xla"
    if impl == "xla":
        return _q_matmul_xla(x, qt, out_dtype)
    if not tileable:
        raise ValueError(
            f"impl='pallas' needs K, N multiples of {_LANES} and K <= 8192; "
            f"got K={k}, N={n} (use impl='auto' to fall back)"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lead = x.shape[:-1]
    m = 1
    for d in lead:
        m *= d
    x2 = x.reshape(m, k)
    # one output tile spans the full contraction: K<=8192 bf16 rows fit a
    # [bm, K] + [K, bn] VMEM working set comfortably inside 16 MiB
    bm = min(_pad_to(max(m, 1), 16), 256)
    bn = min(n, 512)
    while n % bn:
        bn //= 2
    out = _q_matmul_pallas(x2, qt.q, qt.scale, bm=bm, bn=bn,
                           out_dtype=out_dtype, interpret=bool(interpret))
    return out.reshape(*lead, n)


def _q_interceptor(next_fun, args, kwargs, context):
    """flax method interceptor: any ``nn.Dense`` whose params arrived
    quantized (``kernel_q``/``scale``[/``bias``] — what
    :func:`quantize_dense_tree` produces) is served by :func:`q_matmul`
    instead of its own kernel read; everything else runs unchanged."""
    import flax.linen as nn

    m = context.module
    if (type(m) is nn.Dense and context.method_name == "__call__"
            and m.has_variable("params", "kernel_q")):
        q = m.get_variable("params", "kernel_q")
        s = m.get_variable("params", "scale")
        x = args[0] if args else kwargs["inputs"]  # Dense(…)(inputs=x)
        # mirror nn.Dense's promote-to-module-dtype semantics so the
        # quantized forward keeps the fp model's compute dtypes
        cdt = m.dtype if m.dtype is not None else x.dtype
        x = x.astype(cdt)
        out = q_matmul(x, QTensor(q, s), out_dtype=cdt)
        if m.use_bias:
            out = out + jnp.asarray(
                m.get_variable("params", "bias")
            ).astype(cdt)
        return out
    return next_fun(*args, **kwargs)


def quantize_serving(spec, params, state=None):
    """Generic int8 weight-only serving for a flax-backed ``ModelSpec``.

    ``(spec, trained params) → (int8 spec, int8 params)``: the model is
    traced once (``jax.eval_shape`` on the spec's recorded example input)
    to find exactly the ``nn.Dense`` modules in the forward; their kernels
    become int8 matrices + per-output-channel scales
    (:func:`quantize_dense_tree`), and the returned spec's ``apply``
    serves them through a flax method interceptor — no model-code
    changes, so the whole zoo (MLP, the transformer classifiers, custom
    modules BUILT FROM ``nn.Dense``) quantizes the same way. Kernel/bias
    pairs owned by anything other than ``nn.Dense`` (e.g.
    ``nn.DenseGeneral``, convolutions) stay in float — the trace is what
    guarantees nothing is converted that the interceptor cannot serve.
    Inference-only: the returned apply rejects ``training=True``.
    ``models.quantize_lm`` remains the LM-family door (its ``QDense``
    modules also cover the cached-decode entry points, which never pass
    through ``nn.Dense.__call__``).
    """
    import dataclasses

    import flax.linen as nn

    if getattr(spec, "module", None) is None:
        raise ValueError(
            "quantize_serving needs a flax-backed ModelSpec (built by "
            "from_flax, e.g. the models/ zoo); Keras and hand-written "
            "specs have no flax module to intercept"
        )
    if getattr(spec, "example", None) is None:
        raise ValueError(
            "quantize_serving needs the spec's example input to trace the "
            "module (ModelSpec.example — from_flax records it)"
        )
    base_apply = spec.apply
    state = {} if state is None else state

    # trace once to record which param paths belong to real nn.Dense
    # modules reached by the serving forward
    dense_paths: set[tuple] = set()

    def record(next_fun, args, kwargs, context):
        m = context.module
        if type(m) is nn.Dense and context.method_name == "__call__":
            dense_paths.add(tuple(m.path))
        return next_fun(*args, **kwargs)

    x0 = spec.example
    x0 = x0[0] if isinstance(x0, tuple) and len(x0) == 1 else x0
    with nn.intercept_methods(record):
        jax.eval_shape(
            lambda p, s, x: base_apply(p, s, x, False), params, state, x0
        )

    def apply(params, state, x, training):
        if training:
            raise ValueError(
                "int8 weight-only quantization is a serving path; train "
                "the float model and re-quantize"
            )
        with nn.intercept_methods(_q_interceptor):
            return base_apply(params, state, x, training)

    # fused_losses closures capture the FLOAT module and param layout —
    # they must not ride into the int8 serving spec (training it is an
    # error the quantized apply raises; a stale fused fn would bypass it)
    qspec = dataclasses.replace(spec, apply=apply, name=spec.name + "_int8",
                                fused_losses=None)
    return qspec, quantize_dense_tree(params, paths=dense_paths)


def quantize_dense_tree(params, paths: set | None = None):
    """Walk a flax param tree and quantize Dense-shaped leaf groups.

    A subtree ``{"kernel": [K, N] float, "bias": ...}`` (exactly the param
    set ``nn.Dense`` creates) becomes ``{"kernel_q": int8, "scale": f32,
    "bias": ...}`` — the param set ``models.lm.QDense`` and the serving
    interceptor read. Everything else (embeddings, LayerNorm
    scales/biases, conv kernels) passes through unchanged.

    ``paths`` (from :func:`quantize_serving`'s recording trace) restricts
    conversion to subtrees KNOWN to belong to ``nn.Dense`` modules — and
    within it, bias-less Dense params (``{"kernel"}`` alone,
    ``use_bias=False``) convert too. Without ``paths`` (the
    ``quantize_lm`` door) only exact ``{kernel, bias}`` pairs convert,
    since a bare 2-D ``kernel`` could belong to anything.
    """
    from collections.abc import Mapping

    def convert(node):
        qt = quantize(node["kernel"], axis=0)
        out = {"kernel_q": qt.q, "scale": qt.scale}
        if "bias" in node:
            out["bias"] = node["bias"]
        return out

    def rec(node, path):
        if isinstance(node, Mapping):
            is_dense_shape = (
                set(node) in ({"kernel", "bias"}, {"kernel"})
                and getattr(node.get("kernel"), "ndim", 0) == 2
            )
            if paths is not None:
                if path in paths and is_dense_shape:
                    return convert(node)
            elif set(node) == {"kernel", "bias"} and is_dense_shape:
                return convert(node)
            return {k: rec(v, path + (k,)) for k, v in node.items()}
        return node

    return rec(params, ())
