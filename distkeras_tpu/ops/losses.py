"""Loss functions, jit-traceable and bfloat16-safe.

The reference delegated losses to Keras by name (``Trainer.__init__(…, loss)``,
reference ``distkeras/trainers.py :: Trainer``). Here the same string names
resolve to pure JAX functions of ``(y_true, y_pred) -> scalar`` so they can be
traced into the SPMD training step and fused by XLA.

All reductions are over every axis (mean), matching Keras' default reduction.
Log/exp math is done in float32 even when activations are bfloat16 — on TPU the
MXU runs matmuls in bf16 while loss reductions stay fp32 for stability.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

_EPS = 1e-7


def _f32(x):
    return jnp.asarray(x, jnp.float32)


def mean_squared_error(y_true, y_pred):
    return jnp.mean(jnp.square(_f32(y_pred) - _f32(y_true)))


def mean_absolute_error(y_true, y_pred):
    return jnp.mean(jnp.abs(_f32(y_pred) - _f32(y_true)))


def categorical_crossentropy(y_true, y_pred):
    """Keras-style CCE on *probabilities* (model ends in softmax)."""
    p = jnp.clip(_f32(y_pred), _EPS, 1.0 - _EPS)
    return jnp.mean(-jnp.sum(_f32(y_true) * jnp.log(p), axis=-1))


def softmax_cross_entropy(y_true, y_pred):
    """CCE on *logits* — the numerically preferred TPU form."""
    logp = jax.nn.log_softmax(_f32(y_pred), axis=-1)
    return jnp.mean(-jnp.sum(_f32(y_true) * logp, axis=-1))


def sparse_softmax_cross_entropy(y_true, y_pred):
    """CCE on logits with integer class labels."""
    logp = jax.nn.log_softmax(_f32(y_pred), axis=-1)
    labels = y_true.astype(jnp.int32).reshape(y_pred.shape[:-1])
    picked = jnp.take_along_axis(logp, labels[..., None], axis=-1)
    return jnp.mean(-picked)


def sparse_categorical_crossentropy(y_true, y_pred):
    """Keras-style sparse CCE on *probabilities* (model ends in softmax).

    Matches Keras' default ``from_logits=False`` semantics for the name
    ``'sparse_categorical_crossentropy'`` — for logits use
    ``'sparse_softmax_cross_entropy'``.
    """
    p = jnp.clip(_f32(y_pred), _EPS, 1.0 - _EPS)
    labels = y_true.astype(jnp.int32).reshape(y_pred.shape[:-1])
    picked = jnp.take_along_axis(p, labels[..., None], axis=-1)
    return jnp.mean(-jnp.log(picked))


def binary_crossentropy(y_true, y_pred):
    p = jnp.clip(_f32(y_pred), _EPS, 1.0 - _EPS)
    t = _f32(y_true)
    return jnp.mean(-(t * jnp.log(p) + (1.0 - t) * jnp.log(1.0 - p)))


def sigmoid_binary_crossentropy(y_true, y_pred):
    """BCE on logits."""
    logits = _f32(y_pred)
    t = _f32(y_true)
    # log(1+exp(-|x|)) formulation, stable for large |logits|.
    return jnp.mean(
        jnp.maximum(logits, 0) - logits * t + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )


def masked_sparse_softmax_cross_entropy(y_true, y_pred, mask):
    """Sequence CCE with a validity mask (padded-token positions excluded).

    Used by the IMDB-LSTM config: variable-length sequences are padded to
    static XLA shapes (SURVEY.md §7.3 hard part 3) and the pad positions are
    masked out of the loss.
    """
    logp = jax.nn.log_softmax(_f32(y_pred), axis=-1)
    labels = y_true.astype(jnp.int32)
    picked = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    m = _f32(mask)
    return -jnp.sum(picked * m) / jnp.maximum(jnp.sum(m), 1.0)


_LOSSES: dict[str, Callable] = {
    "mse": mean_squared_error,
    "mean_squared_error": mean_squared_error,
    "mae": mean_absolute_error,
    "mean_absolute_error": mean_absolute_error,
    "categorical_crossentropy": categorical_crossentropy,
    "softmax_cross_entropy": softmax_cross_entropy,
    "sparse_softmax_cross_entropy": sparse_softmax_cross_entropy,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "binary_crossentropy": binary_crossentropy,
    "sigmoid_binary_crossentropy": sigmoid_binary_crossentropy,
}


def get_loss(loss) -> Callable:
    """Resolve a loss by Keras-style name, or pass a callable through."""
    if callable(loss):
        return loss
    try:
        return _LOSSES[loss]
    except KeyError:
        raise ValueError(
            f"unknown loss {loss!r}; known: {sorted(_LOSSES)}"
        ) from None
