"""Pallas TPU kernels for hot elementwise paths.

The reference had no native kernels at all — its compute lived in Theano/TF1
(SURVEY.md §2b.4) — so nothing here is a port; it is TPU-native surplus.

``fused_adam`` fuses the whole Adam step — both moment updates, bias
correction, and the parameter update — into ONE Pallas kernel, i.e. one pass
over HBM per leaf instead of the several reads/writes a chain of unfused
elementwise ops would make. At communication-window boundaries every parameter
is touched by the optimizer, so this path is HBM-bandwidth bound; fusing it is
the classic TPU win (XLA usually fuses these too — the kernel makes the
schedule explicit and guaranteed, and serves as the repo's template for
writing Pallas kernels against the engine).

The kernel runs on real TPUs; everywhere else (the 8-fake-device CPU mesh in
CI) it executes in Pallas interpret mode, so the SAME code path is unit-tested
against the optax oracle without TPU hardware. Select it with
``worker_optimizer="fused_adam"`` on any trainer.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128          # TPU lane width (last dim of every tile)
_BLOCK_ROWS = 256     # rows per grid step: 256×128 f32 = 128 KiB/buffer in VMEM


def _adam_kernel(bc_ref, g_ref, m_ref, v_ref, m_out, v_out, u_out,
                 *, lr, b1, b2, eps):
    """One block: new moments + bias-corrected update, single VMEM round."""
    g = g_ref[:]
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    m_out[:] = m
    v_out[:] = v
    # bc holds [1/(1-b1^t), 1/(1-b2^t)] — computed once per step on the host
    # side of the trace (t is a traced scalar, so it can't be closed over)
    mhat = m * bc_ref[0, 0]
    vhat = v * bc_ref[0, 1]
    u_out[:] = (-lr) * mhat / (jnp.sqrt(vhat) + eps)


def _adam_leaf(g, m, v, bc, *, lr, b1, b2, eps, interpret):
    """Apply the kernel to one (arbitrary-shape) leaf via 1D→(rows,128) tiling."""
    shape, dtype = g.shape, g.dtype
    n = g.size
    rows = max(1, -(-n // _LANES))
    rows_p = -(-rows // _BLOCK_ROWS) * _BLOCK_ROWS
    total = rows_p * _LANES

    def prep(x):
        flat = x.reshape(-1)
        return jnp.pad(flat, (0, total - n)).reshape(rows_p, _LANES)

    blk = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    scal = pl.BlockSpec((1, 2), lambda i: (0, 0), memory_space=pltpu.SMEM)
    kernel = functools.partial(_adam_kernel, lr=lr, b1=b1, b2=b2, eps=eps)
    out = jax.ShapeDtypeStruct((rows_p, _LANES), dtype)
    m_new, v_new, u = pl.pallas_call(
        kernel,
        grid=(rows_p // _BLOCK_ROWS,),
        in_specs=[scal, blk, blk, blk],
        out_specs=[blk, blk, blk],
        out_shape=[out, out, out],
        interpret=interpret,
    )(bc, prep(g), prep(m), prep(v))

    def unprep(x):
        return x.reshape(-1)[:n].reshape(shape)

    return unprep(m_new), unprep(v_new), unprep(u)


class FusedAdamState(NamedTuple):
    count: Any
    mu: Any
    nu: Any


def fused_adam(learning_rate: float, b1: float = 0.9, b2: float = 0.999,
               eps: float = 1e-8,
               interpret: bool | None = None) -> optax.GradientTransformation:
    """Adam as a single fused Pallas kernel per leaf (optax-compatible).

    Semantics match ``optax.adam`` exactly (same bias correction, same eps
    placement); the unit tests pin the two against each other. ``interpret``
    defaults to "kernel on TPU, interpreter elsewhere".
    """
    lr = float(learning_rate)

    def _interp():
        if interpret is not None:
            return bool(interpret)
        return jax.default_backend() != "tpu"

    def init(params):
        zeros = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return FusedAdamState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree.map(zeros, params),
            nu=jax.tree.map(zeros, params),
        )

    def update(updates, state, params=None):
        del params
        count = state.count + 1
        t = count.astype(jnp.float32)
        bc = jnp.stack([
            1.0 / (1.0 - jnp.power(b1, t)),
            1.0 / (1.0 - jnp.power(b2, t)),
        ]).astype(jnp.float32).reshape(1, 2)

        g_leaves, treedef = jax.tree.flatten(updates)
        m_leaves = treedef.flatten_up_to(state.mu)
        v_leaves = treedef.flatten_up_to(state.nu)
        interp = _interp()
        new_m, new_v, u = [], [], []
        for g, m, v in zip(g_leaves, m_leaves, v_leaves):
            mi, vi, ui = _adam_leaf(
                g.astype(jnp.float32), m, v, bc,
                lr=lr, b1=b1, b2=b2, eps=eps, interpret=interp,
            )
            new_m.append(mi)
            new_v.append(vi)
            u.append(ui.astype(g.dtype))
        return (
            jax.tree.unflatten(treedef, u),
            FusedAdamState(
                count=count,
                mu=jax.tree.unflatten(treedef, new_m),
                nu=jax.tree.unflatten(treedef, new_v),
            ),
        )

    return optax.GradientTransformation(init, update)
