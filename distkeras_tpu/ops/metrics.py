"""Evaluation metrics (pure JAX, traceable).

Parity: the reference's only metric was classification accuracy
(``distkeras/evaluators.py :: AccuracyEvaluator``, SURVEY.md §2b #17).
"""

from __future__ import annotations

import jax.numpy as jnp


def accuracy(y_true, y_pred):
    """Classification accuracy.

    Accepts one-hot or integer ``y_true``; ``y_pred`` as class scores
    (argmaxed) or already-integer predictions.
    """
    if y_pred.ndim > 1 and y_pred.shape[-1] > 1:
        pred = jnp.argmax(y_pred, axis=-1)
    else:
        pred = jnp.round(y_pred).astype(jnp.int32).reshape(y_pred.shape[0], -1)[:, 0]
    if y_true.ndim > 1 and y_true.shape[-1] > 1:
        true = jnp.argmax(y_true, axis=-1)
    else:
        true = y_true.astype(jnp.int32).reshape(y_true.shape[0], -1)[:, 0]
    return jnp.mean((pred == true).astype(jnp.float32))


def top_k_accuracy(y_true, y_pred, k: int = 5):
    if y_true.ndim > 1 and y_true.shape[-1] > 1:
        true = jnp.argmax(y_true, axis=-1)
    else:
        true = y_true.astype(jnp.int32).reshape(-1)
    topk = jnp.argsort(y_pred, axis=-1)[:, -k:]
    return jnp.mean(jnp.any(topk == true[:, None], axis=-1).astype(jnp.float32))
