"""Column-batch dataset — the Spark DataFrame replacement.

In the reference, training data lived in a Spark DataFrame whose RDD was
repartitioned to ``num_workers`` partitions; each partition became one worker's
shard (reference ``distkeras/trainers.py``, ``rdd.repartition`` +
``mapPartitionsWithIndex``; SURVEY.md §1). On TPU the same role is played by a
host-side column store that assembles *superbatches* shaped
``[num_workers, window, batch, …]`` — the leading worker axis is sharded over
the ``dp`` mesh axis so each chip receives exactly its own shard, and the
``window`` axis is consumed by ``lax.scan`` inside one jitted step (no
host↔device transfer inside the window).

Rows are never materialized as Python objects: all columns are contiguous
NumPy arrays, shuffles are index permutations, and shard assembly is a single
reshape/transpose — the host never becomes the bottleneck the Spark driver was.
"""

from __future__ import annotations

from typing import Iterator, Mapping, Sequence

import numpy as np


class Dataset:
    """Immutable named-column store (all columns share the leading row count)."""

    def __init__(self, columns: Mapping[str, np.ndarray]):
        if not columns:
            raise ValueError("Dataset needs at least one column")
        lengths = {k: len(v) for k, v in columns.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(f"column length mismatch: {lengths}")
        self._columns = {k: np.asarray(v) for k, v in columns.items()}

    # -- construction -------------------------------------------------------

    @classmethod
    def from_arrays(cls, features, labels, features_col="features", label_col="label"):
        return cls({features_col: features, label_col: labels})

    # -- basic frame ops ----------------------------------------------------

    @property
    def columns(self) -> list[str]:
        return list(self._columns)

    def __len__(self) -> int:
        return len(next(iter(self._columns.values())))

    num_rows = property(__len__)

    def __getitem__(self, name: str) -> np.ndarray:
        return self._columns[name]

    def __contains__(self, name: str) -> bool:
        return name in self._columns

    def with_column(self, name: str, values: np.ndarray) -> "Dataset":
        cols = dict(self._columns)
        cols[name] = np.asarray(values)
        return Dataset(cols)

    def select(self, names: Sequence[str]) -> "Dataset":
        return Dataset({n: self._columns[n] for n in names})

    def drop(self, name: str) -> "Dataset":
        return Dataset({k: v for k, v in self._columns.items() if k != name})

    def take(self, n: int) -> "Dataset":
        return Dataset({k: v[:n] for k, v in self._columns.items()})

    def gather(self, idx: np.ndarray) -> "Dataset":
        return Dataset({k: v[idx] for k, v in self._columns.items()})

    def concat(self, other: "Dataset") -> "Dataset":
        return Dataset(
            {k: np.concatenate([v, other[k]]) for k, v in self._columns.items()}
        )

    def split(self, fraction: float, seed: int = 0) -> tuple["Dataset", "Dataset"]:
        """Random train/test split. Parity: Spark ``df.randomSplit``."""
        n = len(self)
        perm = np.random.default_rng(seed).permutation(n)
        cut = int(n * fraction)
        return self.gather(perm[:cut]), self.gather(perm[cut:])

    def shuffle(self, seed: int = 0) -> "Dataset":
        """Full shuffle as an index permutation.

        Parity: reference ``distkeras/utils.py :: shuffle(df)``.
        """
        perm = np.random.default_rng(seed).permutation(len(self))
        return self.gather(perm)

    # -- sharding / batching -------------------------------------------------

    def superbatches(
        self,
        num_workers: int,
        batch_size: int,
        window: int,
        columns: Sequence[str],
        *,
        seed: int | None = None,
        drop_remainder: bool = True,
    ) -> Iterator[tuple[np.ndarray, ...]]:
        """Yield one epoch of superbatches ``[num_workers, window, batch, …]``.

        This is the rebuilt ``rdd.repartition(num_workers)`` +
        per-partition minibatch assembly (reference ``distkeras/workers.py``):
        a worker's row range plays the role of its Spark partition. With
        ``drop_remainder=True`` (default) rows left over after filling whole
        superbatches are dropped (the reference's partition tails were likewise
        truncated to whole minibatches); with ``drop_remainder=False`` the tail
        superbatch is filled by wrapping around to the start, so every row
        appears at least once (some up to twice) — XLA shapes stay static.
        """
        n = len(self)
        n_super, rows_per_super = self._superbatch_counts(
            num_workers, batch_size, window, cover_all=not drop_remainder
        )
        idx = (
            np.random.default_rng(seed).permutation(n)
            if seed is not None
            else np.arange(n)
        )
        if n < n_super * rows_per_super:  # wrap-pad the tail superbatch
            idx = np.resize(idx, n_super * rows_per_super)
        for s in range(n_super):
            sl = idx[s * rows_per_super : (s + 1) * rows_per_super]
            out = []
            for c in columns:
                col = self._columns[c][sl]
                # Layout [window, W, batch, …] → [W, window, batch, …] so that
                # sharding axis 0 over 'dp' gives each chip its own stream.
                col = col.reshape((window, num_workers, batch_size) + col.shape[1:])
                out.append(np.swapaxes(col, 0, 1))
            yield tuple(out)

    def worker_shards(
        self,
        num_workers: int,
        batch_size: int,
        window: int,
        columns: Sequence[str],
        *,
        seed: int | None = None,
        cover_all: bool = False,
    ) -> tuple[np.ndarray, ...]:
        """Per-worker row shards ``[num_workers, rows_per_worker, …]``.

        The device-resident staging layout: upload once, then each epoch is
        reshaped/shuffled on device (``LocalSGDEngine.run_epoch_resident``).
        Rows are assigned to workers with the SAME window-major interleave as
        :meth:`superbatches` — a worker's shard flattens as
        ``[n_super, window, batch]`` — so resident and streaming training see
        identical data order when unshuffled, and class-sorted datasets never
        give a worker a single-class shard.

        ``cover_all=True`` wraps the tail so every row appears at least once
        (some twice); ``False`` drops the tail like :meth:`superbatches`.
        """
        n_super, rows_per_super = self._superbatch_counts(
            num_workers, batch_size, window, cover_all
        )
        idx = (
            np.random.default_rng(seed).permutation(len(self))
            if seed is not None
            else np.arange(len(self))
        )
        if len(idx) < n_super * rows_per_super:  # wrap-pad (cover_all)
            idx = np.resize(idx, n_super * rows_per_super)
        idx = idx[: n_super * rows_per_super]
        out = []
        for c in columns:
            col = self._columns[c][idx]
            col = col.reshape(
                (n_super, window, num_workers, batch_size) + col.shape[1:]
            )
            # [S, win, W, B, …] → [W, S, win, B, …] → [W, rows_per_worker, …]
            col = np.moveaxis(col, 2, 0)
            out.append(
                col.reshape(
                    (num_workers, n_super * window * batch_size) + col.shape[4:]
                )
            )
        return tuple(out)

    def _superbatch_counts(
        self, num_workers: int, batch_size: int, window: int,
        cover_all: bool = False,
    ) -> tuple[int, int]:
        """Shared sizing/validation for all superbatch assemblies."""
        n = len(self)
        rows_per_super = num_workers * batch_size * window
        n_super = n // rows_per_super
        if cover_all:
            n_super = -(-n // rows_per_super)
        elif n_super == 0:
            raise ValueError(
                f"dataset of {n} rows too small for one superbatch of "
                f"{rows_per_super} rows (workers={num_workers} × "
                f"window={window} × batch={batch_size})"
            )
        return n_super, rows_per_super

    def batches(
        self,
        batch_size: int,
        columns: Sequence[str],
        *,
        seed: int | None = None,
        drop_remainder: bool = True,
    ) -> Iterator[tuple[np.ndarray, ...]]:
        """Plain single-stream minibatches (the ``SingleTrainer`` path)."""
        for sb in self.superbatches(
            1, batch_size, 1, columns, seed=seed, drop_remainder=drop_remainder
        ):
            yield tuple(a[0, 0] for a in sb)

    def __repr__(self):
        cols = ", ".join(
            f"{k}:{v.dtype}{list(v.shape[1:])}" for k, v in self._columns.items()
        )
        return f"Dataset({len(self)} rows; {cols})"


def prefetch_to_device(iterable, place, depth: int = 2):
    """Run ``place`` (host→device placement) ``depth`` items ahead of the
    consumer, on a background thread.

    The streaming input pipeline (SURVEY.md §7.3 hard part #4 — "sharded
    per-chip streams that don't bottleneck the chip"): JAX dispatch is
    already asynchronous, so what a naive feed loop serializes with the
    device is the HOST work per step — numpy slicing/assembly in
    ``superbatches`` and the ``device_put`` staging copy. This generator
    moves that work off the consumer's critical path: a bounded queue of
    already-placed batches stays ``depth`` deep, so the device never waits
    for batch ``k+1``'s host prep while ``k`` computes.

    Exceptions from the producer (bad batch, placement failure) re-raise in
    the consumer; an early-exiting consumer (e.g. a raised training error)
    unblocks and joins the thread via generator close. Ordering is exactly
    the source iterable's, so prefetched training is bit-identical to the
    plain loop.

    Memory: up to ``depth + 1`` placed batches are resident at once (the
    queue plus the producer's in-flight one) on top of the consumer's —
    size ``depth`` for the device-memory headroom you have. Depth 1
    (double buffering) already hides the host prep; more only helps when
    step times vary a lot.
    """
    import queue
    import threading

    if depth < 1:
        raise ValueError(f"depth must be >= 1, got {depth}")
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()
    _END, _ERR = object(), object()

    def put_until_stopped(item) -> bool:
        """Deliver unless the consumer already left; never give up early —
        a dropped _END/_ERR sentinel would strand the consumer on q.get()."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for item in iterable:
                if not put_until_stopped(place(item)):
                    return
            put_until_stopped(_END)
        except BaseException as e:  # surface in the consumer, don't die silent
            put_until_stopped((_ERR, e))

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                break
            if isinstance(item, tuple) and len(item) == 2 and item[0] is _ERR:
                raise item[1]
            yield item
    finally:
        stop.set()
        while not q.empty():  # unblock a producer stuck on a full queue
            try:
                q.get_nowait()
            except queue.Empty:
                break
        t.join(timeout=5)


def padded_chunks(
    cols: Sequence[np.ndarray], batch_size: int
) -> Iterator[tuple[list[np.ndarray], int]]:
    """Fixed-size chunks of column arrays for static-shape inference/eval.

    The tail chunk is padded by repeating its last row so every chunk has
    the SAME shape — XLA compiles the downstream apply exactly once. Yields
    ``(chunk_cols, n_real)``; callers trim or mask the ``batch_size -
    n_real`` pad rows. Shared by ``ModelPredictor.predict`` and the
    trainers' ``validation_data`` evaluator.
    """
    n = len(cols[0])
    for start in range(0, n, batch_size):
        chunk = [c[start : start + batch_size] for c in cols]
        real = len(chunk[0])
        pad = batch_size - real
        if pad:
            chunk = [
                np.concatenate([c, np.repeat(c[-1:], pad, axis=0)])
                for c in chunk
            ]
        yield chunk, real
