"""The serving front door: radix prefix cache + SLO-aware tenant admission.

Two host-side data structures the scheduler composes into vLLM-lineage
automatic prefix caching and multi-tenant admission (ISSUE 17):

- :class:`RadixPrefixCache` — a content-hash radix tree over FULL KV
  blocks. Each node is one block of ``block_size`` token ids, keyed by a
  chain hash (``sharding/ring.py::stable_hash`` — the pinned ``blake2b``,
  never the salted builtin: every process hashes a shared system prompt
  identically) of its tokens AND its ancestry, and owns one
  :class:`~distkeras_tpu.serving.paged_cache.BlockAllocator` block holding
  those positions' K/V. A request whose prompt starts with a cached chain
  maps the prefix into its block table for free and prefills only the
  uncached suffix; a request diverging MID-block copies the shared block's
  common slots into a fresh private block (copy-on-write) instead of
  recomputing them. Nodes are refcounted by the requests pinning them;
  eviction takes refcount-0 LEAVES in LRU order, so a shared system
  prompt's root blocks outlive any individual conversation.

- :class:`TenantQueues` — per-tenant FIFO queues bucketed by ``slo_class``
  priority, replacing the global strict-FIFO deque when the engine runs
  ``admission="slo"``. Admission serves the highest-priority class first
  and round-robins across tenants WITHIN a class (one chatty tenant cannot
  starve its class siblings); within one tenant order stays FIFO. The head
  candidate is never skipped — when it cannot fit, admission stops (the
  same no-starvation rule as the strict-FIFO engine) after trying
  preemption-by-recompute against strictly-lower-priority running rows.

Neither class touches the device or takes locks: the engine calls both
under its own scheduler lock, on the scheduler thread, exactly like the
:class:`BlockAllocator` they sit beside.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from distkeras_tpu.sharding.ring import stable_hash

__all__ = ["RadixPrefixCache", "TenantQueues", "PrefixMatch",
           "SLO_PRIORITY", "slo_priority"]

#: slo_class → admission priority (lower number = served first). Classes
#: the map does not name get the "default" priority: an unknown label is
#: ordinary traffic, not an error (submit() already validates shape/knobs;
#: the class is routing metadata).
SLO_PRIORITY = {
    "realtime": 0,
    "interactive": 1,
    "default": 2,
    "batch": 3,
    "best_effort": 4,
}


def slo_priority(slo_class: str) -> int:
    return SLO_PRIORITY.get(str(slo_class), SLO_PRIORITY["default"])


class _Node:
    """One full KV block in the radix tree."""

    __slots__ = ("tokens", "block", "parent", "children", "refs",
                 "last_used", "key")

    def __init__(self, tokens: tuple, block: int, parent, key: int):
        self.tokens = tokens          # the block's token ids (len == bs)
        self.block = int(block)       # the pool block holding their K/V
        self.parent = parent          # _Node or the root sentinel
        self.children: dict[int, _Node] = {}   # chain hash → child
        self.refs = 0                 # active requests pinning this node
        self.last_used = 0            # LRU clock tick of the last pin
        self.key = key                # this node's own chain hash


class PrefixMatch:
    """Result of :meth:`RadixPrefixCache.match`: the matched full-block
    chain (PINNED — the caller owns one reference on each node and must
    :meth:`~RadixPrefixCache.release` them at retire) plus an optional
    copy-on-write candidate ``(cow_node, cow_len)``: a sibling block
    sharing the first ``cow_len`` tokens of the DIVERGENT block, whose
    slots the engine device-copies into a fresh private block instead of
    recomputing. ``tokens`` counts everything served from cache
    (``len(nodes) · block_size + cow_len``)."""

    __slots__ = ("nodes", "cow_node", "cow_len")

    def __init__(self, nodes, cow_node=None, cow_len: int = 0):
        self.nodes = list(nodes)
        self.cow_node = cow_node
        self.cow_len = int(cow_len)

    def tokens(self, block_size: int) -> int:
        return len(self.nodes) * int(block_size) + self.cow_len

    @property
    def blocks(self) -> list[int]:
        return [n.block for n in self.nodes]


class RadixPrefixCache:
    """Content-hash radix tree mapping token-id block chains to pool blocks.

    The tree does not allocate: block ownership is TRANSFERRED in by
    :meth:`insert` (a request donates the prompt blocks it just prefilled)
    and transferred back out by :meth:`evict`/:meth:`flush` (blocks return
    to the caller, who frees them into the allocator). Between those two
    moments the tree's accounting invariant is::

        allocator.used_blocks == Σ slots' private blocks + cache.total_blocks

    which the churn property tests pin (zero leaks under admit / preempt /
    cancel / eos storms).
    """

    def __init__(self, block_size: int):
        if int(block_size) < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.block_size = int(block_size)
        self._root = _Node((), -1, None, stable_hash("radix:root"))
        self._nodes: list[_Node] = []   # every live node (eviction scan)
        self._clock = 0                 # LRU tick
        self.hits = 0                   # match() calls that found ≥1 token
        self.misses = 0
        self.evictions = 0
        self.inserted = 0

    # -- hashing ---------------------------------------------------------

    def _chain_key(self, parent: _Node, tokens: tuple) -> int:
        ids = ",".join(str(int(t)) for t in tokens)
        return stable_hash(f"radix:{parent.key}:{ids}")

    # -- introspection ----------------------------------------------------

    @property
    def total_blocks(self) -> int:
        return len(self._nodes)

    def __len__(self) -> int:
        return len(self._nodes)

    # -- lookup ------------------------------------------------------------

    def match(self, tokens, max_tokens: int) -> PrefixMatch:
        """Longest cached prefix of ``tokens``, capped at ``max_tokens``
        served positions (the engine caps at ``len(prompt) - 1``: it must
        feed at least the last prompt token to get logits to sample from).
        Matched full-block nodes come back PINNED (refs incremented); the
        COW candidate, if any, is NOT pinned — the engine copies its slots
        synchronously under the scheduler lock, before anything could
        evict it."""
        toks = [int(t) for t in np.asarray(tokens).reshape(-1)]
        bs = self.block_size
        self._clock += 1
        node = self._root
        nodes: list[_Node] = []
        i = 0
        while i + bs <= len(toks) and (len(nodes) + 1) * bs <= max_tokens:
            blk = tuple(toks[i: i + bs])
            child = node.children.get(self._chain_key(node, blk))
            if child is None or child.tokens != blk:
                break        # hash miss (or collision: token check failed)
            child.refs += 1
            child.last_used = self._clock
            nodes.append(child)
            node = child
            i += bs
        # partial-block divergence: a sibling sharing m > 0 leading tokens
        # of the next (divergent) block is a copy-on-write candidate —
        # its first m slots are this request's positions i .. i+m-1
        cow_node, cow_len = None, 0
        rest = toks[i:]
        if rest:
            budget = max_tokens - len(nodes) * bs
            for child in node.children.values():
                m = 0
                for a, b in zip(child.tokens, rest):
                    if a != b:
                        break
                    m += 1
                m = min(m, budget)
                if m > cow_len or (m == cow_len and m > 0
                                   and child.block <
                                   (cow_node.block if cow_node else 1 << 62)):
                    cow_node, cow_len = child, m
            if cow_len <= 0:
                cow_node, cow_len = None, 0
            else:
                cow_node.last_used = self._clock
        if nodes or cow_len:
            self.hits += 1
        else:
            self.misses += 1
        return PrefixMatch(nodes, cow_node, cow_len)

    def release(self, nodes) -> None:
        """Drop one reference per node (a retired request unpinning its
        matched chain). Blocks stay cached until eviction needs them."""
        for n in nodes:
            if n.refs <= 0:
                raise ValueError(
                    f"release of unpinned radix node (block {n.block})"
                )
            n.refs -= 1

    # -- growth ------------------------------------------------------------

    def insert(self, tokens, blocks) -> tuple[list, list[int]]:
        """Register a prefilled prompt's full blocks. ``tokens`` is the
        full prompt; ``blocks[k]`` is the pool block holding positions
        ``k·bs .. (k+1)·bs - 1`` and ``len(blocks)`` full blocks are
        offered (``len(blocks)·bs <= len(tokens)``).

        Walks the chain: where a node already exists (this request's own
        pinned prefix, or a twin another request inserted first), the
        offered block is NOT adopted — the request keeps it private.
        Where the chain ends, a new node adopts the offered block
        (ownership transfers to the tree) and comes back pinned for the
        inserting request. Returns ``(new_nodes, adopted_blocks)`` — the
        engine appends the nodes to the slot's pin list and removes the
        adopted blocks from the slot's private list."""
        toks = [int(t) for t in np.asarray(tokens).reshape(-1)]
        bs = self.block_size
        if len(blocks) * bs > len(toks):
            raise ValueError(
                f"{len(blocks)} blocks cover {len(blocks) * bs} tokens but "
                f"the prompt has only {len(toks)}"
            )
        self._clock += 1
        node = self._root
        new_nodes: list[_Node] = []
        adopted: list[int] = []
        for k, block in enumerate(blocks):
            blk = tuple(toks[k * bs: (k + 1) * bs])
            key = self._chain_key(node, blk)
            child = node.children.get(key)
            if child is not None and child.tokens == blk:
                child.last_used = self._clock
                node = child
                continue
            if child is not None:
                # chain-hash collision with different tokens: leave the
                # incumbent alone and stop growing this path
                break
            child = _Node(blk, block, node, key)
            child.refs = 1
            child.last_used = self._clock
            node.children[key] = child
            self._nodes.append(child)
            new_nodes.append(child)
            adopted.append(int(block))
            self.inserted += 1
            node = child
        return new_nodes, adopted

    # -- eviction ------------------------------------------------------------

    def _evictable(self):
        return [n for n in self._nodes if n.refs == 0 and not n.children]

    def evict(self, n_blocks: int) -> list[int]:
        """Free up to ``n_blocks`` cached blocks, LRU refcount-0 leaves
        first (freeing a leaf can expose its parent as the next leaf).
        Returns the freed block ids — the CALLER returns them to the
        allocator; the tree never touches it."""
        freed: list[int] = []
        while len(freed) < int(n_blocks):
            cands = self._evictable()
            if not cands:
                break
            victim = min(cands, key=lambda n: (n.last_used, n.block))
            self._drop(victim)
            freed.append(victim.block)
            self.evictions += 1
        return freed

    def flush(self) -> list[int]:
        """Evict everything evictable (refcount-0 subtrees, leaves-first).
        Returns the freed block ids. Pinned chains survive — a flush
        under live traffic only drops the idle tail."""
        freed: list[int] = []
        while True:
            batch = self.evict(len(self._nodes) or 1)
            if not batch:
                return freed
            freed.extend(batch)

    def _drop(self, node: _Node) -> None:
        node.parent.children.pop(node.key, None)
        self._nodes.remove(node)


class TenantQueues:
    """Per-tenant FIFO queues bucketed by SLO-class priority.

    ``push`` appends to the request's ``(priority, tenant)`` queue;
    ``candidate`` returns (without popping) the request admission should
    try next: the highest-priority non-empty class, round-robin across
    its tenants (each ``pop`` advances that class's rotation), FIFO within
    one tenant. ``push_front`` re-queues a preempted/refilled request at
    its tenant's head so recompute happens in original admission order."""

    def __init__(self):
        # priority → tenant → deque[Request]
        self._q: dict[int, dict[str, deque]] = {}
        # priority → rotation list of tenant names (round-robin order)
        self._rr: dict[int, deque] = {}
        self._n = 0

    def __len__(self) -> int:
        return self._n

    def _bucket(self, req) -> tuple[int, str]:
        return slo_priority(req.slo_class), str(
            getattr(req, "tenant", "default"))

    def push(self, req) -> None:
        prio, tenant = self._bucket(req)
        by_tenant = self._q.setdefault(prio, {})
        if tenant not in by_tenant:
            by_tenant[tenant] = deque()
            self._rr.setdefault(prio, deque()).append(tenant)
        by_tenant[tenant].append(req)
        self._n += 1

    def push_front(self, req) -> None:
        prio, tenant = self._bucket(req)
        by_tenant = self._q.setdefault(prio, {})
        if tenant not in by_tenant:
            by_tenant[tenant] = deque()
            # a re-queued request's tenant goes to the FRONT of the
            # rotation: recompute before fresh same-class admissions
            self._rr.setdefault(prio, deque()).appendleft(tenant)
        by_tenant[tenant].appendleft(req)
        self._n += 1

    def candidate(self):
        """The next request admission should try, or None. Does not pop."""
        for prio in sorted(self._q):
            rr = self._rr.get(prio)
            if not rr:
                continue
            for _ in range(len(rr)):
                tenant = rr[0]
                q = self._q[prio].get(tenant)
                if q:
                    return q[0]
                rr.rotate(-1)   # empty tenant: look at the next one
        return None

    def pop(self, req) -> None:
        """Pop ``req`` — it must be its tenant queue's head. Advances the
        class rotation so the NEXT candidate is the next tenant."""
        prio, tenant = self._bucket(req)
        q = self._q.get(prio, {}).get(tenant)
        if not q or q[0] is not req:
            raise ValueError(f"pop of non-head request {req.id}")
        q.popleft()
        self._n -= 1
        rr = self._rr.get(prio)
        if rr and rr[0] == tenant:
            rr.rotate(-1)

    def remove(self, req) -> bool:
        """Remove a request from anywhere in its queue (cancel sweep)."""
        prio, tenant = self._bucket(req)
        q = self._q.get(prio, {}).get(tenant)
        if q is None:
            return False
        try:
            q.remove(req)
        except ValueError:
            return False
        self._n -= 1
        return True

    def drain(self) -> list:
        """Pop everything, priority-then-rotation order (engine teardown)."""
        out = []
        while self._n:
            req = self.candidate()
            if req is None:   # pragma: no cover — _n and queues disagree
                break
            self.pop(req)
            out.append(req)
        return out

    def __iter__(self):
        for prio in sorted(self._q):
            for q in self._q[prio].values():
                yield from q
