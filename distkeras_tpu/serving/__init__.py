"""Serving tier: continuous-batching generation over a block-paged KV cache.

The decode stack (KV cache, GQA/MQA, sliding-window, beam, speculative,
int8 — SCALING.md) served one request at a time through
``GeneratorPredictor``; this package is the millions-of-users front end on
top of it:

- :mod:`~distkeras_tpu.serving.paged_cache` — the block pool
  (:class:`BlockAllocator`, :class:`PagedKVCache`): sequences of different
  lengths share ONE preallocated static-shape cache through per-sequence
  block tables (PagedAttention, Kwon et al. SOSP '23); the table-indexed
  addressing lives in ``models/lm.py :: DecoderBlock.paged_extend`` and is
  bit-identical to dense-cache decode.
- :mod:`~distkeras_tpu.serving.scheduler` — :class:`GenerationEngine`,
  iteration-level continuous batching (Orca, Yu et al. OSDI '22): FIFO
  admission into free slots/blocks, mixed prefill+decode across in-flight
  requests, per-row sampling params, per-step retirement, optional greedy
  speculative decoding with per-row advancement.
- :mod:`~distkeras_tpu.serving.frontdoor` — the admission/reuse layer
  (ISSUE 17): :class:`RadixPrefixCache`, a content-hash radix tree over
  full KV blocks (vLLM-lineage automatic prefix caching with
  copy-on-write), and :class:`TenantQueues`, per-tenant SLO-class
  priority queues with preemption-by-recompute — switched on per engine
  via ``prefix_cache=`` / ``prefill_chunk=`` / ``admission="slo"``.
- :mod:`~distkeras_tpu.serving.server` — :class:`GenerationServer` /
  :class:`GenerationClient` / :class:`ResilientGenerationClient` on the
  hardened ``networking.py`` framing, with bounded-queue backpressure
  (``ServerBusyError``), mid-stream death detection that frees the dead
  client's blocks, and graceful drain.

Benchmark: ``bench.py --serve`` (Poisson open-loop load, throughput vs
p50/p99, vs the sequential ``GeneratorPredictor`` baseline).
"""

from distkeras_tpu.serving.frontdoor import (  # noqa: F401
    SLO_PRIORITY,
    PrefixMatch,
    RadixPrefixCache,
    TenantQueues,
    slo_priority,
)
from distkeras_tpu.serving.paged_cache import (  # noqa: F401
    BlockAllocator,
    BlockPoolExhausted,
    PagedKVCache,
    slot_map,
)
from distkeras_tpu.serving.scheduler import (  # noqa: F401
    GenerationEngine,
    Request,
    per_row_new_token_counts,
)
from distkeras_tpu.serving.server import (  # noqa: F401
    GenerationClient,
    GenerationServer,
    ResilientGenerationClient,
)

__all__ = [
    "SLO_PRIORITY",
    "PrefixMatch",
    "RadixPrefixCache",
    "TenantQueues",
    "slo_priority",
    "BlockAllocator",
    "BlockPoolExhausted",
    "PagedKVCache",
    "slot_map",
    "GenerationEngine",
    "Request",
    "per_row_new_token_counts",
    "GenerationClient",
    "GenerationServer",
    "ResilientGenerationClient",
]
