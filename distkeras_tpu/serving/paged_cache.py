"""Block-paged KV cache: one preallocated static-shape pool, many sequences.

The decode stack's dense cache is ``[B, maxlen, Hkv, Dh]`` per layer — every
sequence pays ``maxlen`` slots no matter its length, and a batch of
concurrent requests of different lengths cannot share one compiled program
without all paying the longest row's memory. PagedAttention's answer (Kwon
et al., SOSP '23) is virtual memory for the KV cache: carve the pool into
fixed-size **blocks** ``[num_blocks, block_size, Hkv, Dh]``, give every
sequence a **block table** mapping its logical positions to pool blocks, and
let the attention step gather through the table. Sequences then consume
``ceil(len/block_size)`` blocks instead of ``maxlen`` slots, concurrent
requests of any length mix share ONE compiled step, and admission becomes a
host-side allocator decision rather than a recompile.

This module is the host side: :class:`BlockAllocator` (free-list with leak
accounting — the scheduler property tests pin "no block survives its
request") and :class:`PagedKVCache` (the per-layer device pools, stored
FLAT as ``[num_blocks·block_size, Hkv, Dh]`` so the model-side gather in
``models/lm.py :: DecoderBlock.paged_extend`` is one ``pool[slots]``
index). Block 0 is reserved as the scratch block: free batch rows and
unallocated table entries point at it, so inactive rows write garbage
nobody reads instead of needing a masked scatter.

The device side — table-indexed addressing generalizing the ring cache's
``slot = pos % cache_len`` to ``slot = table[pos // bs] · bs + pos % bs``
— lives with the model (``paged_extend`` / ``paged_decode_step`` /
``prefill_raw``), sharing the attention body with dense decode so paged
serving is bit-identical to :func:`~distkeras_tpu.models.lm.generate`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


class BlockPoolExhausted(RuntimeError):
    """The allocator has fewer free blocks than the request needs. Internal
    to the scheduler: admission simply waits (requests queue) until
    retirements free blocks — it is never a client-visible failure."""


class BlockAllocator:
    """Host-side free-list over the block pool. Block 0 is the reserved
    scratch block (never handed out); capacity is ``num_blocks - 1``.

    Deterministic: blocks are handed out lowest-id-first and returned to
    the free list in sorted order, so a seeded request mix allocates
    identically run-to-run (the scheduler property tests rely on it)."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is scratch), "
                f"got {num_blocks}"
            )
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._free = list(range(num_blocks - 1, 0, -1))  # pop() → block 1
        self._allocated: set[int] = set()
        self.high_water = 0

    @property
    def capacity(self) -> int:
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return len(self._allocated)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> list[int]:
        if n > len(self._free):
            raise BlockPoolExhausted(
                f"need {n} blocks, {len(self._free)} free "
                f"(capacity {self.capacity})"
            )
        blocks = [self._free.pop() for _ in range(n)]
        self._allocated.update(blocks)
        self.high_water = max(self.high_water, len(self._allocated))
        return blocks

    def free(self, blocks) -> None:
        for b in blocks:
            if b not in self._allocated:
                raise ValueError(
                    f"double-free or foreign block {b} (allocated: "
                    f"{len(self._allocated)} blocks)"
                )
            self._allocated.discard(b)
            self._free.append(b)
        self._free.sort(reverse=True)  # keep pop() order deterministic


class PagedKVCache:
    """Per-layer flat slot pools for one :class:`TransformerLM`.

    ``k_pools``/``v_pools`` are tuples (one per layer) of
    ``[num_blocks · block_size, Hkv, Dh]`` arrays in the model dtype —
    plain pytrees handed in and out of the jitted step with buffer
    donation, so steady-state decode updates them in place."""

    def __init__(self, module, num_blocks: int, block_size: int):
        hkv = module.kv_heads if module.kv_heads is not None \
            else module.heads
        dh = module.dim // module.heads
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.num_slots = self.num_blocks * self.block_size
        shape = (self.num_slots, hkv, dh)
        self.k_pools = tuple(
            jnp.zeros(shape, module.dtype) for _ in range(module.depth)
        )
        self.v_pools = tuple(
            jnp.zeros(shape, module.dtype) for _ in range(module.depth)
        )

    @property
    def nbytes(self) -> int:
        per = self.k_pools[0].dtype.itemsize
        return 2 * len(self.k_pools) * int(np.prod(self.k_pools[0].shape)) \
            * per

    def copy_slots(self, src_slots, dst_slots) -> None:
        """Device-copy K/V from flat pool slots ``src_slots`` to
        ``dst_slots`` in every layer — the prefix cache's copy-on-write
        primitive: a request diverging mid-block duplicates the shared
        block's common positions into its own fresh block instead of
        recomputing them. One jitted gather-scatter per call (all layers),
        donated so steady-state COW never copies a whole pool."""
        src = jnp.asarray(np.asarray(src_slots, np.int32))
        dst = jnp.asarray(np.asarray(dst_slots, np.int32))
        self.k_pools = _copy_pool_slots(self.k_pools, src, dst)
        self.v_pools = _copy_pool_slots(self.v_pools, src, dst)


@functools.partial(jax.jit, donate_argnums=(0,))
def _copy_pool_slots(pools, src, dst):
    return tuple(p.at[dst].set(p[src]) for p in pools)


def slot_map(tables: np.ndarray, block_size: int) -> np.ndarray:
    """Flatten block tables ``[B, nb]`` into per-position pool slots
    ``[B, nb·bs]``: ``slots[b, t] = tables[b, t // bs] · bs + t % bs`` —
    the table-indexed generalization of the ring cache's ``pos % window``
    addressing, precomputed host-side once per step and shared by every
    layer."""
    bs = int(block_size)
    nb = tables.shape[1]
    return (np.repeat(tables, bs, axis=1) * bs
            + np.tile(np.arange(bs, dtype=tables.dtype), nb))


def sample_rows(logits, keys, temperature, top_k, top_p, greedy):
    """Per-ROW sampling inside one batched step: every row carries its own
    temperature / top-k / top-p / PRNG key, because a continuous batch
    mixes requests with different sampling params. Same filter semantics
    as the batch-static :func:`models.lm._warp_fn` (temperature scale →
    top-k → minimal nucleus, ties at the boundary survive), encoded
    per-row: a row with ``top_k = vocab`` / ``top_p = 1.0`` is unfiltered.
    ``greedy`` rows take ``argmax`` of the RAW logits — bit-identical to
    greedy :func:`generate`, independent of the warp path entirely."""
    V = logits.shape[-1]
    logits = logits.astype(jnp.float32)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # greedy rows run the warp with temp 1 so their (discarded) sampled
    # lane never sees inf/nan from a zero temperature
    temp = jnp.where(greedy, 1.0, jnp.maximum(temperature, 1e-6))
    scaled = logits / temp[:, None]
    desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(
        desc, jnp.clip(top_k - 1, 0, V - 1)[:, None], axis=-1
    )
    scaled = jnp.where(scaled < kth, -1e30, scaled)
    desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(desc, axis=-1)
    keep = jnp.cumsum(probs, axis=-1) - probs < top_p[:, None]
    cutoff = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1,
                     keepdims=True)
    scaled = jnp.where(scaled < cutoff, -1e30, scaled)
    sampled = jax.vmap(jax.random.categorical)(keys, scaled) \
        .astype(jnp.int32)
    return jnp.where(greedy, greedy_tok, sampled)
