"""Iteration-level continuous batching over the block-paged KV cache.

Orca's scheduling insight (Yu et al., OSDI '22): batch at the granularity
of one decode ITERATION, not one request. A static batch drains before
admitting anyone new, so a 512-token generation holds 31 finished slots
hostage; iteration-level scheduling retires a row the step its request
finishes and admits a queued request into the freed slot at the very next
step — the batch composition changes every iteration, the compiled step
never does (fixed ``max_batch`` rows; free rows write the scratch block
and are ignored).

:class:`GenerationEngine` is that scheduler plus the device programs:

- **admit** — strictly FIFO (the head of the queue is never skipped, so
  long prompts cannot starve behind a stream of short ones) whenever a
  batch slot AND enough pool blocks for the request's full budget
  (``ceil((Lp + max_new [+ spec])/block_size)``) are free. Reserving the
  whole budget up front keeps the pool overcommit-free: an admitted
  request can never die of block exhaustion mid-flight, so there is no
  preemption machinery to get wrong.
- **prefill** — one BATCHED forward per admission burst and padded-length
  group (prompts padded to a block multiple, group row count bucketed to
  powers of two: compile count is ``O(maxlen/block_size · log
  max_batch)``), scattered into the rows' allocated blocks through
  ``TransformerLM.prefill_raw``. Pad K/V beyond a real prompt is masked
  until decode overwrites it; dummy bucket rows write the scratch block.
- **decode** — ONE jitted fixed-shape step for all in-flight rows, each at
  its own position with its own sampling params
  (:func:`~distkeras_tpu.serving.paged_cache.sample_rows`), pools updated
  in place via buffer donation.
- **retire** — host-side per step: EOS, budget exhaustion, or client
  cancellation frees the row's blocks immediately (a dead connection
  releases its memory before its request would have finished).

With a ``draft`` model the engine runs greedy speculative decoding INSIDE
the continuous batch: each iteration the draft proposes ``spec_tokens``
greedily through its own paged pools (same block tables — the allocator is
shared), the target verifies all rows in one ``paged_extend_rows`` pass,
and each row advances by its OWN accepted length — no batch-minimum
lockstep, because per-row positions are native here (the dense
``speculative_generate`` must advance uniformly; the paged batch never
had that constraint).
"""

from __future__ import annotations

import itertools
import math
import threading
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.model import ModelSpec
from distkeras_tpu.networking import ServerBusyError
from distkeras_tpu.observability import trace as _trace
from distkeras_tpu.serving.frontdoor import (
    RadixPrefixCache,
    TenantQueues,
    slo_priority,
)
from distkeras_tpu.serving.paged_cache import (
    BlockAllocator,
    PagedKVCache,
    sample_rows,
    slot_map,
)

_req_ids = itertools.count()


def per_row_new_token_counts(new_tokens, eos_id: int | None):
    """Real tokens per row of a ``[B, T]`` generated block: everything up to
    and INCLUDING the first ``eos_id`` (or all ``T`` when none appears /
    ``eos_id`` is None). This is the batch form of the serving tier's
    per-step retire rule — ``GeneratorPredictor(per_row_new_tokens=True)``
    and the tests share it instead of re-deriving eos semantics."""
    new_tokens = np.asarray(new_tokens)
    B, T = new_tokens.shape
    if eos_id is None:
        return np.full((B,), T, np.int32)
    hit = new_tokens == int(eos_id)
    first = np.argmax(hit, axis=1)
    return np.where(hit.any(axis=1), first + 1, T).astype(np.int32)


def summarize_latencies(records, window_s: float | None = None,
                        now: float | None = None) -> dict:
    """Per-SLO-class latency summary over retired-request records (the
    engine's ``_retired`` ring — or any iterable of dicts with ``t``,
    ``slo_class``, ``state``, ``total_s``, ``queue_s``, ``prefill_s``,
    ``decode_s``): p50/p99 end-to-end plus mean queue/prefill/decode
    breakdown, in ms, over COMPLETED requests only — a cancelled
    request's lifetime is how long its client waited before giving up,
    not a served latency, and pooling it in would let a storm of fast
    cancels mask a real SLO breach of the requests that finished.
    ``window_s`` restricts to records retired within the trailing
    window (None = everything in the ring). Pure function so the
    watchdog tests feed it synthetic records."""
    recs = [r for r in records if r.get("state", "done") == "done"]
    if window_s is not None:
        t_end = now if now is not None else (
            max(r["t"] for r in recs) if recs else 0.0)
        recs = [r for r in recs if r["t"] >= t_end - window_s]
    out: dict[str, dict] = {}
    by_cls: dict[str, list] = {}
    for r in recs:
        by_cls.setdefault(r.get("slo_class", "default"), []).append(r)
    for cls, rs in sorted(by_cls.items()):
        total = np.asarray([r["total_s"] for r in rs], np.float64) * 1e3
        rec = {
            "count": len(rs),
            "p50_ms": float(np.percentile(total, 50)),
            "p99_ms": float(np.percentile(total, 99)),
        }
        for key, out_key in (("queue_s", "queue_ms"),
                             ("prefill_s", "prefill_ms"),
                             ("decode_s", "decode_ms")):
            vals = [r[key] for r in rs if r.get(key) is not None]
            if vals:
                rec[out_key] = float(np.mean(vals)) * 1e3
        out[cls] = rec
    return out


class Request:
    """One generation request moving through the engine.

    States: ``queued`` → ``running`` → ``done`` | ``cancelled`` |
    ``failed``; ``rejected`` never enters the queue. ``result()`` blocks
    on completion and returns the NEW tokens (prompt excluded) as int32."""

    def __init__(self, prompt: np.ndarray, *, max_new_tokens: int,
                 temperature: float, top_k: int | None,
                 top_p: float | None, seed: int, eos_id: int | None,
                 request_id: str | None = None,
                 slo_class: str = "default", tenant: str = "default"):
        self.id = request_id if request_id is not None \
            else f"req-{next(_req_ids)}"
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        self.seed = int(seed)
        self.eos_id = eos_id
        # SLO class (ISSUE 13): a latency-telemetry label always; under
        # admission="slo" (ISSUE 17) ALSO the admission priority — see
        # frontdoor.SLO_PRIORITY
        self.slo_class = str(slo_class)
        # multi-tenant admission (ISSUE 17): the fairness bucket — one
        # tenant's backlog round-robins against its class siblings'
        # instead of occupying the whole queue. Scheduling metadata only
        # under admission="fifo".
        self.tenant = str(tenant)
        # the engine's model version this request was ADMITTED under
        # (stamped at admission; re-stamped when a hot swap re-prefills
        # it) — the version its served stream is bit-identical to
        self.model_version: int | None = None
        self.new_tokens: list[int] = []
        self.state = "queued"
        self.error: str | None = None
        self.t_submit = time.monotonic()
        self.t_admit: float | None = None
        self.t_done: float | None = None
        self.prefill_s: float | None = None
        self._cancelled = False
        self._event = threading.Event()

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0

    def wait(self, timeout: float | None = None) -> bool:
        return self._event.wait(timeout)

    def result(self, timeout: float | None = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError(f"request {self.id} still {self.state}")
        if self.state != "done":
            raise RuntimeError(
                f"request {self.id} {self.state}"
                + (f": {self.error}" if self.error else "")
            )
        return np.asarray(self.new_tokens, np.int32)

    @property
    def latency_s(self) -> float | None:
        if self.t_done is None:
            return None
        return self.t_done - self.t_submit


class _Slot:
    """Host bookkeeping for one occupied batch row.

    ``blocks`` are the row's PRIVATE pool blocks (freed at retire);
    under a prefix cache the row may additionally reference shared
    tree blocks through ``pinned`` (released, never freed, at retire).
    ``phase`` is ``"decode"`` for legacy rows; front-door rows start in
    ``"prefill"`` and feed ``feed[next_pos:feed_len]`` in chunks before
    flipping to decode."""

    __slots__ = ("request", "blocks", "next_pos", "last_tok",
                 "phase", "feed", "feed_len", "pinned", "cow",
                 "sample_first", "resume_tok")

    def __init__(self, request: Request, blocks: list[int]):
        self.request = request
        self.blocks = blocks
        self.next_pos = 0   # absolute position of the token being FED
        self.last_tok = 0
        self.phase = "decode"
        self.feed: np.ndarray | None = None   # tokens still to prefill
        self.feed_len = 0
        self.pinned: list = []                # pinned radix-tree nodes
        self.cow: tuple | None = None         # (node, m, dst_block)
        self.sample_first = True   # sample at prefill end (fresh request)
        self.resume_tok = 0        # pending token of a preempted request


class GenerationEngine:
    """Continuous-batching generation over a block-paged KV cache.

    ``model``/``params`` as accepted by :func:`models.lm.generate`
    (``ModelSpec`` or bare ``TransformerLM`` — int8 specs from
    ``quantize_lm`` drop in unchanged). ``draft``/``draft_params`` switch
    on greedy speculative serving with ``spec_tokens`` proposals per
    iteration. ``num_blocks`` defaults to enough for ``max_batch`` rows of
    ``maxlen`` each (+ the scratch block) — shrink it to oversubscribe and
    let admission apply backpressure through the bounded queue instead.
    """

    def __init__(self, model, params, *, max_batch: int = 8,
                 block_size: int = 16, num_blocks: int | None = None,
                 max_queue: int = 64, draft=None, draft_params=None,
                 spec_tokens: int = 4, model_version: int = 0,
                 prefix_cache: bool = False,
                 prefill_chunk: int | None = None,
                 admission: str = "fifo"):
        from distkeras_tpu.models.lm import TransformerLM

        module = model.module if isinstance(model, ModelSpec) else model
        if not isinstance(module, TransformerLM):
            raise TypeError(
                f"GenerationEngine needs a TransformerLM (or its "
                f"ModelSpec), got {type(module)}"
            )
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if block_size < 1 or block_size > module.maxlen:
            raise ValueError(
                f"block_size must be in [1, maxlen={module.maxlen}], "
                f"got {block_size}"
            )
        self._module = module
        self._params = params
        # live-deployment version gate (distkeras_tpu/deploy): _params is
        # ONLY ever replaced at the top of step(), on the scheduler
        # thread, under the lock — swap_params from any other thread just
        # STAGES (params, version, policy) here. One decode_step can
        # therefore never see two weight sets: the atomic-swap invariant.
        self.model_version = int(model_version)
        self._staged_swap: tuple | None = None
        self.max_batch = int(max_batch)
        self.block_size = int(block_size)
        self.max_queue = int(max_queue)
        self._nb_per_seq = math.ceil(module.maxlen / self.block_size)
        self._L = self._nb_per_seq * self.block_size
        if num_blocks is None:
            num_blocks = self.max_batch * self._nb_per_seq + 1
        self.allocator = BlockAllocator(num_blocks, self.block_size)
        self.cache = PagedKVCache(module, num_blocks, self.block_size)

        # -- the serving front door (ISSUE 17) ---------------------------
        if admission not in ("fifo", "slo"):
            raise ValueError(
                f"admission must be 'fifo' or 'slo', got {admission!r}"
            )
        if prefill_chunk is not None and int(prefill_chunk) < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}"
            )
        self.prefix_cache = bool(prefix_cache)
        self.prefill_chunk = (None if prefill_chunk is None
                              else int(prefill_chunk))
        self.admission = str(admission)
        # any front-door feature routes ALL prefill through the chunked
        # paged program (suffix prefill past a cached prefix and
        # preemption's prompt+generated recompute are the same mechanism)
        self._frontdoor = (self.prefix_cache
                           or self.prefill_chunk is not None
                           or self.admission == "slo")
        if self._frontdoor and draft is not None:
            raise ValueError(
                "prefix_cache/prefill_chunk/admission='slo' cannot be "
                "combined with a draft model: the draft's pools never "
                "hold a cached prefix's K/V, so speculative verify "
                "would read garbage"
            )
        self._prefix = (RadixPrefixCache(self.block_size)
                        if self.prefix_cache else None)
        self._tq = TenantQueues() if self.admission == "slo" else None
        self._chunk_fns: dict[tuple, object] = {}

        self._draft_module = None
        self._draft_params = draft_params
        self.spec_tokens = 0
        if draft is not None:
            dm = draft.module if isinstance(draft, ModelSpec) else draft
            if not isinstance(dm, TransformerLM):
                raise TypeError(
                    f"draft must be a TransformerLM (or its ModelSpec), "
                    f"got {type(dm)}"
                )
            if dm.vocab != module.vocab:
                raise ValueError(
                    f"draft vocab {dm.vocab} != target vocab {module.vocab}"
                )
            if int(spec_tokens) < 1:
                raise ValueError(
                    f"spec_tokens must be >= 1, got {spec_tokens}"
                )
            if module.attn_window is not None or dm.attn_window is not None:
                raise ValueError(
                    "speculative serving does not support sliding-window "
                    "models (the verify span crosses the window band)"
                )
            self._draft_module = dm
            self.spec_tokens = int(spec_tokens)
            self.draft_cache = PagedKVCache(dm, num_blocks, self.block_size)

        self._tables = np.zeros((self.max_batch, self._nb_per_seq),
                                np.int32)
        self._slots: list[_Slot | None] = [None] * self.max_batch
        # per-step hot-loop caches, refreshed only when the batch
        # composition changes (admission/retire), not every token: the
        # flattened slot map and the per-row sampling-param arrays
        self._batch_dirty = True
        self._np_slots: np.ndarray | None = None
        self._dev_tables_by_width: dict[int, object] = {}
        self._dev_sampling = None
        self._all_greedy = True
        self._queue: deque[Request] = deque()
        self._lock = threading.RLock()
        self._wake = threading.Condition(self._lock)
        self._closed = False
        self._stop = False
        self._thread: threading.Thread | None = None
        self.stats_ = {
            "submitted": 0, "admitted": 0, "completed": 0,
            "cancelled": 0, "rejected": 0, "failed": 0,
            "steps": 0, "prefills": 0, "tokens_generated": 0,
            "occupancy_sum": 0,
            "spec_rounds": 0, "spec_proposed": 0, "spec_accepted": 0,
            "swaps": 0, "refilled": 0,
        }
        if self.admission == "slo":
            self.stats_["preemptions"] = 0
        if self.prefix_cache:
            self.stats_.update(prefix_hit_tokens=0,
                               prefix_prompt_tokens=0, cow_copies=0)
        # retired-request latency ring (ISSUE 13): one bounded record
        # per finalized request — the per-SLO-class p50/p99 +
        # queue/prefill/decode breakdown the watchtower samples and the
        # serving SLO rule judges. Appended under the engine lock.
        self._retired: deque = deque(maxlen=2048)

        self._decode_fn, self._decode_fn_greedy = self._make_decode()
        self._prefill_fns: dict[int, object] = {}
        self._spec_fn = self._make_spec() if self._draft_module else None

    # -- device programs -----------------------------------------------------

    def _make_decode(self):
        from distkeras_tpu.models.lm import TransformerLM

        module, bs = self._module, self.block_size

        def fn(params, k_pools, v_pools, tok, tables, write_slot, positions,
               temp, top_k, top_p, greedy, seeds):
            logits, k_pools, v_pools = module.apply(
                {"params": params}, tok, k_pools, v_pools, tables,
                write_slot, positions, bs,
                method=TransformerLM.paged_decode_step,
            )
            # deterministic per (request seed, absolute position): a
            # resubmitted request replays the same stream
            keys = jax.vmap(
                lambda s, p: jax.random.fold_in(jax.random.PRNGKey(s), p)
            )(seeds, positions + 1)
            nxt = sample_rows(logits, keys, temp, top_k, top_p, greedy)
            return nxt, k_pools, v_pools

        # all-greedy fast path: serving batches are frequently pure-greedy
        # and the per-row warp costs two [B, vocab] sorts per token
        def fn_greedy(params, k_pools, v_pools, tok, tables, write_slot,
                      positions):
            logits, k_pools, v_pools = module.apply(
                {"params": params}, tok, k_pools, v_pools, tables,
                write_slot, positions, bs,
                method=TransformerLM.paged_decode_step,
            )
            nxt = jnp.argmax(logits.astype(jnp.float32),
                             axis=-1).astype(jnp.int32)
            return nxt, k_pools, v_pools

        return (jax.jit(fn, donate_argnums=(1, 2)),
                jax.jit(fn_greedy, donate_argnums=(1, 2)))

    def _make_prefill(self):
        from distkeras_tpu.models.lm import TransformerLM

        module, dm = self._module, self._draft_module

        def fn(params, d_params, k_pools, v_pools, dk_pools, dv_pools,
               prompts, row_slots, lp, temp, top_k, top_p, greedy, seeds):
            logits, kvs = module.apply(
                {"params": params}, prompts,
                method=TransformerLM.prefill_raw,
            )
            k_pools = tuple(p.at[row_slots].set(k)
                            for p, (k, _) in zip(k_pools, kvs))
            v_pools = tuple(p.at[row_slots].set(v)
                            for p, (_, v) in zip(v_pools, kvs))
            if dm is not None:
                _, dkvs = dm.apply(
                    {"params": d_params}, prompts,
                    method=TransformerLM.prefill_raw,
                )
                dk_pools = tuple(p.at[row_slots].set(k)
                                 for p, (k, _) in zip(dk_pools, dkvs))
                dv_pools = tuple(p.at[row_slots].set(v)
                                 for p, (_, v) in zip(dv_pools, dkvs))
            last = jnp.take_along_axis(
                logits, (lp - 1)[:, None, None], axis=1
            )[:, 0]                                          # [n, V]
            keys = jax.vmap(
                lambda s, p: jax.random.fold_in(jax.random.PRNGKey(s), p)
            )(seeds, lp)
            tok = sample_rows(last, keys, temp, top_k, top_p, greedy)
            return tok, k_pools, v_pools, dk_pools, dv_pools

        return jax.jit(fn, donate_argnums=(2, 3, 4, 5))

    def _make_chunk(self):
        """The front-door prefill program: one ``paged_extend_rows`` pass
        feeding each row's next chunk of uncached tokens at its own
        position — suffix prefill past a cached prefix, Sarathi-style
        chunked prefill of a long prompt, and preemption's
        prompt+generated recompute are all this one program. The sampled
        token is only meaningful on a row's FINAL chunk (``last_idx``
        points at the last prompt token's logits; ``sample_pos`` is the
        prompt length so the key matches ``_make_prefill`` exactly);
        intermediate chunks discard it."""
        from distkeras_tpu.models.lm import TransformerLM

        module, bs = self._module, self.block_size

        def fn(params, k_pools, v_pools, tokens, tables, write_slots,
               positions, last_idx, temp, top_k, top_p, greedy, seeds,
               sample_pos):
            logits, k_pools, v_pools = module.apply(
                {"params": params}, tokens, k_pools, v_pools, tables,
                write_slots, positions, bs,
                method=TransformerLM.paged_extend_rows,
            )
            last = jnp.take_along_axis(
                logits, last_idx[:, None, None], axis=1
            )[:, 0]                                          # [n, V]
            keys = jax.vmap(
                lambda s, p: jax.random.fold_in(jax.random.PRNGKey(s), p)
            )(seeds, sample_pos)
            tok = sample_rows(last, keys, temp, top_k, top_p, greedy)
            return tok, k_pools, v_pools

        return jax.jit(fn, donate_argnums=(1, 2))

    def _make_spec(self):
        from distkeras_tpu.models.lm import TransformerLM

        module, dm, K = self._module, self._draft_module, self.spec_tokens
        bs = self.block_size

        def fn(params, d_params, k, v, dk, dv, tok, tables, positions,
               write_slots):
            def draft_step(carry, xs):
                t, dkp, dvp = carry
                i, ws = xs
                lg, dkp, dvp = dm.apply(
                    {"params": d_params}, t, dkp, dvp, tables, ws,
                    positions + i, bs,
                    method=TransformerLM.paged_decode_step,
                )
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                return (nxt, dkp, dvp), nxt

            # K+1 draft steps for K proposals: the extra step writes the
            # LAST proposal's K/V (its logits are discarded). Without it a
            # fully-accepted round leaves a permanent hole in the draft
            # cache at position p+K (the target's verify writes p..p+K,
            # the draft scan only p..p+K-1) — a zero K/V that rescales
            # the draft's softmax forever after and quietly erodes
            # acceptance. Exactness never depends on the draft, but
            # acceptance is the throughput, so the hole is worth one
            # draft step per round.
            xs = (jnp.arange(K + 1), jnp.swapaxes(write_slots, 0, 1))
            (_, dk, dv), outs = jax.lax.scan(draft_step, (tok, dk, dv), xs)
            props = outs.T[:, :K]                            # [B, K]
            block = jnp.concatenate([tok[:, None], props], axis=1)
            t_logits, k, v = module.apply(
                {"params": params}, block, k, v, tables, write_slots,
                positions, bs, method=TransformerLM.paged_extend_rows,
            )
            g = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)  # [B, K+1]
            match = (props == g[:, :K]).astype(jnp.int32)
            a_row = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
            return props, g, a_row, k, v, dk, dv

        return jax.jit(fn, donate_argnums=(2, 3, 4, 5))

    # -- client surface ------------------------------------------------------

    def _blocks_needed(self, lp: int, max_new: int) -> int:
        return math.ceil((lp + max_new + self.spec_tokens)
                         / self.block_size)

    def submit(self, prompt, *, max_new_tokens: int = 32,
               temperature: float = 0.0, top_k: int | None = None,
               top_p: float | None = None, seed: int = 0,
               eos_id: int | None = None,
               request_id: str | None = None,
               slo_class: str = "default",
               tenant: str = "default") -> Request:
        """Queue one generation; returns the :class:`Request` handle
        immediately. Raises :class:`ServerBusyError` when the bounded
        admission queue is full (backpressure) and ``ValueError`` on
        malformed requests — both BEFORE the queue, so a rejected request
        costs the engine nothing. ``slo_class`` labels the request's
        latency telemetry (per-class p50/p99 vs SLO in the watchdog);
        under ``admission="slo"`` it is ALSO the admission priority, and
        ``tenant`` buckets the per-tenant fairness rotation."""
        module = self._module
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1:
            raise ValueError(f"prompt must be 1-D [length], got "
                             f"{prompt.shape}")
        lp = prompt.shape[0]
        if lp < 1:
            raise ValueError("prompt must have at least one token")
        if prompt.min() < 0 or prompt.max() >= module.vocab:
            raise ValueError(
                f"prompt tokens outside [0, vocab={module.vocab})"
            )
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if lp + max_new + self.spec_tokens > module.maxlen:
            raise ValueError(
                f"prompt length {lp} + max_new_tokens {max_new}"
                + (f" + spec_tokens {self.spec_tokens}"
                   if self.spec_tokens else "")
                + f" exceeds the model's maxlen {module.maxlen}"
            )
        if self._blocks_needed(lp, max_new) > self.allocator.capacity:
            raise ValueError(
                f"request needs {self._blocks_needed(lp, max_new)} blocks "
                f"but the pool only has {self.allocator.capacity}"
            )
        if temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if top_k is not None and not 1 <= int(top_k) <= module.vocab:
            raise ValueError(
                f"top_k must be in [1, vocab={module.vocab}], got {top_k}"
            )
        if top_p is not None and not 0.0 < float(top_p) <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if eos_id is not None and not 0 <= int(eos_id) < module.vocab:
            raise ValueError(
                f"eos_id {eos_id} outside vocab {module.vocab}"
            )
        if self.spec_tokens and (temperature != 0.0 or top_k is not None
                                 or top_p is not None):
            raise ValueError(
                "speculative serving is greedy-only: temperature/top_k/"
                "top_p cannot be combined with a draft model"
            )
        req = Request(
            prompt, max_new_tokens=max_new, temperature=float(temperature),
            top_k=top_k, top_p=top_p, seed=int(seed),
            eos_id=None if eos_id is None else int(eos_id),
            request_id=request_id, slo_class=slo_class, tenant=tenant,
        )
        with self._wake:
            if self._closed:
                raise ServerBusyError("engine is draining: not accepting "
                                      "new requests")
            if self._queued_count() >= self.max_queue:
                self.stats_["rejected"] += 1
                req.state = "rejected"
                raise ServerBusyError(
                    f"admission queue full ({self.max_queue} waiting)"
                )
            self.stats_["submitted"] += 1
            self._q_push(req)
            self._wake.notify_all()
        # flight recorder: the request id is the serving tier's
        # correlation id (carried in the wire frame), so this enqueue
        # mark, the queued/prefill spans, and the final serve.request
        # span stitch one request across threads
        _trace.instant("serve.enqueue", corr=req.id)
        return req

    def cancel(self, request: Request) -> None:
        """Mark a request for cancellation; the engine frees its slot and
        blocks at the next iteration (queued requests never start)."""
        with self._wake:
            request._cancelled = True
            self._wake.notify_all()

    # -- queue plumbing: one strict-FIFO deque, or the tenant queues ---------

    def _queued_count(self) -> int:
        return len(self._tq) if self._tq is not None else len(self._queue)

    def _q_push(self, req: Request) -> None:
        if self._tq is not None:
            self._tq.push(req)
        else:
            self._queue.append(req)

    def _q_push_front(self, req: Request) -> None:
        if self._tq is not None:
            self._tq.push_front(req)
        else:
            self._queue.appendleft(req)

    def _q_drain(self) -> list[Request]:
        if self._tq is not None:
            return self._tq.drain()
        out = list(self._queue)
        self._queue.clear()
        return out

    # -- the hot-swap version gate (distkeras_tpu/deploy) --------------------

    def swap_params(self, params, version: int, policy: str = "drain",
                    draft_params=None) -> None:
        """Stage a model swap; the scheduler applies it BETWEEN decode
        steps (never inside one — old and new weights in a single
        ``decode_step`` would be a correctness bug, so ``_params`` is
        only replaced at the top of ``step()`` on the scheduler thread).

        ``policy`` decides what happens to in-flight requests:

        - ``"drain"`` — admission pauses, in-flight rows finish on the
          OLD weights, the swap lands once the batch is empty. No work
          is discarded; the swap waits for the longest active request.
        - ``"refill"`` — in-flight rows are watermarked and re-prefilled
          under the NEW weights: their blocks are freed, their emitted
          tokens reset, and they re-enter the queue head in admission
          order. Re-admission stamps the new ``model_version``; sampling
          is deterministic per (seed, position), so the re-served stream
          is bit-identical to an oracle run at the NEW version.

        ``version`` is not required to increase — a rollback re-stages
        the baseline. Staging twice replaces the earlier staged swap.
        """
        if policy not in ("drain", "refill"):
            raise ValueError(
                f"policy must be 'drain' or 'refill', got {policy!r}"
            )
        with self._wake:
            self._staged_swap = (params, int(version), policy, draft_params)
            self._wake.notify_all()

    def _apply_swap_locked(self) -> None:
        """Apply a staged swap if its policy allows (call under the lock,
        from the scheduler thread only)."""
        staged = self._staged_swap
        if staged is None:
            return
        params, version, policy, draft_params = staged
        active = [b for b, s in enumerate(self._slots) if s is not None]
        if policy == "drain" and active:
            return  # admission is paused; the batch drains first
        if policy == "refill" and active:
            # watermark: requeue at the FRONT, preserving admission
            # order, with blocks freed and emitted tokens reset — the
            # re-prefill under the new weights replays the stream
            rows = sorted(active,
                          key=lambda b: self._slots[b].request.t_admit,
                          reverse=True)
            for b in rows:
                self._evacuate_row(b, reset_tokens=True)
                self.stats_["refilled"] += 1
        if self._prefix is not None:
            # version gate for the radix tree: every cached block holds
            # K/V computed under the OLD weights — flush it all (no node
            # is pinned here: drain waited for an empty batch, refill
            # just evacuated every row)
            freed = self._prefix.flush()
            if freed:
                self.allocator.free(freed)
        self._params = params
        if draft_params is not None:
            self._draft_params = draft_params
        self.model_version = version
        self._staged_swap = None
        self.stats_["swaps"] += 1
        _trace.instant("serve.swap", cat="deploy",
                       args={"version": version, "policy": policy})

    # -- the scheduler loop --------------------------------------------------

    def _finalize(self, req: Request, state: str,
                  error: str | None = None) -> None:
        req.state = state
        req.error = error
        req.t_done = time.monotonic()
        key = {"done": "completed", "cancelled": "cancelled",
               "failed": "failed"}[state]
        self.stats_[key] += 1
        if state == "done":
            self.stats_["tokens_generated"] += len(req.new_tokens)
        # latency telemetry (ISSUE 13): queue wait + prefill + decode
        # decompose the end-to-end latency from timestamps the request
        # already carries — no tracing required
        queue_s = (req.t_admit - req.t_submit
                   if req.t_admit is not None else None)
        total_s = req.t_done - req.t_submit
        decode_s = None
        if queue_s is not None:
            decode_s = total_s - queue_s - (req.prefill_s or 0.0)
        self._retired.append({
            "t": req.t_done, "slo_class": req.slo_class, "state": state,
            "total_s": total_s, "queue_s": queue_s,
            "prefill_s": req.prefill_s, "decode_s": decode_s,
            "new_tokens": len(req.new_tokens),
            "model_version": req.model_version,
        })
        if _trace.enabled():
            # whole-lifetime span (submit → retire); time.monotonic and
            # the tracer's perf_counter share CLOCK_MONOTONIC on Linux
            _trace.record(
                "serve.request", int(req.t_submit * 1e9),
                int(req.t_done * 1e9), corr=req.id,
                args={"state": state,
                      "new_tokens": len(req.new_tokens)},
            )
        req._event.set()

    def _retire(self, b: int, state: str, error: str | None = None) -> None:
        with self._wake:  # RLock: safe from inside step()'s locked region
            slot = self._slots[b]
            self._slots[b] = None
            self._tables[b, :] = 0
            self._batch_dirty = True
            self._release_pins(slot)
            self.allocator.free(slot.blocks)
            self._finalize(slot.request, state, error)

    def _release_pins(self, slot: _Slot) -> None:
        """Drop the row's references on shared radix-tree nodes: its
        matched chain and, if the copy-on-write landed nobody yet, the
        pending COW source (pinned at admission so eviction could not
        free it between match and copy)."""
        if self._prefix is None:
            return
        if slot.pinned:
            self._prefix.release(slot.pinned)
            slot.pinned = []
        if slot.cow is not None:
            self._prefix.release([slot.cow[0]])
            slot.cow = None

    def _evacuate_row(self, b: int, *, reset_tokens: bool) -> None:
        """Tear one RUNNING row down and re-queue its request at the
        head: private blocks freed, tree pins released, request back to
        ``queued``. Hot-swap ``refill`` and preemption-by-recompute share
        this — refill also resets the emitted stream (it replays under
        the new weights); preemption keeps ``new_tokens`` and the
        re-admission re-prefills prompt+generated-so-far, so sampling
        (deterministic per seed and absolute position) resumes
        bit-identically."""
        slot = self._slots[b]
        self._slots[b] = None
        self._tables[b, :] = 0
        self._batch_dirty = True
        self._release_pins(slot)
        self.allocator.free(slot.blocks)
        req = slot.request
        if reset_tokens:
            req.new_tokens = []
        req.state = "queued"
        req.t_admit = None
        req.prefill_s = None
        req.model_version = None
        self._q_push_front(req)

    def _admit(self) -> list[tuple[int, Request]]:
        """FIFO admission under the lock; returns newly filled (row, req)
        pairs whose prefill still has to run (device work happens outside
        the lock — ``submit`` must never block behind a forward pass)."""
        admitted = []
        if (self._staged_swap is not None
                and self._staged_swap[2] == "drain"):
            return admitted  # draining toward a staged swap: hold the door
        free_rows = [b for b, s in enumerate(self._slots) if s is None]
        while self._queue and free_rows:
            head = self._queue[0]
            if head._cancelled:
                self._queue.popleft()
                self._finalize(head, "cancelled", "cancelled while queued")
                continue
            need = self._blocks_needed(head.prompt.shape[0],
                                       head.max_new_tokens)
            if not self.allocator.can_alloc(need):
                break       # strict FIFO: never skip the head (starvation)
            self._queue.popleft()
            b = free_rows.pop(0)
            blocks = self.allocator.alloc(need)
            slot = _Slot(head, blocks)
            self._slots[b] = slot
            self._tables[b, :] = 0
            self._tables[b, :need] = blocks
            self._batch_dirty = True
            head.state = "running"
            head.t_admit = time.monotonic()
            head.model_version = self.model_version
            self.stats_["admitted"] += 1
            if _trace.enabled():
                # the admission-wait span: submit → admit, per request
                _trace.record("serve.queued", int(head.t_submit * 1e9),
                              int(head.t_admit * 1e9), corr=head.id)
            admitted.append((b, head))
        return admitted

    # -- front-door admission (ISSUE 17) --------------------------------------

    def _q_pop_head(self, req: Request) -> None:
        if self._tq is not None:
            self._tq.pop(req)
        else:
            self._queue.popleft()

    def _admit_frontdoor(self) -> int:
        """Admission with the front door on: the head candidate (highest
        SLO class, tenant round-robin within it) is matched against the
        prefix cache, reserved only its UNCACHED blocks, and installed in
        ``"prefill"`` phase for the chunk loop. The head is never skipped
        — when it cannot fit even after tree eviction and (under SLO
        admission) preemption of strictly-lower-priority rows, admission
        stops: the same no-starvation rule as strict FIFO."""
        admitted = 0
        if (self._staged_swap is not None
                and self._staged_swap[2] == "drain"):
            return admitted  # draining toward a staged swap
        while True:
            if not any(s is None for s in self._slots):
                break
            if self._tq is not None:
                head = self._tq.candidate()
            else:
                head = self._queue[0] if self._queue else None
            if head is None:
                break
            if head._cancelled:
                self._q_pop_head(head)
                self._finalize(head, "cancelled", "cancelled while queued")
                continue
            res = self._reserve_for(head)
            if res is None:
                break
            self._q_pop_head(head)
            b = next(i for i, s in enumerate(self._slots) if s is None)
            self._install_row(b, head, res)
            admitted += 1
        return admitted

    def _reserve_for(self, req: Request):
        """Reserve blocks (and a pinned prefix-cache match) for ``req``
        under the lock, or return None when the pool cannot fit it. The
        shortfall ladder: evict refcount-0 cached chains first, then
        (SLO admission only) preempt strictly-lower-priority running
        rows, latest-admitted first. A valid request always fits an
        empty pool (submit() rejects anything over capacity), so the
        ladder terminates."""
        bs = self.block_size
        lp = req.prompt.shape[0]
        g = len(req.new_tokens)
        if g:
            # resume after preemption/requeue: re-prefill the prompt plus
            # everything emitted EXCEPT the pending last token — its K/V
            # is written when decode feeds it, exactly as if the request
            # had never left the batch
            feed = np.concatenate(
                [req.prompt, np.asarray(req.new_tokens[:-1], np.int32)])
        else:
            feed = req.prompt
        feed_len = int(feed.shape[0])
        total = self._blocks_needed(lp, req.max_new_tokens)
        match, cached_len = None, 0
        if self._prefix is not None:
            # fresh requests keep at least the LAST prompt token uncached
            # (its logits seed the first sample); a resumed request's
            # pending token is already known, so it may match all of feed
            cap = feed_len if g else lp - 1
            match = self._prefix.match(feed, cap)
            if match.cow_node is not None:
                match.cow_node.refs += 1  # pin until the slots are copied
            cached_len = match.tokens(bs)
        cb = len(match.nodes) if match else 0
        need = total - cb
        while not self.allocator.can_alloc(need):
            if self._prefix is not None:
                freed = self._prefix.evict(
                    need - self.allocator.free_blocks)
                if freed:
                    self.allocator.free(freed)
                    continue
            if not self._preempt_lower(req):
                if match is not None:
                    if match.cow_node is not None:
                        self._prefix.release([match.cow_node])
                    self._prefix.release(match.nodes)
                return None
        blocks = self.allocator.alloc(need)
        return (match, blocks, feed, feed_len, cached_len, g)

    def _preempt_lower(self, req: Request) -> bool:
        """Preempt ONE running row whose request has a strictly lower
        SLO priority (latest admitted first — the least sunk prefill
        cost), freeing its private blocks. The victim re-queues at its
        tenant's head and recomputes prompt+generated on re-admission."""
        if self._tq is None:
            return False
        prio = slo_priority(req.slo_class)
        victims = [b for b, s in enumerate(self._slots)
                   if s is not None
                   and slo_priority(s.request.slo_class) > prio]
        if not victims:
            return False
        b = max(victims, key=lambda x: self._slots[x].request.t_admit)
        victim_id = self._slots[b].request.id
        self._evacuate_row(b, reset_tokens=False)
        self.stats_["preemptions"] += 1
        _trace.instant("serve.preempt", corr=victim_id,
                       args={"for": req.id})
        return True

    def _install_row(self, b: int, req: Request, res) -> None:
        match, blocks, feed, feed_len, cached_len, g = res
        slot = _Slot(req, blocks)
        self._tables[b, :] = 0
        cb = len(match.nodes) if match else 0
        if cb:
            self._tables[b, :cb] = match.blocks
            slot.pinned = list(match.nodes)
        self._tables[b, cb:cb + len(blocks)] = blocks
        slot.feed = np.asarray(feed, np.int32)
        slot.feed_len = feed_len
        slot.next_pos = int(cached_len)
        if match is not None and match.cow_node is not None:
            # the divergent block's first cow_len slots are copied from
            # the COW source into the row's FIRST private block before
            # any forward touches them (_apply_cows, same step)
            slot.cow = (match.cow_node, match.cow_len, blocks[0])
        if self.prefix_cache:
            self.stats_["prefix_hit_tokens"] += int(cached_len)
            self.stats_["prefix_prompt_tokens"] += feed_len
        if g:
            slot.sample_first = False
            slot.resume_tok = int(req.new_tokens[-1])
        if cached_len >= feed_len:
            # fully cached resume: nothing left to prefill
            slot.phase = "decode"
            slot.last_tok = slot.resume_tok
            req.prefill_s = 0.0
        else:
            slot.phase = "prefill"
        self._slots[b] = slot
        self._batch_dirty = True
        req.state = "running"
        req.t_admit = time.monotonic()
        req.model_version = self.model_version
        self.stats_["admitted"] += 1
        if _trace.enabled():
            _trace.record("serve.queued", int(req.t_submit * 1e9),
                          int(req.t_admit * 1e9), corr=req.id)

    def _run_prefills(self, admitted) -> None:
        """Prefill an admission burst in as few forwards as possible: one
        BATCHED ``prefill_raw`` per padded-length group (row count bucketed
        to powers of two — dummy rows write the scratch block — so compile
        count stays ``O(len buckets · log max_batch)``, not one program per
        group size). A burst of admissions at saturation was serializing
        ``n`` batch-1 forwards, each streaming the full weights; grouping
        streams them once per length bucket."""
        groups: dict[int, list] = {}
        for b, req in admitted:
            lp = req.prompt.shape[0]
            lpad = math.ceil(lp / self.block_size) * self.block_size
            groups.setdefault(lpad, []).append((b, req))
        vocab = self._module.vocab
        for lpad, grp in groups.items():
            n = len(grp)
            npad = 1 << (n - 1).bit_length()
            prompts = np.zeros((npad, lpad), np.int32)
            # dummy rows scatter into the scratch block (block 0) only:
            # duplicate indices are fine, nobody reads those slots
            row_slots = np.tile(
                np.tile(np.arange(self.block_size, dtype=np.int32),
                        lpad // self.block_size), (npad, 1))
            lp_arr = np.ones((npad,), np.int32)
            temp = np.zeros((npad,), np.float32)
            top_k = np.full((npad,), vocab, np.int32)
            top_p = np.ones((npad,), np.float32)
            greedy = np.ones((npad,), bool)
            seeds = np.zeros((npad,), np.int32)
            for i, (b, req) in enumerate(grp):
                lp = req.prompt.shape[0]
                prompts[i, :lp] = req.prompt
                row_slots[i] = slot_map(self._tables[b:b + 1],
                                        self.block_size)[0, :lpad]
                lp_arr[i] = lp
                temp[i] = req.temperature
                if req.top_k is not None:
                    top_k[i] = req.top_k
                if req.top_p is not None:
                    top_p[i] = req.top_p
                greedy[i] = req.greedy
                seeds[i] = req.seed
            key = (lpad, npad)
            if key not in self._prefill_fns:
                self._prefill_fns[key] = self._make_prefill()
            c, dc = self.cache, getattr(self, "draft_cache", None)
            # always timed (one clock read per prefill FORWARD, not per
            # request): the duration feeds each request's latency
            # breakdown whether or not tracing is on
            t_pf = time.perf_counter_ns()
            tok, c.k_pools, c.v_pools, dk, dv = self._prefill_fns[key](
                self._params, self._draft_params, c.k_pools, c.v_pools,
                dc.k_pools if dc else (), dc.v_pools if dc else (),
                jnp.asarray(prompts), jnp.asarray(row_slots),
                jnp.asarray(lp_arr), jnp.asarray(temp),
                jnp.asarray(top_k), jnp.asarray(top_p),
                jnp.asarray(greedy), jnp.asarray(seeds),
            )
            if dc:
                dc.k_pools, dc.v_pools = dk, dv
            tok = np.asarray(jax.device_get(tok))
            t1_pf = time.perf_counter_ns()
            for _, req in grp:
                # the group forward, attributed to every request it
                # prefilled (same interval — the latency breakdown and,
                # when tracing, the span, each with its own corr)
                req.prefill_s = (t1_pf - t_pf) / 1e9
                if _trace.enabled():
                    _trace.record("serve.prefill", t_pf, t1_pf,
                                  corr=req.id,
                                  args={"rows": n, "lpad": lpad})
            self.stats_["prefills"] += n
            for i, (b, req) in enumerate(grp):
                slot = self._slots[b]
                slot.next_pos = req.prompt.shape[0]
                slot.last_tok = int(tok[i])
                self._emit(b, [slot.last_tok])

    def _apply_cows(self) -> None:
        """Land every pending copy-on-write: device-copy each COW source
        block's shared leading slots into the row's first private block,
        then unpin the source. Runs BEFORE any forward each step — the
        chunk (or the fully-cached resume's decode) attends over those
        positions."""
        rows = [b for b, s in enumerate(self._slots)
                if s is not None and s.cow is not None]
        if not rows:
            return
        bs = self.block_size
        src, dst, pending = [], [], []
        for b in rows:
            node, m, d = self._slots[b].cow
            src.append(node.block * bs + np.arange(m, dtype=np.int64))
            dst.append(d * bs + np.arange(m, dtype=np.int64))
            pending.append((b, node))
        src = np.concatenate(src)
        dst = np.concatenate(dst)
        # pad to a power of two with scratch self-copies (slot 0 → slot
        # 0) so the jitted gather-scatter compiles a handful of shapes
        npad = 1 << (len(src) - 1).bit_length()
        pad = npad - len(src)
        if pad:
            src = np.concatenate([src, np.zeros(pad, np.int64)])
            dst = np.concatenate([dst, np.zeros(pad, np.int64)])
        self.cache.copy_slots(src, dst)
        with self._wake:
            for b, node in pending:
                self._slots[b].cow = None
                self._prefix.release([node])
                self.stats_["cow_copies"] += 1

    def _run_chunks(self, rows) -> None:
        """One chunk of front-door prefill for every ``"prefill"``-phase
        row: each feeds up to ``prefill_chunk`` (or its whole remaining
        suffix) tokens at its own position through ONE batched
        ``paged_extend_rows`` — then the step's decode batch runs, so a
        long prompt interleaves with in-flight decode instead of
        head-of-line-blocking it. A row whose feed completes flips to
        decode; fresh rows sample their first token from the last prompt
        position's logits, resumed rows re-emit nothing (their pending
        token was sampled before preemption)."""
        bs = self.block_size
        vocab = self._module.vocab
        rem = max(self._slots[b].feed_len - self._slots[b].next_pos
                  for b in rows)
        Tpad = (self.prefill_chunk if self.prefill_chunk is not None
                else 1 << (rem - 1).bit_length())
        n = len(rows)
        npad = 1 << (n - 1).bit_length()
        need_pos = max(min(Tpad, self._slots[b].feed_len
                           - self._slots[b].next_pos)
                       + self._slots[b].next_pos for b in rows)
        nb = min(self._nb_per_seq,
                 2 * math.ceil(math.ceil(need_pos / bs) / 2))
        tokens = np.zeros((npad, Tpad), np.int32)
        tables = np.zeros((npad, nb), np.int32)
        # pad rows / pad positions write the scratch block's slots —
        # garbage nobody reads, same trick as the legacy prefill buckets
        write_slots = np.tile((np.arange(Tpad) % bs).astype(np.int32),
                              (npad, 1))
        positions = np.zeros((npad,), np.int32)
        last_idx = np.zeros((npad,), np.int32)
        sample_pos = np.zeros((npad,), np.int32)
        temp = np.zeros((npad,), np.float32)
        top_k = np.full((npad,), vocab, np.int32)
        top_p = np.ones((npad,), np.float32)
        greedy = np.ones((npad,), bool)
        seeds = np.zeros((npad,), np.int32)
        t_real = []
        for i, b in enumerate(rows):
            s = self._slots[b]
            r = s.request
            t = min(Tpad, s.feed_len - s.next_pos)
            t_real.append(t)
            tokens[i, :t] = s.feed[s.next_pos: s.next_pos + t]
            tables[i] = self._tables[b, :nb]
            pos = s.next_pos + np.arange(t)
            write_slots[i, :t] = tables[i, pos // bs] * bs + pos % bs
            positions[i] = s.next_pos
            last_idx[i] = min(max(s.feed_len - 1 - s.next_pos, 0),
                              Tpad - 1)
            sample_pos[i] = s.feed_len   # == lp for fresh requests: the
            temp[i] = r.temperature      # key matches _make_prefill
            if r.top_k is not None:
                top_k[i] = r.top_k
            if r.top_p is not None:
                top_p[i] = r.top_p
            greedy[i] = r.greedy
            seeds[i] = r.seed
        key = (Tpad, npad, nb)
        if key not in self._chunk_fns:
            self._chunk_fns[key] = self._make_chunk()
        c = self.cache
        t_pf = time.perf_counter_ns()
        tok, c.k_pools, c.v_pools = self._chunk_fns[key](
            self._params, c.k_pools, c.v_pools, jnp.asarray(tokens),
            jnp.asarray(tables), jnp.asarray(write_slots),
            jnp.asarray(positions), jnp.asarray(last_idx),
            jnp.asarray(temp), jnp.asarray(top_k), jnp.asarray(top_p),
            jnp.asarray(greedy), jnp.asarray(seeds),
            jnp.asarray(sample_pos),
        )
        tok = np.asarray(jax.device_get(tok))
        t1_pf = time.perf_counter_ns()
        with self._wake:
            for i, b in enumerate(rows):
                s = self._slots[b]
                r = s.request
                r.prefill_s = (r.prefill_s or 0.0) + (t1_pf - t_pf) / 1e9
                if _trace.enabled():
                    _trace.record("serve.prefill", t_pf, t1_pf, corr=r.id,
                                  args={"rows": n, "chunk": int(t_real[i]),
                                        "pos": int(s.next_pos)})
                s.next_pos += t_real[i]
                if s.next_pos < s.feed_len:
                    continue
                # feed complete: this row decodes from the next step
                s.phase = "decode"
                self.stats_["prefills"] += 1
                if self._prefix is not None:
                    # donate the prompt's full blocks to the radix tree;
                    # blocks already cached along the chain stay private
                    lp = r.prompt.shape[0]
                    nfull = lp // bs
                    if nfull:
                        new_nodes, adopted = self._prefix.insert(
                            r.prompt,
                            [int(self._tables[b, k]) for k in range(nfull)],
                        )
                        s.pinned.extend(new_nodes)
                        if adopted:
                            adset = set(adopted)
                            s.blocks = [x for x in s.blocks
                                        if x not in adset]
                if s.sample_first:
                    s.last_tok = int(tok[i])
                    self._emit(b, [s.last_tok])
                else:
                    s.last_tok = s.resume_tok

    def _emit(self, b: int, tokens: list[int]) -> None:
        """Append emitted tokens to row ``b``'s request, applying the
        retire rule (budget, then first EOS — the rule
        :func:`per_row_new_token_counts` mirrors batch-wide)."""
        slot = self._slots[b]
        req = slot.request
        done = False
        for t in tokens:
            req.new_tokens.append(int(t))
            if req.eos_id is not None and int(t) == req.eos_id:
                done = True
                break
            if len(req.new_tokens) >= req.max_new_tokens:
                done = True
                break
        if done:
            self._retire(b, "done")

    def step(self) -> bool:
        """One scheduler iteration: retire cancellations, admit + prefill,
        one batched decode (or speculative) step. Returns whether any work
        was done — the loop thread sleeps on False."""
        with self._wake:
            for b, slot in enumerate(self._slots):
                if slot is not None and slot.request._cancelled:
                    self._retire(b, "cancelled", "cancelled by client")
            self._apply_swap_locked()
            admitted = (self._admit_frontdoor() if self._frontdoor
                        else self._admit())
        worked = bool(admitted)
        if self._frontdoor:
            self._apply_cows()
            prefill_rows = [b for b, s in enumerate(self._slots)
                            if s is not None and s.phase == "prefill"]
            if prefill_rows:
                self._run_chunks(prefill_rows)
                worked = True
            active = [b for b, s in enumerate(self._slots)
                      if s is not None and s.phase == "decode"]
        else:
            if admitted:
                self._run_prefills(admitted)
            active = [b for b, s in enumerate(self._slots)
                      if s is not None]
        if not active:
            return worked
        # rows-in-flight rides the span (ISSUE 14): the analyzer's
        # batch-occupancy input ("batch" kept for older readers)
        _args = ({"batch": len(active), "rows": len(active)}
                 if _trace.enabled() else None)
        with _trace.span("serve.decode_step", args=_args):
            if self._spec_fn is not None:
                self._spec_step(active)
            else:
                self._decode_step(active)
        with self._wake:
            self.stats_["steps"] += 1
            self.stats_["occupancy_sum"] += len(active)
        return True

    def _refresh_batch_cache(self):
        """Rebuild the per-batch device arrays — ONLY when the batch
        composition changed (admission/retire), never per token: the slot
        map and sampling params are constants of a batch lineup, and
        rebuilding + re-uploading them each step was measurable per-step
        overhead on the 1-core bench host."""
        if not self._batch_dirty:
            return
        B = self.max_batch
        self._np_slots = slot_map(self._tables, self.block_size)
        self._dev_tables_by_width = {}
        temp = np.zeros((B,), np.float32)
        top_k = np.full((B,), self._module.vocab, np.int32)
        top_p = np.ones((B,), np.float32)
        greedy = np.ones((B,), bool)
        seeds = np.zeros((B,), np.int32)
        for b, s in enumerate(self._slots):
            if s is None:
                continue
            r = s.request
            temp[b] = r.temperature
            if r.top_k is not None:
                top_k[b] = r.top_k
            if r.top_p is not None:
                top_p[b] = r.top_p
            greedy[b] = r.greedy
            seeds[b] = r.seed
        self._all_greedy = bool(greedy.all())
        self._dev_sampling = tuple(
            jnp.asarray(a) for a in (temp, top_k, top_p, greedy, seeds)
        )
        self._batch_dirty = False

    def _tables_for(self, need_pos: int):
        """Device block tables truncated to the working width: the paged
        gather (and the attention scores behind it) only needs to cover
        positions ``< need_pos``, so the step attends over the longest
        ACTIVE sequence, not ``maxlen`` — a real advantage over the dense
        scan, whose ``[B, maxlen]`` cache pays full width every step.
        Width is bucketed to 2-block multiples so XLA compiles a handful
        of step shapes, not one per length."""
        nb = min(self._nb_per_seq,
                 2 * math.ceil(math.ceil(need_pos / self.block_size) / 2))
        if nb not in self._dev_tables_by_width:
            self._dev_tables_by_width[nb] = jnp.asarray(
                self._tables[:, :nb]
            )
        return self._dev_tables_by_width[nb]

    def _tok_positions(self, active):
        B = self.max_batch
        tok = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        for b in active:
            s = self._slots[b]
            tok[b] = s.last_tok
            positions[b] = s.next_pos
        return tok, positions

    def _decode_step(self, active) -> None:
        self._refresh_batch_cache()
        tok, positions = self._tok_positions(active)
        write_slot = self._np_slots[np.arange(self.max_batch), positions]
        if self._frontdoor:
            # rows mid-chunked-prefill sit in the batch with REAL blocks
            # in their tables but position 0 here — without masking, the
            # decode write would land in their (possibly SHARED, cached)
            # first block's slot 0. Park every non-decode row's write in
            # the scratch block instead.
            mask = np.zeros((self.max_batch,), bool)
            mask[active] = True
            write_slot = np.where(
                mask, write_slot,
                np.arange(self.max_batch) % self.block_size)
        dev_tables = self._tables_for(int(positions.max()) + 1)
        c = self.cache
        if self._all_greedy:
            nxt, c.k_pools, c.v_pools = self._decode_fn_greedy(
                self._params, c.k_pools, c.v_pools, jnp.asarray(tok),
                dev_tables, jnp.asarray(write_slot),
                jnp.asarray(positions),
            )
        else:
            nxt, c.k_pools, c.v_pools = self._decode_fn(
                self._params, c.k_pools, c.v_pools, jnp.asarray(tok),
                dev_tables, jnp.asarray(write_slot),
                jnp.asarray(positions), *self._dev_sampling,
            )
        nxt = np.asarray(jax.device_get(nxt))
        for b in active:
            slot = self._slots[b]
            slot.next_pos += 1
            slot.last_tok = int(nxt[b])
            self._emit(b, [slot.last_tok])

    def _spec_step(self, active) -> None:
        K = self.spec_tokens
        self._refresh_batch_cache()
        tok, positions = self._tok_positions(active)
        slots = self._np_slots
        idx = positions[:, None] + np.arange(K + 1)[None, :]
        write_slots = np.take_along_axis(slots, idx, axis=1)
        c, dc = self.cache, self.draft_cache
        dev_tables = self._tables_for(int(positions.max()) + K + 1)
        props, g, a_row, c.k_pools, c.v_pools, dc.k_pools, dc.v_pools = \
            self._spec_fn(
                self._params, self._draft_params, c.k_pools, c.v_pools,
                dc.k_pools, dc.v_pools, jnp.asarray(tok),
                dev_tables, jnp.asarray(positions),
                jnp.asarray(write_slots),
            )
        props, g, a_row = jax.device_get((props, g, a_row))
        with self._wake:
            self.stats_["spec_rounds"] += 1
            self.stats_["spec_proposed"] += K * len(active)
        for b in active:
            slot = self._slots[b]
            a = int(a_row[b])
            emitted = [int(x) for x in props[b, :a]] + [int(g[b, a])]
            with self._wake:
                self.stats_["spec_accepted"] += a
            # per-row advancement: this row moves a+1 positions no matter
            # what the rest of the batch accepted
            slot.next_pos += a + 1
            slot.last_tok = int(g[b, a])
            self._emit(b, emitted)

    # -- lifecycle -----------------------------------------------------------

    def _idle(self) -> bool:
        return (self._queued_count() == 0
                and all(s is None for s in self._slots))

    def run_until_idle(self, max_steps: int = 1_000_000) -> None:
        """Synchronous drive (tests, parity oracles): step until every
        queued and running request has retired."""
        for _ in range(max_steps):
            with self._lock:
                if self._idle():
                    return
            self.step()
        raise RuntimeError(f"no progress after {max_steps} steps")

    def run(self) -> None:
        while True:
            with self._wake:
                if self._stop:
                    return
                if self._idle() and self._staged_swap is None:
                    # a staged swap on an idle engine still needs one
                    # step() to land (an activated version must not wait
                    # for the next request to arrive)
                    self._wake.wait(0.05)
                    continue
            try:
                self.step()
            except Exception as e:  # a poisoned step must not hang clients
                with self._wake:
                    # stop admitting: with the loop thread dead, anything
                    # submitted later would queue forever — reject it as
                    # busy (retryable) instead of hanging the client
                    self._closed = True
                    for b, slot in enumerate(self._slots):
                        if slot is not None:
                            self._retire(b, "failed", repr(e))
                    for req in self._q_drain():
                        self._finalize(req, "failed", repr(e))
                raise

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()

    def close(self) -> None:
        """Stop accepting new requests (drain begins); in-flight and queued
        requests keep running to completion."""
        with self._wake:
            self._closed = True
            self._wake.notify_all()

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait until every accepted request has retired."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self._idle():
                    return True
            time.sleep(0.005)
        return False

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        self.close()
        if drain and self._thread is not None:
            self.drain(timeout)
        with self._wake:
            self._stop = True
            self._wake.notify_all()
        if self._thread is not None:
            # join BEFORE retiring leftovers: a step in flight reads
            # _slots/_tables outside the lock, so yanking rows under it
            # races into use-after-retire; the loop re-checks _stop each
            # iteration, so the join is bounded by one step
            self._thread.join(timeout=10)
            self._thread = None
        with self._wake:
            # anything still queued/running dies visibly, not silently
            for b, slot in enumerate(self._slots):
                if slot is not None:
                    self._retire(b, "cancelled", "engine stopped")
            for req in self._q_drain():
                self._finalize(req, "cancelled", "engine stopped")

    def latency_stats(self, window_s: float | None = None) -> dict:
        """Per-SLO-class latency summary (see
        :func:`summarize_latencies`) from the retired-request ring."""
        with self._lock:
            recs = list(self._retired)
        return summarize_latencies(recs, window_s=window_s)

    def stats(self) -> dict:
        with self._lock:
            s = dict(self.stats_)
            # snapshot the ring under the lock, summarize AFTER: the
            # percentile math is O(ring) and the decode loop contends
            # for this lock — a scrape must not stall token generation
            retired = list(self._retired)
            s["queued"] = self._queued_count()
            s["active"] = sum(1 for x in self._slots if x is not None)
            s["model_version"] = self.model_version
            s["staged_version"] = (
                self._staged_swap[1] if self._staged_swap else None
            )
            s["blocks_in_use"] = self.allocator.used_blocks
            s["blocks_free"] = self.allocator.free_blocks
            s["blocks_high_water"] = self.allocator.high_water
            s["mean_batch_occupancy"] = (
                round(s["occupancy_sum"] / s["steps"], 3)
                if s["steps"] else 0.0
            )
            if self.spec_tokens:
                s["spec_acceptance"] = (
                    round(s["spec_accepted"] / s["spec_proposed"], 4)
                    if s["spec_proposed"] else 0.0
                )
            if self._prefix is not None:
                s["prefix_cached_blocks"] = len(self._prefix)
                s["prefix_evictions"] = self._prefix.evictions
                tot = s["prefix_prompt_tokens"]
                s["prefix_hit_rate"] = (
                    round(s["prefix_hit_tokens"] / tot, 4) if tot else 0.0
                )
        s["latency"] = summarize_latencies(retired)
        return s

    def prefix_hit_rate(self) -> float:
        """Lifetime token-level prefix-cache hit rate (0.0 when the cache
        is off or nothing admitted yet) — the number the server publishes
        into directory meta so the router can weight replica affinity by
        where prefixes are already warm."""
        with self._lock:
            if self._prefix is None:
                return 0.0
            tot = self.stats_["prefix_prompt_tokens"]
            if not tot:
                return 0.0
            return round(self.stats_["prefix_hit_tokens"] / tot, 4)

    def flush_prefix_cache(self) -> int:
        """Drop every unpinned cached chain, returning its blocks to the
        allocator; returns how many blocks were freed. Chains pinned by
        in-flight rows survive."""
        with self._lock:
            if self._prefix is None:
                return 0
            freed = self._prefix.flush()
            if freed:
                self.allocator.free(freed)
            return len(freed)
