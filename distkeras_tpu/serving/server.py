"""Socket front end for the generation engine.

Same transport discipline as the parameter-server tier: length-prefixed
restricted-pickle frames (``networking.py`` — a forged frame cannot execute
code), one handler thread per connection, typed :class:`ProtocolError`
triage so the reconnecting client can tell weather (peer died mid-frame —
retry) from protocol violations (fatal) from backpressure
(:class:`ServerBusyError` — back off and resubmit).

Wire protocol: the client sends ``{"action": "generate", "prompt":
int32 array, "max_new_tokens": n, ...sampling knobs...}`` and blocks for
``{"ok": True, "tokens": int32 array, "new_tokens": n}``. While a request
is in flight the handler polls the connection for liveness: a client that
dies mid-generation is detected by its EOF, its request is cancelled, and
the scheduler frees its cache blocks the next iteration — a dead
connection cannot leak pool memory (the resilience triage the integration
test kills a client to prove). ``stats`` returns the engine + server
counters; ``server.stop(drain=True)`` stops admission, lets in-flight
requests finish, then closes.

:class:`ResilientGenerationClient` mirrors ``ResilientPSClient``: a client
factory + :class:`~distkeras_tpu.resilience.retry.RetryPolicy`, reconnect
on retryable failure, jittered backoff on busy. Generation is one
idempotent request/response, so a replay after a dead server is safe —
no seqno machinery needed.
"""

from __future__ import annotations

import select
import socket
import threading
from typing import Callable

import numpy as np

from distkeras_tpu import networking
from distkeras_tpu.networking import ProtocolError, ServerBusyError
from distkeras_tpu.serving.scheduler import GenerationEngine, Request

_SAMPLING_KEYS = ("max_new_tokens", "temperature", "top_k", "top_p",
                  "seed", "eos_id", "request_id", "slo_class", "tenant")


class GenerationServer:
    """Threaded TCP service around a :class:`GenerationEngine`.

    ``initialize()`` binds (ephemeral port resolved into ``.port``),
    ``start()`` runs the accept loop and the engine thread; ``stop()``
    drains gracefully by default."""

    def __init__(self, engine: GenerationEngine, host: str = "127.0.0.1",
                 port: int = 0, poll_interval: float = 0.05,
                 trace: bool = False, trace_dir: str | None = None,
                 trace_sample: float = 1.0):
        self.engine = engine
        self.host = host
        self.port = int(port)
        self.poll_interval = float(poll_interval)
        # Flight recorder (ISSUE 11): trace=/trace_dir= arm the span
        # recorder for this server's lifetime (request lifecycle spans —
        # enqueue→admit→prefill→decode→retire — stitched by request id);
        # stop() writes the timeline to trace_dir (path in trace_path_).
        # Ownership mirrors the trainer's: only an enable WE performed
        # is disabled at stop, so a bench that already enabled tracing
        # keeps its recorder.
        self.trace = bool(trace) or trace_dir is not None
        self.trace_dir = trace_dir
        self.trace_sample = float(trace_sample)
        self.trace_path_: str | None = None
        self._trace_owner = False
        self._server_sock: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._handlers: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self._conns_lock = threading.Lock()
        self._running = False
        self.connections_ = 0
        self.dead_connections_ = 0
        # Watchtower (ISSUE 13): attach one and the `metrics` wire
        # action carries its alert ledger to remote scrapers (the CLI's
        # `health --watch` relays server-side alerts it cannot derive
        # from counters alone)
        self.watchtower = None
        # Membership directory (ISSUE 15): register_with() publishes this
        # replica under the "serve" role with a renewed lease, so a
        # RoutedGenerationClient discovers it — and a killed replica's
        # entry ages out instead of lying
        self._dir_reg: tuple | None = None   # (client, key, ttl, epoch)
        self._dir_renewer: threading.Thread | None = None
        self._dir_stop = threading.Event()
        # Live deployment (distkeras_tpu/deploy): a SnapshotStore of
        # streamed model versions. With one attached, the
        # deploy_activate wire action swaps the engine to any stored
        # version, and the directory registration meta carries the
        # CURRENT model_version (re-published by the renewer, so a swap
        # shows up fleet-wide within ttl/3).
        self.snapshots = None

    def initialize(self) -> None:
        self._server_sock = socket.socket(socket.AF_INET,
                                          socket.SOCK_STREAM)
        self._server_sock.setsockopt(socket.SOL_SOCKET,
                                     socket.SO_REUSEADDR, 1)
        self._server_sock.bind((self.host, self.port))
        self.port = self._server_sock.getsockname()[1]
        self._server_sock.listen(64)
        self._running = True

    def start(self) -> None:
        if self._server_sock is None:
            self.initialize()
        if self.trace:
            from distkeras_tpu.observability import trace as _trace

            if not _trace.enabled():
                _trace.enable(sample=self.trace_sample)
                self._trace_owner = True
        self.engine.start()
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._server_sock.accept()
            except OSError:
                break
            if not self._running:
                conn.close()
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.append(conn)
                self.connections_ += 1
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()
            # reap finished handlers: client connections are many and
            # short-lived here (unlike the PS tier's few long-lived
            # workers) — keeping every Thread ever accepted grows
            # memory linearly with total connections
            self._handlers = [h for h in self._handlers if h.is_alive()]
            self._handlers.append(t)

    @staticmethod
    def _peer_dead(conn: socket.socket) -> bool:
        """EOF probe without consuming data: readable + empty peek means
        the peer closed (readable with bytes would be a pipelined frame —
        left buffered; this protocol is strictly request/response, so data
        here just waits for the current reply). ``poll`` rather than
        ``select``: a loaded server holds more than FD_SETSIZE=1024
        descriptors and ``select()`` raises on any fd beyond it."""
        try:
            p = select.poll()
            p.register(conn, select.POLLIN)
            if not p.poll(0):
                return False
            return conn.recv(1, socket.MSG_PEEK) == b""
        except (OSError, ValueError):
            return True

    def _serve_generate(self, conn: socket.socket, msg: dict) -> None:
        try:
            prompt = np.asarray(msg["prompt"], np.int32)
            knobs = {k: msg[k] for k in _SAMPLING_KEYS if k in msg}
            req = self.engine.submit(prompt, **knobs)
        except ServerBusyError as e:
            networking.send_data(conn, {"error": "busy",
                                        "message": str(e)})
            return
        except (ValueError, TypeError, KeyError) as e:
            networking.send_data(conn, {"error": "bad_request",
                                        "message": str(e)})
            return
        # wait for completion, watching the connection: a client killed
        # mid-stream must free its blocks, not ride the batch to the end
        while not req.wait(self.poll_interval):
            if self._peer_dead(conn):
                self.engine.cancel(req)
                with self._conns_lock:
                    self.dead_connections_ += 1
                raise ConnectionResetError(
                    f"client died mid-generation ({req.id} cancelled)"
                )
        if req.state == "done":
            networking.send_data(conn, {
                "ok": True,
                "tokens": np.asarray(req.new_tokens, np.int32),
                "new_tokens": len(req.new_tokens),
                "request_id": req.id,
            })
        else:
            networking.send_data(conn, {
                "error": req.state,
                "message": req.error or req.state,
                "request_id": req.id,
                # a server-side cancel (stop/drain tearing the batch) is
                # retryable weather to a routed client — the request is
                # idempotent and a sibling replica can serve it; a
                # "failed" model error is deterministic and is not
                "retryable": req.state == "cancelled",
            })

    def _handle(self, conn: socket.socket) -> None:
        try:
            while True:
                msg = networking.recv_data(conn)
                action = msg.get("action")
                if action == "generate":
                    self._serve_generate(conn, msg)
                elif action == "stats":
                    networking.send_data(conn, {"ok": True,
                                                "stats": self.stats()})
                elif action == "deploy_activate":
                    # hot swap: stage a stored snapshot version onto the
                    # engine (applied between decode steps — the version
                    # gate). The rollout controller's activation path.
                    networking.send_data(
                        conn, self._deploy_activate(msg)
                    )
                elif action == "deploy_status":
                    store = self.snapshots
                    networking.send_data(conn, {
                        "ok": True,
                        "model_version": self.engine.model_version,
                        "staged_version": (
                            self.engine._staged_swap[1]
                            if self.engine._staged_swap else None
                        ),
                        "versions": (
                            store.versions() if store is not None else []
                        ),
                    })
                elif action == "metrics":
                    # unified metrics surface (ISSUE 11/13): the serving
                    # counters + per-class latency summary normalized
                    # into typed metrics — the ONE metrics_reply shape
                    # every server sends, plus the alert ledger when a
                    # watchtower is attached
                    from distkeras_tpu.observability.metrics import (
                        metrics_reply,
                        serving_metrics,
                    )

                    networking.send_data(conn, metrics_reply(
                        serving_metrics(self.stats()), self.watchtower,
                    ))
                else:
                    networking.send_data(conn, {
                        "error": "bad_request",
                        "message": f"unknown action {action!r}",
                    })
        except (ConnectionError, EOFError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._conns_lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def _deploy_activate(self, msg: dict) -> dict:
        version = int(msg["version"])
        policy = msg.get("policy", "drain")
        store = self.snapshots
        if store is None:
            return {"ok": False, "error": "no snapshot store attached"}
        snap = store.get(version)
        if snap is None:
            return {"ok": False, "error": f"unknown version {version}",
                    "versions": store.versions()}
        try:
            self.engine.swap_params(snap.tree, snap.version, policy=policy)
        except ValueError as e:
            return {"ok": False, "error": str(e)}
        return {"ok": True, "version": snap.version, "policy": policy}

    def register_with(self, directory, key: str | None = None,
                      ttl: float = 5.0, epoch: int = 0) -> str:
        """Publish this replica into a membership directory (ISSUE 15):
        ``("serve", key) → (host, port)`` with a ``ttl`` lease renewed
        by a background thread at a third of the lease, so the entry
        expires within one TTL of this replica's death and the router's
        next refresh drops it. The registration meta carries the
        engine's CURRENT ``model_version`` and is refreshed on every
        renewal — a hot swap is visible to routers within ``ttl/3``.
        ``stop()`` withdraws cleanly. Returns the registered key."""
        from distkeras_tpu.directory.client import DirectoryClient

        if not isinstance(directory, DirectoryClient):
            directory = DirectoryClient(directory)
        if key is None:
            key = f"{self.host}:{self.port}"

        def publish():
            # the meta rides every renewal, so a hot swap (version) and a
            # warming prefix cache (hit rate → router affinity weights,
            # ISSUE 17) are both fleet-visible within ttl/3
            directory.publish(
                "serve", key, self.host, self.port, epoch=int(epoch),
                ttl=float(ttl),
                meta={
                    "model_version": int(self.engine.model_version),
                    "prefix_hit_rate": float(
                        self.engine.prefix_hit_rate()),
                },
            )

        publish()
        self._dir_reg = (directory, key, float(ttl), int(epoch))
        self._dir_stop.clear()

        def renewer():
            while not self._dir_stop.wait(max(ttl / 3.0, 0.05)):
                try:
                    publish()
                except Exception:
                    pass  # directory weather; the next tick retries

        self._dir_renewer = threading.Thread(
            target=renewer, daemon=True, name="dk-serve-dir-renew",
        )
        self._dir_renewer.start()
        return key

    def _withdraw_registration(self) -> None:
        self._dir_stop.set()
        if self._dir_renewer is not None:
            self._dir_renewer.join(timeout=2)
            self._dir_renewer = None
        reg, self._dir_reg = self._dir_reg, None
        if reg is not None:
            directory, key, _ttl, epoch = reg
            try:
                directory.withdraw("serve", key, epoch=epoch)
            except Exception:
                pass  # the lease expiry is the backstop

    def stats(self) -> dict:
        s = self.engine.stats()
        with self._conns_lock:
            s["connections"] = self.connections_
            s["open_connections"] = len(self._conns)
            s["dead_connections"] = self.dead_connections_
        return s

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Graceful by default: stop accepting, let every admitted request
        finish and its reply flush, then tear down."""
        self._withdraw_registration()
        self._running = False
        if self._server_sock is not None:
            try:
                self._server_sock.close()
            except OSError:
                pass
        self.engine.stop(drain=drain, timeout=timeout)
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        for t in self._handlers:
            t.join(timeout=2)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2)
        if self.trace:
            import os as _os
            import time as _time

            from distkeras_tpu.observability import trace as _trace

            if self.trace_dir is not None and _trace.enabled():
                self.trace_path_ = _trace.save(_os.path.join(
                    self.trace_dir,
                    f"serve-trace-{_os.getpid()}-{_time.time_ns()}.json",
                ))
            if self._trace_owner:
                _trace.disable()
                self._trace_owner = False


class GenerationClient:
    """Blocking request/response client for :class:`GenerationServer`."""

    def __init__(self, host: str, port: int,
                 connect_timeout: float | None = 30.0):
        self._sock = networking.connect(host, port,
                                        timeout=connect_timeout)
        self._sock.settimeout(None)

    def generate(self, prompt, *, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int | None = None,
                 top_p: float | None = None, seed: int = 0,
                 eos_id: int | None = None,
                 request_id: str | None = None,
                 slo_class: str = "default",
                 tenant: str = "default") -> np.ndarray:
        networking.send_data(self._sock, {
            "action": "generate",
            "prompt": np.asarray(prompt, np.int32),
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "top_k": top_k, "top_p": top_p, "seed": int(seed),
            "eos_id": eos_id, "request_id": request_id,
            "slo_class": str(slo_class), "tenant": str(tenant),
        })
        r = networking.recv_data(self._sock)
        if r.get("error") == "busy":
            raise ServerBusyError(r.get("message", "server busy"),
                                  peer=networking._peer_of(self._sock))
        if "error" in r:
            # bad_request / failed: replaying the same frame can only
            # fail the same way. A server-side "cancelled" (stop/drain)
            # carries retryable=True — a routed/resilient client replays
            # it against whoever serves next.
            raise ProtocolError(
                f"server rejected request: {r['error']}: "
                f"{r.get('message', '')}",
                peer=networking._peer_of(self._sock),
                retryable=bool(r.get("retryable")),
            )
        return np.asarray(r["tokens"], np.int32)

    def stats(self) -> dict:
        networking.send_data(self._sock, {"action": "stats"})
        r = networking.recv_data(self._sock)
        return r["stats"]

    def deploy_activate(self, version: int,
                        policy: str = "drain") -> dict:
        """Hot-swap the server to a stored snapshot ``version`` (the
        rollout controller's activation RPC). Returns the server's reply
        (``ok=False`` with the available versions on a miss)."""
        networking.send_data(self._sock, {
            "action": "deploy_activate", "version": int(version),
            "policy": str(policy),
        })
        return networking.recv_data(self._sock)

    def deploy_status(self) -> dict:
        """Current/staged model version + stored snapshot versions."""
        networking.send_data(self._sock, {"action": "deploy_status"})
        return networking.recv_data(self._sock)

    def wait_for_swap(self, timeout: float = 10.0,
                      poll: float = 0.02) -> dict:
        """Block until no swap is staged (``deploy_status()``'s
        ``staged_version`` is None — a drain landed, a refill applied)
        and return the final status. Replaces the hand-rolled
        staged-swap polling every deploy test used to write. Raises
        :class:`TimeoutError` with the stuck status when ``timeout``
        elapses first — e.g. a drain-policy swap behind a request that
        never finishes."""
        import time as _time

        deadline = _time.monotonic() + float(timeout)
        while True:
            status = self.deploy_status()
            if status.get("staged_version") is None:
                return status
            if _time.monotonic() >= deadline:
                raise TimeoutError(
                    f"swap still staged after {timeout}s: {status}"
                )
            _time.sleep(poll)

    def set_timeout(self, seconds: float | None) -> None:
        self._sock.settimeout(seconds)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class ResilientGenerationClient:
    """Reconnect-and-retry wrapper over a :class:`GenerationClient`
    factory — the serving sibling of ``ResilientPSClient``. Retryable
    failures (dead server mid-frame, connection refused during a restart,
    :class:`ServerBusyError` backpressure) reconnect under the
    ``RetryPolicy``'s jittered backoff and replay the request; generation
    is a pure request/response, so a replay is safe without seqnos. A
    fixed ``seed`` per request keeps the replayed stream identical."""

    def __init__(self, make_client: Callable[[], GenerationClient],
                 policy=None):
        from distkeras_tpu.resilience.retry import RetryPolicy

        self._make_client = make_client
        self.policy = policy if policy is not None else RetryPolicy()
        self._client = make_client()
        self.retries = 0
        self.reconnects = 0
        self._calls = 0

    def _reconnect(self, attempt: int, exc: BaseException) -> None:
        self.retries += 1
        if isinstance(exc, ServerBusyError):
            return      # server is healthy, just full: keep the connection
        try:
            self._client.close()
        except Exception:
            pass
        try:
            self._client = self._make_client()
            self.reconnects += 1
        except Exception:
            pass        # still down: next attempt fails fast, backs off

    def _run(self, fn):
        self._calls += 1
        return self.policy.run(fn, on_retry=self._reconnect,
                               salt=self._calls)

    def generate(self, prompt, **kw) -> np.ndarray:
        return self._run(lambda: self._client.generate(prompt, **kw))

    def stats(self) -> dict:
        return self._run(lambda: self._client.stats())

    def close(self) -> None:
        self._client.close()
