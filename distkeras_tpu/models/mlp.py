"""Dense MLP family (MNIST-MLP and Higgs-MLP benchmark configs)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.model import ModelSpec, from_flax


class MLP(nn.Module):
    """Flatten → hidden dense+relu stack → logits.

    Compute dtype defaults to bfloat16 (MXU native); params stay float32.
    """

    hidden: Sequence[int] = (500, 300)
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for h in self.hidden:
            x = nn.Dense(h, dtype=self.dtype)(x)
            x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def mlp(input_shape=(28, 28, 1), hidden=(500, 300), num_classes=10,
        dtype=jnp.bfloat16) -> ModelSpec:
    module = MLP(hidden=tuple(hidden), num_classes=num_classes, dtype=dtype)
    example = jnp.zeros((1,) + tuple(input_shape), jnp.float32)
    return from_flax(module, example, name="mlp")
