"""SRU sequence classifier — the recurrence that isn't latency-bound.

SCALING.md's LSTM roofline analysis ends at an irreducible ~21 µs/step
sequential-chain latency: every LSTM timestep needs ``h_{t-1}`` through a
matmul, so a T=200 sequence is 200 dependent MXU dispatches no kernel can
parallelize away — "the leftover levers are architectural (QRNN/SRU-style
recurrences that break the dependency)". This module is that lever.

The Simple Recurrent Unit (Lei et al. 2018, "Simple Recurrent Units for
Highly Parallelizable Recurrence") moves ALL matmuls out of the recurrence:

    x̃_t, f_t, r_t  =  split(x_t @ W)          (one [B·T, E]·[E, 3H] matmul)
    c_t  =  f_t ⊙ c_{t-1} + (1 − f_t) ⊙ x̃_t   (elementwise, linear in c)
    h_t  =  r_t ⊙ g(c_t) + (1 − r_t) ⊙ x_t    (highway output)

The cell update is a FIRST-ORDER LINEAR recurrence, and linear recurrences
compose associatively: ``(f₁,g₁)∘(f₂,g₂) = (f₁f₂, f₂g₁+g₂)``. On TPU that
means ``jax.lax.associative_scan`` evaluates all T steps in O(log T)
parallel depth on the VPU — one fused program, no per-step dispatch, no
h→matmul dependency — while the MXU sees a single big time-parallel
projection. Same classifier interface as ``models.lstm`` (padded tokens +
mask, masked-mean pooling), so it drops into the IMDB BASELINE config
unchanged; measured throughput vs the LSTM is in SCALING.md.

No reference counterpart (the Spark-era reference topped out at a Keras
LSTM — SURVEY.md §2b.2); this is the beyond-parity answer to its slowest
benchmark config rather than a port of anything.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from distkeras_tpu.model import ModelSpec, from_flax


def sru_recurrence(gates, impl: str = "assoc"):
    """Run the SRU cell update over time.

    ``gates``: ``[B, T, 3H]`` packed ``(x̃, pre_f, pre_r)`` projections.
    Returns ``h``-ready pieces ``(c [B,T,H] f32, r [B,T,H] f32)``.

    ``impl="assoc"`` evaluates the linear recurrence with
    ``jax.lax.associative_scan`` (O(log T) depth — the TPU path);
    ``impl="scan"`` is the sequential ``lax.scan`` oracle the tests pin
    against (identical math, different evaluation order).
    """
    H = gates.shape[-1] // 3
    xt = gates[..., :H].astype(jnp.float32)
    f = jax.nn.sigmoid(gates[..., H: 2 * H].astype(jnp.float32))
    r = jax.nn.sigmoid(gates[..., 2 * H:].astype(jnp.float32))
    g = (1.0 - f) * xt  # the additive term of c_t = f·c_{t-1} + g_t

    if impl == "assoc":
        def combine(a, b):
            fa, ga = a
            fb, gb = b
            return fa * fb, fb * ga + gb

        _, c = jax.lax.associative_scan(combine, (f, g), axis=1)
    elif impl == "scan":
        def step(c_prev, fg):
            f_t, g_t = fg
            c_t = f_t * c_prev + g_t
            return c_t, c_t

        f_tm = jnp.moveaxis(f, 1, 0)  # scan over time-major
        g_tm = jnp.moveaxis(g, 1, 0)
        _, c = jax.lax.scan(step, jnp.zeros_like(f[:, 0]), (f_tm, g_tm))
        c = jnp.moveaxis(c, 0, 1)
    else:
        raise ValueError(f"unknown SRU impl {impl!r}; use 'assoc' or 'scan'")
    return c, r


class SRUClassifier(nn.Module):
    """Token sequence → class logits through ``depth`` SRU layers."""

    vocab: int = 20000
    embed_dim: int = 128
    hidden_dim: int = 128
    num_classes: int = 2
    depth: int = 1
    dtype: jnp.dtype = jnp.bfloat16
    impl: str = "assoc"

    @nn.compact
    def __call__(self, tokens, mask=None, training: bool = False):
        if mask is None:
            mask = jnp.ones(tokens.shape, jnp.float32)
        H = self.hidden_dim
        x = nn.Embed(self.vocab, self.embed_dim, dtype=self.dtype)(tokens)
        for layer in range(self.depth):
            # all three gates of every timestep in one MXU matmul
            gates = nn.Dense(3 * H, dtype=self.dtype,
                             name=f"w_{layer}")(x)            # [B, T, 3H]
            c, r = sru_recurrence(gates, impl=self.impl)
            # highway: project x once per layer if widths differ
            skip = x.astype(jnp.float32)
            if skip.shape[-1] != H:
                skip = nn.Dense(H, dtype=self.dtype,
                                name=f"skip_{layer}")(x).astype(jnp.float32)
            h = r * jnp.tanh(c) + (1.0 - r) * skip             # [B, T, H] f32
            x = h.astype(self.dtype)
        m = mask.astype(jnp.float32)[..., None]
        pooled = jnp.sum(x.astype(jnp.float32) * m, axis=1) / jnp.maximum(
            jnp.sum(m, axis=1), 1.0
        )
        logits = nn.Dense(self.num_classes, dtype=self.dtype)(
            pooled.astype(self.dtype)
        )
        return logits.astype(jnp.float32)


def sru_classifier(vocab=20000, maxlen=200, embed_dim=128, hidden_dim=128,
                   num_classes=2, depth=1, dtype=jnp.bfloat16,
                   impl="assoc") -> ModelSpec:
    """Drop-in alternative to :func:`models.lstm.lstm_classifier` whose
    recurrence parallelizes over time (module docstring) — same
    ``(tokens, mask)`` inputs and BASELINE-config column layout."""
    module = SRUClassifier(
        vocab=vocab, embed_dim=embed_dim, hidden_dim=hidden_dim,
        num_classes=num_classes, depth=depth, dtype=dtype, impl=impl,
    )
    example = (
        jnp.zeros((1, maxlen), jnp.int32),
        jnp.ones((1, maxlen), jnp.float32),
    )
    return from_flax(module, example, name="sru_classifier")
