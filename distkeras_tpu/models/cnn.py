"""Convolutional families: LeNet (MNIST north-star) and VGG-small (CIFAR-10).

bf16 activations keep convs on the MXU; pooling/reductions are cheap VPU work.
No batch-norm in these configs (matching the 2016-era reference models), which
also keeps every model in the zoo stateless — simpler SPMD state.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.model import ModelSpec, from_flax


class LeNet(nn.Module):
    """LeNet-style MNIST CNN (BASELINE config 2, the ADAG north-star model)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(256, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class VGGSmall(nn.Module):
    """VGG-small for CIFAR-10 (BASELINE config 3): 3 conv blocks + 2 dense."""

    num_classes: int = 10
    widths: tuple = (64, 128, 256)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = x.astype(self.dtype)
        for w in self.widths:
            x = nn.Conv(w, (3, 3), padding="SAME", dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.Conv(w, (3, 3), padding="SAME", dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(512, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def lenet(input_shape=(28, 28, 1), num_classes=10, dtype=jnp.bfloat16) -> ModelSpec:
    module = LeNet(num_classes=num_classes, dtype=dtype)
    example = jnp.zeros((1,) + tuple(input_shape), jnp.float32)
    return from_flax(module, example, name="lenet")


def vgg_small(input_shape=(32, 32, 3), num_classes=10, dtype=jnp.bfloat16) -> ModelSpec:
    module = VGGSmall(num_classes=num_classes, dtype=dtype)
    example = jnp.zeros((1,) + tuple(input_shape), jnp.float32)
    return from_flax(module, example, name="vgg_small")
