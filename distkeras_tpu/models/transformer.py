"""Transformer encoder classifier — the long-context model family.

Beyond-reference addition (the Spark-era reference's newest model was an
LSTM): a pre-norm transformer encoder whose attention runs through the same
math as :mod:`distkeras_tpu.parallel.sequence` — single-device training uses
:func:`attention_reference`, and the identical per-head computation can be
executed sequence-parallel with :func:`ring_attention` on a mesh (equality is
pinned by tests/test_sequence_parallel.py). bf16 activations keep the QKV/MLP
matmuls on the MXU; all control flow is static for XLA.
"""

from __future__ import annotations

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.model import ModelSpec, from_flax
from distkeras_tpu.parallel.mesh import put_global
from distkeras_tpu.parallel.sequence import attention_reference


def sincos_positions(maxlen: int, dim: int) -> np.ndarray:
    """Fixed sinusoidal position table [maxlen, dim] (Vaswani et al. 2017)."""
    pos = np.arange(maxlen)[:, None]
    i = np.arange(dim // 2)[None, :]
    angle = pos / np.power(10000.0, 2 * i / dim)
    table = np.zeros((maxlen, dim), np.float32)
    table[:, 0::2] = np.sin(angle)
    table[:, 1::2] = np.cos(angle)
    return table


def attention_sublayer(x, mask, *, dim, heads, causal, dtype,
                       attn_impl: str = "reference",
                       sp_axis: str | None = None, sp_size: int | None = None,
                       attn_window: int | None = None):
    """Pre-norm self-attention + residual, shared by the dense and MoE
    encoder blocks (must be called from a compact ``__call__``).

    Layer names are load-bearing: parallel.tensor.megatron_specs shards
    qkv/mlp_up column-wise and attn_out/mlp_down row-wise over 'tp'.
    ``attn_impl``: "reference" (XLA einsums), "flash" (the Pallas kernel in
    ops.flash_attention), "auto" (kernel when shapes are tile-friendly), or
    "ring" (sequence-parallel ring attention — only valid when the caller is
    already inside ``shard_map`` over mesh axis ``sp_axis`` of size
    ``sp_size``, with ``x``/``mask`` holding this shard's sequence slice).
    ``attn_window``: sliding-window (local) attention span — on the flash
    path the kernel only visits in-band tiles, so long-context compute
    scales as O(L·window).
    """
    B, L, _ = x.shape
    h = nn.LayerNorm(dtype=jnp.float32, name="ln_attn")(x)
    qkv = nn.Dense(3 * dim, dtype=dtype, name="qkv")(h.astype(dtype))
    q, k, v = jnp.split(qkv, 3, axis=-1)
    shape = (B, L, heads, dim // heads)
    q, k, v = (t.reshape(shape) for t in (q, k, v))
    if attn_impl == "ring":
        from distkeras_tpu.parallel.sequence import ring_attention_shard

        window = attn_window
        if window is not None and window >= sp_size * L:
            window = None  # band covers the whole (global) sequence
        # no f32 pre-cast: the ring body casts per block internally, and
        # rotating K/V in bf16 halves the per-step ICI payload; under a
        # window the ring only rotates through the band's blocks
        att = ring_attention_shard(
            q, k, v, mask,
            axis_name=sp_axis, axis_size=sp_size, causal=causal,
            scale=(dim // heads) ** -0.5, window=window,
        )
    elif attn_impl == "reference":
        att = attention_reference(q, k, v, causal=causal, key_mask=mask,
                                  window=attn_window)
    else:
        from distkeras_tpu.ops.flash_attention import attention

        att = attention(q, k, v, causal=causal, key_mask=mask,
                        impl=attn_impl, window=attn_window)
    att = att.reshape(B, L, dim)
    return x + nn.Dense(dim, dtype=dtype, name="attn_out")(
        att.astype(dtype)
    ).astype(jnp.float32)


class EncoderBlock(nn.Module):
    dim: int
    heads: int
    mlp_ratio: int = 4
    causal: bool = False
    dtype: jnp.dtype = jnp.bfloat16
    attn_impl: str = "reference"
    sp_axis: str | None = None   # set (with sp_size) for attn_impl="ring"
    sp_size: int | None = None
    attn_window: int | None = None  # sliding-window (local) attention span

    @nn.compact
    def __call__(self, x, mask=None, training: bool = False):
        x = attention_sublayer(x, mask, dim=self.dim, heads=self.heads,
                               causal=self.causal, dtype=self.dtype,
                               attn_impl=self.attn_impl,
                               sp_axis=self.sp_axis, sp_size=self.sp_size,
                               attn_window=self.attn_window)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_mlp")(x)
        h = nn.Dense(self.mlp_ratio * self.dim, dtype=self.dtype,
                     name="mlp_up")(h.astype(self.dtype))
        h = nn.gelu(h)
        h = nn.Dense(self.dim, dtype=self.dtype, name="mlp_down")(h)
        return x + h.astype(jnp.float32)


class TransformerClassifier(nn.Module):
    """Token sequence → class logits (IMDB-style inputs: tokens + mask).

    Setup-style so the encoder stack is addressable piecewise: the
    ``embed_tokens`` / ``head_logits`` methods and the per-block params
    (``blocks_0 … blocks_{depth-1}``) let
    :func:`pipelined_transformer_forward` run the homogeneous block stack
    pipeline-parallel over a ``pp`` mesh axis while embed/head stay
    replicated.
    """

    vocab: int = 20000
    maxlen: int = 200
    dim: int = 128
    heads: int = 4
    depth: int = 2
    num_classes: int = 2
    causal: bool = False
    dtype: jnp.dtype = jnp.bfloat16
    attn_impl: str = "reference"
    sp_axis: str | None = None   # set (with sp_size) for attn_impl="ring"
    sp_size: int | None = None
    attn_window: int | None = None  # sliding-window (local) attention span
    #: rematerialize each block's activations in the backward pass
    #: (jax.checkpoint): ~L·dim per block of saved activations traded for
    #: one extra forward — the standard long-context memory lever
    remat: bool = False

    def setup(self):
        self.embed = nn.Embed(self.vocab, self.dim, dtype=self.dtype)
        # nn.remat preserves the params tree (blocks_i names unchanged), so
        # checkpoints/megatron specs/pipelining all work regardless of remat;
        # training (arg 3, counting self as 0) is a static python bool
        block_cls = (nn.remat(EncoderBlock, static_argnums=(3,))
                     if self.remat else EncoderBlock)
        self.blocks = [
            block_cls(dim=self.dim, heads=self.heads, causal=self.causal,
                      dtype=self.dtype, attn_impl=self.attn_impl,
                      sp_axis=self.sp_axis, sp_size=self.sp_size,
                      attn_window=self.attn_window)
            for _ in range(self.depth)
        ]
        self.ln_head = nn.LayerNorm(dtype=jnp.float32)
        self.head = nn.Dense(self.num_classes, dtype=self.dtype)

    def embed_tokens(self, tokens):
        x = self.embed(tokens)
        table = jnp.asarray(sincos_positions(self.maxlen, self.dim))
        if self.sp_axis is not None:
            # this shard holds sequence positions [off, off + L_local)
            off = jax.lax.axis_index(self.sp_axis) * tokens.shape[1]
            pos = jax.lax.dynamic_slice(
                table, (off, 0), (tokens.shape[1], self.dim)
            )
        else:
            pos = table[: tokens.shape[1]]
        return x.astype(jnp.float32) + pos[None]

    def head_logits(self, x, mask):
        m = mask.astype(jnp.float32)[..., None]
        num = jnp.sum(x * m, axis=1)
        den = jnp.sum(m, axis=1)
        if self.sp_axis is not None:
            # masked mean over the FULL sequence: combine shard partials
            num = jax.lax.psum(num, self.sp_axis)
            den = jax.lax.psum(den, self.sp_axis)
        pooled = num / jnp.maximum(den, 1.0)
        h = self.ln_head(pooled)
        return self.head(h.astype(self.dtype)).astype(jnp.float32)

    def __call__(self, tokens, mask=None, training: bool = False):
        if mask is None:
            mask = jnp.ones(tokens.shape, jnp.float32)
        x = self.embed_tokens(tokens)
        for blk in self.blocks:
            x = blk(x, mask, training)
        return self.head_logits(x, mask)


def pipelined_transformer_forward(module: TransformerClassifier, params,
                                  tokens, mask, mesh, axis: str = "pp",
                                  microbatches: int | None = None,
                                  batch_axis: str | None = None):
    """Transformer forward with the encoder blocks pipelined over ``axis``.

    Embed and head run replicated; the ``depth`` homogeneous blocks are the
    pipeline stages (``depth == mesh.shape[axis]`` required). Numerically
    equal to ``module.apply`` (pinned by tests/test_pipeline_parallel.py) and
    differentiable, so a full training step can run pipeline-parallel.
    """
    from distkeras_tpu.parallel.pipeline import (
        pipeline_apply,
        stack_stage_params,
    )

    if module.depth != mesh.shape[axis]:
        raise ValueError(
            f"depth {module.depth} != mesh axis '{axis}' size "
            f"{mesh.shape[axis]}"
        )
    if mask is None:
        mask = jnp.ones(tokens.shape, jnp.float32)
    x = module.apply({"params": params}, tokens,
                     method=TransformerClassifier.embed_tokens)
    stage_params = stack_stage_params(
        [params[f"blocks_{i}"] for i in range(module.depth)]
    )
    impl = "reference" if module.attn_impl == "ring" else module.attn_impl
    block = EncoderBlock(dim=module.dim, heads=module.heads,
                         causal=module.causal, dtype=module.dtype,
                         attn_impl=impl, attn_window=module.attn_window)

    def stage(p, act):
        h, m = act
        return block.apply({"params": p}, h, m, False), m

    x, _ = pipeline_apply(stage, stage_params, (x, mask), mesh, axis=axis,
                          microbatches=microbatches, batch_axis=batch_axis)
    return module.apply({"params": params}, x, mask,
                        method=TransformerClassifier.head_logits)


def sequence_parallel_transformer_forward(module: TransformerClassifier,
                                          params, tokens, mask, mesh,
                                          axis: str = "sp",
                                          batch_axis: str | None = None):
    """Full transformer forward with activations sharded along L over ``axis``.

    One ``shard_map`` program: every pointwise layer (embed lookup, layernorm,
    QKV/MLP matmuls) runs on its shard's sequence slice, attention is the
    ring-rotation body from :mod:`distkeras_tpu.parallel.sequence`
    (``ppermute`` K/V/mask exchanges over ICI), position embeddings are
    offset per shard, and the masked-mean head combines shard partials with
    ``psum``. Per-chip activation memory is O(L/N) — context length scales
    linearly with the mesh. Numerically equal to ``module.apply`` on the
    gathered sequence (pinned by tests/test_sequence_parallel.py) and
    differentiable, so full training steps run sequence-parallel.

    ``batch_axis`` composes data parallelism on a 2-D mesh (e.g.
    ``get_mesh_nd({"dp": 2, "sp": 4})``): the batch dimension shards over
    ``batch_axis``, the sequence over ``axis``, and the returned logits are
    sharded over ``batch_axis`` — a dp×sp training step when differentiated
    (the batch-mean loss's gradient psum over dp is inserted by GSPMD).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    N = mesh.shape[axis]
    L = tokens.shape[1]
    if L % N:
        raise ValueError(f"sequence length {L} not divisible by mesh axis "
                         f"'{axis}' of size {N}")
    if L > module.maxlen:
        raise ValueError(
            f"sequence length {L} exceeds the model's maxlen "
            f"{module.maxlen} (the plain forward would fail too)"
        )
    if batch_axis is not None and tokens.shape[0] % mesh.shape[batch_axis]:
        raise ValueError(
            f"batch {tokens.shape[0]} not divisible by mesh axis "
            f"'{batch_axis}' of size {mesh.shape[batch_axis]}"
        )
    if mask is None:
        mask = jnp.ones(tokens.shape, jnp.float32)
    shard_fn = _sp_forward_fn(
        module.clone(attn_impl="ring", sp_axis=axis, sp_size=N), mesh, axis,
        batch_axis,
    )
    sh = NamedSharding(mesh, P(batch_axis, axis))
    tokens = put_global(tokens, sh)
    mask = put_global(mask, sh)
    return shard_fn(params, tokens, mask)


@functools.lru_cache(maxsize=32)
def _sp_forward_fn(smod, mesh, axis, batch_axis=None):
    """Build + jit the shard_map'd SP forward once per
    (module, mesh, axis, batch_axis);
    flax modules are frozen dataclasses, so they key the cache by config.
    Without this every call would rebuild shard_map and recompile."""
    from jax.sharding import PartitionSpec as P

    def body(params, toks_l, mask_l):
        return smod.apply({"params": params}, toks_l, mask_l, False)

    io = P(batch_axis, axis)
    # P() is a pytree PREFIX: it broadcasts over the whole params tree
    return jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(), io, io),
        out_specs=P(batch_axis),
        check_vma=False,
    ))


def transformer_classifier(vocab=20000, maxlen=200, dim=128, heads=4, depth=2,
                           num_classes=2, causal=False,
                           dtype=jnp.bfloat16,
                           attn_impl="reference",
                           remat=False,
                           attn_window=None) -> ModelSpec:
    module = TransformerClassifier(
        vocab=vocab, maxlen=maxlen, dim=dim, heads=heads, depth=depth,
        num_classes=num_classes, causal=causal, dtype=dtype,
        attn_impl=attn_impl, remat=remat, attn_window=attn_window,
    )
    example = (
        jnp.zeros((1, maxlen), jnp.int32),
        jnp.ones((1, maxlen), jnp.float32),
    )
    return from_flax(module, example, name="transformer_classifier")
