"""Decoder-only causal language model + TPU-idiomatic autoregressive decoding.

Beyond-reference model family (the Spark-era reference topped out at an LSTM
classifier — SURVEY.md §2b.2 "reference predates long-context"): a pre-norm
causal transformer LM trainable by every trainer in this framework (the
next-token objective is plain ``sparse_softmax_cross_entropy`` on the
``[B, L, V]`` logits against the shifted token labels), plus a
:func:`generate` path built the TPU way:

- **Static shapes everywhere**: the prompt is one fixed-length prefill, the
  KV cache is a preallocated ``[B, maxlen, Hkv, Dh]`` buffer per block
  (``Hkv = kv_heads`` under grouped-query attention, else ``heads``)
  updated with ``lax.dynamic_update_slice``, and the decode loop is a
  single ``lax.scan`` over ``max_new_tokens`` steps — one XLA compilation,
  no per-token Python.
- **MXU-friendly**: cache and activations live in the model dtype (bf16 on
  TPU); attention math accumulates in f32 like the training path.
- The per-block parameter names (``qkv``/``attn_out``/``mlp_up``/
  ``mlp_down``) match the encoder family, so ``parallel.tensor``'s Megatron
  sharding rules apply unchanged and ``MeshTrainer`` trains the LM with any
  ``parameter_sharding``.
"""

from __future__ import annotations

import dataclasses
import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.model import ModelSpec, from_flax
from distkeras_tpu.models.transformer import sincos_positions
from distkeras_tpu.parallel.sequence import attention_reference


def rope_angles(maxlen: int, head_dim: int, base: float = 10000.0):
    """Rotary position-embedding angle table ``[maxlen, head_dim // 2]``
    (Su et al. 2021): position ``p`` rotates feature pair ``i`` by
    ``p · base^(-2i/head_dim)``."""
    inv = base ** (-np.arange(0, head_dim, 2) / head_dim)
    return (np.arange(maxlen)[:, None] * inv[None, :]).astype(np.float32)


def apply_rope(x, angles):
    """Rotate feature pairs of ``x`` [..., L, H, Dh] by per-position
    ``angles`` [L, Dh//2] (pairing (x[2i], x[2i+1]), rotation in f32, cast
    back to x.dtype). ``angles`` may also be ``[B, L, Dh//2]`` — the paged
    decode path, where every row sits at its own absolute position."""
    f32 = x.astype(jnp.float32)
    x1, x2 = f32[..., 0::2], f32[..., 1::2]
    # angles broadcast over batch and heads: [L, Dh/2] → [L, 1, Dh/2]
    # (or [B, L, Dh/2] → [B, L, 1, Dh/2] for per-row positions)
    cos = jnp.cos(angles)[..., None, :]
    sin = jnp.sin(angles)[..., None, :]
    r1 = x1 * cos - x2 * sin
    r2 = x1 * sin + x2 * cos
    out = jnp.stack([r1, r2], axis=-1).reshape(f32.shape)
    return out.astype(x.dtype)


class QDense(nn.Module):
    """Dense over an int8 weight-only-quantized kernel (``ops.quant``).

    Param set: ``kernel_q`` int8 ``[in, features]``, per-output-channel
    ``scale`` f32, ``bias`` in the activation dtype — exactly what
    :func:`distkeras_tpu.ops.quant.quantize_dense_tree` produces from a
    trained ``nn.Dense`` subtree. The matmul streams int8 from HBM and
    dequantizes in VMEM (Pallas), which is the decode bandwidth win.
    """

    features: int
    dtype: jnp.dtype = jnp.bfloat16
    impl: str = "auto"

    @nn.compact
    def __call__(self, x):
        from distkeras_tpu.ops.quant import QTensor, q_matmul

        k = x.shape[-1]
        q = self.param("kernel_q", nn.initializers.zeros,
                       (k, self.features), jnp.int8)
        s = self.param("scale", nn.initializers.ones,
                       (self.features,), jnp.float32)
        b = self.param("bias", nn.initializers.zeros,
                       (self.features,), self.dtype)
        out = q_matmul(x, QTensor(q, s), impl=self.impl, out_dtype=x.dtype)
        # trained biases arrive f32 (flax master params); add in the
        # activation dtype like nn.Dense(dtype=...) does — a bare f32 add
        # would silently promote the whole downstream block to f32
        return out + b.astype(out.dtype)


class DecoderBlock(nn.Module):
    """Pre-norm causal block with three entry points sharing one parameter
    set: ``__call__`` (training / full forward), ``prefill`` (full forward
    that also returns this block's K/V for the cache), and ``step`` (one
    decode position against the cache)."""

    dim: int
    heads: int
    mlp_ratio: int = 4
    dtype: jnp.dtype = jnp.bfloat16
    attn_impl: str = "reference"
    attn_window: int | None = None  # sliding-window (local) attention span
    #: grouped-query attention: number of shared K/V heads (None = heads,
    #: i.e. standard MHA; 1 = MQA). Query head h reads K/V head h // group.
    #: The KV cache shrinks heads/kv_heads ×, the decode win GQA exists for.
    kv_heads: int | None = None
    #: rotary position embeddings: rotate q/k at projection time (the cache
    #: stores PRE-ROTATED keys); ``maxlen`` bounds the decode angle table
    rope: bool = False
    maxlen: int = 0
    #: int8 weight-only serving: every Dense becomes a QDense (params from
    #: quantize_lm); architecture and entry points are otherwise identical
    quant: bool = False

    @property
    def _hkv(self) -> int:
        return self.kv_heads if self.kv_heads is not None else self.heads

    def _rope_qk(self, q, k, pos):
        """Rotate q and k for RoPE. ``pos`` is the first position the inputs
        occupy: 0 with a static length-L forward, a traced scalar with the
        single-position decode step."""
        if not self.rope:
            return q, k
        dh = self.dim // self.heads
        L = q.shape[1]
        if isinstance(pos, int) and pos == 0:
            angles = jnp.asarray(rope_angles(L, dh))
        else:
            table = jnp.asarray(rope_angles(self.maxlen, dh))
            angles = jax.lax.dynamic_slice(table, (pos, 0), (L, dh // 2))
        return apply_rope(q, angles), apply_rope(k, angles)

    def setup(self):
        if self.rope and self.maxlen < 1:
            raise ValueError(
                "DecoderBlock(rope=True) needs maxlen >= 1 for the decode "
                "angle table (TransformerLM passes its own maxlen)"
            )
        f32 = jnp.float32
        dh = self.dim // self.heads
        dense = QDense if self.quant else nn.Dense
        self.ln_attn = nn.LayerNorm(dtype=f32)
        # one fused projection, width (H + 2·Hkv)·Dh; splitting at H·Dh /
        # (H+Hkv)·Dh reduces to the classic thirds split when Hkv == H, so
        # MHA checkpoints/params are unchanged by the GQA seam
        self.qkv = dense((self.heads + 2 * self._hkv) * dh,
                         dtype=self.dtype)
        self.attn_out = dense(self.dim, dtype=self.dtype)
        self.ln_mlp = nn.LayerNorm(dtype=f32)
        self.mlp_up = dense(self.mlp_ratio * self.dim, dtype=self.dtype)
        self.mlp_down = dense(self.dim, dtype=self.dtype)

    def _project_qkv(self, x):
        """→ q [B, L, H, Dh], k/v [B, L, Hkv, Dh]."""
        B, L, _ = x.shape
        dh = self.dim // self.heads
        hkv = self._hkv
        h = self.ln_attn(x)
        qkv = self.qkv(h.astype(self.dtype))
        q = qkv[..., : self.heads * dh].reshape(B, L, self.heads, dh)
        k = qkv[..., self.heads * dh: (self.heads + hkv) * dh]
        v = qkv[..., (self.heads + hkv) * dh:]
        return q, k.reshape(B, L, hkv, dh), v.reshape(B, L, hkv, dh)

    def _mlp(self, x):
        h = self.ln_mlp(x)
        h = self.mlp_up(h.astype(self.dtype))
        h = nn.gelu(h)
        h = self.mlp_down(h)
        return x + h.astype(jnp.float32)

    def _attn_full(self, x, mask):
        B, L, _ = x.shape
        q, k, v = self._project_qkv(x)
        q, k = self._rope_qk(q, k, 0)   # k rotated BEFORE caching
        # GQA needs no expansion: both attention paths read the shared Hkv
        # heads directly (the flash kernels via index maps — no repeated-KV
        # tensor is ever materialized)
        if self.attn_impl == "reference":
            att = attention_reference(q, k, v, causal=True, key_mask=mask,
                                      window=self.attn_window)
        else:
            from distkeras_tpu.ops.flash_attention import attention

            # "flash" means "auto" here: decode prompts are ragged by
            # nature, so a hard-forced kernel would reject prefill lengths
            # that aren't tile multiples; training shapes (maxlen-derived)
            # stay tile-friendly and keep the kernel
            impl = "auto" if self.attn_impl == "flash" else self.attn_impl
            att = attention(q, k, v, causal=True, key_mask=mask,
                            impl=impl, window=self.attn_window)
        att = att.reshape(B, L, self.dim)
        x = x + self.attn_out(att.astype(self.dtype)).astype(jnp.float32)
        return x, k, v

    def __call__(self, x, mask=None, training: bool = False):
        x, _, _ = self._attn_full(x, mask)
        return self._mlp(x)

    def prefill(self, x, mask=None):
        x, k, v = self._attn_full(x, mask)
        return self._mlp(x), k, v

    def step(self, x_t, k_cache, v_cache, pos):
        """One decode position. ``x_t``: [B, 1, dim] residual stream;
        ``k_cache``/``v_cache``: [B, cache_len, Hkv, Dh]; ``pos`` may be a
        traced scalar. ``cache_len`` is ``maxlen`` normally, or ``window``
        for sliding-window models — then the cache is a RING: position
        ``p`` lives in slot ``p % window`` (decode reads ``window``, not
        ``maxlen``, keys per step — the bandwidth the window promises)."""
        cache_len = k_cache.shape[1]
        if cache_len >= self.maxlen:
            # the non-ring step IS the T=1 multi-token pass; one shared
            # body keeps cached decode and the speculative verify forward
            # (extend) from ever drifting apart
            return self.extend(x_t, k_cache, v_cache, pos)
        q, k, v = self._project_qkv(x_t)  # q [B,1,H,Dh]; k/v [B,1,Hkv,Dh]
        q, k = self._rope_qk(q, k, pos)   # cache holds pre-rotated keys
        slot = pos % cache_len
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, slot, 0, 0)
        )
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, slot, 0, 0)
        )
        B = x_t.shape[0]
        dh = self.dim // self.heads
        hkv = self._hkv
        group = self.heads // hkv
        # same dtype path as attention_reference (parallel/sequence.py:39-52)
        # so cached decode is bit-compatible with the full forward in bf16:
        # q·k in model dtype, softmax in f32, p·v back in model dtype.
        # GQA: the [H] head axis factors as [Hkv, group] (group-major match
        # with the kernels' index maps); the cache stays Hkv-wide.
        qg = q.reshape(B, 1, hkv, group, dh)
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k_cache) \
            .astype(jnp.float32) * (dh ** -0.5)
        kp = jnp.arange(cache_len)
        # slot s holds absolute position pos - ((pos - s) % window),
        # automatically causal and in-band; only never-written slots
        # (absolute < 0, early decode) need masking
        valid = pos - ((pos - kp) % cache_len) >= 0
        s = jnp.where(valid[None, None, None, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        att = jnp.einsum(
            "bhgqk,bkhd->bqhgd", p.astype(v_cache.dtype), v_cache
        )
        att = att.reshape(B, 1, self.dim)
        x_t = x_t + self.attn_out(att.astype(self.dtype)).astype(jnp.float32)
        return self._mlp(x_t), k_cache, v_cache

    def extend(self, x, k_cache, v_cache, pos0):
        """``T`` consecutive decode positions in one pass: ``x`` [B, T, dim]
        residual stream occupying absolute positions ``pos0 .. pos0+T-1``
        (``pos0`` may be a traced scalar). Cache entries for those positions
        are written and each query attends causally to every cached position
        ≤ its own — the multi-token sibling of :meth:`step`, and speculative
        decoding's verify forward (T candidate tokens scored against the
        cache in one batched matmul instead of T sequential steps). Ring
        (sliding-window) caches are not supported — a wrapped
        ``dynamic_update_slice`` cannot write a contiguous span."""
        B, T, _ = x.shape
        cache_len = k_cache.shape[1]
        if cache_len < self.maxlen:
            raise ValueError(
                "extend() needs a full-length cache; sliding-window models "
                "use a ring cache that cannot take a contiguous span write"
            )
        q, k, v = self._project_qkv(x)
        q, k = self._rope_qk(q, k, pos0)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, pos0, 0, 0)
        )
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, pos0, 0, 0)
        )
        dh = self.dim // self.heads
        hkv = self._hkv
        group = self.heads // hkv
        # same dtype/GQA discipline as step(): q·k in model dtype, softmax
        # f32, p·v in model dtype; the [H] axis factors as [Hkv, group]
        qg = q.reshape(B, T, hkv, group, dh)
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k_cache) \
            .astype(jnp.float32) * (dh ** -0.5)
        kp = jnp.arange(cache_len)[None, :]
        qp = pos0 + jnp.arange(T)[:, None]
        valid = kp <= qp                          # causal: cache ≤ own pos
        if self.attn_window is not None:
            valid &= qp - kp < self.attn_window
        s = jnp.where(valid[None, None, None, :, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        att = jnp.einsum(
            "bhgqk,bkhd->bqhgd", p.astype(v_cache.dtype), v_cache
        )
        att = att.reshape(B, T, self.dim)
        x = x + self.attn_out(att.astype(self.dtype)).astype(jnp.float32)
        return self._mlp(x), k_cache, v_cache

    def paged_extend(self, x, k_pool, v_pool, tables, write_slots,
                     positions, block_size: int):
        """``T`` decode positions per row against a BLOCK-PAGED cache — the
        serving tier's generalization of :meth:`extend`'s addressing:
        instead of one ``[B, cache_len]`` buffer per sequence, all
        sequences share a flat slot pool ``[S, Hkv, Dh]`` (``S =
        num_blocks · block_size``) and a per-row **block table**
        ``tables`` [B, nb] maps logical block ``t // block_size`` of row
        ``b`` to pool block ``tables[b, t // bs]`` (generalizing the ring
        cache's ``slot = pos % cache_len`` to table indexing). Every row
        sits at its OWN absolute position: row ``b``'s ``T`` tokens occupy
        ``positions[b] .. positions[b]+T-1`` and are written to flat pool
        slots ``write_slots[b]`` ([B, T], precomputed by the caller —
        shared across layers, so it is computed once per step, not per
        block). ``block_size`` must be a static Python int.

        Math is the :meth:`extend` body unchanged (q·k in model dtype,
        softmax f32, p·v in model dtype; GQA head-axis factoring): the
        gather reconstructs each row's logical ``[nb·bs, Hkv, Dh]`` cache
        exactly — at BLOCK granularity (``B·nb`` contiguous
        ``block_size``-row chunks, not ``B·L`` scalar rows: gather cost on
        CPU/TPU tracks the index count, and this is the difference between
        the paged step tracking the dense step's cost or trailing it) —
        and unwritten slots are masked by the per-row causal validity
        ``kp <= positions[b]+t``, so paged decode is bit-identical to
        dense-cache decode: the parity oracle in tests/test_serving.py.
        Sliding windows keep their band mask."""
        B, T, _ = x.shape
        bs = int(block_size)
        nb = tables.shape[1]
        L = nb * bs
        q, k, v = self._project_qkv(x)
        if self.rope:
            dh = self.dim // self.heads
            table = jnp.asarray(rope_angles(self.maxlen, dh))
            # per-row angle rows [B, T, Dh/2] — same table rows the dense
            # step slices at its (shared) scalar position
            angles = table[positions[:, None] + jnp.arange(T)[None, :]]
            q = apply_rope(q, angles)
            k = apply_rope(k, angles)
        k_pool = k_pool.at[write_slots].set(k.astype(k_pool.dtype))
        v_pool = v_pool.at[write_slots].set(v.astype(v_pool.dtype))
        hkv_, dh_ = k_pool.shape[1], k_pool.shape[2]
        kb = k_pool.reshape(-1, bs, hkv_, dh_)[tables]   # [B, nb, bs, ...]
        vb = v_pool.reshape(-1, bs, hkv_, dh_)[tables]
        k_seq = kb.reshape(B, L, hkv_, dh_)              # [B, L, Hkv, Dh]
        v_seq = vb.reshape(B, L, hkv_, dh_)
        dh = self.dim // self.heads
        hkv = self._hkv
        group = self.heads // hkv
        qg = q.reshape(B, T, hkv, group, dh)
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k_seq) \
            .astype(jnp.float32) * (dh ** -0.5)
        kp = jnp.arange(L)[None, None, :]
        qp = (positions[:, None] + jnp.arange(T)[None, :])[:, :, None]
        valid = kp <= qp                  # per-row causal; unwritten slots
        if self.attn_window is not None:  # (kp > qp) are masked here too
            valid &= qp - kp < self.attn_window
        s = jnp.where(valid[:, None, None, :, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        att = jnp.einsum(
            "bhgqk,bkhd->bqhgd", p.astype(v_seq.dtype), v_seq
        )
        att = att.reshape(B, T, self.dim)
        x = x + self.attn_out(att.astype(self.dtype)).astype(jnp.float32)
        return self._mlp(x), k_pool, v_pool


class TransformerLM(nn.Module):
    """Token sequence → next-token logits ``[B, L, vocab]`` (training), with
    ``prefill``/``decode_step`` methods for cached autoregressive decoding."""

    vocab: int = 1024
    maxlen: int = 256
    dim: int = 128
    heads: int = 4
    depth: int = 2
    dtype: jnp.dtype = jnp.bfloat16
    attn_impl: str = "reference"
    attn_window: int | None = None  # sliding-window (local) attention span
    kv_heads: int | None = None     # GQA shared K/V heads (1 = MQA)
    #: "sincos" (additive table at the embedding, Vaswani et al.) or "rope"
    #: (rotary q/k rotations in every block, Su et al. — relative positions,
    #: nothing added to the residual stream)
    pos_embedding: str = "sincos"
    #: int8 weight-only serving mode — see :func:`quantize_lm`
    quant: bool = False
    #: rematerialize each block in the backward pass (jax.checkpoint) —
    #: the long-context training memory lever, same as the encoder family;
    #: decode entry points (prefill/step) are never differentiated and
    #: stay unwrapped
    remat: bool = False
    #: share the token embedding with the output head (Press & Wolf 2017):
    #: logits = hidden @ embedding.T — V·dim fewer parameters, and the
    #: embedding receives both input- and output-side gradients
    tie_embeddings: bool = False

    def setup(self):
        if self.kv_heads is not None and self.heads % self.kv_heads:
            raise ValueError(
                f"heads {self.heads} must be a multiple of kv_heads "
                f"{self.kv_heads}"
            )
        if self.pos_embedding not in ("sincos", "rope"):
            raise ValueError(
                f"unknown pos_embedding {self.pos_embedding!r}; use "
                f"'sincos' or 'rope'"
            )
        if self.pos_embedding == "rope" and (self.dim // self.heads) % 2:
            raise ValueError(
                f"RoPE needs an even head dim, got dim//heads = "
                f"{self.dim // self.heads}"
            )
        self.embed = nn.Embed(self.vocab, self.dim, dtype=self.dtype)
        # nn.remat preserves the params tree (blocks_i names unchanged) and
        # transforms __call__ only — prefill/step run through the same
        # parameters un-rematted, which is exactly right for decode
        block_cls = (nn.remat(DecoderBlock, static_argnums=(3,))
                     if self.remat else DecoderBlock)
        self.blocks = [
            block_cls(dim=self.dim, heads=self.heads, dtype=self.dtype,
                      attn_impl=self.attn_impl,
                      attn_window=self.attn_window,
                      kv_heads=self.kv_heads,
                      rope=self.pos_embedding == "rope",
                      maxlen=self.maxlen,
                      quant=self.quant)
            for _ in range(self.depth)
        ]
        self.ln_head = nn.LayerNorm(dtype=jnp.float32)
        if not self.tie_embeddings:
            head = QDense if self.quant else nn.Dense
            self.lm_head = head(self.vocab, dtype=self.dtype)

    def _embed_at(self, tokens, pos0: int | jax.Array = 0):
        """Embed ``tokens`` occupying positions ``pos0 .. pos0+L``."""
        x = self.embed(tokens).astype(jnp.float32)
        if self.pos_embedding == "rope":
            return x  # positions enter through the per-block q/k rotations
        table = jnp.asarray(sincos_positions(self.maxlen, self.dim))
        pos = jax.lax.dynamic_slice(
            table, (pos0, 0), (tokens.shape[1], self.dim)
        )
        return x + pos[None]

    def _head(self, h):
        """Output projection over post-``ln_head`` hiddens — the ONE place
        the head cast discipline lives (bf16 matmul, f32 logits); shared by
        training, prefill, and decode so the paths cannot drift. Tied mode
        contracts against the embedding table (``nn.Embed.attend``)."""
        h16 = h.astype(self.dtype)
        if self.tie_embeddings:
            return self.embed.attend(h16).astype(jnp.float32)
        return self.lm_head(h16).astype(jnp.float32)

    def _logits(self, x):
        return self._head(self.ln_head(x))

    def __call__(self, tokens, mask=None, training: bool = False):
        # one forward definition: the unfused path is exactly hidden() + the
        # head matmul, so the fused_ce loss can never drift from training's
        return self._head(self.hidden(tokens, mask, training))

    def hidden(self, tokens, mask=None, training: bool = False):
        """Final pre-head hidden states ``[B, L, dim]`` (after the head
        LayerNorm, f32) — the ``fused_ce`` loss path consumes these and
        applies ``lm_head`` chunk-by-chunk, so the ``[B, L, vocab]`` logits
        tensor never materializes (``ops/fused_ce.py``)."""
        x = self._embed_at(tokens)
        for blk in self.blocks:
            x = blk(x, mask, training)
        return self.ln_head(x)

    def prefill(self, tokens):
        """Full forward over the prompt; returns ``(logits, caches)`` with
        per-block K/V buffers holding positions ``< L``. Cache length is
        ``maxlen``, or ``attn_window`` for sliding-window models — then the
        buffer is a ring (slot ``p % window``) seeded with the last
        ``window`` prompt positions; decode never reads beyond the band, so
        nothing else is needed."""
        B, L = tokens.shape
        dh = self.dim // self.heads
        hkv = self.kv_heads if self.kv_heads is not None else self.heads
        cache_len = self.maxlen
        if self.attn_window is not None:
            cache_len = min(self.maxlen, int(self.attn_window))
        x = self._embed_at(tokens)
        caches = []
        ring_pos = None
        if cache_len < self.maxlen:
            slots = jnp.arange(cache_len)
            # absolute position living in each slot after prefill; negative
            # ⇒ never written, masked by step()'s validity
            ring_pos = (L - 1) - ((L - 1 - slots) % cache_len)
        for blk in self.blocks:
            x, k, v = blk.prefill(x, None)   # k/v hold Hkv heads under GQA
            if ring_pos is not None:
                kc = jnp.take(k, jnp.maximum(ring_pos, 0), axis=1)
                vc = jnp.take(v, jnp.maximum(ring_pos, 0), axis=1)
                caches.append((kc.astype(self.dtype),
                               vc.astype(self.dtype)))
                continue
            kc = jnp.zeros((B, cache_len, hkv, dh), self.dtype)
            vc = jnp.zeros_like(kc)
            kc = jax.lax.dynamic_update_slice(
                kc, k.astype(self.dtype), (0, 0, 0, 0)
            )
            vc = jax.lax.dynamic_update_slice(
                vc, v.astype(self.dtype), (0, 0, 0, 0)
            )
            caches.append((kc, vc))
        return self._logits(x), tuple(caches)

    def decode_step(self, tok, caches, pos):
        """One decode step: ``tok`` [B] int32 at position ``pos`` (traced
        scalar ok) → ``(next-token logits [B, vocab], updated caches)``."""
        x = self._embed_at(tok[:, None], pos)
        new_caches = []
        for blk, (kc, vc) in zip(self.blocks, caches):
            x, kc, vc = blk.step(x, kc, vc, pos)
            new_caches.append((kc, vc))
        return self._logits(x)[:, 0], tuple(new_caches)

    def extend(self, tokens, caches, pos0):
        """Multi-token cached decode: ``tokens`` [B, T] occupying absolute
        positions ``pos0 .. pos0+T-1`` → ``(logits [B, T, vocab], updated
        caches)``; ``logits[:, t]`` predicts position ``pos0+t+1``.
        Speculative decoding's verify forward — T candidate tokens scored
        against the cache at one batched pass's cost."""
        x = self._embed_at(tokens, pos0)
        new_caches = []
        for blk, (kc, vc) in zip(self.blocks, caches):
            x, kc, vc = blk.extend(x, kc, vc, pos0)
            new_caches.append((kc, vc))
        return self._logits(x), tuple(new_caches)

    # -- block-paged decode (the serving tier's entry points) ---------------

    def _embed_rows(self, tokens, positions):
        """Embed ``tokens`` [B, T] where row ``b`` occupies absolute
        positions ``positions[b] .. positions[b]+T-1`` (per-row positions —
        the paged decode batch mixes sequences of different lengths)."""
        x = self.embed(tokens).astype(jnp.float32)
        if self.pos_embedding == "rope":
            return x
        table = jnp.asarray(sincos_positions(self.maxlen, self.dim))
        T = tokens.shape[1]
        pos = table[positions[:, None] + jnp.arange(T)[None, :]]
        return x + pos

    def prefill_raw(self, tokens):
        """Full forward over the prompt returning ``(logits, kvs)`` with
        per-block UNPADDED K/V ``[B, L, Hkv, Dh]`` (keys pre-rotated under
        RoPE, cast to the cache dtype) — the serving tier scatters these
        into its block pool instead of a dense ``[B, maxlen]`` buffer."""
        x = self._embed_at(tokens)
        kvs = []
        for blk in self.blocks:
            x, k, v = blk.prefill(x, None)
            kvs.append((k.astype(self.dtype), v.astype(self.dtype)))
        return self._logits(x), tuple(kvs)

    def paged_extend_rows(self, tokens, k_pools, v_pools, tables,
                          write_slots, positions, block_size: int):
        """Multi-token decode against the block-paged cache: ``tokens``
        [B, T], row ``b`` occupying positions ``positions[b] ..
        positions[b]+T-1``; ``k_pools``/``v_pools`` are per-layer flat slot
        pools (tuple of ``[S, Hkv, Dh]``), ``tables`` [B, nb] the per-row
        block tables and ``write_slots`` [B, T] this call's flat write
        targets. Returns ``(logits [B, T, vocab], k_pools, v_pools)``;
        ``logits[:, t]`` predicts row position ``positions[b]+t+1``. T=1
        is the serving decode step; T=K+1 is the speculative verify
        forward — same body, same parity guarantees as the dense
        :meth:`extend`."""
        x = self._embed_rows(tokens, positions)
        new_k, new_v = [], []
        for blk, kp, vp in zip(self.blocks, k_pools, v_pools):
            x, kp, vp = blk.paged_extend(x, kp, vp, tables, write_slots,
                                         positions, block_size)
            new_k.append(kp)
            new_v.append(vp)
        return self._logits(x), tuple(new_k), tuple(new_v)

    def paged_decode_step(self, tok, k_pools, v_pools, tables, write_slot,
                          positions, block_size: int):
        """One paged decode step: ``tok`` [B] int32, each row at its own
        ``positions[b]`` writing flat pool slot ``write_slot[b]`` →
        ``(next-token logits [B, vocab], updated pools)``."""
        logits, k_pools, v_pools = self.paged_extend_rows(
            tok[:, None], k_pools, v_pools, tables, write_slot[:, None],
            positions, block_size,
        )
        return logits[:, 0], k_pools, v_pools


def _check_decode_args(fn_name: str, model, prompt, max_new_tokens: int):
    """Shared validation for generate()/beam_search(): returns
    ``(module, prompt int32 [B, Lp])`` or raises."""
    module = model.module if isinstance(model, ModelSpec) else model
    if not isinstance(module, TransformerLM):
        raise TypeError(
            f"{fn_name}() needs a TransformerLM (or its ModelSpec from "
            f"transformer_lm()), got {type(module)}"
        )
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt.ndim != 2:
        raise ValueError(f"prompt must be [batch, length], got {prompt.shape}")
    if prompt.shape[1] + max_new_tokens > module.maxlen:
        raise ValueError(
            f"prompt length {prompt.shape[1]} + max_new_tokens "
            f"{max_new_tokens} exceeds the model's maxlen {module.maxlen}"
        )
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    return module, prompt


def _warp_fn(temperature: float, top_k: int | None,
             top_p: float | None = None):
    """Logit-warping for sampling: temperature scale, then top-k, then
    nucleus (top-p) truncation. Returns warped logits (filtered tokens at
    -1e30); ``softmax(warped)`` is the distribution every sampling path —
    plain :func:`generate` and speculative verify alike — draws from.

    Tie behavior at the nucleus boundary: every token whose warped logit
    EQUALS the cutoff survives (strict ``scaled < cutoff`` filter), so with
    exactly-tied logits the kept support can exceed the minimal nucleus by
    the tied tokens — the conventional choice (matches the common HF
    implementation), and the one that keeps the filter permutation-
    invariant. Requires temperature > 0."""

    def warp(logits):
        scaled = logits.astype(jnp.float32) / temperature
        if top_k is not None:
            kth = jax.lax.top_k(scaled, top_k)[0][..., -1:]
            scaled = jnp.where(scaled < kth, -1e30, scaled)
        if top_p is not None and top_p < 1.0:
            desc = jnp.sort(scaled, axis=-1)[..., ::-1]
            probs = jax.nn.softmax(desc, axis=-1)
            # keep a token iff the mass strictly BEFORE it is < top_p: the
            # minimal nucleus covering top_p, never empty
            keep = jnp.cumsum(probs, axis=-1) - probs < top_p
            cutoff = jnp.min(
                jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True
            )
            scaled = jnp.where(scaled < cutoff, -1e30, scaled)
        return scaled

    return warp


def _sample_fn(temperature: float, top_k: int | None,
               top_p: float | None = None):
    """Greedy for temperature==0, else temperature/top-k/top-p categorical.

    Filters compose in the conventional order: top-k first, then nucleus
    (top-p) over the surviving distribution — smallest prefix of
    descending-probability tokens whose mass reaches ``top_p`` (the top-1
    token always survives; see :func:`_warp_fn` for tie behavior)."""
    if temperature == 0.0:
        def sample(logits, key):
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)

        return sample
    warp = _warp_fn(temperature, top_k, top_p)

    def sample(logits, key):
        return jax.random.categorical(
            key, warp(logits), axis=-1
        ).astype(jnp.int32)

    return sample


@functools.lru_cache(maxsize=64)
def _generate_program(module: TransformerLM, max_new_tokens: int,
                      temperature: float, top_k: int | None,
                      top_p: float | None = None,
                      eos_id: int | None = None):
    """One jitted prefill+scan program per (module, decode config) — flax
    modules are frozen dataclasses, so the lru_cache key is by value and
    repeated generate()/GeneratorPredictor chunks reuse the compilation
    (jit itself still specializes per prompt shape).

    With ``eos_id`` the scan becomes a ``lax.while_loop`` carrying a
    per-row ``done`` flag: a finished row keeps its static shape but emits
    ``eos_id`` pads, and the loop exits early once EVERY row is done (the
    only early stop a static-shape program gets for free). The eos-free
    path is byte-identical to before — eos costs nothing when unused."""
    sample = _sample_fn(temperature, top_k, top_p)

    def run(params, prompt, key):
        lp = prompt.shape[1]
        logits, caches = module.apply(
            {"params": params}, prompt, method=TransformerLM.prefill
        )
        key, k0 = jax.random.split(key)
        tok = sample(logits[:, -1], k0)

        if eos_id is None:
            def body(carry, key_i):
                tok, caches, pos = carry
                logits, caches = module.apply(
                    {"params": params}, tok, caches, pos,
                    method=TransformerLM.decode_step,
                )
                nxt = sample(logits, key_i)
                return (nxt, caches, pos + 1), tok

            keys = jax.random.split(key, max_new_tokens)[1:]
            (last, _, _), toks = jax.lax.scan(
                body, (tok, caches, jnp.asarray(lp, jnp.int32)), keys
            )
            # toks: [max_new-1, B] emitted per step, plus the final carry
            out = jnp.concatenate([toks, last[None]], axis=0)
            return jnp.concatenate(
                [prompt, out.T.astype(jnp.int32)], axis=1
            )

        # eos path: mask-and-carry a per-row done flag into a preallocated
        # eos-padded output buffer; while_loop exits when all rows finish
        B = prompt.shape[0]
        done = tok == eos_id
        out = jnp.full((B, max_new_tokens), eos_id, jnp.int32)
        out = out.at[:, 0].set(tok)

        def cond(carry):
            n = carry[0]
            return (n < max_new_tokens) & ~jnp.all(carry[4])

        def body(carry):
            n, tok, caches, out, done = carry
            logits, caches = module.apply(
                {"params": params}, tok, caches, lp + n - 1,
                method=TransformerLM.decode_step,
            )
            nxt = sample(logits, jax.random.fold_in(key, n))
            nxt = jnp.where(done, eos_id, nxt)   # pad after EOS
            out = jax.lax.dynamic_update_slice(out, nxt[:, None], (0, n))
            return (n + 1, nxt, caches, out, done | (nxt == eos_id))

        _, _, _, out, _ = jax.lax.while_loop(
            cond, body,
            (jnp.asarray(1, jnp.int32), tok, caches, out, done),
        )
        return jnp.concatenate([prompt, out], axis=1)

    return jax.jit(run)


def generate(model, params, prompt, max_new_tokens: int, *,
             temperature: float = 0.0, top_k: int | None = None,
             top_p: float | None = None, seed: int = 0,
             eos_id: int | None = None):
    """Autoregressive decoding: ``prompt`` [B, Lp] int32 → [B, Lp+new] int32.

    One jitted program: prefill writes the KV caches for the whole prompt in
    a single batched forward, then a ``lax.scan`` emits one token per step
    against the cache (O(L) per token instead of the O(L²) of re-running the
    full forward). ``temperature=0`` is greedy; otherwise categorical
    sampling at the given temperature, optionally truncated to the ``top_k``
    highest-probability tokens and/or the smallest nucleus of tokens whose
    probability mass reaches ``top_p`` (applied after ``top_k``).
    Deterministic for a fixed ``seed``.

    ``eos_id`` stops a row at its first end-of-sequence token: the row pads
    with ``eos_id`` from there on (static output shape — shapes never
    depend on data), and the decode loop exits early once every row has
    finished. Rows that never emit ``eos_id`` run the full budget. Count
    real tokens with :func:`distkeras_tpu.serving.per_row_new_token_counts`
    — the same retire rule the serving tier applies per step. NOTE: the
    eos path draws its sampling keys from a different (per-step
    ``fold_in``) schedule than the eos-free scan, so sampled streams with
    and without ``eos_id`` are not token-for-token comparable; greedy
    streams are identical up to the first eos.
    """
    module, prompt = _check_decode_args(
        "generate", model, prompt, max_new_tokens
    )
    if top_k is not None and not 1 <= int(top_k) <= module.vocab:
        raise ValueError(
            f"top_k must be in [1, vocab={module.vocab}], got {top_k}"
        )
    if top_p is not None and not 0.0 < float(top_p) <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if eos_id is not None and not 0 <= int(eos_id) < module.vocab:
        raise ValueError(f"eos_id {eos_id} outside vocab {module.vocab}")
    run = _generate_program(
        module, int(max_new_tokens), float(temperature), top_k,
        None if top_p is None else float(top_p),
        None if eos_id is None else int(eos_id),
    )
    return np.asarray(run(params, prompt, jax.random.PRNGKey(seed)))


@functools.lru_cache(maxsize=32)
def _speculative_program(target: TransformerLM, draft: TransformerLM,
                         max_new_tokens: int, spec_tokens: int):
    """One jitted speculative-decode program per (target, draft, config)."""
    K = spec_tokens

    def run(t_params, d_params, prompt):
        B, lp = prompt.shape
        cap = max_new_tokens + K + 1  # emission block may overhang the tail

        t_logits, t_caches = target.apply(
            {"params": t_params}, prompt, method=TransformerLM.prefill
        )
        _, d_caches = draft.apply(
            {"params": d_params}, prompt, method=TransformerLM.prefill
        )
        tok0 = jnp.argmax(t_logits[:, -1], axis=-1).astype(jnp.int32)
        out = jnp.zeros((B, cap), jnp.int32)
        out = jax.lax.dynamic_update_slice(out, tok0[:, None], (0, 0))

        def cond(carry):
            return carry[1] < max_new_tokens

        def body(carry):
            (out, n, last, t_caches, d_caches, rounds, accepted,
             proposed) = carry
            cur = lp + n - 1  # absolute position of `last`; not yet cached

            def draft_step(c, i):
                tok, caches = c
                logits, caches = draft.apply(
                    {"params": d_params}, tok, caches, cur + i,
                    method=TransformerLM.decode_step,
                )
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (nxt, caches), nxt

            (_, d_caches), props = jax.lax.scan(
                draft_step, (last, d_caches), jnp.arange(K)
            )
            props = props.T  # [B, K]: proposals for positions cur+1..cur+K

            # verify: one cached forward over [last, props…]; logits[:, t]
            # is the target's prediction for position cur+t+1
            block = jnp.concatenate([last[:, None], props], axis=1)
            t_logits, t_caches = target.apply(
                {"params": t_params}, block, t_caches, cur,
                method=TransformerLM.extend,
            )
            g = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)  # [B, K+1]

            # accepted prefix per row, then lockstep on the batch minimum:
            # every row's first `a` proposals equal its own greedy tokens,
            # so emitting props[:, :a] + g[:, a] is exact for every row —
            # uniform positions keep the cache writes dynamic_update_slice
            match = (props == g[:, :K]).astype(jnp.int32)
            a_row = jnp.sum(jnp.cumprod(match, axis=1), axis=1)  # [B]
            a = jnp.min(a_row)

            cols = jnp.arange(K + 1)[None, :]
            emit = jnp.where(
                cols == a, g,
                jnp.concatenate(
                    [props, jnp.zeros((B, 1), jnp.int32)], axis=1
                ),
            )  # [B, K+1]: props below a, the correction g[:, a] at a,
            #    garbage above (overwritten by the next round or trimmed)
            out = jax.lax.dynamic_update_slice(out, emit, (0, n))
            last = jnp.take_along_axis(
                g, jnp.full((B, 1), a, jnp.int32), axis=1
            )[:, 0]
            # stats clamp to the emission budget: the final round's block
            # may overhang max_new_tokens; proposals (and accepts) beyond
            # the budget never land in `out`, so they don't count.
            # PER-ROW sums (ADVICE r4): acceptance reports mean draft/
            # target agreement across rows, not the batch-min lockstep
            # advancement (which `rounds` captures). Rows past the
            # batch-min re-propose their overhang next round, so the same
            # POSITION can be counted in proposed/accepted more than once
            # — agreement-per-proposal semantics, documented in
            # speculative_generate's docstring.
            room = max_new_tokens - n
            return (out, n + a + 1, last, t_caches, d_caches, rounds + 1,
                    accepted + jnp.sum(jnp.minimum(a_row, room)),
                    proposed + B * jnp.minimum(K, room))

        out, _, _, _, _, rounds, accepted, proposed = jax.lax.while_loop(
            cond,
            body,
            (out, jnp.asarray(1, jnp.int32), tok0, t_caches, d_caches,
             jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32),
             jnp.asarray(0, jnp.int32)),
        )
        full = jnp.concatenate([prompt, out[:, :max_new_tokens]], axis=1)
        return full, rounds, accepted, proposed

    return jax.jit(run)


@functools.lru_cache(maxsize=32)
def _speculative_sampled_program(target: TransformerLM,
                                 draft: TransformerLM,
                                 max_new_tokens: int, spec_tokens: int,
                                 temperature: float, top_k: int | None,
                                 top_p: float | None):
    """Sampled speculative decoding (Leviathan et al. 2023, §3): the draft
    SAMPLES K proposals from its warped distribution q; each proposal x_i is
    accepted with probability min(1, p(x_i)/q(x_i)) against the target's
    warped distribution p, and the first rejection is replaced by a sample
    from the residual norm(max(p − q, 0)). Per position the emitted token is
    then distributed EXACTLY as p — acceptance only moves latency, never the
    distribution. Both p and q are warped identically (temperature/top-k/
    top-p), so the preserved distribution is the one plain
    :func:`generate` samples from.

    Lockstep batching: each round every row advances by the batch-minimum
    accepted length ``a``. All rows accepted their first ``a`` proposals, so
    positions 0..a-1 emit proposals; at the cut position each row emits its
    own scheme token — its accepted proposal if it accepted position ``a``,
    else its residual resample (and a fresh p-sample at a == K, where no
    proposal exists). Dropped later proposals were never emitted, so the
    per-row output stream stays exactly p-distributed."""
    K = spec_tokens
    warp = _warp_fn(temperature, top_k, top_p)

    def run(t_params, d_params, prompt, key):
        B, lp = prompt.shape
        cap = max_new_tokens + K + 1

        t_logits, t_caches = target.apply(
            {"params": t_params}, prompt, method=TransformerLM.prefill
        )
        _, d_caches = draft.apply(
            {"params": d_params}, prompt, method=TransformerLM.prefill
        )
        key, k0 = jax.random.split(key)
        tok0 = jax.random.categorical(
            k0, warp(t_logits[:, -1]), axis=-1
        ).astype(jnp.int32)
        out = jnp.zeros((B, cap), jnp.int32)
        out = jax.lax.dynamic_update_slice(out, tok0[:, None], (0, 0))

        def cond(carry):
            return carry[1] < max_new_tokens

        def body(carry):
            (out, n, last, t_caches, d_caches, rounds, accepted,
             proposed) = carry
            cur = lp + n - 1
            kd, ka, kc = jax.random.split(
                jax.random.fold_in(key, rounds), 3
            )

            def draft_step(c, i):
                tok, caches = c
                logits, caches = draft.apply(
                    {"params": d_params}, tok, caches, cur + i,
                    method=TransformerLM.decode_step,
                )
                wl = warp(logits)                          # [B, V] f32
                nxt = jax.random.categorical(
                    jax.random.fold_in(kd, i), wl, axis=-1
                ).astype(jnp.int32)
                return (nxt, caches), (nxt, jax.nn.log_softmax(wl, -1))

            (_, d_caches), (props, q_lp) = jax.lax.scan(
                draft_step, (last, d_caches), jnp.arange(K)
            )
            props = props.T                    # [B, K]
            q_lp = jnp.swapaxes(q_lp, 0, 1)    # [B, K, V]

            block = jnp.concatenate([last[:, None], props], axis=1)
            t_logits, t_caches = target.apply(
                {"params": t_params}, block, t_caches, cur,
                method=TransformerLM.extend,
            )
            p_lp = jax.nn.log_softmax(warp(t_logits), -1)  # [B, K+1, V]

            # accept x_i iff log u < log p(x_i) − log q(x_i)
            idx = props[..., None]
            p_at = jnp.take_along_axis(p_lp[:, :K], idx, axis=-1)[..., 0]
            q_at = jnp.take_along_axis(q_lp, idx, axis=-1)[..., 0]
            log_u = jnp.log(jax.random.uniform(
                ka, (B, K), jnp.float32, minval=1e-37
            ))
            accept = (log_u < p_at - q_at).astype(jnp.int32)   # [B, K]
            a_row = jnp.sum(jnp.cumprod(accept, axis=1), axis=1)
            a = jnp.min(a_row)

            # cut-position token per row (position cur+a+1):
            #  • a == K: no proposal exists — fresh sample from p_K
            #  • row accepted position a: its proposed token stands
            #  • row rejected position a: residual resample from
            #    norm(max(p − q, 0)) (zero-mass guard: if p ≤ q everywhere
            #    the rejection had probability 0; fall back to p)
            a_k = jnp.minimum(a, K - 1)
            ga = jnp.full((B, 1, 1), a_k, jnp.int32)
            p_cut = jnp.take_along_axis(
                p_lp, jnp.broadcast_to(ga, (B, 1, p_lp.shape[-1])), axis=1
            )[:, 0]                                             # [B, V]
            q_cut = jnp.take_along_axis(
                q_lp, jnp.broadcast_to(ga, (B, 1, q_lp.shape[-1])), axis=1
            )[:, 0]
            residual = jnp.maximum(jnp.exp(p_cut) - jnp.exp(q_cut), 0.0)
            has_mass = jnp.sum(residual, -1, keepdims=True) > 0
            res_logits = jnp.where(
                has_mass,
                jnp.where(residual > 0, jnp.log(residual), -jnp.inf),
                p_cut,
            )
            kc1, kc2 = jax.random.split(kc)
            res_tok = jax.random.categorical(
                kc1, res_logits, axis=-1
            ).astype(jnp.int32)
            p_k_tok = jax.random.categorical(
                kc2, p_lp[:, K], axis=-1
            ).astype(jnp.int32)
            accept_at_a = jnp.take_along_axis(
                accept, jnp.full((B, 1), a_k, jnp.int32), axis=1
            )[:, 0].astype(bool)
            prop_at_a = jnp.take_along_axis(
                props, jnp.full((B, 1), a_k, jnp.int32), axis=1
            )[:, 0]
            cut_tok = jnp.where(
                a == K, p_k_tok,
                jnp.where(accept_at_a, prop_at_a, res_tok),
            )

            cols = jnp.arange(K + 1)[None, :]
            emit = jnp.where(
                cols == a, cut_tok[:, None],
                jnp.concatenate(
                    [props, jnp.zeros((B, 1), jnp.int32)], axis=1
                ),
            )
            out = jax.lax.dynamic_update_slice(out, emit, (0, n))
            # per-row stat sums, clamped to the emission budget (see the
            # greedy program): acceptance is mean per-row agreement per
            # PROPOSAL — overhang positions past the batch-min cut are
            # re-proposed (and re-counted) next round, as documented in
            # speculative_generate's docstring
            room = max_new_tokens - n
            return (out, n + a + 1, cut_tok, t_caches, d_caches,
                    rounds + 1,
                    accepted + jnp.sum(jnp.minimum(a_row, room)),
                    proposed + B * jnp.minimum(K, room))

        out, _, _, _, _, rounds, accepted, proposed = jax.lax.while_loop(
            cond,
            body,
            (out, jnp.asarray(1, jnp.int32), tok0, t_caches, d_caches,
             jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32),
             jnp.asarray(0, jnp.int32)),
        )
        full = jnp.concatenate([prompt, out[:, :max_new_tokens]], axis=1)
        return full, rounds, accepted, proposed

    return jax.jit(run)


def speculative_generate(target, target_params, draft, draft_params, prompt,
                         max_new_tokens: int, *, spec_tokens: int = 4,
                         temperature: float = 0.0, top_k: int | None = None,
                         top_p: float | None = None, seed: int = 0):
    """Speculative decoding (Leviathan et al. 2023): a cheap ``draft``
    model proposes ``spec_tokens`` tokens autoregressively; the ``target``
    model scores all of them in ONE cached forward
    (:meth:`TransformerLM.extend`) and keeps an accepted prefix plus a
    correction token.

    ``temperature=0`` (default) is the greedy scheme: proposals are kept
    while they match the target's own argmax, and the output is **exactly**
    the target's greedy :func:`generate` stream — the draft changes the
    number of target passes (latency), never the tokens. (Exactness rides
    on both paths sharing ONE attention/cache body — ``decode_step`` and
    ``extend`` route through the same block code — so the verify block's
    logits are the same program XLA compiles for plain decode. The
    remaining hazard is EXACT bf16 logit ties: a saturated bf16 model can
    emit several identically-rounded max logits (measured: a 4-way tie on
    a 400M model trained to saturation), and the multi-token verify
    matmul may round a tie one ulp differently than the single-token
    step, after which the two streams are different-but-equally-valid
    greedy decodes. The test suite asserts bitwise equality on f32
    models, where ties have measure zero; the bench's bf16 legs fall
    back to an argmax-within-two-ulps check when streams differ (one
    true ulp is the measured drift of plain greedy itself against a
    full-forward oracle).)

    ``temperature>0`` is the paper's rejection-sampling scheme: the draft
    SAMPLES each proposal from its warped distribution ``q``; proposal
    ``x`` is accepted with probability ``min(1, p(x)/q(x))`` against the
    target's warped distribution ``p``, and the first rejection is
    replaced by a sample from ``norm(max(p − q, 0))``. Each emitted token
    is then distributed EXACTLY as ``p`` — the same distribution plain
    ``generate(..., temperature, top_k, top_p)`` samples from (the
    warps compose identically) — while the draft only moves latency.
    Deterministic for a fixed ``seed``.

    Returns ``(tokens [B, Lp+new] int32, stats)`` where ``stats`` reports
    ``rounds`` (target verify passes), ``proposed``/``accepted`` draft
    tokens SUMMED PER ROW (final-round proposals that overhang
    ``max_new_tokens`` are excluded from both counts), and the
    ``acceptance`` rate — the mean per-row draft/target agreement PER
    PROPOSAL, not per distinct emitted position. Because the lockstep
    advances every row by the batch-MINIMUM accepted length, a row that
    accepted further than the minimum re-proposes the overhang positions
    next round, and those re-proposals are counted again in both
    ``proposed`` and ``accepted`` (typically re-accepted, having already
    agreed once). The per-position sums can therefore exceed the number
    of distinct emitted positions — ``acceptance`` remains an unbiased
    estimate of P(draft token == target token at a sampled proposal),
    which is the draft-quality number the ratio is meant to report, but
    ``accepted`` is NOT "distinct tokens emitted via the draft".
    Latency is governed separately by the batch-minimum lockstep: every
    row advances ``~max_new_tokens/rounds`` positions per verify pass, so
    per-pass progress can trail ``acceptance·K`` when one slow row drags
    the batch — ``rounds`` is the latency stat, ``acceptance`` the
    draft-quality stat.

    Batched prompts are supported lockstep: each round advances every row
    by the batch-minimum accepted length (still exact for every row: at
    the cut position each row emits its own accepted proposal / residual
    resample, and discarded later proposals were never emitted).
    TPU shape discipline throughout: one jitted program, a
    ``lax.while_loop`` over rounds, static ``[B, K+1]`` verify blocks.
    Sliding-window (``attn_window``) models are not supported — their
    ring caches cannot take the verify block's contiguous span write.
    """
    tm, prompt = _check_decode_args(
        "speculative_generate", target, prompt, max_new_tokens
    )
    dm = draft.module if isinstance(draft, ModelSpec) else draft
    if not isinstance(dm, TransformerLM):
        raise TypeError(
            f"speculative_generate() needs a TransformerLM draft (or its "
            f"ModelSpec), got {type(dm)}"
        )
    if dm.vocab != tm.vocab:
        raise ValueError(
            f"draft vocab {dm.vocab} != target vocab {tm.vocab}"
        )
    if tm.attn_window is not None or dm.attn_window is not None:
        raise ValueError(
            "speculative_generate does not support sliding-window models "
            "(ring caches cannot take the verify block's span write)"
        )
    K = int(spec_tokens)
    if K < 1:
        raise ValueError(f"spec_tokens must be >= 1, got {spec_tokens}")
    need = prompt.shape[1] + int(max_new_tokens) + K - 1
    for name, m in (("target", tm), ("draft", dm)):
        if need > m.maxlen:
            raise ValueError(
                f"prompt {prompt.shape[1]} + max_new_tokens "
                f"{max_new_tokens} + spec_tokens {K} - 1 = {need} exceeds "
                f"the {name}'s maxlen {m.maxlen} (the verify block probes "
                f"spec_tokens positions past the emitted stream)"
            )
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k is not None and not 1 <= int(top_k) <= tm.vocab:
        raise ValueError(
            f"top_k must be in [1, vocab={tm.vocab}], got {top_k}"
        )
    if top_p is not None and not 0.0 < float(top_p) <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if temperature == 0.0:
        run = _speculative_program(tm, dm, int(max_new_tokens), K)
        toks, rounds, accepted, proposed = run(
            target_params, draft_params, prompt
        )
    else:
        run = _speculative_sampled_program(
            tm, dm, int(max_new_tokens), K, float(temperature), top_k,
            None if top_p is None else float(top_p),
        )
        toks, rounds, accepted, proposed = run(
            target_params, draft_params, prompt, jax.random.PRNGKey(seed)
        )
    # ONE device->host transfer for all four outputs: separate fetches cost
    # a full device round-trip EACH (~100 ms through a tunnel-attached
    # host — measured ~0.47 s of fixed cost per call as four fetches,
    # which alone erased the speculative win at 400M params)
    toks, rounds, accepted, proposed = jax.device_get(
        (toks, rounds, accepted, proposed)
    )
    rounds, accepted, proposed = int(rounds), int(accepted), int(proposed)
    stats = {
        "rounds": rounds,
        "proposed": proposed,
        "accepted": accepted,
        "acceptance": accepted / proposed if proposed else 0.0,
    }
    return np.asarray(toks), stats


@functools.lru_cache(maxsize=64)
def _beam_program(module: TransformerLM, max_new_tokens: int, beams: int,
                  length_penalty: float, eos_id: int | None):
    """One jitted prefill+scan beam-search program per (module, config)."""

    def run(params, prompt):
        B, lp = prompt.shape
        K, V = beams, module.vocab
        NEG = jnp.float32(-1e30)

        logits, caches = module.apply(
            {"params": params}, prompt, method=TransformerLM.prefill
        )
        logp0 = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), -1)
        # eos stays a legal FIRST pick — it just finishes that beam
        # immediately (a prompt is never "already finished")
        scores, tok0 = jax.lax.top_k(logp0, K)          # [B, K]
        # every beam shares the prompt's cache: tile rows to [B*K, …]
        caches = jax.tree.map(
            lambda c: jnp.repeat(c, K, axis=0), caches
        )
        toks = jnp.zeros((B, K, max_new_tokens), jnp.int32)
        toks = toks.at[:, :, 0].set(tok0)
        finished = (
            tok0 == eos_id if eos_id is not None
            else jnp.zeros((B, K), bool)
        )

        def body(carry, i):
            scores, toks, caches, finished = carry
            tok = jax.lax.dynamic_index_in_dim(
                toks, i - 1, axis=2, keepdims=False
            )                                            # [B, K]
            logits, caches = module.apply(
                {"params": params}, tok.reshape(B * K), caches,
                lp + i - 1, method=TransformerLM.decode_step,
            )
            logp = jax.nn.log_softmax(
                logits.astype(jnp.float32), -1
            ).reshape(B, K, V)
            if eos_id is not None:
                # finished beams emit only eos at zero cost — their score
                # is frozen and they stay comparable with live beams
                only_eos = jnp.full((V,), NEG).at[eos_id].set(0.0)
                logp = jnp.where(finished[:, :, None], only_eos, logp)
            cand = scores[:, :, None] + logp             # [B, K, V]
            scores, flat = jax.lax.top_k(cand.reshape(B, K * V), K)
            parent, tok_new = flat // V, flat % V        # [B, K]
            # reorder beam-major state to follow the surviving parents
            gather = (jnp.arange(B)[:, None] * K + parent).reshape(-1)
            caches = jax.tree.map(
                lambda c: jnp.take(c, gather, axis=0), caches
            )
            toks = jnp.take_along_axis(toks, parent[:, :, None], axis=1)
            toks = toks.at[:, :, i].set(tok_new)
            finished = jnp.take_along_axis(finished, parent, axis=1)
            if eos_id is not None:
                finished = finished | (tok_new == eos_id)
            return (scores, toks, caches, finished), None

        if max_new_tokens > 1:
            (scores, toks, caches, finished), _ = jax.lax.scan(
                body, (scores, toks, caches, finished),
                jnp.arange(1, max_new_tokens),
            )
        if length_penalty:
            # GNMT length normalization: rank by score / ((5+len)/6)^alpha,
            # len = tokens up to and including eos (or all, if none)
            if eos_id is not None:
                hit = toks == eos_id
                first = jnp.argmax(hit, axis=2)
                any_hit = jnp.any(hit, axis=2)
                length = jnp.where(any_hit, first + 1, max_new_tokens)
            else:
                length = jnp.full((B, K), max_new_tokens)
            norm = ((5.0 + length.astype(jnp.float32)) / 6.0) \
                ** jnp.float32(length_penalty)
            ranked = scores / norm
        else:
            ranked = scores
        order = jnp.argsort(-ranked, axis=1)
        ranked = jnp.take_along_axis(ranked, order, axis=1)
        toks = jnp.take_along_axis(toks, order[:, :, None], axis=1)
        full = jnp.concatenate(
            [jnp.broadcast_to(prompt[:, None], (B, K, lp)), toks], axis=2
        )
        return full.astype(jnp.int32), ranked

    return jax.jit(run)


def beam_search(model, params, prompt, max_new_tokens: int, *,
                beams: int = 4, length_penalty: float = 0.0,
                eos_id: int | None = None):
    """KV-cached beam-search decoding: ``prompt`` [B, Lp] int32 →
    ``(tokens [B, beams, Lp+new], scores [B, beams])``, best beam first.

    Same TPU shape discipline as :func:`generate` — one jitted program
    (prefill + ``lax.scan``), static shapes throughout, the per-block KV
    caches tiled to ``B·beams`` rows and re-gathered each step to follow
    surviving parents. ``scores`` are accumulated token log-probabilities;
    with ``length_penalty`` α > 0 they are GNMT-normalized
    (``score / ((5+len)/6)^α``). ``eos_id`` finishes a beam: its score
    freezes and it pads with ``eos_id`` while staying in the candidate set.
    ``beams=1`` reduces exactly to greedy :func:`generate`.
    """
    module, prompt = _check_decode_args(
        "beam_search", model, prompt, max_new_tokens
    )
    if not 1 <= int(beams) <= module.vocab:
        raise ValueError(
            f"beams must be in [1, vocab={module.vocab}], got {beams}"
        )
    if eos_id is not None and not 0 <= int(eos_id) < module.vocab:
        raise ValueError(f"eos_id {eos_id} outside vocab {module.vocab}")
    run = _beam_program(
        module, int(max_new_tokens), int(beams), float(length_penalty),
        None if eos_id is None else int(eos_id),
    )
    toks, scores = jax.device_get(run(params, prompt))  # one transfer
    return np.asarray(toks), np.asarray(scores)


def transformer_lm(vocab=1024, maxlen=256, dim=128, heads=4, depth=2,
                   dtype=jnp.bfloat16, attn_impl="reference",
                   attn_window=None, kv_heads=None,
                   pos_embedding="sincos", fused_ce=False,
                   ce_chunk=256, remat=False,
                   tie_embeddings=False) -> ModelSpec:
    """Causal-LM ModelSpec. Train with ``loss="sparse_softmax_cross_entropy"``
    on ``features=tokens [B, L]`` / ``label=tokens shifted left [B, L]``
    (see :func:`next_token_dataset`); decode with :func:`generate`.
    ``attn_window`` enables Mistral-style sliding-window attention (training
    compute O(L·window) on the flash path; decode masks the cache to the
    window band). ``kv_heads`` enables grouped-query attention (``1`` =
    multi-query): query head ``h`` reads shared K/V head ``h // group``, and
    the decode KV cache shrinks ``heads / kv_heads`` ×. ``pos_embedding``:
    "sincos" (additive, the default) or "rope" (rotary q/k rotations —
    relative positions; composes with GQA and sliding windows).
    ``fused_ce=True`` computes the training loss as a chunked fused
    linear+cross-entropy (``ce_chunk`` rows of logits at a time,
    ``ops/fused_ce.py``) so the ``[B, L, vocab]`` logits tensor never
    materializes — the large-vocab memory lever; inference/`generate` are
    unchanged. ``remat=True`` checkpoints each decoder block (the
    long-context activation-memory lever; composes with ``fused_ce``).
    ``tie_embeddings=True`` shares the token embedding with the output
    head (V·dim fewer parameters; the head matmul contracts against the
    embedding table, so int8 ``quantize_lm`` leaves the head in the
    trained dtype)."""
    module = TransformerLM(
        vocab=vocab, maxlen=maxlen, dim=dim, heads=heads, depth=depth,
        dtype=dtype, attn_impl=attn_impl, attn_window=attn_window,
        kv_heads=kv_heads, pos_embedding=pos_embedding, remat=remat,
        tie_embeddings=tie_embeddings,
    )
    example = jnp.zeros((1, maxlen), jnp.int32)
    spec = from_flax(module, example, name="transformer_lm")
    if fused_ce:
        from distkeras_tpu.ops.fused_ce import chunked_softmax_cross_entropy

        chunk = int(ce_chunk)

        def fused(params, state, x, y, training, mask=None):
            h = module.apply(
                {"params": params, **state}, x, training=training,
                method=TransformerLM.hidden,
            )
            b_, l_, d_ = h.shape
            token_mask = None
            if mask is not None:
                # per-row validity [B] broadcasts to every token of the row
                # (the validator's padded-chunk mask); [B, L] passes through
                mask = jnp.asarray(mask, jnp.float32)
                token_mask = (
                    jnp.repeat(mask, l_) if mask.ndim == 1
                    else mask.reshape(b_ * l_)
                )
            if module.tie_embeddings:
                # the head IS the embedding: contract against its transpose
                # (same math as nn.Embed.attend in _head), no bias
                kernel = params["embed"]["embedding"].T.astype(module.dtype)
                bias = None
            else:
                kernel = params["lm_head"]["kernel"].astype(module.dtype)
                bias = params["lm_head"]["bias"]
            loss = chunked_softmax_cross_entropy(
                h.astype(module.dtype).reshape(b_ * l_, d_),
                jnp.reshape(y, (b_ * l_,)),
                kernel,
                bias,
                mask=token_mask,
                chunk=chunk,
            )
            return loss, state

        spec = dataclasses.replace(
            spec, fused_losses={"sparse_softmax_cross_entropy": fused}
        )
    return spec


def quantize_lm(model, params) -> tuple[ModelSpec, dict]:
    """Post-training int8 weight-only quantization of a trained LM.

    ``(spec, trained_params) → (int8 spec, int8 params)``: every Dense
    kernel (qkv/attn_out/mlp_up/mlp_down/lm_head in every block) becomes an
    int8 matrix + per-output-channel f32 scale served by :class:`QDense`;
    embeddings and LayerNorms stay in their trained dtypes. The returned
    pair drops into :func:`generate` and ``predictors.GeneratorPredictor``
    unchanged — same architecture, same entry points, ~half the weight
    bytes per decode step (see ``ops/quant.py`` for the TPU rationale).
    """
    from distkeras_tpu.ops.quant import quantize_dense_tree

    module = model.module if isinstance(model, ModelSpec) else model
    if not isinstance(module, TransformerLM):
        raise TypeError(
            f"quantize_lm() needs a TransformerLM (or its ModelSpec), got "
            f"{type(module)}"
        )
    if module.quant:
        raise ValueError("model is already quantized")
    qmodule = module.clone(quant=True)
    example = jnp.zeros((1, module.maxlen), jnp.int32)
    qspec = from_flax(qmodule, example, name="transformer_lm_int8")
    return qspec, quantize_dense_tree(params)


def next_token_dataset(tokens: np.ndarray):
    """``[N, L+1]`` token rows → Dataset with ``features`` ``[N, L]`` and the
    next-token ``label`` ``[N, L]`` (inputs shifted left by one)."""
    from distkeras_tpu.data import Dataset

    tokens = np.asarray(tokens, np.int32)
    return Dataset(
        {"features": tokens[:, :-1], "label": tokens[:, 1:]}
    )
