"""ResNet family — the batch-norm model, exercising non-trainable state.

Beyond-reference addition (the reference zoo stops at 2016-era MLP/CNN/LSTM,
SURVEY.md §2b #19): a small CIFAR-style residual network whose BatchNorm
running statistics flow through the frameworks's non-trainable state path —
per-worker stats are carried in the stacked ``nt`` pytree by the local-SGD
engine (one independent set per replica, as in standard data-parallel BN),
and updated through the ``mutable=["batch_stats"]`` seam in
:func:`distkeras_tpu.model.from_flax`.

TPU notes: convs in bf16 (``use_bias=False`` under BN, the standard fusion),
BN statistics in f32 for numerical stability; everything static-shaped.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.model import ModelSpec, from_flax


class ResidualBlock(nn.Module):
    filters: int
    strides: tuple = (1, 1)
    dtype: jnp.dtype = jnp.bfloat16
    bn_momentum: float = 0.9
    bn_axis_name: str | None = None  # set for cross-replica (sync) BN

    @nn.compact
    def __call__(self, x, training: bool = False):
        bn = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=not training, momentum=self.bn_momentum,
            dtype=jnp.float32, name=name, axis_name=self.bn_axis_name,
        )
        h = nn.Conv(self.filters, (3, 3), strides=self.strides,
                    padding="SAME", use_bias=False, dtype=self.dtype)(x)
        h = bn("bn1")(h.astype(jnp.float32))
        h = nn.relu(h)
        h = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(h.astype(self.dtype))
        h = bn("bn2")(h.astype(jnp.float32))
        if x.shape[-1] != self.filters or self.strides != (1, 1):
            x = nn.Conv(self.filters, (1, 1), strides=self.strides,
                        use_bias=False, dtype=self.dtype,
                        name="proj")(x.astype(self.dtype))
            x = bn("bn_proj")(x.astype(jnp.float32))
        return nn.relu(x + h)


class ResNetSmall(nn.Module):
    """ResNet-8-style CIFAR network: stem + 3 stages of residual blocks."""

    num_classes: int = 10
    widths: tuple = (16, 32, 64)
    blocks_per_stage: int = 1
    dtype: jnp.dtype = jnp.bfloat16
    #: e.g. parallel.local_sgd.WORKER_AXIS for sync BN across the stacked
    #: workers of the collective backend (global-batch statistics)
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = nn.Conv(self.widths[0], (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype, name="stem")(x.astype(self.dtype))
        x = nn.BatchNorm(use_running_average=not training, momentum=0.9,
                         dtype=jnp.float32, name="bn_stem",
                         axis_name=self.bn_axis_name)(
            x.astype(jnp.float32))
        x = nn.relu(x)
        for i, w in enumerate(self.widths):
            for b in range(self.blocks_per_stage):
                strides = (2, 2) if (i > 0 and b == 0) else (1, 1)
                x = ResidualBlock(filters=w, strides=strides,
                                  dtype=self.dtype,
                                  bn_axis_name=self.bn_axis_name,
                                  name=f"stage{i}_block{b}")(x, training)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x.astype(self.dtype))
        return x.astype(jnp.float32)


def resnet_small(num_classes: int = 10, input_shape=(32, 32, 3),
                 widths=(16, 32, 64), blocks_per_stage: int = 1,
                 dtype=jnp.bfloat16, sync_bn: bool = False) -> ModelSpec:
    """``sync_bn=True`` pmeans BN statistics over the collective backend's
    stacked-worker axis (global-batch BN); collective backend only — the PS
    backend's hogwild threads have no such axis to reduce over."""
    from distkeras_tpu.parallel.local_sgd import WORKER_AXIS

    module = ResNetSmall(num_classes=num_classes, widths=tuple(widths),
                         blocks_per_stage=blocks_per_stage, dtype=dtype,
                         bn_axis_name=WORKER_AXIS if sync_bn else None)
    example = jnp.zeros((1,) + tuple(input_shape), jnp.float32)
    import dataclasses

    spec = from_flax(module, example, name="resnet_small")
    return dataclasses.replace(spec, requires_worker_axis=bool(sync_bn))
