"""Mixture-of-experts transformer — the expert-parallel model family.

Beyond-reference (the reference's parallelism is PS data-parallel only,
SURVEY.md §2b.2): encoder blocks whose feed-forward is the GShard-style MoE
layer from :mod:`distkeras_tpu.parallel.expert`. With ``mesh=None`` the block
runs the single-device oracle math; handing it a mesh with an ``ep`` axis
runs the identical computation expert-parallel (tokens and experts exchanged
with ``all_to_all`` over ICI) — same values, different placement, pinned by
tests/test_expert_parallel.py / tests/test_models.py.

The gating auxiliary (load-balancing) loss is sown into the ``moe_aux``
collection; pass ``mutable=["moe_aux"]`` (or use
:func:`moe_aux_loss`) to read it for the training objective.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.model import ModelSpec, from_flax
from distkeras_tpu.models.transformer import (
    attention_sublayer,
    sincos_positions,
)
from distkeras_tpu.parallel.expert import moe_mlp, moe_mlp_reference


class MoEEncoderBlock(nn.Module):
    dim: int
    heads: int
    num_experts: int = 8
    top_k: int = 2
    mlp_ratio: int = 4
    capacity_factor: float = 2.0
    causal: bool = False
    dtype: jnp.dtype = jnp.bfloat16
    mesh: object = None          # jax Mesh with an 'ep' axis, or None
    ep_axis: str = "ep"

    @nn.compact
    def __call__(self, x, mask=None, training: bool = False):
        B, L, _ = x.shape
        x = attention_sublayer(x, mask, dim=self.dim, heads=self.heads,
                               causal=self.causal, dtype=self.dtype)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_moe")(x)
        E, D, Hd = self.num_experts, self.dim, self.mlp_ratio * self.dim
        init = nn.initializers.normal(0.02)
        zeros = nn.initializers.zeros
        params = {
            "gate": self.param("gate", init, (D, E)),
            "w1": self.param("w1", init, (E, D, Hd)),
            "b1": self.param("b1", zeros, (E, Hd)),
            "w2": self.param("w2", init, (E, Hd, D)),
            "b2": self.param("b2", zeros, (E, D)),
        }
        tokens = h.reshape(B * L, D).astype(jnp.float32)
        if self.mesh is not None:
            y, aux = moe_mlp(
                params, tokens, self.mesh, axis=self.ep_axis,
                top_k=self.top_k, capacity_factor=self.capacity_factor,
            )
        else:
            y, aux = moe_mlp_reference(
                params, tokens, top_k=self.top_k,
                capacity_factor=self.capacity_factor,
            )
        self.sow("moe_aux", "aux", aux)
        return x + y.reshape(B, L, D)


class MoETransformerClassifier(nn.Module):
    """Token sequence → class logits with MoE feed-forwards."""

    vocab: int = 20000
    maxlen: int = 200
    dim: int = 128
    heads: int = 4
    depth: int = 2
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 2.0
    num_classes: int = 2
    causal: bool = False
    dtype: jnp.dtype = jnp.bfloat16
    mesh: object = None
    ep_axis: str = "ep"

    @nn.compact
    def __call__(self, tokens, mask=None, training: bool = False):
        if mask is None:
            mask = jnp.ones(tokens.shape, jnp.float32)
        x = nn.Embed(self.vocab, self.dim, dtype=self.dtype,
                     name="embed")(tokens)
        x = x.astype(jnp.float32) + jnp.asarray(
            sincos_positions(self.maxlen, self.dim)
        )[None, : tokens.shape[1]]
        for i in range(self.depth):
            x = MoEEncoderBlock(
                dim=self.dim, heads=self.heads,
                num_experts=self.num_experts, top_k=self.top_k,
                capacity_factor=self.capacity_factor, causal=self.causal,
                dtype=self.dtype, mesh=self.mesh, ep_axis=self.ep_axis,
                name=f"block_{i}",
            )(x, mask, training)
        m = mask.astype(jnp.float32)[..., None]
        pooled = jnp.sum(x * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_head")(pooled)
        logits = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(
            x.astype(self.dtype)
        )
        return logits.astype(jnp.float32)


def moe_aux_loss(module: nn.Module, params, inputs, training: bool = True):
    """Run the model collecting the gating auxiliary loss.

    Returns ``(logits, aux)`` where ``aux`` is the mean of the per-block
    load-balancing losses — add ``aux_weight * aux`` to the objective.
    """
    out, state = module.apply(
        {"params": params}, *inputs, training=training, mutable=["moe_aux"]
    )
    leaves = jnp.stack(
        [jnp.asarray(v) for v in _collect(state["moe_aux"])]
    )
    return out, jnp.mean(leaves)


def _collect(tree):
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _collect(v)
    elif isinstance(tree, (tuple, list)):
        for v in tree:
            yield from _collect(v)
    else:
        yield tree


def moe_transformer_classifier(vocab=20000, maxlen=200, dim=128, heads=4,
                               depth=2, num_experts=8, top_k=2,
                               capacity_factor=2.0, num_classes=2,
                               causal=False, dtype=jnp.bfloat16,
                               mesh=None, ep_axis="ep") -> ModelSpec:
    module = MoETransformerClassifier(
        vocab=vocab, maxlen=maxlen, dim=dim, heads=heads, depth=depth,
        num_experts=num_experts, top_k=top_k,
        capacity_factor=capacity_factor, num_classes=num_classes,
        causal=causal, dtype=dtype, mesh=mesh, ep_axis=ep_axis,
    )
    example = (
        jnp.zeros((1, maxlen), jnp.int32),
        jnp.ones((1, maxlen), jnp.float32),
    )
    return from_flax(module, example, name="moe_transformer_classifier")
