"""Native flax model zoo covering the reference's benchmark model families.

The reference era's models were Keras 1.x MLP/CNN/LSTM (SURVEY.md §5.7); the
five BASELINE configs map to:

- :func:`mlp` — MNIST 3-layer MLP (config 1) and ATLAS-Higgs tabular MLP
  (config 4);
- :func:`lenet` — MNIST LeNet-style CNN (config 2, the north-star config);
- :func:`vgg_small` — CIFAR-10 VGG-small (config 3);
- :func:`lstm_classifier` — IMDB LSTM sentiment (config 5);
- :func:`transformer_classifier` — beyond-reference long-context family whose
  attention math is shared with ``parallel.ring_attention`` (sequence
  parallelism);
- :func:`resnet_small` — beyond-reference batch-norm family: BatchNorm
  running stats ride the engines' non-trainable-state path (per-worker
  stats, the standard data-parallel BN);
- :func:`transformer_lm` — beyond-reference decoder-only causal LM with
  KV-cached autoregressive :func:`~distkeras_tpu.models.lm.generate`
  (prefill + one ``lax.scan`` decode loop, static shapes throughout).

All models emit **logits** (pair with the ``softmax_cross_entropy`` family) and
default to bfloat16 activations with float32 parameters — bf16 keeps matmuls
and convs on the MXU's fast path while fp32 master weights keep optimizer math
exact.
"""

from distkeras_tpu.models.mlp import MLP, mlp
from distkeras_tpu.models.cnn import LeNet, VGGSmall, lenet, vgg_small
from distkeras_tpu.models.lstm import LSTMClassifier, lstm_classifier
from distkeras_tpu.models.moe import (
    MoETransformerClassifier,
    moe_transformer_classifier,
)
from distkeras_tpu.models.lm import (
    TransformerLM,
    beam_search,
    generate,
    speculative_generate,
    next_token_dataset,
    quantize_lm,
    transformer_lm,
)
from distkeras_tpu.models.resnet import ResNetSmall, resnet_small
from distkeras_tpu.models.sru import SRUClassifier, sru_classifier
from distkeras_tpu.models.transformer import (
    TransformerClassifier,
    pipelined_transformer_forward,
    sequence_parallel_transformer_forward,
    transformer_classifier,
)

__all__ = [
    "MLP", "mlp",
    "LeNet", "lenet",
    "VGGSmall", "vgg_small",
    "LSTMClassifier", "lstm_classifier",
    "SRUClassifier", "sru_classifier",
    "ResNetSmall", "resnet_small",
    "TransformerClassifier", "transformer_classifier",
    "pipelined_transformer_forward",
    "sequence_parallel_transformer_forward",
    "MoETransformerClassifier", "moe_transformer_classifier",
    "TransformerLM", "transformer_lm", "generate", "beam_search",
    "speculative_generate",
    "next_token_dataset", "quantize_lm",
]
