"""LSTM sequence classifier (IMDB sentiment, BASELINE config 5).

Variable-length sequences arrive pre-padded to a static length with a mask
column (see ``distkeras_tpu.datasets.imdb`` / ``SequencePadTransformer``) —
XLA traces one static-shape program, no recompiles per length bucket
(SURVEY.md §7.3 hard part 3). Classification reads a mask-weighted mean over
valid timesteps, which avoids a gather on the last-valid index and fuses into
the final matmul.

TPU note — hoisted input projection: the input half of the LSTM's gate math
(``x_t @ W_x`` for every t) has no sequential dependence, so it runs as ONE
big ``[B·T, E] @ [E, 4H]`` matmul before the scan (MXU-friendly), leaving
only the recurrent ``h @ W_h`` inside the ``lax.scan``. On a bare jitted
train step this measured ~1.25× over ``nn.RNN(OptimizedLSTMCell)`` (B=64,
T=200, 128/128, v5e); through the window-scan engine the two are within
chip run-to-run variance — kept for the simpler code and the microbench
win. Cell state stays f32; gates/hidden compute in ``dtype``.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from distkeras_tpu.model import ModelSpec, from_flax


class LSTMClassifier(nn.Module):
    vocab: int = 20000
    embed_dim: int = 128
    hidden_dim: int = 128
    num_classes: int = 2
    dtype: jnp.dtype = jnp.bfloat16
    #: recurrence implementation: "pallas" (the fused VMEM-carry kernel in
    #: ops.recurrent — forget bias +1.0, same gate math), "xla" (lax.scan),
    #: "auto" (kernel natively on TPU with tile-friendly shapes)
    scan_impl: str = "auto"

    @nn.compact
    def __call__(self, tokens, mask=None, training: bool = False):
        from distkeras_tpu.ops.recurrent import lstm_scan

        if mask is None:
            mask = jnp.ones(tokens.shape, jnp.float32)
        H = self.hidden_dim
        x = nn.Embed(self.vocab, self.embed_dim, dtype=self.dtype)(tokens)
        # all timesteps' input projections in one matmul (bias lives here)
        gates_x = nn.Dense(4 * H, dtype=self.dtype, name="wx")(x)  # [B,T,4H]
        wh = self.param("wh", nn.initializers.orthogonal(), (H, 4 * H),
                        jnp.float32)
        # ys in `dtype`: the [B, T, H] buffer (and its saved-for-backward
        # copy) stays bf16; the mask-mean below accumulates in f32
        outs = lstm_scan(gates_x, wh, impl=self.scan_impl)  # [B, T, H]
        m = mask.astype(jnp.float32)[..., None]
        pooled = jnp.sum(outs.astype(jnp.float32) * m, axis=1) / jnp.maximum(
            jnp.sum(m, axis=1), 1.0
        )
        logits = nn.Dense(self.num_classes, dtype=self.dtype)(
            pooled.astype(self.dtype)
        )
        return logits.astype(jnp.float32)


def lstm_classifier(vocab=20000, maxlen=200, embed_dim=128, hidden_dim=128,
                    num_classes=2, dtype=jnp.bfloat16,
                    scan_impl="auto") -> ModelSpec:
    module = LSTMClassifier(
        vocab=vocab, embed_dim=embed_dim, hidden_dim=hidden_dim,
        num_classes=num_classes, dtype=dtype, scan_impl=scan_impl,
    )
    example = (
        jnp.zeros((1, maxlen), jnp.int32),
        jnp.ones((1, maxlen), jnp.float32),
    )
    return from_flax(module, example, name="lstm_classifier")
