"""LSTM sequence classifier (IMDB sentiment, BASELINE config 5).

Variable-length sequences arrive pre-padded to a static length with a mask
column (see ``distkeras_tpu.datasets.imdb`` / ``SequencePadTransformer``) —
XLA traces one static-shape program, no recompiles per length bucket
(SURVEY.md §7.3 hard part 3). The recurrence itself is a ``flax.linen.RNN``
(``lax.scan`` underneath — compiler-friendly sequential control flow);
classification reads a mask-weighted mean over valid timesteps, which avoids a
gather on the last-valid index and fuses into the final matmul.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.model import ModelSpec, from_flax


class LSTMClassifier(nn.Module):
    vocab: int = 20000
    embed_dim: int = 128
    hidden_dim: int = 128
    num_classes: int = 2
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, tokens, mask=None, training: bool = False):
        if mask is None:
            mask = jnp.ones(tokens.shape, jnp.float32)
        x = nn.Embed(self.vocab, self.embed_dim, dtype=self.dtype)(tokens)
        rnn = nn.RNN(nn.OptimizedLSTMCell(self.hidden_dim, dtype=self.dtype))
        outs = rnn(x)  # [batch, time, hidden]
        m = mask.astype(jnp.float32)[..., None]
        pooled = jnp.sum(outs.astype(jnp.float32) * m, axis=1) / jnp.maximum(
            jnp.sum(m, axis=1), 1.0
        )
        logits = nn.Dense(self.num_classes, dtype=self.dtype)(
            pooled.astype(self.dtype)
        )
        return logits.astype(jnp.float32)


def lstm_classifier(vocab=20000, maxlen=200, embed_dim=128, hidden_dim=128,
                    num_classes=2, dtype=jnp.bfloat16) -> ModelSpec:
    module = LSTMClassifier(
        vocab=vocab, embed_dim=embed_dim, hidden_dim=hidden_dim,
        num_classes=num_classes, dtype=dtype,
    )
    example = (
        jnp.zeros((1, maxlen), jnp.int32),
        jnp.ones((1, maxlen), jnp.float32),
    )
    return from_flax(module, example, name="lstm_classifier")
