"""Shared-memory ring-buffer transport — the colocated fast lane (ISSUE 12).

Every bench record since PR 6 carries a ``host_cores`` honesty field
because the socket/native wires serialize behind syscalls, kernel socket
copies, and pickle passes that the colocated regime (workers and PS on one
host — CI, single-VM, the single-TPU-slice deployment) never needed.
This module attacks that constant factor: ``ps_transport="shm"`` moves
every frame through an mmap'd SPSC ring pair (one
``multiprocessing.shared_memory`` segment per worker↔PS connection), so a
steady-state exchange costs **zero syscalls** and the O(model) payload is
written **once** into the ring and folded by the server **directly from
the mapped view** — no pickle of the bulk tensor, no kernel copies.

Layout (one segment per connection, created and unlinked by the server)::

    [0..4096)          header: magic, ring capacity, pids, closed flags;
                       head/tail cursors on their own cache lines
    [4096 .. 4096+cap)       client→server ring (requests)
    [4096+cap .. 4096+2cap)  server→client ring (replies)

Each ring is a byte pipe (head/tail are monotonic u64 byte counters; the
writer owns head, the reader owns tail — SPSC, no locks) carrying
length-prefixed records: a u64 word (``flags<<56 | length``) followed by
the payload. Three record kinds:

- **pickle records** (``FLAG_PKL``): exactly the socket wire's frames —
  the 8-byte big-endian length prefix plus the restricted-pickle payload,
  decoded by :func:`networking.decode_frame`, the SAME function the TCP
  wire and WAL wire-frame replay use. Payloads stream through the ring
  with wraparound and progressive publication, so a record LARGER than
  the ring drains through it in chunks — the oversize **spill path**.
- **bulk records** (``FLAG_BULK``): the zero-copy lane. ndarray leaves
  are lifted out of the message, replaced by ``(tag, offset, dtype,
  shape)`` markers in a small pickled skeleton, and written once into a
  64-byte-aligned contiguous region of the ring; the receiver rebuilds
  the tree as numpy **views over the mapped ring** and releases the
  region only after the fold/copy consumed it. Bulk records must be
  contiguous (a PAD record skips the ring tail when they would wrap) and
  at most half the ring — anything bigger falls back to the spill path.
- **pad records** (``FLAG_PAD``): dead bytes both sides skip.

Wakeup is condvar-based with a bounded wait slice: in the colocated
regime both endpoints live in one process, so the writer bumps head/tail
and notifies a process-local per-segment condition — no futex syscall
from Python, immediate wakeup, and (crucially, under the GIL) no spin
loop starving the peer thread. A cross-process peer degrades to the same
loop's 0.5 ms timeout polling. Every wait slice re-checks liveness: the
peer's closed flag, and (cross-process) its pid — a worker that dies
mid-ring-write surfaces as a retryable
:class:`~distkeras_tpu.networking.PeerDeadError` instead of wedging the
server, and the PR 4 heartbeat eviction closes an abandoned worker's
connection so its handler exits and the segment is **unlinked** (no
/dev/shm leaks; pinned by test).

Everything above the framing is the existing PS stack, unchanged:
``_fault_hook`` chaos fires at the top of every send/recv (FaultPlan
drops/delays work verbatim), the server handler is the socket handler's
action dispatch over ``recv_msg``, commits carry the same seqno/epoch
resilience tokens, and a durable server's clients send commit/exchange
frames on the pickle lane so the WAL logs the wire bytes VERBATIM
(``REC_COMMIT_WIRE``) and replays through the one shared decode pipeline
— bit-identical recovery, same as TCP (the handshake advertises
``wal_frames`` so the client picks the lane).

Security posture: the segment is a private mmap named under /dev/shm with
the creating process's permissions — narrower exposure than a TCP port.
The skeleton still decodes through the restricted unpickler; bulk leaf
markers can only produce numpy views bounded by the record's extent.
"""

from __future__ import annotations

import itertools
import os
import pickle
import socket as _socket
import struct
import threading
import time
from multiprocessing import shared_memory
from typing import Any

import numpy as np

from distkeras_tpu import networking, utils
from distkeras_tpu.networking import PeerDeadError, ProtocolError
from distkeras_tpu.observability import trace as _trace
from distkeras_tpu.parameter_servers import (
    ParameterServerClient,
    SocketParameterServer,
)
from distkeras_tpu.parallel.compression import is_encoded, maybe_decode

Pytree = Any

#: Per-direction ring capacity (bytes). One exchange needs roughly
#: 2×model bytes of ring traffic (delta in, center out, on separate
#: rings); 8 MiB comfortably holds a ~1M-param f32 model's frames with
#: bulk-lane headroom, and /dev/shm is charged lazily (only touched
#: pages cost memory). Override per server via ``ring_bytes=``.
DEFAULT_RING_BYTES = 8 * 1024 * 1024

_HDR_BYTES = 4096
_MAGIC = 0x31304D48534B44  # "DKSHM01" little-endian
_OFF_MAGIC = 0
_OFF_CAP = 8
# cursors on their own cache lines: head/tail of each ring are written
# by different threads at frame rate — sharing a line would bounce it
_OFF_C2S_HEAD = 64
_OFF_C2S_TAIL = 128
_OFF_S2C_HEAD = 192
_OFF_S2C_TAIL = 256
_OFF_CLIENT_PID = 320
_OFF_SERVER_PID = 328
_OFF_CLIENT_CLOSED = 384
_OFF_SERVER_CLOSED = 448

_WORD = struct.Struct("<Q")
_U32 = struct.Struct("<I")
_FLAG_SHIFT = 56
_LEN_MASK = (1 << _FLAG_SHIFT) - 1
FLAG_PKL = 0
FLAG_BULK = 1
FLAG_PAD = 0x7F

#: bulk leaf marker tag in the skeleton tree (see module docstring)
_LEAF_TAG = "__dkshm__"

#: condvar wait slice: the notify path makes this latency irrelevant
#: in-process; cross-process peers poll at this cadence
_WAIT_SLICE = 0.0005
#: cadence of the cross-process peer-pid liveness probe during waits
_LIVENESS_PERIOD = 0.25

_seg_counter = itertools.count()

# process-local segment registry (observability satellite): every mint
# registers, every unlink path unregisters — segment_inventory() reads
# /dev/shm where it exists (the cross-process truth) and falls back to
# this registry elsewhere, so the no-leak property is operator-visible
# in health_snapshot, not just test-visible
_SEG_REGISTRY: dict[str, int] = {}
_SEG_REGISTRY_LOCK = threading.Lock()

# Cross-process rendezvous (ISSUE 15, ROADMAP item 5 residual): when a
# membership directory is configured, every mint publishes the segment
# name under the directory's "shm" role and every unlink withdraws it —
# SEPARATE trainer processes on one host can then find each other's ring
# segments by name (`DirectoryClient.shm_segments()`) instead of passing
# them by hand. The process-local registry above stays the fallback when
# no directory is installed. Installed via `set_rendezvous` (see
# `distkeras_tpu.directory.install_shm_rendezvous`); both callbacks are
# best-effort by design — a directory outage must never fail a mint.
_RENDEZVOUS: tuple | None = None   # (publish(name, size), withdraw(name))


def set_rendezvous(publish, withdraw) -> None:
    """Install the named-rendezvous callbacks for this process's shm
    segments (exactly one rendezvous at a time — the directory is a
    singleton per process by construction)."""
    global _RENDEZVOUS
    _RENDEZVOUS = (publish, withdraw)


def clear_rendezvous(publish=None) -> None:
    """Uninstall the rendezvous (matching ``publish`` when given, so a
    stale uninstaller cannot clobber a newer installation)."""
    global _RENDEZVOUS
    if publish is None or (_RENDEZVOUS is not None
                           and _RENDEZVOUS[0] is publish):
        _RENDEZVOUS = None


def unregister_segment(name: str) -> None:
    """Drop one segment from the live-inventory registry (called by
    every unlink path — Python lane and native lane)."""
    with _SEG_REGISTRY_LOCK:
        _SEG_REGISTRY.pop(name, None)
    rdv = _RENDEZVOUS
    if rdv is not None:
        try:
            rdv[1](name)
        except Exception:
            pass  # best-effort: the directory lease is the backstop


def segment_inventory() -> dict:
    """Live dkshm segment inventory: names + sizes, from a /dev/shm
    scan when the OS exposes one (covers segments OTHER processes on
    this host minted too — the colocated regime's whole truth) or from
    the process-local registry otherwise. An empty list after a run IS
    the no-/dev/shm-leak proof, now visible to operators via
    ``health_snapshot`` instead of only to the leak-check tests."""
    segs = []
    shm_dir = "/dev/shm"
    if os.path.isdir(shm_dir):
        for fn in sorted(os.listdir(shm_dir)):
            if not fn.startswith("dkshm"):
                continue
            try:
                size = os.stat(os.path.join(shm_dir, fn)).st_size
            except OSError:
                continue  # unlinked between listdir and stat
            segs.append({"name": fn, "bytes": int(size)})
    else:
        with _SEG_REGISTRY_LOCK:
            segs = [{"name": n, "bytes": b}
                    for n, b in sorted(_SEG_REGISTRY.items())]
    return {
        "count": len(segs),
        "total_bytes": sum(s["bytes"] for s in segs),
        "segments": segs,
    }


def mint_segment(name_prefix: str,
                 ring_bytes: int) -> shared_memory.SharedMemory:
    """Create one header-initialized dkshm segment (the ONE place the
    name scheme and header layout are written — the native lane's
    ``NativeSocketParameterServer.attach_shm`` mints through here too,
    so the two lanes cannot drift on the contract)."""
    seg = shared_memory.SharedMemory(
        create=True,
        name=f"{name_prefix}_{os.getpid()}_{next(_seg_counter)}",
        size=_HDR_BYTES + 2 * int(ring_bytes),
    )
    _WORD.pack_into(seg.buf, _OFF_MAGIC, _MAGIC)
    _WORD.pack_into(seg.buf, _OFF_CAP, int(ring_bytes))
    with _SEG_REGISTRY_LOCK:
        _SEG_REGISTRY[seg.name] = seg.size
    rdv = _RENDEZVOUS
    if rdv is not None:
        try:
            rdv[0](seg.name, seg.size)
        except Exception:
            pass  # best-effort: mint must not fail on a directory outage
    return seg


def _align64(n: int) -> int:
    return (n + 63) & ~63


def _resolve_dtype(name: str) -> np.dtype:
    """dtype by name, reaching through ml_dtypes for the extension
    floats (bfloat16/float8) jax environments register."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


# -- process-local wakeup registry -------------------------------------------
#
# Both endpoints of a segment in ONE process (the colocated regime this
# transport exists for) share a Condition keyed by segment name: bumping
# a cursor notifies it, so a blocked peer wakes immediately instead of
# polling. The lost-wakeup race is closed the classic way — the waiter
# re-checks its predicate INSIDE the condition lock before waiting, and
# the notifier publishes the cursor BEFORE taking that lock.

_WAKERS: dict[str, threading.Condition] = {}
_WAKERS_LOCK = threading.Lock()


def _waker_for(name: str) -> threading.Condition:
    with _WAKERS_LOCK:
        return _WAKERS.setdefault(name, threading.Condition())


def _waker_drop(name: str) -> None:
    with _WAKERS_LOCK:
        _WAKERS.pop(name, None)


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return True  # never stamped: no verdict
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


class _ShmConn:
    """One endpoint of a segment's ring pair.

    Two API layers share the rings:

    - the **message layer** (``send_msg`` / ``recv_msg``): pickle-lane
      control frames and zero-copy bulk frames — the shm server handler
      and the bulk client paths live here;
    - a **socket-duck byte layer** (``sendall`` / ``sendmsg`` / ``recv``
      / ``settimeout`` / ``getpeername`` / ``close``), so
      ``networking.send_data`` / ``recv_data`` — and therefore every
      inherited :class:`ParameterServerClient` action and the
      ``_fault_hook`` chaos seam — run over the ring UNCHANGED. Byte
      reads transparently consume pickle records (a bulk record in a
      byte-stream read is a protocol violation and fails fast).
    """

    def __init__(self, seg: shared_memory.SharedMemory, side: str,
                 waker: threading.Condition):
        if side not in ("client", "server"):
            raise ValueError(f"side must be 'client' or 'server', got {side!r}")
        self._seg = seg
        self._buf = seg.buf
        self._name = seg.name
        self._side = side
        self._waker = waker
        (magic,) = _WORD.unpack_from(self._buf, _OFF_MAGIC)
        if magic != _MAGIC:
            raise ProtocolError(
                f"segment {seg.name} is not a dkshm segment", retryable=False
            )
        (self._cap,) = _WORD.unpack_from(self._buf, _OFF_CAP)
        if side == "client":
            self._tx_head, self._tx_tail = _OFF_C2S_HEAD, _OFF_C2S_TAIL
            self._rx_head, self._rx_tail = _OFF_S2C_HEAD, _OFF_S2C_TAIL
            self._my_closed, self._peer_closed = (
                _OFF_CLIENT_CLOSED, _OFF_SERVER_CLOSED)
            self._peer_pid_off = _OFF_SERVER_PID
            _WORD.pack_into(self._buf, _OFF_CLIENT_PID, os.getpid())
        else:
            self._tx_head, self._tx_tail = _OFF_S2C_HEAD, _OFF_S2C_TAIL
            self._rx_head, self._rx_tail = _OFF_C2S_HEAD, _OFF_C2S_TAIL
            self._my_closed, self._peer_closed = (
                _OFF_SERVER_CLOSED, _OFF_CLIENT_CLOSED)
            self._peer_pid_off = _OFF_CLIENT_PID
            _WORD.pack_into(self._buf, _OFF_SERVER_PID, os.getpid())
        self._tx_data = _HDR_BYTES if side == "client" \
            else _HDR_BYTES + self._cap
        self._rx_data = _HDR_BYTES + self._cap if side == "client" \
            else _HDR_BYTES
        self._timeout: float | None = None
        self._closed = False
        self._cur = 0  # bytes left in the current pickle record (byte reads)
        # bulk records at most half the ring: a full-ring record would
        # require exact lockstep; half guarantees forward progress with
        # one record in flight while the previous one drains
        self._bulk_max = max(0, self._cap // 2 - 64)

    # -- cursor primitives ---------------------------------------------------

    def _torn(self, exc: BaseException) -> PeerDeadError:
        """A released-mapping error (``SharedMemory.close`` ran while
        this op was in flight — server stop/crash/eviction racing a live
        peer) IS peer death: convert it to the typed retryable error the
        whole resilience stack already triages. Reads raise ValueError
        ("operation forbidden on released memoryview"), writes raise
        TypeError (the released view stops being read-write). Anything
        else re-raises untouched."""
        if isinstance(exc, (ValueError, TypeError)) \
                and "memoryview" in str(exc):
            return PeerDeadError(
                "shm segment torn down mid-operation", peer=self._name
            )
        raise exc

    def _u64(self, off: int) -> int:
        return _WORD.unpack_from(self._buf, off)[0]

    def _set_u64(self, off: int, v: int) -> None:
        _WORD.pack_into(self._buf, off, v)

    def _notify(self) -> None:
        cond = self._waker
        with cond:
            cond.notify_all()

    def _check_alive(self, what: str) -> None:
        if self._buf is None or self._u64(self._my_closed):
            raise PeerDeadError(
                f"shm connection closed during {what}", peer=self._name
            )
        if self._u64(self._peer_closed):
            raise PeerDeadError(
                f"shm peer closed its endpoint during {what}",
                peer=self._name,
            )
        pid = self._u64(self._peer_pid_off)
        if pid and pid != os.getpid() and not _pid_alive(pid):
            # cross-process peer died without flagging: the pid probe is
            # the liveness backstop (in-process thread death is covered
            # by close()/eviction setting the flag instead)
            raise PeerDeadError(
                f"shm peer pid {pid} is gone (died mid-{what})",
                peer=self._name,
            )

    def _wait(self, pred, what: str) -> None:
        """Block until ``pred()`` holds — condvar wait with liveness
        checks each slice and the socket-style timeout contract
        (``socket.timeout`` after ``settimeout`` lapses, so the retry
        triage sees exactly what a TCP stall produces)."""
        if pred():
            return
        deadline = (None if self._timeout is None
                    else time.monotonic() + self._timeout)
        t_live = time.monotonic() + _LIVENESS_PERIOD
        cond = self._waker
        while True:
            self._check_alive(what)
            with cond:
                if pred():
                    return
                cond.wait(_WAIT_SLICE)
            if pred():
                return
            now = time.monotonic()
            if deadline is not None and now >= deadline:
                raise _socket.timeout(
                    f"shm {what} timed out after {self._timeout}s"
                )
            if now >= t_live:
                self._check_alive(what)
                t_live = now + _LIVENESS_PERIOD

    # -- byte layer: writer --------------------------------------------------

    def _tx_free(self) -> int:
        return self._cap - (self._u64(self._tx_head) - self._u64(self._tx_tail))

    def _advance_head(self, n: int) -> None:
        self._set_u64(self._tx_head, self._u64(self._tx_head) + n)
        self._notify()

    def _skip_to_word_boundary_tx(self) -> None:
        """Record words never wrap: if fewer than 8 bytes remain to the
        ring's end, both sides skip them (dead bytes)."""
        pos = self._u64(self._tx_head) % self._cap
        rem = self._cap - pos
        if rem < 8:
            self._wait(lambda: self._tx_free() >= rem, "send")
            self._advance_head(rem)

    def _stream_tx(self, chunks) -> None:
        """Write raw bytes with wraparound, publishing progressively so
        the reader drains concurrently — the spill path for records
        bigger than the ring rides exactly this."""
        for chunk in chunks:
            mv = memoryview(chunk)
            if mv.ndim != 1 or mv.itemsize != 1:
                mv = mv.cast("B")
            i = 0
            n = len(mv)
            while i < n:
                self._wait(lambda: self._tx_free() > 0, "send")
                head = self._u64(self._tx_head)
                pos = head % self._cap
                k = min(n - i, self._tx_free(), self._cap - pos)
                self._buf[self._tx_data + pos:self._tx_data + pos + k] = \
                    mv[i:i + k]
                i += k
                self._advance_head(k)

    def _send_record(self, flags: int, chunks) -> None:
        total = sum(len(memoryview(c).cast("B")) for c in chunks)
        self._skip_to_word_boundary_tx()
        self._stream_tx([_WORD.pack((flags << _FLAG_SHIFT) | total)])
        self._stream_tx(chunks)

    # -- byte layer: reader --------------------------------------------------

    def _rx_avail(self) -> int:
        return self._u64(self._rx_head) - self._u64(self._rx_tail)

    def _advance_tail(self, n: int) -> None:
        self._set_u64(self._rx_tail, self._u64(self._rx_tail) + n)
        self._notify()

    def _read_exact(self, n: int) -> bytearray:
        """Copy exactly n bytes out of the ring (wrapping, progressive
        tail release so an oversize record streams through)."""
        out = bytearray(n)
        i = 0
        while i < n:
            self._wait(lambda: self._rx_avail() > 0, "recv")
            tail = self._u64(self._rx_tail)
            pos = tail % self._cap
            k = min(n - i, self._rx_avail(), self._cap - pos)
            out[i:i + k] = self._buf[self._rx_data + pos:
                                     self._rx_data + pos + k]
            i += k
            self._advance_tail(k)
        return out

    def _next_record(self) -> tuple[int, int]:
        """Consume pads/dead bytes up to the next record word; returns
        ``(flags, payload_length)`` with the word consumed."""
        while True:
            tail = self._u64(self._rx_tail)
            pos = tail % self._cap
            rem = self._cap - pos
            if rem < 8:
                self._wait(lambda: self._rx_avail() >= rem, "recv")
                self._advance_tail(rem)
                continue
            self._wait(lambda: self._rx_avail() >= 8, "recv")
            (word,) = _WORD.unpack_from(self._buf, self._rx_data + pos)
            flags, length = word >> _FLAG_SHIFT, word & _LEN_MASK
            if flags == FLAG_PAD:
                self._wait(lambda: self._rx_avail() >= 8 + length, "recv")
                self._advance_tail(8 + length)
                continue
            self._advance_tail(8)
            return flags, length

    # -- socket-duck surface (networking.send_data / recv_data) --------------

    def sendmsg(self, buffers) -> int:
        if self._closed:
            raise PeerDeadError("send on closed shm connection",
                                peer=self._name)
        try:
            self._send_record(FLAG_PKL, list(buffers))
            return sum(len(memoryview(b).cast("B")) for b in buffers)
        except (ValueError, TypeError) as e:
            raise self._torn(e) from e

    def sendall(self, data) -> None:
        self.sendmsg([data])

    def recv(self, n: int) -> bytes:
        try:
            if self._cur == 0:
                flags, length = self._next_record()
                if flags != FLAG_PKL:
                    raise ProtocolError(
                        f"bulk shm record (flags={flags}) in a byte-stream "
                        f"read — protocol violation", retryable=False,
                        peer=self._name,
                    )
                self._cur = length
            self._wait(lambda: self._rx_avail() > 0, "recv")
            tail = self._u64(self._rx_tail)
            pos = tail % self._cap
            k = min(n, self._cur, self._rx_avail(), self._cap - pos)
            out = bytes(
                self._buf[self._rx_data + pos:self._rx_data + pos + k]
            )
            self._advance_tail(k)
            self._cur -= k
            return out
        except (ValueError, TypeError) as e:
            raise self._torn(e) from e

    def settimeout(self, t: float | None) -> None:
        self._timeout = None if t is None else float(t)

    def gettimeout(self) -> float | None:
        return self._timeout

    def getpeername(self) -> str:
        return f"shm:{self._name}"

    def close(self) -> None:
        """Flag this endpoint closed and wake the peer; the segment's
        unlink is the SERVER'S job (it created the name)."""
        if self._closed:
            return
        self._closed = True
        buf = self._buf
        if buf is not None:
            try:
                self._set_u64(self._my_closed, 1)
            except (ValueError, TypeError):
                pass  # segment already torn down under us
        self._notify()

    def detach_buffer(self) -> None:
        """Mark this endpoint dead ahead of the segment's unlink. The
        buffer reference is deliberately KEPT: a concurrent op on the
        dying connection must fault through the closed-flag check (a
        typed, retryable PeerDeadError), never through a torn attribute
        — the mapping itself stays valid until the refs are dropped
        (unlink only removes the name)."""
        self._closed = True

    # -- message layer -------------------------------------------------------

    def send_msg(self, msg: dict, bulk: bool = False) -> None:
        """One framed message. ``bulk=True`` ships ndarray leaves on the
        zero-copy lane when they fit (≤ half the ring, written once into
        a contiguous aligned region); otherwise — and for all control
        frames — the pickle lane carries the socket wire's exact frame
        bytes (length prefix + restricted pickle), streamed through the
        ring with wraparound: the oversize spill path."""
        if networking._fault_hook is not None:
            networking._fault_hook("send", self)
        if self._closed:
            raise PeerDeadError("send on closed shm connection",
                                peer=self._name)
        try:
            if bulk:
                enc = self._encode_bulk(msg)
                if enc is not None:
                    skel, leaves, payload_len = enc
                    self._send_bulk(skel, leaves, payload_len)
                    return
            payload = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
            self._send_record(
                FLAG_PKL, [networking._LEN.pack(len(payload)), payload]
            )
        except (ValueError, TypeError) as e:
            raise self._torn(e) from e

    def _encode_bulk(self, msg: dict):
        """Lift ndarray leaves out of ``msg`` into a placement plan:
        returns ``(skeleton_pickle, [(arr, rel_offset)...], payload_len)``
        or None when the record wouldn't fit the bulk lane (the caller
        falls back to the spill path)."""
        leaves: list[tuple[np.ndarray, int]] = []
        state = {"off": 0}

        def walk(o):
            if isinstance(o, np.ndarray):
                arr = np.ascontiguousarray(o)
                off = _align64(state["off"])
                state["off"] = off + arr.nbytes
                leaves.append((arr, off))
                return (_LEAF_TAG, off, arr.dtype.name, tuple(arr.shape))
            if isinstance(o, dict):
                return {k: walk(v) for k, v in o.items()}
            if isinstance(o, (list, tuple)):
                return type(o)(walk(v) for v in o)
            return o

        skel_tree = walk(msg)
        if not leaves:
            return None  # pure control frame: the pickle lane is cheaper
        skel = pickle.dumps(skel_tree, protocol=pickle.HIGHEST_PROTOCOL)
        leaf_base = _align64(_U32.size + len(skel))
        payload_len = leaf_base + state["off"]
        if 8 + payload_len > self._bulk_max:
            return None  # oversize: spill through the pickle lane
        return skel, leaves, payload_len

    def _send_bulk(self, skel: bytes, leaves, payload_len: int) -> None:
        total = 8 + payload_len
        # contiguity: pad to the ring's end when the record would wrap
        head = self._u64(self._tx_head)
        pos = head % self._cap
        rem = self._cap - pos
        if rem < total:
            self._wait(lambda: self._tx_free() >= rem, "send")
            if rem >= 8:
                _WORD.pack_into(
                    self._buf, self._tx_data + pos,
                    (FLAG_PAD << _FLAG_SHIFT) | (rem - 8),
                )
            self._advance_head(rem)
        self._wait(lambda: self._tx_free() >= total, "send")
        base = self._tx_data + (self._u64(self._tx_head) % self._cap)
        _WORD.pack_into(self._buf, base,
                        (FLAG_BULK << _FLAG_SHIFT) | payload_len)
        _U32.pack_into(self._buf, base + 8, len(skel))
        self._buf[base + 8 + _U32.size:base + 8 + _U32.size + len(skel)] = \
            skel
        leaf_base = base + 8 + _align64(_U32.size + len(skel))
        for arr, rel in leaves:
            if arr.nbytes == 0:
                continue
            view = np.frombuffer(
                self._buf, dtype=np.uint8, count=arr.nbytes,
                offset=leaf_base + rel,
            )
            # the ONE copy of the bulk payload: staging buffer → ring
            view[:] = arr.reshape(-1).view(np.uint8)
        self._advance_head(total)

    def recv_msg(self, copy: bool = False):
        """→ ``(msg, raw, release)``.

        ``raw`` is the frame's pickle bytes for pickle-lane records (the
        WAL's verbatim wire frame) and None for bulk records. ``release``
        is None unless the message holds live ring views (bulk,
        ``copy=False``): the caller MUST call it once the views are
        consumed — the ring space stays pinned (and the sender blocked
        past one in-flight record) until then. ``copy=True`` materializes
        views into fresh arrays and releases before returning."""
        if networking._fault_hook is not None:
            networking._fault_hook("recv", self)
        try:
            flags, length = self._next_record()
            if flags == FLAG_PKL:
                if length > networking.MAX_FRAME_BYTES + 8:
                    raise ProtocolError(
                        f"shm record of {length} bytes exceeds the frame "
                        f"cap", frame_size=int(length), peer=self._name,
                        retryable=False,
                    )
                prefix = self._read_exact(8)
                (n,) = networking._LEN.unpack(prefix)
                if n != length - 8:
                    raise ProtocolError(
                        f"shm pickle record length mismatch ({n} vs "
                        f"{length - 8})", peer=self._name, retryable=False,
                    )
                raw = bytes(self._read_exact(n))
                return networking.decode_frame(raw), raw, None
            if flags != FLAG_BULK:
                raise ProtocolError(
                    f"unknown shm record flags {flags}", peer=self._name,
                    retryable=False,
                )
            self._wait(lambda: self._rx_avail() >= length, "recv")
            base = self._rx_data + (self._u64(self._rx_tail) % self._cap)
            msg = self._decode_bulk(base, copy)
            if copy:
                self._advance_tail(length)
                return msg, None, None
        except (ValueError, TypeError) as e:
            raise self._torn(e) from e
        released = [False]

        def release():
            if not released[0]:
                released[0] = True
                try:
                    self._advance_tail(length)
                except (ValueError, TypeError) as e:
                    raise self._torn(e) from e

        return msg, None, release

    def _decode_bulk(self, base: int, copy: bool):
        (skel_len,) = _U32.unpack_from(self._buf, base)
        skel = bytes(self._buf[base + _U32.size:base + _U32.size + skel_len])
        tree = networking.decode_frame(skel)  # restricted unpickler
        leaf_base = base + _align64(_U32.size + skel_len)

        def rebuild(o):
            if (isinstance(o, tuple) and len(o) == 4
                    and o[0] == _LEAF_TAG):
                _, rel, dtname, shape = o
                dt = _resolve_dtype(dtname)
                count = int(np.prod(shape, dtype=np.int64))
                if count == 0:
                    return np.empty(shape, dt)
                view = np.frombuffer(
                    self._buf, dtype=dt, count=count,
                    offset=leaf_base + rel,
                ).reshape(shape)
                return np.array(view) if copy else view
            if isinstance(o, dict):
                return {k: rebuild(v) for k, v in o.items()}
            if isinstance(o, (list, tuple)):
                return type(o)(rebuild(v) for v in o)
            return o

        return rebuild(tree)


class ShmParameterServer(SocketParameterServer):
    """The PS served over shared-memory rings — ``ps_transport="shm"``.

    Colocated-only by design (the segment name is this process's), which
    is exactly the regime the socket wire was overpaying in. The action
    dispatch, fold path, WAL, fencing, heartbeats, elastic membership,
    stats, and trace spans are the inherited server's — only the framing
    differs: requests arrive through :meth:`_ShmConn.recv_msg` (pickle
    OR bulk lane), pull/exchange replies ship the center's leaves on the
    bulk lane (written once from the immutable snapshot into the mapped
    ring), and a durable server's commit frames arrive on the pickle
    lane so the WAL logs them VERBATIM (``REC_COMMIT_WIRE``) with the
    same replay pipeline as TCP.

    Connection lifecycle: :meth:`connect_shm` creates the segment and a
    dedicated handler thread; the segment is unlinked when the handler
    exits — client close, server stop/crash, or the heartbeat eviction
    of an abandoned worker (``_on_evict`` closes its connections), so
    /dev/shm never leaks.
    """

    def __init__(self, center: Pytree, rule, num_workers: int,
                 ring_bytes: int = DEFAULT_RING_BYTES,
                 ema_decay: float | None = None,
                 lease_timeout: float | None = None,
                 wal_dir: str | None = None, snapshot_every: int = 100,
                 fence_epoch: int = 0, wal_group_window: int = 8,
                 wal_group_interval: float = 0.25):
        super().__init__(center, rule, num_workers, host="shm", port=0,
                         ema_decay=ema_decay, lease_timeout=lease_timeout,
                         wal_dir=wal_dir, snapshot_every=snapshot_every,
                         fence_epoch=fence_epoch,
                         wal_group_window=wal_group_window,
                         wal_group_interval=wal_group_interval)
        if int(ring_bytes) < _HDR_BYTES:
            raise ValueError(
                f"ring_bytes must be >= {_HDR_BYTES}, got {ring_bytes}"
            )
        self.ring_bytes = int(ring_bytes)
        # segment records: {"seg", "conn", "wid", "released"} — guarded
        # by the inherited _conns_lock
        self._segments: list[dict] = []

    # -- lifecycle (no TCP anywhere) -----------------------------------------

    def initialize(self) -> None:
        self._running = True

    def start(self) -> None:
        pass  # no accept loop: connect_shm spawns handlers directly

    def run(self) -> None:
        pass

    def attach_standby(self, host: str, port: int,
                       timeout: float = 10.0) -> None:
        raise NotImplementedError(
            "the shm transport is colocated-only; replication streams "
            "(standby/chain) are the socket transport's job — "
            "trainers.py enforces ps_chain_length > 1 => socket"
        )

    def connect_shm(self, worker_id: int) -> tuple[_ShmConn, dict]:
        """Mint one worker↔PS connection: create the segment, spawn its
        handler thread, return the client endpoint plus the handshake
        record (``wal_frames``: send commit/exchange on the pickle lane
        so the WAL logs wire frames verbatim). Any worker id works —
        the elastic coordinator mints joiner clients through here."""
        if not self._running:
            raise ConnectionRefusedError("shm parameter server is stopped")
        seg = mint_segment("dkshm", self.ring_bytes)
        waker = _waker_for(seg.name)
        srv_conn = _ShmConn(seg, "server", waker)
        cli_conn = _ShmConn(seg, "client", waker)
        rec = {"seg": seg, "conn": srv_conn, "wid": int(worker_id),
               "released": False}
        with self._conns_lock:
            raced_stop = not self._running  # stop() raced the mint
            if not raced_stop:
                self._segments.append(rec)
        if raced_stop:
            self._release_segment(rec)
            raise ConnectionRefusedError("shm parameter server is stopped")
        t = threading.Thread(
            target=self._serve_shm, args=(srv_conn, rec), daemon=True,
            name=f"dkshm-handler-{worker_id}",
        )
        t.start()
        self._handlers.append(t)
        return cli_conn, {
            "wal_frames": self._wal is not None, "worker_id": int(worker_id),
        }

    def _release_segment(self, rec: dict) -> None:
        """Close + UNLINK one connection's segment (idempotent): flag
        both endpoints closed (waking any blocked peer), then remove the
        /dev/shm name — the no-leak contract. The client's mapping stays
        valid until it drops its own references (unlink only removes the
        name)."""
        with self._conns_lock:
            if rec.get("released"):
                return
            rec["released"] = True
            if rec in self._segments:
                self._segments.remove(rec)
        seg = rec["seg"]
        rec["conn"].close()
        rec["conn"].detach_buffer()
        try:
            _WORD.pack_into(seg.buf, _OFF_SERVER_CLOSED, 1)
            _WORD.pack_into(seg.buf, _OFF_CLIENT_CLOSED, 1)
        except (ValueError, TypeError):
            pass
        cond = _waker_for(seg.name)
        with cond:
            cond.notify_all()
        _waker_drop(seg.name)
        try:
            seg.close()
        except BufferError:
            # live numpy views into the mapping (a client mid-teardown):
            # the name still unlinks below; the pages unmap at GC
            pass
        try:
            seg.unlink()
        except FileNotFoundError:
            pass
        unregister_segment(seg.name)

    def ring_occupancy(self) -> list[dict]:
        """Per-connection ring occupancy read straight off the mapped
        headers (no locks, no syscalls): used bytes of each direction's
        ring and the fuller direction's used fraction. The watchtower's
        scraper samples the max across connections into
        ``shm.ring_occupancy_frac`` — near 1.0 means a writer is about
        to block on a stalled reader (or the ring is undersized)."""
        with self._conns_lock:
            recs = list(self._segments)
        out = []
        for rec in recs:
            seg = rec["seg"]
            try:
                buf = seg.buf
                cap = _WORD.unpack_from(buf, _OFF_CAP)[0]
                c2s = (_WORD.unpack_from(buf, _OFF_C2S_HEAD)[0]
                       - _WORD.unpack_from(buf, _OFF_C2S_TAIL)[0])
                s2c = (_WORD.unpack_from(buf, _OFF_S2C_HEAD)[0]
                       - _WORD.unpack_from(buf, _OFF_S2C_TAIL)[0])
            except (ValueError, TypeError):
                continue  # racing a release: this segment is going away
            if cap <= 0:
                continue
            out.append({
                "name": seg.name, "worker_id": rec["wid"],
                "cap": int(cap), "c2s_used": int(c2s),
                "s2c_used": int(s2c),
                "frac": max(int(c2s), int(s2c)) / int(cap),
            })
        return out

    def stop(self) -> None:
        if not self._running:
            self._close_durability()
            return
        self._running = False
        with self._conns_lock:
            recs = list(self._segments)
        for rec in recs:
            self._release_segment(rec)
        for t in self._handlers:
            t.join(timeout=5)
        self._close_durability()

    def _crash(self) -> None:
        """Chaos seam: tear every ring and abandon the WAL un-flushed,
        like the socket server's SIGKILL simulation. Segments are still
        unlinked — a REAL kill would leave /dev/shm entries for a
        restart janitor; the in-process simulation cleans up so chaos
        tests cannot leak them into the suite."""
        self.crashed_ = True
        self._running = False
        with self._conns_lock:
            recs = list(self._segments)
        for rec in recs:
            self._release_segment(rec)
        if self._wal is not None:
            self._wal.abandon()

    def _on_evict(self, worker_ids) -> None:
        """Lease expiry reclaims the zombie's transport too: close its
        connections so their handlers exit and the segments unlink —
        the heartbeat eviction IS the shm lane's abandoned-worker
        garbage collector (satellite: no /dev/shm leaks)."""
        super()._on_evict(worker_ids)
        wids = set(int(w) for w in worker_ids)
        with self._conns_lock:
            recs = [r for r in self._segments if r["wid"] in wids]
        for rec in recs:
            self._release_segment(rec)

    # -- the handler ---------------------------------------------------------

    def _serve_shm(self, conn: _ShmConn, rec: dict) -> None:
        """The socket handler's action dispatch over ring framing. Bulk
        commit/exchange payloads are folded DIRECTLY from the mapped
        ring views — the region is released only after the dispatch
        consumed it (request-reply keeps at most one record in flight,
        so pinning it never deadlocks the sender)."""
        try:
            while True:
                msg, raw, release = conn.recv_msg()
                try:
                    action = msg.get("action")
                    if _trace.enabled():
                        _trace.set_corr(msg.get("corr"))
                    if action == "pull":
                        self._serve_pull_shm(conn, msg["worker_id"])
                    elif action == "pull_int8":
                        self._serve_compressed_pull_shm(
                            conn, msg["worker_id"]
                        )
                    elif action == "commit":
                        try:
                            applied = self.commit(
                                msg["worker_id"], msg["payload"],
                                seq=msg.get("seq"), epoch=msg.get("epoch"),
                                wire_frame=raw,
                            )
                        except networking.FencedEpochError as fe:
                            conn.send_msg({
                                "error": "fenced", "epoch": fe.server_epoch,
                            })
                            continue
                        conn.send_msg({"ok": True, "dup": not applied})
                    elif action == "exchange":
                        self._serve_exchange_shm(conn, msg, raw)
                    elif action == "ping":
                        conn.send_msg({
                            "ok": True, "epoch": self.fence_epoch,
                            "num_updates": self.num_updates,
                            "standby": False,
                            "shard": self.shard_info,
                        })
                    elif action == "shard_map":
                        conn.send_msg({
                            "ok": True, "shard": self.shard_info,
                            "epoch": self.fence_epoch,
                        })
                    elif action == "fence":
                        conn.send_msg({
                            "ok": True,
                            "epoch": self.fence(int(msg["epoch"])),
                        })
                    elif action == "heartbeat":
                        known = self.heartbeat(
                            msg["worker_id"],
                            retries=msg.get("retries", 0),
                        )
                        conn.send_msg({"ok": True, "known": known})
                    elif action == "deregister":
                        self.deregister_worker(msg["worker_id"])
                        conn.send_msg({"ok": True})
                    elif action == "join":
                        out = self.join_worker(msg["worker_id"])
                        out["ok"] = True
                        conn.send_msg(out)
                    elif action == "drain":
                        self.drain_worker(msg["worker_id"],
                                          timeout=bool(msg.get("timeout")))
                        conn.send_msg({"ok": True})
                    elif action == "stats":
                        conn.send_msg({"ok": True, "stats": self.stats()})
                    elif action == "metrics":
                        from distkeras_tpu.observability.metrics import (
                            metrics_reply,
                            ps_metrics,
                        )

                        conn.send_msg(metrics_reply(
                            ps_metrics(self.stats()), self.watchtower,
                        ))
                    elif action in ("stop", "bye"):
                        break
                    else:
                        conn.send_msg({"error": f"bad action {action}"})
                finally:
                    if release is not None:
                        release()
        except (ConnectionError, EOFError, OSError):
            pass  # torn ring / dead peer / injected fault: drop the conn
        except pickle.UnpicklingError:
            pass  # garbled frame rejected by the restricted unpickler
        finally:
            self._release_segment(rec)

    def _serve_pull_shm(self, conn: _ShmConn, worker_id: int) -> None:
        """Bulk-lane pull reply: the immutable center snapshot's leaves
        written ONCE into the ring (no pickle pass); counters land after
        delivery — the same delivered-traffic semantics as TCP."""
        with _trace.span("ps.pull"):
            snap, _ = self._begin_pull(worker_id, compressed=False)
            self._begin_reply()
            try:
                conn.send_msg({"weights": snap}, bulk=True)
                self._count(pulls=1, bytes_out=self._center_nbytes)
            finally:
                self._end_reply()

    def _serve_compressed_pull_shm(self, conn: _ShmConn,
                                   worker_id: int) -> None:
        """int8 error-feedback pull with the dropped-reply residual
        rollback (epoch-guarded, same as the socket/native lanes)."""
        with _trace.span("ps.pull_int8"):
            snap, st = self._begin_pull(worker_id, compressed=True)
            with st.lock:
                blob, nbytes = self._encode_pull(st, snap)
                epoch = st.epoch
            self._begin_reply()
            try:
                conn.send_msg({"weights": blob}, bulk=True)
                self._count(compressed_pulls=1, bytes_out=nbytes)
            except (ConnectionError, OSError):
                with st.lock:
                    if st.epoch == epoch:
                        self._rollback_encode_locked(st, snap, blob)
                raise
            finally:
                self._end_reply()

    def _serve_exchange_shm(self, conn: _ShmConn, msg: dict,
                            raw: bytes | None) -> None:
        """Fused commit+pull over the rings: the commit half folds from
        the request's mapped views (or the pickle lane's decoded frame
        on durable servers, logged verbatim), the pull half ships the
        post-fold snapshot on the bulk lane."""
        compressed = bool(msg.get("compressed"))
        with _trace.span("ps.exchange"):
            try:
                applied, snap, st = self._commit_impl(
                    msg["worker_id"], msg["payload"], seq=msg.get("seq"),
                    epoch=msg.get("epoch"), wire_frame=raw, fused=True,
                    lag=bool(msg.get("lag")), compressed=compressed,
                )
            except networking.FencedEpochError as fe:
                conn.send_msg({"error": "fenced", "epoch": fe.server_epoch})
                return
            if not compressed:
                self._begin_reply()
                try:
                    conn.send_msg(
                        {"ok": True, "dup": not applied, "weights": snap},
                        bulk=True,
                    )
                    self._count(pulls=1, bytes_out=self._center_nbytes,
                                fused=1)
                finally:
                    self._end_reply()
                return
            with st.lock:
                blob, nbytes = self._encode_pull(st, snap)
                epoch_ = st.epoch
            self._begin_reply()
            try:
                conn.send_msg(
                    {"ok": True, "dup": not applied, "weights": blob},
                    bulk=True,
                )
                self._count(compressed_pulls=1, bytes_out=nbytes, fused=1)
            except (ConnectionError, OSError):
                with st.lock:
                    if st.epoch == epoch_:
                        self._rollback_encode_locked(st, snap, blob)
                raise
            finally:
                self._end_reply()


class ShmPSClient(ParameterServerClient):
    """Worker-side shm client — :class:`ParameterServerClient`'s exact
    surface over a ring pair. Control actions (ping/heartbeat/join/
    drain/fence/shard_map/deregister/close) run through the INHERITED
    implementations: ``networking.send_data``/``recv_data`` speak to the
    duck-socket, so the wire semantics (and the fault-injection seam)
    cannot drift from TCP. Only the O(model) paths are overridden:

    - ``pull``/``exchange`` replies arrive on the bulk lane and are
      materialized (one copy out of the mapped ring) before release;
    - ``commit``/``exchange`` requests ship staged delta leaves on the
      bulk lane — written once into the ring, folded server-side from
      the mapped view. Against a DURABLE server (handshake
      ``wal_frames``) they use the pickle lane instead, so the WAL's
      verbatim wire-frame logging and replay work unchanged.
    """

    def __init__(self, server: ShmParameterServer, worker_id: int,
                 pull_compression: str | None = None,
                 epoch: int | None = None):
        from distkeras_tpu.parallel.compression import (
            validate_pull_compression,
        )

        self.pull_compression = validate_pull_compression(pull_compression)
        self.worker_id = int(worker_id)
        self.epoch = None if epoch is None else int(epoch)
        conn, info = server.connect_shm(self.worker_id)
        self._sock = conn  # the duck-socket: inherited actions just work
        self._wal_frames = bool(info.get("wal_frames"))

    def _request(self, msg: dict, bulk: bool) -> dict:
        """One request-reply round trip on the message layer; bulk
        replies are materialized (copy) so the ring region frees before
        the caller holds the tree long-term."""
        self._sock.send_msg(msg, bulk=bulk)
        reply, _raw, _release = self._sock.recv_msg(copy=True)
        return reply

    def pull(self, worker_id: int | None = None) -> Pytree:
        action = "pull_int8" if self.pull_compression == "int8" else "pull"
        reply = self._request(
            {"action": action, "worker_id": self.worker_id}, bulk=False
        )
        if "weights" not in reply:
            raise ProtocolError(
                f"pull refused: {reply.get('error', reply)}", retryable=True
            )
        return maybe_decode(reply["weights"])

    def commit(self, worker_id: int | None, payload: Pytree,
               seq: int | None = None) -> None:
        if not is_encoded(payload):
            payload = utils.tree_to_numpy(payload)
        msg = {
            "action": "commit",
            "worker_id": self.worker_id,
            "payload": payload,
        }
        if _trace.enabled() and (corr := _trace.current_corr()):
            msg["corr"] = corr
        if seq is not None:
            msg["seq"] = int(seq)
        if self.epoch is not None:
            msg["epoch"] = self.epoch
        # durable servers get the pickle lane (verbatim WAL wire frames);
        # otherwise the payload leaves ride the zero-copy bulk lane
        ack = self._request(msg, bulk=not self._wal_frames)
        err = ack.get("error") if isinstance(ack, dict) else None
        if err == "fenced":
            raise networking.FencedEpochError(
                "commit fenced by the server",
                client_epoch=self.epoch, server_epoch=ack.get("epoch"),
            )
        if err is not None:
            raise ProtocolError(f"commit refused: {err}", retryable=True)

    def exchange(self, worker_id: int | None, payload: Pytree,
                 seq: int | None = None, lag: bool = False) -> Pytree:
        if not is_encoded(payload):
            payload = utils.tree_to_numpy(payload)
        msg = {
            "action": "exchange",
            "worker_id": self.worker_id,
            "payload": payload,
        }
        if _trace.enabled() and (corr := _trace.current_corr()):
            msg["corr"] = corr
        if self.pull_compression == "int8":
            msg["compressed"] = True
        if seq is not None:
            msg["seq"] = int(seq)
        if self.epoch is not None:
            msg["epoch"] = self.epoch
        if lag:
            msg["lag"] = True
        reply = self._request(msg, bulk=not self._wal_frames)
        err = reply.get("error") if isinstance(reply, dict) else None
        if err == "fenced":
            raise networking.FencedEpochError(
                "exchange fenced by the server",
                client_epoch=self.epoch, server_epoch=reply.get("epoch"),
            )
        if "weights" not in reply:
            raise ProtocolError(
                f"exchange refused: {reply.get('error', reply)}",
                retryable=True,
            )
        return maybe_decode(reply["weights"])
