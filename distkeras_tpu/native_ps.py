"""Native parameter-server transport — C++ service, flat-f32 wire, no GIL.

Parity context: the reference's socket PS (reference
``distkeras/parameter_servers.py :: SocketParameterServer`` +
``distkeras/networking.py``) pickled the full weight set per round-trip and
folded commits in Python handler threads holding the GIL — SURVEY.md §3.3
names that loop the scalability choke point. ``ps_transport="native"`` swaps
the whole wire path for the C++ core in ``native/dkps.cpp``: weights travel
as one contiguous float32 vector (no pickle; frame sizes pinned at
handshake, so no attacker-sized allocations either), the commit fold is a
vectorized ``center += scale * commit`` under a C++ mutex, and every ctypes
call releases the GIL — worker threads pull/commit truly concurrently.

The fold math is the SAME linear form every built-in ``MergeRule.fold``
defines (``parallel/merge_rules.py``): ADAG scales commits by
``1/num_workers``, DOWNPOUR and the elastic rules by ``1``, DynSGD by
``1/(τ+1)`` with τ tracked per worker server-side — so both socket and
native transports are pinned to the same oracle by the tests. Custom merge
rules with non-linear folds must use ``ps_transport="socket"``; the
constructor rejects them.

Pytree ↔ wire translation happens once per call at the Python boundary
(:class:`FlatSpec`): leaves are raveled C-order into one float32 vector in
canonical ``jax.tree.flatten`` order, and restored to their original shapes
and dtypes on the way out.
"""

from __future__ import annotations

import ctypes
import os
import struct
import time
from typing import Any

import numpy as np

from distkeras_tpu.native import load_dkps
from distkeras_tpu.parallel.merge_rules import (
    ADAGMerge,
    DownpourMerge,
    DynSGDMerge,
    ElasticAverageMerge,
    MergeRule,
)

Pytree = Any

_MODE_FIXED = 0
_MODE_INV_STALENESS = 1


def fold_mode(rule: MergeRule, num_workers: int) -> tuple[int, float]:
    """Map a built-in merge rule to the server's (mode, fixed_scale).

    Mirrors each rule's ``fold``: ADAG ``c + d/W``; DOWNPOUR/elastic
    ``c + d``; DynSGD ``c + d/(τ+1)``.
    """
    if isinstance(rule, DynSGDMerge):
        return _MODE_INV_STALENESS, 1.0
    if isinstance(rule, ADAGMerge):
        return _MODE_FIXED, 1.0 / float(num_workers)
    if isinstance(rule, (DownpourMerge, ElasticAverageMerge)):
        return _MODE_FIXED, 1.0
    raise ValueError(
        f"ps_transport='native' supports the built-in linear merge rules "
        f"(ADAG/DOWNPOUR/elastic/DynSGD); {type(rule).__name__} defines an "
        f"arbitrary fold — use ps_transport='socket'"
    )


class FlatSpec:
    """Shape/dtype spec translating a numpy pytree ↔ one float32 vector."""

    def __init__(self, template: Pytree):
        import jax

        leaves, self.treedef = jax.tree.flatten(template)
        self.shapes = [np.shape(l) for l in leaves]
        self.dtypes = [np.asarray(l).dtype for l in leaves]
        self.sizes = [int(np.prod(s, dtype=np.int64)) for s in self.shapes]
        self.n = int(sum(self.sizes))

    def flatten(self, tree: Pytree) -> np.ndarray:
        import jax

        leaves = jax.tree.leaves(tree)
        if len(leaves) != len(self.sizes):
            raise ValueError(
                f"tree has {len(leaves)} leaves, spec expects {len(self.sizes)}"
            )
        out = np.empty(self.n, dtype=np.float32)
        off = 0
        for leaf, size in zip(leaves, self.sizes):
            out[off:off + size] = np.ravel(
                np.asarray(leaf, dtype=np.float32), order="C"
            )
            off += size
        return out

    def unflatten(self, vec: np.ndarray) -> Pytree:
        import jax

        leaves = []
        off = 0
        for shape, dtype, size in zip(self.shapes, self.dtypes, self.sizes):
            leaves.append(
                vec[off:off + size].reshape(shape).astype(dtype, copy=False)
            )
            off += size
        return jax.tree.unflatten(self.treedef, leaves)


def _f32p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class NativeSocketParameterServer:
    """C++ TCP parameter server with the ``SocketParameterServer`` surface.

    ``initialize()`` binds (resolving an ephemeral port), ``start()`` runs
    the C++ accept loop, ``stop()`` shuts it down and joins every handler.
    ``get_model()``/``num_updates`` read the center under the C++ mutex.
    """

    def __init__(self, center: Pytree, rule: MergeRule, num_workers: int,
                 host: str = "127.0.0.1", port: int = 0,
                 ema_decay: float | None = None,
                 lease_timeout: float | None = None,
                 wal_dir: str | None = None, snapshot_every: int = 100,
                 fence_epoch: int = 0, wal_group_window: int = 8,
                 wal_group_interval: float = 0.25):
        # Durability (ISSUE 7 — the fastest transport is no longer the
        # least durable): `wal_dir` attaches the C++ group-commit WAL.
        # The C++ side appends flat CRC-framed records (same frame format
        # as resilience/wal.py) and defers each commit's ACK until its
        # group's fsync; THIS side owns recovery — it replays
        # (snapshot, wal) through the same recover_ps_state path the
        # Python PS uses (bit-identical: flat records carry the exact
        # fold scale), restores the center/EMA/dedup/staleness state into
        # the C++ server, publishes a fresh base snapshot, and hands the
        # live segment to the native appender.
        self._requested_fence_epoch = int(fence_epoch)
        self.wal_dir = None if wal_dir is None else str(wal_dir)
        self.snapshot_every = int(snapshot_every)
        self.wal_group_window = int(wal_group_window)
        self.wal_group_interval = float(wal_group_interval)
        self.recovered_ = False
        self.wal_replay_s = 0.0
        self.crashed_ = False
        self.shard_info: dict | None = None  # see set_shard_info
        self._lib = load_dkps(required=True)
        self.spec = FlatSpec(center)
        self.rule = rule
        self.num_workers = int(num_workers)
        self.host = host
        self.port = int(port)
        self._requested_port = int(port)
        self._handle = None
        self._init_vec = self.spec.flatten(center)
        # Polyak/EMA of the center, folded per commit in C++ (parity with
        # ParameterServer.get_ema); negative sentinel = off on the C ABI
        if ema_decay is not None:
            ema_decay = float(ema_decay)
            if not 0.0 <= ema_decay < 1.0:
                raise ValueError(
                    f"ema_decay must be in [0, 1), got {ema_decay}"
                )
        self.ema_decay = ema_decay
        # worker-lease timeout (HEARTBEAT wire action; parity with the
        # Python PS's registry): <= 0 / None keeps the server's 30 s
        # default — leases only bite once a client heartbeats
        if lease_timeout is not None and lease_timeout <= 0:
            raise ValueError(
                f"lease_timeout must be positive, got {lease_timeout}"
            )
        self.lease_timeout = lease_timeout
        # shm ring lane (ISSUE 12): segments minted by attach_shm, owned
        # (and unlinked) by this wrapper — the C++ side only maps them
        self._shm_segments: list = []

    def initialize(self) -> None:
        state = self._recover_wal_state()
        mode, scale = fold_mode(self.rule, self.num_workers)
        init_vec = self._init_vec
        if state is not None:
            init_vec = np.ascontiguousarray(
                self.spec.flatten(state["center"])
            )
        h = self._lib.dkps_server_create(
            _f32p(init_vec), self.spec.n, mode, scale,
            self.host.encode(), self._requested_port,
            -1.0 if self.ema_decay is None else self.ema_decay,
            -1.0 if self.lease_timeout is None else self.lease_timeout,
        )
        if not h:
            raise OSError(
                f"dkps server failed to bind {self.host}:{self._requested_port}"
            )
        self._handle = h
        self.port = int(self._lib.dkps_server_port(h))
        fence = self._requested_fence_epoch
        if state is not None:
            self._restore_state(state)
            fence = max(fence, int(state["fence_epoch"]))
        if fence:
            self._lib.dkps_server_fence(h, fence)
        # elastic pool gauge base (stats parity with the Python PS, whose
        # _pool_size starts at num_workers; the C ABI has no worker count
        # of its own — the fold scale is baked into the mode)
        self._lib.dkps_server_set_pool_size(h, self.num_workers)
        if self.wal_dir is not None:
            self._attach_wal(state)
        self._t_start = time.monotonic()  # stats() rate denominator

    # -- durability plumbing (recovery is Python's job, appending C++'s) -----

    def _recover_wal_state(self) -> dict | None:
        if self.wal_dir is None:
            return None
        from distkeras_tpu.resilience.wal import recover_ps_state

        t0 = time.monotonic()
        state = recover_ps_state(
            self.wal_dir, self.rule, self.num_workers, self.ema_decay,
            template=self.spec.unflatten(self._init_vec),
        )
        if state is not None:
            self.recovered_ = True
            self.wal_replay_s = time.monotonic() - t0
        return state

    def _restore_state(self, state: dict) -> None:
        """Install the replayed durable state into the C++ server: update
        count, per-worker dedup seqnos + pull versions (the exactly-once
        fence and the DynSGD staleness base), and the EMA."""
        self._lib.dkps_server_set_num_updates(
            self._handle, int(state["num_updates"])
        )
        prev = state.get("prev_pull_versions", {})
        wids = set(state["pull_versions"]) | set(state["last_seq"]) \
            | set(prev)
        for wid in wids:
            self._lib.dkps_server_restore_worker(
                self._handle, int(wid),
                int(state["last_seq"].get(wid, -1)),
                int(state["pull_versions"].get(wid, -1)),
                int(prev.get(wid, -1)),
            )
        if self.ema_decay is not None and state.get("ema") is not None:
            ema_vec = np.ascontiguousarray(self.spec.flatten(state["ema"]))
            self._lib.dkps_server_set_ema(self._handle, _f32p(ema_vec))

    def _attach_wal(self, state: dict | None) -> None:
        """Publish a fresh base snapshot at the (possibly recovered)
        version — which also truncates pre-snapshot history — and hand
        the live segment to the C++ appender. The snapshot is written by
        the SAME CommitLog machinery the Python PS uses, so the on-disk
        layout is transport-agnostic: a native log replays through
        recover_ps_state, a recovered directory can even switch
        transports between runs."""
        from distkeras_tpu.resilience import wal as _wal

        version = self.num_updates
        if state is not None:
            snap_state = dict(state)
            snap_state.pop("replayed", None)
        else:
            center = self.spec.unflatten(self._init_vec)
            snap_state = _wal.ps_state_dict(
                center, 0, {}, {},
                None, 0, self.fence_epoch,
            )
            if self.ema_decay is not None:
                import jax

                snap_state["ema"] = jax.tree.map(
                    np.copy, snap_state["center"]
                )
                snap_state["ema_version"] = 0
        snap_state["fence_epoch"] = max(
            int(snap_state.get("fence_epoch", 0)), self.fence_epoch
        )
        log = _wal.CommitLog(self.wal_dir,
                             snapshot_every=self.snapshot_every)
        try:
            # rotate-then-publish, the Python PS's snapshot discipline:
            # open (and torn-tail-truncate) the live segment at the base
            # version FIRST, so the publish's history truncation never
            # strands un-snapshotted records
            log.rotate(version)
            log.publish_snapshot(snap_state)
        finally:
            log.close()
        seg_path = os.path.join(
            self.wal_dir, f"{_wal._SEG_PREFIX}{version:012d}{_wal._SEG_SUFFIX}"
        )
        rc = self._lib.dkps_server_wal_open(
            self._handle, seg_path.encode(),
            max(0, self.wal_group_window), self.wal_group_interval,
        )
        if rc != 0:
            raise OSError(f"dkps could not open WAL segment {seg_path}")

    def crash(self) -> None:
        """Chaos seam (parity with SocketParameterServer._crash): die like
        a SIGKILL'd process — connections torn, WAL abandoned losing its
        un-flushed pending buffer, no final fsync."""
        if self._handle is not None:
            self._lib.dkps_server_crash(self._handle)
        self.crashed_ = True
        self._release_shm_segments()  # crash joins handlers first (C++)

    def start(self) -> None:
        self._lib.dkps_server_start(self._handle)

    def run(self) -> None:  # surface parity; the accept loop is a C++ thread
        self.start()

    def stop(self) -> None:
        if self._handle is not None:
            self._lib.dkps_server_stop(self._handle)
        # stop joined every handler thread in C++, so no ring is in use:
        # safe to drop the /dev/shm names now (no-leak contract)
        self._release_shm_segments()

    # -- shm ring lane (ISSUE 12, parity with distkeras_tpu/shm.py) ----------

    def attach_shm(self, ring_bytes: int | None = None):
        """Mint one ring-pair segment and attach a C++ handler thread to
        it; returns the ``SharedMemory`` segment the colocated client
        connects through (``NativePSClient.connect_shm``). The segment
        carries the SAME header layout as the Python shm transport; the
        native wire's own framing rides the rings as a raw byte pipe.
        Segments are unlinked at server stop/crash — the C++ side joins
        every handler before Python drops the names."""
        from distkeras_tpu import shm as _shm

        if ring_bytes is None:
            # default: one full f32 frame per ring plus slack, capped at
            # the Python lane's default (the byte pipe streams larger
            # frames through anyway — size is throughput, not a limit)
            ring_bytes = min(
                _shm.DEFAULT_RING_BYTES,
                max(1 << 16, int(self.spec.n) * 4 + 8192),
            )
        # the ONE segment mint (name scheme + header layout live in
        # shm.py — the two lanes cannot drift on the contract)
        seg = _shm.mint_segment("dkshm_native", ring_bytes)
        addr = ctypes.addressof(ctypes.c_char.from_buffer(seg.buf))
        rc = int(self._lib.dkps_server_attach_shm(
            self._handle, ctypes.c_void_p(addr), seg.size
        ))
        if rc == 0:
            try:
                seg.close()
            except BufferError:
                pass
            seg.unlink()
            _shm.unregister_segment(seg.name)
            raise OSError("dkps_server_attach_shm failed (server stopped "
                          "or channel table full)")
        self._shm_segments.append(seg)
        return seg

    def _release_shm_segments(self) -> None:
        from distkeras_tpu import shm as _shm

        segs, self._shm_segments = self._shm_segments, []
        for seg in segs:
            try:
                seg.close()
            except BufferError:
                pass  # a client still maps it; the name still unlinks
            try:
                seg.unlink()
            except FileNotFoundError:
                pass
            _shm.unregister_segment(seg.name)

    def __del__(self):
        if getattr(self, "_handle", None) is not None:
            self._lib.dkps_server_destroy(self._handle)
            self._handle = None

    # -- center access -------------------------------------------------------

    @property
    def num_updates(self) -> int:
        if self._handle is None:
            return 0
        return int(self._lib.dkps_server_num_updates(self._handle))

    @num_updates.setter
    def num_updates(self, v: int) -> None:
        self._lib.dkps_server_set_num_updates(self._handle, int(v))

    def get_model(self) -> Pytree:
        out = np.empty(self.spec.n, dtype=np.float32)
        self._lib.dkps_server_get_center(self._handle, _f32p(out))
        return self.spec.unflatten(out)

    def set_model(self, tree: Pytree) -> None:
        vec = np.ascontiguousarray(self.spec.flatten(tree))
        self._lib.dkps_server_set_center(self._handle, _f32p(vec))

    def get_ema(self) -> Pytree | None:
        """The Polyak-averaged center (None unless ``ema_decay`` was set)."""
        if self.ema_decay is None:
            return None
        out = np.empty(self.spec.n, dtype=np.float32)
        if self._lib.dkps_server_get_ema(self._handle, _f32p(out)) != 0:
            return None
        return self.spec.unflatten(out)

    def stats(self) -> dict:
        """Contention + throughput counters — the SAME keys and derived
        math as ``ParameterServer.stats()`` (shared ``build_ps_stats``
        assembler; parity pinned by test_native_ps.py), sourced from the
        C++ server's atomics: op counts, payload bytes moved, and
        center-mutex wait/hold totals for the hot-path sections (pull
        snapshot memcpy, commit fold). Rates are computed here against
        the time since ``initialize()``."""
        from distkeras_tpu.parameter_servers import build_ps_stats

        raw = (ctypes.c_uint64 * 22)()
        self._lib.dkps_server_stats(self._handle, raw)
        (pulls, cpulls, commits, bytes_in, bytes_out, acq, wait, hold,
         dups, active, evicted, heartbeats, retries, fenced,
         wal_records, wal_fsyncs, wal_group_max, pool, joined,
         preempted, drain_to, fused) = (
            int(v) for v in raw)
        return build_ps_stats(
            pulls, cpulls, commits, bytes_in, bytes_out, acq, wait, hold,
            time.monotonic() - self._t_start, dup_commits=dups,
            active_workers=active, evicted_workers=evicted,
            heartbeats=heartbeats, worker_retries=retries,
            fenced_commits=fenced, num_updates=self.num_updates,
            wal_records=wal_records, wal_fsyncs=wal_fsyncs,
            wal_group_max=wal_group_max, pool_size=pool,
            joined_workers=joined, preempted_workers=preempted,
            drain_timeouts=drain_to, fused_exchanges=fused,
        )

    # -- fencing (protocol parity with the Python PS) ------------------------

    @property
    def fence_epoch(self) -> int:
        if self._handle is None:
            return self._requested_fence_epoch
        return int(self._lib.dkps_server_fence_epoch(self._handle))

    def fence(self, epoch: int) -> int:
        """Raise the fencing epoch (monotone); returns the new value."""
        return int(self._lib.dkps_server_fence(self._handle, int(epoch)))

    # -- shard-map handshake (distkeras_tpu/sharding) ------------------------

    def set_shard_info(self, shard_id: int, num_shards: int) -> None:
        """Mark this server as holding one shard of an N-way partitioned
        center; SHARD_INFO (action 11) then advertises it to clients.
        Also mirrors the record onto ``self.shard_info`` for surface
        parity with the Python servers."""
        self._lib.dkps_server_set_shard(
            self._handle, int(shard_id), int(num_shards)
        )
        self.shard_info = {
            "shard_id": int(shard_id), "num_shards": int(num_shards),
        }

    # -- flight recorder (ISSUE 11, distkeras_tpu/observability) -------------

    #: span-kind → name map for the C++ ring (dkps.cpp TK_*): the scraped
    #: spans use the same "ps.*" namespace the Python server records, so
    #: a Perfetto timeline reads identically across transports
    _TRACE_KINDS = {1: "ps.fold", 2: "ps.wal_wait", 3: "wal.fsync"}

    def set_trace(self, on: bool) -> None:
        """Arm (or disarm) the C++ span ring: fold sections, deferred-ACK
        WAL waits, and group fsyncs start recording (CLOCK_MONOTONIC ns —
        the Python tracer's clock)."""
        self._lib.dkps_server_set_trace(self._handle, 1 if on else 0)

    def scrape_trace_events(self, max_records: int = 8192) -> list[dict]:
        """Drain the server's span ring over the TRACE wire action into
        tracer-shaped event dicts (the ``observability.trace.add_events``
        contract). The correlation id is rebuilt from the wire-carried
        (worker id, seqno) — ``w<id>:s<seq>`` — matching what the
        resilient client stamped on the worker side; spans without a
        seqno (plain commits, fsyncs) carry the worker id alone or no
        corr at all."""
        import ctypes as _ct

        client = NativePSClient("127.0.0.1", self.port, 2**32 - 2,
                                self.spec)
        try:
            buf = (_ct.c_uint64 * (5 * max_records))()
            n = int(self._lib.dkps_client_trace_scrape(
                client._handle, buf, max_records
            ))
        finally:
            client.close()
        if n < 0:
            raise ConnectionError("dkps trace scrape failed")
        events = []
        for i in range(n):
            kind, wid, seq, t0, dur = buf[5 * i : 5 * i + 5]
            if wid == 0xFFFFFFFF:
                corr = None          # server-internal (flusher fsync)
            elif seq:
                corr = f"w{wid}:s{seq}"
            else:
                corr = f"w{wid}"
            events.append({
                "name": self._TRACE_KINDS.get(kind, f"ps.kind{kind}"),
                "cat": "dkps", "corr": corr, "t0_ns": int(t0),
                "dur_ns": int(dur), "tid": 1 + (self.port & 0xFFFF),
                "tname": f"dkps:{self.port}",
            })
        return events


class NativePSClient:
    """Worker-side proxy over the C ABI — same call surface as
    ``ParameterServerClient``, GIL released for the whole round-trip."""

    def __init__(self, host: str, port: int, worker_id: int, spec: FlatSpec,
                 connect_timeout: float = 30.0,
                 pull_compression: str | None = None,
                 epoch: int | None = None):
        import socket as _socket

        from distkeras_tpu.parallel.compression import (
            validate_pull_compression,
        )

        self.pull_compression = validate_pull_compression(pull_compression)
        # fencing token: commits with seq AND epoch ride COMMIT_SEQ_E
        # (action 10); None = legacy COMMIT_SEQ, never fenced
        self.epoch = None if epoch is None else int(epoch)
        self._lib = load_dkps(required=True)
        self.worker_id = int(worker_id)
        self.spec = spec
        # Python owns connection establishment (DNS names, IPv6, connect
        # timeout — same semantics as networking.connect); C adopts the fd
        # for the hot-path framing. Blocking mode must be restored before
        # the handover: a create_connection timeout leaves O_NONBLOCK set.
        try:
            sock = _socket.create_connection(
                (host, int(port)), timeout=connect_timeout
            )
        except OSError as e:
            raise ConnectionError(
                f"dkps client could not connect to {host}:{port}: {e}"
            ) from e
        sock.settimeout(None)  # clear O_NONBLOCK before the C side recv()s
        # …but keep the handshake itself bounded (a silent listener must not
        # hang us): SO_RCVTIMEO survives the fd handover, unlike settimeout
        sec = max(1, int(connect_timeout))
        tv = struct.pack("ll", sec, 0)
        sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_RCVTIMEO, tv)
        sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_SNDTIMEO, tv)
        self._handle = self._lib.dkps_client_from_fd(
            sock.detach(), self.worker_id, spec.n
        )
        if not self._handle:
            raise ConnectionError(
                f"dkps handshake with {host}:{port} failed (is it a dkps "
                f"server, and does its vector length match {spec.n}?)"
            )
        # blocking round-trips by default, like ParameterServerClient (a
        # pull may legitimately wait behind many commits)
        self.set_timeout(None)

    @classmethod
    def connect_shm(cls, server: "NativeSocketParameterServer",
                    worker_id: int,
                    pull_compression: str | None = None,
                    epoch: int | None = None,
                    ring_bytes: int | None = None) -> "NativePSClient":
        """Mint a shm ring-lane client against a COLOCATED native server
        (ISSUE 12): the server attaches a fresh segment + handler thread
        and this side handshakes through the rings — every client op
        then runs unchanged over the zero-syscall byte pipes. The
        returned client keeps the mapping alive; the server owns the
        /dev/shm name and unlinks it at stop."""
        from distkeras_tpu.parallel.compression import (
            validate_pull_compression,
        )

        seg = server.attach_shm(ring_bytes)
        self = cls.__new__(cls)
        self.pull_compression = validate_pull_compression(pull_compression)
        self.epoch = None if epoch is None else int(epoch)
        self._lib = load_dkps(required=True)
        self.worker_id = int(worker_id)
        self.spec = server.spec
        self._seg = seg
        # PIN the mapping with a live buffer export: the C++ endpoints
        # hold raw pointers into it, and the server's stop-time
        # seg.close() would otherwise munmap the pages under them (a
        # SIGSEGV, not an exception — caught in review). With the export
        # alive, that close() raises BufferError (caught server-side:
        # the name still unlinks, no /dev/shm leak) and the mapping
        # survives until THIS client drops the pin in close().
        self._shm_pin = ctypes.c_char.from_buffer(seg.buf)
        self._handle = self._lib.dkps_client_connect_shm(
            ctypes.c_void_p(ctypes.addressof(self._shm_pin)), seg.size,
            self.worker_id, server.spec.n,
        )
        if not self._handle:
            self._shm_pin = None
            raise ConnectionError(
                "dkps shm handshake failed (vector-length mismatch or "
                "channel table full)"
            )
        self.set_timeout(None)
        return self

    def pull(self, worker_id: int | None = None) -> Pytree:
        out = np.empty(self.spec.n, dtype=np.float32)
        if self.pull_compression == "int8":
            # compressed-pull wire (action 5): ~n payload bytes instead of
            # 4n; the server holds this worker's quantization residual
            # (error feedback), so the received stream telescopes to the
            # exact center stream — see dkps.cpp PULL_INT8
            version = self._lib.dkps_client_pull_int8(
                self._handle, _f32p(out)
            )
        else:
            version = self._lib.dkps_client_pull(self._handle, _f32p(out))
        if version < 0:
            raise ConnectionError("dkps pull failed (server gone?)")
        return self.spec.unflatten(out)

    def commit(self, worker_id: int | None, payload: Pytree,
               seq: int | None = None) -> None:
        from distkeras_tpu.parallel.compression import is_encoded

        if is_encoded(payload):
            if seq is not None:
                # the segmented-int8 frame has no seq slot; the trainer
                # rejects resilience+compression on the native transport
                # up front — this guards direct callers
                raise ValueError(
                    "ps_transport='native' carries commit seqnos on the "
                    "raw f32 wire only; use ps_transport='socket' to "
                    "combine compression with retries"
                )
            return self._commit_int8(payload)
        vec = np.ascontiguousarray(self.spec.flatten(payload))
        if seq is not None and self.epoch is not None:
            # COMMIT_SEQ_E (action 10): dedup + fencing — a mismatched
            # epoch is rejected server-side and surfaces as the typed
            # fatal-or-re-resolve FencedEpochError, like the socket wire
            from distkeras_tpu.networking import FencedEpochError

            sepoch = ctypes.c_uint64(0)
            rc = self._lib.dkps_client_commit_seq_e(
                self._handle, int(self.epoch), int(seq), _f32p(vec),
                ctypes.byref(sepoch),
            )
            if rc < 0:
                raise ConnectionError("dkps commit failed (server gone?)")
            if rc == 2:
                raise FencedEpochError(
                    "commit fenced by the native server",
                    client_epoch=self.epoch, server_epoch=int(sepoch.value),
                )
            return
        if seq is not None:
            # COMMIT_SEQ (action 7): server-side (worker, seq) dedup —
            # replay-safe; a duplicate ack (rc 1) is success
            rc = self._lib.dkps_client_commit_seq(
                self._handle, int(seq), _f32p(vec)
            )
            if rc < 0:
                raise ConnectionError("dkps commit failed (server gone?)")
            return
        if self._lib.dkps_client_commit(self._handle, _f32p(vec)) != 0:
            raise ConnectionError("dkps commit failed (server gone?)")

    def exchange(self, worker_id: int | None, payload: Pytree,
                 seq: int | None = None, lag: bool = False) -> Pytree:
        """Fused commit + pull (EXCHANGE, action 14): one round trip
        folds ``payload`` and returns the fresh post-fold center — the
        pull reply rides the same compressed wire when
        ``pull_compression='int8'``. Codec-encoded (segmented int8)
        commits have no fused frame; they fall back to the 2-RTT
        ``commit(); pull()`` pair, which keeps the semantics while the
        raw-f32 wire (the resilient path's only wire) gets the fusion."""
        from distkeras_tpu.parallel.compression import is_encoded

        if is_encoded(payload):
            self.commit(worker_id, payload, seq=seq)
            return self.pull()
        vec = np.ascontiguousarray(self.spec.flatten(payload))
        out = np.empty(self.spec.n, dtype=np.float32)
        flags = 0
        if seq is not None:
            flags |= 1
        if self.epoch is not None:
            flags |= 2
        if self.pull_compression == "int8":
            flags |= 4
        if lag:
            flags |= 8
        sepoch = ctypes.c_uint64(0)
        rc = self._lib.dkps_client_exchange(
            self._handle, flags,
            0 if self.epoch is None else int(self.epoch),
            0 if seq is None else int(seq),
            _f32p(vec), _f32p(out), ctypes.byref(sepoch),
        )
        if rc == -2:
            from distkeras_tpu.networking import FencedEpochError

            raise FencedEpochError(
                "exchange fenced by the native server",
                client_epoch=self.epoch, server_epoch=int(sepoch.value),
            )
        if rc < 0:
            raise ConnectionError("dkps exchange failed (server gone?)")
        return self.spec.unflatten(out)

    def heartbeat(self, retries: int = 0) -> bool:
        """Renew this worker's liveness lease (HEARTBEAT, action 6);
        returns True when the lease already existed (a renewal)."""
        rc = self._lib.dkps_client_heartbeat(self._handle, int(retries))
        if rc < 0:
            raise ConnectionError("dkps heartbeat failed (server gone?)")
        return rc == 1

    def deregister(self) -> None:
        """Clean exit: drop this worker's lease without an eviction."""
        if self._lib.dkps_client_deregister(self._handle) != 0:
            raise ConnectionError("dkps deregister failed (server gone?)")

    def join(self) -> dict:
        """Elastic live-join admission (JOIN, action 12) — surface
        parity with ``ParameterServerClient.join``."""
        updates = ctypes.c_uint64(0)
        pool = ctypes.c_uint64(0)
        if self._lib.dkps_client_join(
                self._handle, ctypes.byref(updates), ctypes.byref(pool)
        ) != 0:
            raise ConnectionError("dkps join failed (server gone?)")
        return {"ok": True, "num_updates": int(updates.value),
                "pool_size": int(pool.value)}

    def drain(self, timeout: bool = False) -> None:
        """Preemption drain (DRAIN, action 13): clean deregister plus
        the server's elastic counters."""
        if self._lib.dkps_client_drain(
                self._handle, 1 if timeout else 0) != 0:
            raise ConnectionError("dkps drain failed (server gone?)")

    def fence(self, epoch: int) -> int:
        """Admin (FENCE, action 9): raise the server's fencing epoch;
        returns the post-fence value."""
        rc = int(self._lib.dkps_client_fence(self._handle, int(epoch)))
        if rc < 0:
            raise ConnectionError("dkps fence failed (server gone?)")
        return rc

    def shard_info(self) -> dict | None:
        """Shard-map handshake (SHARD_INFO, action 11): the server's
        shard record, or None when it serves an unsharded center —
        surface parity with ``ParameterServerClient.shard_map``."""
        sid = ctypes.c_uint32(0)
        num = ctypes.c_uint32(0)
        epoch = ctypes.c_uint64(0)
        rc = self._lib.dkps_client_shard_info(
            self._handle, ctypes.byref(sid), ctypes.byref(num),
            ctypes.byref(epoch),
        )
        if rc != 0:
            raise ConnectionError("dkps shard_info failed (server gone?)")
        if int(num.value) == 0:
            return None
        return {"shard_id": int(sid.value), "num_shards": int(num.value),
                "epoch": int(epoch.value)}

    def _commit_int8(self, blob: dict) -> None:
        """Ship an Int8Codec blob on the segmented-int8 wire (action 4):
        4× fewer payload bytes; the C++ fold dequantizes per segment with
        the same per-leaf scales, so the center sees exactly the tree
        ``Int8Codec.decode`` yields (the worker's feedback residual is
        computed against that same tree)."""
        import jax

        from distkeras_tpu.parallel.compression import _LEAF, _MARK

        if blob[_MARK] != "int8":
            raise ValueError(
                f"ps_transport='native' carries compression='int8' only; "
                f"got codec {blob[_MARK]!r} (use ps_transport='socket')"
            )
        leaves = jax.tree.flatten(
            blob["tree"],
            is_leaf=lambda x: isinstance(x, dict) and _LEAF in x,
        )[0]
        if len(leaves) != len(self.spec.sizes):
            raise ValueError(
                f"blob has {len(leaves)} leaves, spec expects "
                f"{len(self.spec.sizes)}"
            )
        segs = len(leaves)
        qv = np.empty(self.spec.n, np.int8)
        scales = np.empty(segs, np.float32)
        off = 0
        for i, (leaf, size) in enumerate(zip(leaves, self.spec.sizes)):
            if not (isinstance(leaf, dict) and _LEAF in leaf):
                raise ValueError(
                    "native int8 commits need every float leaf encoded "
                    "(Int8Codec(min_size=1) — run_async_training sets this)"
                )
            if leaf.get("dt", "float32") != "float32":
                # the C++ fold applies q*scale in f32; a non-f32 wire dtype
                # would make the center differ from Int8Codec.decode (which
                # rounds back to the leaf dtype) and break the feedback
                # invariant — bf16-param models use the pickle wire
                raise ValueError(
                    f"leaf {i}: native int8 wire carries float32 leaves "
                    f"only, got {leaf['dt']!r}; use ps_transport='socket'"
                )
            q = np.ravel(leaf["q"], order="C")
            if q.size != size:
                raise ValueError(
                    f"leaf {i}: blob size {q.size} != spec size {size}"
                )
            qv[off:off + size] = q
            scales[i] = leaf["s"]
            off += size
        lens = np.asarray(self.spec.sizes, np.uint64)
        rc = self._lib.dkps_client_commit_int8(
            self._handle,
            qv.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            _f32p(scales), segs,
        )
        if rc != 0:
            raise ConnectionError("dkps int8 commit failed (server gone?)")

    def set_timeout(self, seconds: float | None) -> None:
        """Bound every subsequent round-trip (0/None = block forever)."""
        ms = 0 if seconds is None else max(1, int(seconds * 1000))
        self._lib.dkps_client_set_timeout_ms(self._handle, ms)

    def close(self) -> None:
        if self._handle is not None:
            self._lib.dkps_client_close(self._handle)
            self._handle = None
        # Ring-lane clients: drop the mapping pin only AFTER the C++
        # side stopped using the rings. Deliberately NO seg.close() here
        # — the server's handler thread may still be draining the bye
        # action, and closing the SHARED SharedMemory object would unmap
        # the pages under it; the server's stop (which joins handlers
        # first) or final GC performs the actual unmap.
        if getattr(self, "_shm_pin", None) is not None:
            self._shm_pin = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
