"""Sequence/context parallelism: ring attention over a mesh axis.

The reference predates long-context entirely (SURVEY.md §5.7 — its longest
sequences were IMDB-LSTM inputs on one replica), so nothing here is a port:
this is the TPU-native long-context extension. Sequences are sharded along
their length over a mesh axis; each device holds one Q/K/V block and computes
exact attention by rotating K/V blocks around the ring with
``jax.lax.ppermute`` (ICI neighbor exchanges, overlapped by XLA with the
block computation) while maintaining a numerically stable online softmax —
the blockwise/ring-attention construction of Liu et al. 2023. Peak memory per
chip is O(L/N · L/N) for scores instead of O(L²), so context length scales
linearly with the ring size.

No Python control flow inside: the ring is a ``lax.fori_loop`` with a static
trip count, shard_map'ed over the mesh — one compiled SPMD program.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.parallel.mesh import put_global

_NEG = -1e9  # finite "masked" score: keeps the online softmax NaN-free


def attention_reference(q, k, v, causal: bool = False, scale=None,
                        key_mask=None, window: int | None = None):
    """Plain single-device softmax attention — the correctness oracle.

    Shapes: q/k/v ``[B, L, H, D]`` → ``[B, L, H, D]``. ``key_mask`` is an
    optional ``[B, Lk]`` validity mask (1 = attend, 0 = ignore, e.g.
    padding). ``window`` restricts attention to a sliding local band:
    query ``i`` sees keys ``(i-window, i]`` when causal, ``|i-j| < window``
    otherwise (same contract as ``ops.flash_attention``).
    """
    from distkeras_tpu.ops.flash_attention import _gqa_groups, band_predicate

    if window is not None and int(window) < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    rep = _gqa_groups(q, k)  # shared validation with the flash kernels
    if rep > 1:
        # grouped-query attention: expand the shared K/V heads (query head
        # h reads kv head h // group — same convention as the flash
        # kernels' index maps and the LM cache decode)
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    Lq, Lk = s.shape[-2], s.shape[-1]
    # one shared band predicate with the flash kernels — the oracle and the
    # kernel cannot drift apart on window semantics
    band = band_predicate(jnp.arange(Lq)[:, None], jnp.arange(Lk)[None, :],
                          causal, window)
    if band is not None:
        s = jnp.where(band, s, _NEG)
    if key_mask is not None:
        valid = key_mask[:, None, None, :].astype(bool)
        if band is not None:
            valid = valid & band[None, None]
        s = jnp.where(valid, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    if key_mask is not None:
        # rows whose whole band is masked yield zeros (same convention as
        # ring_attention and the flash kernel), not the mean of values a
        # softmax over uniform -1e9 would give
        p = p * valid
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def ring_window_steps(axis_size: int, block_len: int, causal: bool,
                      window: int | None) -> tuple[int, int]:
    """Static ring trip counts under a sliding window: ``(fwd, bwd)``.

    ``fwd`` counts the self block plus lower-position blocks reached by
    rotating the ring forward; ``bwd`` the higher-position blocks reached
    by the reverse chain (0 when causal). Unwindowed: ``(axis_size, 0)``
    — the classic full ring. A window only needs the blocks it can touch:
    ``1 + ceil((window-1)/block_len)`` per side, so a ring of 8 shards
    with a one-block window runs 2 hops instead of 8 — communication AND
    compute scale with the band, the distributed twin of the flash
    kernel's restricted grid. ``fwd + bwd <= axis_size`` always (the
    clamp also guarantees no block is ever visited by both chains)."""
    if window is None:
        return axis_size, 0
    side_hops = -(-(window - 1) // block_len)  # ceil; 0 when window == 1
    fwd = min(axis_size, 1 + side_hops)
    if causal:
        return fwd, 0
    return fwd, min(axis_size - fwd, side_hops)


def _ring_attention_shard(q, k, v, key_mask=None, *, axis_name, axis_size,
                          causal, scale, window=None):
    """Per-shard body: my Q block against the contributing K/V blocks via
    ring rotation (all blocks unwindowed; only the band's blocks under a
    sliding window — see :func:`ring_window_steps`).

    ``key_mask`` presence is static: the no-padding path compiles with no
    mask rotation or masking ops at all.
    """
    from distkeras_tpu.ops.flash_attention import band_predicate

    if window is not None and int(window) < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    has_mask = key_mask is not None
    idx = jax.lax.axis_index(axis_name)
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    qf = q.astype(jnp.float32) * scale

    q_pos = idx * Lq + jnp.arange(Lq)  # global positions of my queries
    fwd_perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    bwd_perm = [(j, (j - 1) % axis_size) for j in range(axis_size)]
    n_fwd, n_bwd = ring_window_steps(axis_size, Lk, causal, window)

    def fold(src, k_blk, v_blk, km_blk, m, l, o):
        """Fold block ``src`` into the online softmax state."""
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32))
        k_pos = src * Lk + jnp.arange(Lk)
        valid = band_predicate(q_pos[:, None], k_pos[None, :], causal,
                               window)                       # [Lq, Lk]|None
        if valid is not None:
            valid = jnp.broadcast_to(valid[None, None], s.shape)
        if has_mask:
            km = km_blk.astype(bool)[:, None, None, :]       # [B,1,1,Lk]
            valid = km if valid is None else (valid & km)
        if valid is not None:
            s = jnp.where(valid, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        if valid is not None:
            p = jnp.where(valid, p, 0.0)
        corr = jnp.exp(m - m_new)                            # [B, H, Lq]
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )
        return m_new, l, o

    def rotate(k_blk, v_blk, km_blk, perm):
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        if has_mask:
            km_blk = jax.lax.ppermute(km_blk, axis_name, perm)
        return k_blk, v_blk, km_blk

    def step_fwd(i, carry):
        k_blk, v_blk, km_blk, m, l, o = carry
        # rotate FIRST: after i+1 forward hops I hold block idx - i - 1
        k_blk, v_blk, km_blk = rotate(k_blk, v_blk, km_blk, fwd_perm)
        src = (idx - i - 1) % axis_size
        m, l, o = fold(src, k_blk, v_blk, km_blk, m, l, o)
        return k_blk, v_blk, km_blk, m, l, o

    def step_bwd(i, carry):
        k_blk, v_blk, km_blk, m, l, o = carry
        # rotate FIRST: after i+1 reverse hops I hold block idx + i + 1
        k_blk, v_blk, km_blk = rotate(k_blk, v_blk, km_blk, bwd_perm)
        src = (idx + i + 1) % axis_size
        m, l, o = fold(src, k_blk, v_blk, km_blk, m, l, o)
        return k_blk, v_blk, km_blk, m, l, o

    m0 = jnp.full((B, H, Lq), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, Lq), jnp.float32)
    o0 = jnp.zeros((B, H, Lq, D), jnp.float32)
    km0 = key_mask if has_mask else ()
    # self block outside the loops, rotate-then-fold inside: each chain
    # does exactly the hops it folds (a window=1 band does ZERO ppermutes;
    # the classic full ring does axis_size - 1, not axis_size)
    m, l, o = fold(idx, k, v, km0, m0, l0, o0)
    if n_fwd > 1:
        *_, m, l, o = jax.lax.fori_loop(
            0, n_fwd - 1, step_fwd, (k, v, km0, m, l, o)
        )
    if n_bwd:
        # upper-side chain restarts from my OWN block and rotates the
        # other way; the (m, l, o) state carries over
        *_, m, l, o = jax.lax.fori_loop(
            0, n_bwd, step_bwd, (k, v, km0, m, l, o)
        )
    out = o / jnp.maximum(l, 1e-30)[..., None]               # [B, H, Lq, D]
    return jnp.moveaxis(out, 1, 2).astype(q.dtype)           # [B, Lq, H, D]


#: public alias — the per-shard ring body, for composing ring attention into
#: a larger computation that is ALREADY inside shard_map over the sequence
#: axis (e.g. models.transformer.sequence_parallel_transformer_forward)
ring_attention_shard = _ring_attention_shard


def ring_attention(q, k, v, mesh: Mesh, axis: str | None = None,
                   causal: bool = False, scale=None, key_mask=None,
                   window: int | None = None):
    """Exact attention with Q/K/V sharded along sequence length over ``axis``.

    ``q/k/v``: ``[B, L, H, D]`` with ``L % mesh_axis_size == 0``; ``key_mask``
    an optional ``[B, L]`` validity mask (padding), sharded and rotated with
    K/V. Returns the attention output with the same sharding. Matches
    :func:`attention_reference` to f32 tolerance (pinned by the unit tests on
    an 8-device mesh); rows whose keys are ALL masked yield zeros in both.
    ``window`` enables sliding-window (local) attention with the same band
    contract as the flash kernel — AND the ring only rotates through the
    blocks the band touches (:func:`ring_window_steps`), so per-chip
    communication and compute scale with the window, not with L.
    """
    axis = axis or mesh.axis_names[0]
    n = mesh.shape[axis]
    if q.shape[1] % n:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by mesh axis "
            f"'{axis}' of size {n}"
        )
    if window is not None:
        window = int(window)
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if window >= q.shape[1]:
            window = None  # band covers everything: the classic full ring
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    body = functools.partial(
        _ring_attention_shard, axis_name=axis, axis_size=n,
        causal=causal, scale=scale, window=window,
    )
    spec = P(None, axis, None, None)
    sharding = NamedSharding(mesh, spec)
    q, k, v = (put_global(x, sharding) for x in (q, k, v))
    if key_mask is None:
        shard_fn = jax.shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
        return jax.jit(shard_fn)(q, k, v)
    mspec = P(None, axis)
    shard_fn = jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec, mspec), out_specs=spec,
        check_vma=False,
    )
    key_mask = put_global(key_mask, NamedSharding(mesh, mspec))
    return jax.jit(shard_fn)(q, k, v, key_mask)
