"""Trainer-level strategy seams for pipeline / sequence / expert parallelism.

The reference's product surface was *trainer ergonomics*: one class per
distribution strategy, ``trainer.train(dataset)`` and nothing else (reference
``distkeras/trainers.py`` — SURVEY.md §2b #3-8). The rebuild's PP/SP/EP
libraries (:mod:`distkeras_tpu.parallel.pipeline`, ``.sequence``, ``.expert``)
were originally reachable only by writing your own loop; this module closes
that gap by expressing each strategy as the pieces
:class:`~distkeras_tpu.parallel.tensor.SPMDEngine` consumes:

- a ``loss_step(params, nt, batch) -> (loss, new_nt)`` whose forward runs the
  strategy's mesh program (GPipe scan, ring attention shard_map, GShard
  all_to_all);
- a ``PartitionSpec`` pytree giving the parameter layout the strategy wants
  (stages over ``pp``, replicated for SP, experts over ``ep``);
- for pipeline, a params re-layout: per-block subtrees are stacked onto a
  leading ``[S]`` axis so each device *stores* exactly its stage (true
  pipeline memory scaling), and unstacked again for the returned model.

``MeshTrainer(strategy=...)`` wires these into the ordinary engine loop, so
checkpointing, profiling, metrics, and the resident input path work for every
strategy for free.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

Pytree = object


def _split_batch(batch):
    """``(*features, label)`` → (tokens, mask, label); mask defaults to ones.

    All three strategies train the transformer families, whose feature
    columns are ``(tokens,)`` or ``(tokens, mask)`` — anything else is a
    configuration error, not something to paper over with a ones-mask.
    """
    if len(batch) not in (2, 3):
        raise ValueError(
            f"pipeline/sequence/expert strategies take features_col="
            f"['tokens'] or ['tokens', 'mask']; got {len(batch) - 1} "
            f"feature columns"
        )
    toks = batch[0]
    if len(batch) == 3:
        mask = batch[1]
    else:
        mask = jnp.ones(toks.shape, jnp.float32)
    return toks, mask, batch[-1]


def _require_module(spec, strategy: str, cls):
    module = getattr(spec, "module", None)
    if module is None or not isinstance(module, cls):
        raise TypeError(
            f"strategy={strategy!r} needs a ModelSpec built by from_flax "
            f"around a {cls.__name__} (got "
            f"{type(module).__name__ if module else 'no module'}); use "
            f"distkeras_tpu.models.{'moe_transformer_classifier' if strategy == 'expert' else 'transformer_classifier'}(...)"
        )
    return module


# ---------------------------------------------------------------------------
# Pipeline (GPipe over 'pp', optionally × dp)
# ---------------------------------------------------------------------------


def split_pipeline_params(params, depth: int):
    """Model params → engine layout: ``blocks_i`` stacked on a ``[S]`` axis.

    The stacked subtree is what :func:`...pipeline.pipeline_apply` consumes
    and — sharded ``P('pp')`` — what makes each device store only its stage.
    """
    from distkeras_tpu.parallel.pipeline import stack_stage_params

    missing = [i for i in range(depth) if f"blocks_{i}" not in params]
    if missing:
        raise ValueError(
            f"params lack pipeline stages blocks_{missing}; strategy="
            f"'pipeline' needs the TransformerClassifier block layout"
        )
    stages = stack_stage_params([params[f"blocks_{i}"] for i in range(depth)])
    rest = {k: v for k, v in params.items() if not k.startswith("blocks_")}
    return {"stages": stages, "rest": rest}


def join_pipeline_params(split, depth: int):
    """Engine layout → model params (host-side, for the trained result)."""
    params = dict(split["rest"])
    for i in range(depth):
        params[f"blocks_{i}"] = jax.tree.map(
            lambda s: np.asarray(s[i]), split["stages"]
        )
    return params


def pipeline_strategy(spec, loss_fn, mesh, *, pp_axis: str = "pp",
                      dp_axis: str | None = None,
                      microbatches: int | None = None):
    """Build (loss_step, param_specs, to_engine, from_engine) for GPipe.

    Stage params live stacked ``[S, …]`` sharded over ``pp`` (one stage per
    device); embed/head replicated. The loss forward is the differentiable
    collective pipeline — XLA derives the reverse schedule through the scan.
    Cites reference ``distkeras/trainers.py`` ergonomics; pipeline math per
    Huang et al. 2019 (GPipe).
    """
    from distkeras_tpu.models.transformer import (
        EncoderBlock,
        TransformerClassifier,
    )
    from distkeras_tpu.parallel.pipeline import pipeline_apply

    module = _require_module(spec, "pipeline", TransformerClassifier)
    if module.depth != mesh.shape[pp_axis]:
        raise ValueError(
            f"model depth {module.depth} != mesh axis '{pp_axis}' size "
            f"{mesh.shape[pp_axis]} (one encoder block per stage)"
        )
    block = EncoderBlock(dim=module.dim, heads=module.heads,
                         causal=module.causal, dtype=module.dtype,
                         attn_impl=module.attn_impl)
    depth = module.depth

    def loss_step(params, nt, batch):
        toks, mask, y = _split_batch(batch)
        x = module.apply({"params": params["rest"]}, toks,
                         method=TransformerClassifier.embed_tokens)

        def stage(p, act):
            h, m = act
            return block.apply({"params": p}, h, m, False), m

        x, _ = pipeline_apply(stage, params["stages"], (x, mask), mesh,
                              axis=pp_axis, microbatches=microbatches,
                              batch_axis=dp_axis)
        logits = module.apply({"params": params["rest"]}, x, mask,
                              method=TransformerClassifier.head_logits)
        return loss_fn(y, logits), nt

    def specs_for(eparams):
        return {
            "stages": jax.tree.map(lambda _: P(pp_axis), eparams["stages"]),
            "rest": jax.tree.map(lambda _: P(), eparams["rest"]),
        }

    return (loss_step, specs_for,
            lambda p: split_pipeline_params(p, depth),
            lambda p: join_pipeline_params(p, depth))


# ---------------------------------------------------------------------------
# Sequence (ring attention over 'sp', optionally × dp)
# ---------------------------------------------------------------------------


def sequence_strategy(spec, loss_fn, mesh, *, sp_axis: str = "sp",
                      dp_axis: str | None = None):
    """Build the SP pieces: activations sharded along L, ring attention.

    Params replicated (they are small relative to long-context activations —
    the memory axis SP scales is L); compose ``parameter_sharding`` needs via
    dp×sp + fsdp in a later round if a use case appears.
    """
    from distkeras_tpu.models.transformer import (
        TransformerClassifier,
        sequence_parallel_transformer_forward,
    )

    module = _require_module(spec, "sequence", TransformerClassifier)

    def loss_step(params, nt, batch):
        toks, mask, y = _split_batch(batch)
        logits = sequence_parallel_transformer_forward(
            module, params, toks, mask, mesh, axis=sp_axis,
            batch_axis=dp_axis,
        )
        return loss_fn(y, logits), nt

    def specs_for(eparams):
        return jax.tree.map(lambda _: P(), eparams)

    ident = lambda p: p
    return loss_step, specs_for, ident, ident


# ---------------------------------------------------------------------------
# Expert (GShard MoE over 'ep')
# ---------------------------------------------------------------------------


def expert_specs(params, ep_axis: str = "ep"):
    """PartitionSpec pytree for the MoE family: expert-stacked leaves
    (``w1/b1/w2/b2``, leading ``[E]`` axis) shard over ``ep``; the gate,
    attention, and embed/head stay replicated (GShard layout, Lepikhin et
    al. 2020)."""

    def spec_for(path, leaf):
        last = getattr(path[-1], "key", getattr(path[-1], "name", None))
        if last in ("w1", "b1", "w2", "b2"):
            return P(ep_axis)
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)


def expert_strategy(spec, loss_fn, mesh, *, ep_axis: str = "ep",
                    aux_weight: float = 1e-2):
    """Build the EP pieces: experts sharded over ``ep``, tokens exchanged
    with ``all_to_all``, gating auxiliary loss folded into the objective.

    Composes with data parallelism on a 2-D mesh (``{"dp": d, "ep": e}``):
    the batch shards over ``dp`` (the engine's dp_axis) while the MoE
    layer's ``shard_map`` maps only ``ep`` manually — dp stays auto and
    GSPMD partitions the routing work over it (expert weights replicate
    over dp by propagation)."""
    from distkeras_tpu.models.moe import (
        MoETransformerClassifier,
        moe_aux_loss,
    )

    module = _require_module(spec, "expert", MoETransformerClassifier)
    if module.num_experts % mesh.shape[ep_axis]:
        raise ValueError(
            f"{module.num_experts} experts not divisible by mesh axis "
            f"'{ep_axis}' of size {mesh.shape[ep_axis]}"
        )
    smod = module.clone(mesh=mesh, ep_axis=ep_axis)

    def loss_step(params, nt, batch):
        toks, mask, y = _split_batch(batch)
        logits, aux = moe_aux_loss(smod, params, (toks, mask), training=True)
        return loss_fn(y, logits) + aux_weight * aux, nt

    def specs_for(eparams):
        return expert_specs(eparams, ep_axis)

    ident = lambda p: p
    return loss_step, specs_for, ident, ident


STRATEGIES = {
    "pipeline": pipeline_strategy,
    "sequence": sequence_strategy,
    "expert": expert_strategy,
}
