"""SPMD machinery: mesh discovery, collectives, merge rules, local-SGD engine.

This package replaces the reference's entire L1+L2 (socket transport +
parameter server, ``distkeras/networking.py`` + ``distkeras/parameter_servers.py``)
for the default synchronous path: parameter exchange is an XLA collective over
ICI at communication-window boundaries, not a TCP round-trip (SURVEY.md §2,
"the part the north_star says to delete and replace with JAX collectives").
"""

from distkeras_tpu.parallel.mesh import get_mesh, mesh_info
from distkeras_tpu.parallel.merge_rules import (
    ADAGMerge,
    DownpourMerge,
    DynSGDMerge,
    ElasticAverageMerge,
    MergeRule,
    get_merge_rule,
)
from distkeras_tpu.parallel.local_sgd import LocalSGDEngine, TrainState
from distkeras_tpu.parallel.expert import (
    init_moe_params,
    moe_mlp,
    moe_mlp_reference,
)
from distkeras_tpu.parallel.pipeline import (
    pipeline_apply,
    sequential_apply,
    stack_stage_params,
)
from distkeras_tpu.parallel.sequence import (
    attention_reference,
    ring_attention,
    ring_attention_shard,
)
from distkeras_tpu.parallel.tensor import (
    SPMDEngine,
    get_mesh_nd,
    megatron_specs,
    shard_pytree,
)
from distkeras_tpu.parallel.fsdp import FSDPEngine, fsdp_specs

__all__ = [
    "attention_reference",
    "ring_attention",
    "ring_attention_shard",
    "pipeline_apply",
    "sequential_apply",
    "stack_stage_params",
    "init_moe_params",
    "moe_mlp",
    "moe_mlp_reference",
    "SPMDEngine",
    "FSDPEngine",
    "fsdp_specs",
    "get_mesh_nd",
    "megatron_specs",
    "shard_pytree",
    "get_mesh",
    "mesh_info",
    "MergeRule",
    "ADAGMerge",
    "DownpourMerge",
    "ElasticAverageMerge",
    "DynSGDMerge",
    "get_merge_rule",
    "LocalSGDEngine",
    "TrainState",
]
