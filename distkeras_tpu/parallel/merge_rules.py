"""Merge rules — each distributed algorithm's parameter-exchange semantics.

Every reference algorithm shares one skeleton: a worker trains locally for
``communication_window`` minibatches, then exchanges with the center
(SURVEY.md §2b.3). They differ only in *what is committed* and *how the center
folds it in*. Here each algorithm is a pure function on pytrees:

    merge(center, workers_stacked) -> (center', workers_stacked')

with ``workers_stacked`` carrying a leading ``W`` axis sharded over the ``dp``
mesh axis — the reductions over that axis ARE the parameter exchange, lowered
by XLA to ``psum``/``pmean`` over ICI instead of the reference's pickled TCP
round-trips (reference ``distkeras/parameter_servers.py`` commit handlers).

Because every optax update is additive (``params += update``), a worker's
window-accumulated commit equals ``worker − center_at_pull``, so every rule
needs only the post-window worker params and the window-start center — no
separate accumulator threads through the scan.

Async lowering note (SURVEY.md §7.3 hard part 1): the originals folded commits
one at a time into a center guarded by a lock, so each fold saw the partial
result of earlier folds. The sync lowering makes a deterministic, documented
choice per rule (parallel fold for ADAG/DOWNPOUR/elastic; fold-position
staleness for DynSGD). Each rule also provides :meth:`fold` — the one-commit
form used by the genuinely-async parameter-server backend
(``distkeras_tpu.parameter_servers``), so both backends share the same
algorithm definitions and the unit tests pin them to one oracle.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Pytree = Any


def _delta(workers, center):
    """Per-worker commit payload: worker − center, leafwise (stacked)."""
    return jax.tree.map(lambda w, c: w - c[None], workers, center)


def _reset_to(center, workers):
    """Broadcast the new center back to every worker (the post-merge 'pull')."""
    return jax.tree.map(
        lambda c, w: jnp.broadcast_to(c[None].astype(w.dtype), w.shape), center, workers
    )


class MergeRule:
    """Base: subclasses define sync ``merge`` and async one-commit ``fold``."""

    #: whether workers are re-based onto the new center after each merge
    resets_workers: bool = True

    def merge(self, center: Pytree, workers: Pytree) -> tuple[Pytree, Pytree]:
        raise NotImplementedError

    def fold(self, center: Pytree, commit: Pytree, num_workers: int,
             staleness: int) -> Pytree:
        """Async PS form: fold ONE worker's commit (= its delta) into center."""
        raise NotImplementedError


class ADAGMerge(MergeRule):
    """ADAG — asynchronous distributed adaptive gradients (the repo author's
    algorithm; reference ``distkeras/trainers.py :: ADAG``).

    Commit: the window-accumulated, locally-optimized update; fold: add the
    commit normalized by the worker count — the normalization that reduced
    staleness error in the async original. Sync lowering: center += mean over
    workers of (worker − center). With ``communication_window=1`` and SGD this
    is EXACTLY synchronous mean-gradient all-reduce — BASELINE.json's "sync
    allreduce path".
    """

    def merge(self, center, workers):
        deltas = _delta(workers, center)
        center = jax.tree.map(
            lambda c, d: c + jnp.mean(d, axis=0, dtype=c.dtype), center, deltas
        )
        return center, _reset_to(center, workers)

    def fold(self, center, commit, num_workers, staleness):
        return jax.tree.map(lambda c, d: c + d / num_workers, center, commit)


class DownpourMerge(MergeRule):
    """DOWNPOUR (Dean et al. 2012; reference ``distkeras/trainers.py ::
    DOWNPOUR``): each worker's weight delta is added to the center unscaled.

    Sync lowering: center += SUM over workers of (worker − center) — the same
    total displacement the async PS accumulated over one round. Like the
    original, the effective step grows with worker count; users tune
    ``communication_window``/learning rate accordingly.
    """

    def merge(self, center, workers):
        deltas = _delta(workers, center)
        center = jax.tree.map(
            lambda c, d: c + jnp.sum(d, axis=0, dtype=c.dtype), center, deltas
        )
        return center, _reset_to(center, workers)

    def fold(self, center, commit, num_workers, staleness):
        # operator add: keeps host-side PS folds in numpy (see ElasticAverage)
        return jax.tree.map(lambda c, d: c + d, center, commit)


class ElasticAverageMerge(MergeRule):
    """AEASGD / EAMSGD (Zhang, Choromanska & LeCun 2015; reference
    ``distkeras/trainers.py :: AEASGD, EAMSGD``).

    Workers keep their own variables (never re-based); each exchange moves
    worker and center toward each other by the elastic force
    ``alpha = rho · learning_rate``:

        diff_i  = alpha · (worker_i − center)
        worker_i −= diff_i
        center  += Σ_i diff_i

    Stability requires ``alpha · num_workers < 1`` in this lockstep fold (the
    async original spread the folds over time); the constructor warns when
    ``num_workers`` is known and the product reaches 1. EAMSGD differs only in
    the worker-side optimizer (Nesterov momentum), configured in the trainer —
    the merge rule is identical.
    """

    resets_workers = False

    def __init__(self, alpha: float, num_workers: int | None = None):
        self.alpha = float(alpha)
        if num_workers is not None and self.alpha * num_workers >= 1.0:
            import warnings

            warnings.warn(
                f"elastic force alpha={self.alpha:.3f} × num_workers="
                f"{num_workers} = {self.alpha * num_workers:.2f} ≥ 1: the "
                "lockstep center update will overshoot; lower rho, the "
                "learning rate, or the worker count",
                stacklevel=3,
            )

    def merge(self, center, workers):
        a = self.alpha
        diffs = jax.tree.map(lambda w, c: a * (w - c[None]), workers, center)
        new_workers = jax.tree.map(jnp.subtract, workers, diffs)
        new_center = jax.tree.map(
            lambda c, d: c + jnp.sum(d, axis=0, dtype=c.dtype), center, diffs
        )
        return new_center, new_workers

    def fold(self, center, commit, num_workers, staleness):
        # Async form: commit is already the elastic difference alpha·(w − c).
        # Operator add keeps host-side PS folds in numpy (no device bounce).
        return jax.tree.map(lambda c, d: c + d, center, commit)

    def worker_commit(self, worker, center):
        """Async worker side: elastic difference, subtracted locally too."""
        return jax.tree.map(lambda w, c: self.alpha * (w - c), worker, center)


class DynSGDMerge(MergeRule):
    """DynSGD — staleness-aware dynamic-LR SGD (after Jiang et al. 2017;
    reference ``distkeras/trainers.py :: DynSGD``): each commit is scaled by
    ``1/(τ+1)`` where τ counts center updates since that worker's last pull.

    Deterministic lockstep lowering: within one merge the commits fold in
    worker-index order, so worker *i* sees τ = i center updates from this
    round: center += Σ_i (worker_i − center)/(i+1). The 1/(τ+1) formula is
    preserved exactly; on TPU τ is the within-round fold position (documented
    divergence from wall-clock staleness, which lockstep makes constant —
    SURVEY.md §7.1).
    """

    def merge(self, center, workers):
        deltas = _delta(workers, center)

        def fold_leaf(c, d):
            w = d.shape[0]
            scale = 1.0 / (jnp.arange(w, dtype=jnp.float32) + 1.0)
            scale = scale.reshape((w,) + (1,) * (d.ndim - 1)).astype(c.dtype)
            return c + jnp.sum(d * scale, axis=0, dtype=c.dtype)

        center = jax.tree.map(fold_leaf, center, deltas)
        return center, _reset_to(center, workers)

    def fold(self, center, commit, num_workers, staleness):
        s = 1.0 / (float(staleness) + 1.0)
        return jax.tree.map(lambda c, d: c + d * s, center, commit)


def get_merge_rule(name: str, *, rho: float = 3.0, learning_rate: float = 0.05,
                   **_) -> MergeRule:
    name = name.lower()
    if name == "adag":
        return ADAGMerge()
    if name == "downpour":
        return DownpourMerge()
    if name in ("aeasgd", "eamsgd", "easgd"):
        return ElasticAverageMerge(alpha=rho * learning_rate)
    if name == "dynsgd":
        return DynSGDMerge()
    raise ValueError(f"unknown merge rule {name!r}")
