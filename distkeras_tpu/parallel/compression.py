"""Commit-payload compression for the asynchronous PS/DCN path.

Beyond-reference (the reference shipped full-precision pickled weight deltas
over TCP — reference ``distkeras/networking.py :: send_data``): the async
backend's pull/commit traffic is the one part of this framework that rides
DCN instead of ICI, so its bytes are the scarce resource. Two classic lossy
codecs compress the *commit* direction (worker → PS), combined with
worker-side **error feedback** (Seide et al. 2014; Karimireddy et al. 2019
— see PAPERS.md): the part of each window delta the codec dropped is
remembered and added to the next window's delta, so the transmitted stream
telescopes to the true update stream and convergence is preserved.

- :class:`Int8Codec` — symmetric per-leaf absmax int8: 4× fewer payload
  bytes, error bounded by half a quantization step per element.
- :class:`TopKCodec` — magnitude top-k sparsification per leaf (default 5%):
  ~10-20× fewer bytes; error feedback is what makes this converge.

Codecs encode a pytree into a **wire-safe** blob: plain dicts/lists of numpy
arrays and primitives, so it travels the existing restricted-pickle frames
(``networking.py``) unchanged, and the PS decodes before folding
(``ParameterServer.commit`` calls :func:`maybe_decode`). The pull direction
stays exact: a lossily-compressed center would inject persistent error the
worker-side feedback loop cannot see.

Select with ``compression="int8"`` / ``"topk"`` / ``TopKCodec(0.01)`` on any
async trainer (PS backend; the collective backend's merges are XLA psums
over ICI, where compression has nothing to buy).
"""

from __future__ import annotations

from typing import Any

import numpy as np

Pytree = Any

#: blob key marking an encoded commit (never a param name in any model tree)
_MARK = "__dk_codec__"
_LEAF = "__dk_leaf__"


class Codec:
    """Commit-payload codec: ``encode(tree) → wire blob``, ``decode`` back.

    ``decode(encode(t))`` is the *transmitted* (lossy) tree — workers use it
    to compute the error-feedback residual; the PS folds exactly it.
    """

    name: str = "identity"

    def encode_leaf(self, arr: np.ndarray) -> dict:
        raise NotImplementedError

    def decode_leaf(self, blob: dict) -> np.ndarray:
        raise NotImplementedError

    # -- tree plumbing (structure travels as plain containers) --------------

    def encode(self, tree: Pytree) -> dict:
        def rec(node):
            if isinstance(node, dict):
                return {k: rec(v) for k, v in node.items()}
            if isinstance(node, (list, tuple)):
                enc = [rec(v) for v in node]
                return enc if isinstance(node, list) else tuple(enc)
            arr = np.asarray(node)
            if arr.dtype == np.float32 and arr.size >= 16:
                return {_LEAF: self.name, **self.encode_leaf(arr)}
            return arr  # tiny/integer leaves: not worth a codec round-trip
        return {_MARK: self.name, "tree": rec(tree)}

    def decode(self, blob: dict) -> Pytree:
        def rec(node):
            if isinstance(node, dict):
                if _LEAF in node:
                    return self.decode_leaf(node)
                return {k: rec(v) for k, v in node.items()}
            if isinstance(node, (list, tuple)):
                # commit trees are dicts-of-dicts in every model family here;
                # lists appear only for stacked/tuple params — preserve type
                return type(node)(rec(v) for v in node) \
                    if isinstance(node, tuple) else [rec(v) for v in node]
            return node
        return rec(blob["tree"])


class Int8Codec(Codec):
    """Symmetric per-leaf absmax int8 (~4× smaller commits)."""

    name = "int8"

    def encode_leaf(self, arr: np.ndarray) -> dict:
        amax = float(np.max(np.abs(arr)))
        scale = amax / 127.0 if amax > 0 else 1.0
        q = np.clip(np.rint(arr / scale), -127, 127).astype(np.int8)
        return {"q": q, "s": scale}

    def decode_leaf(self, blob: dict) -> np.ndarray:
        return blob["q"].astype(np.float32) * np.float32(blob["s"])


class TopKCodec(Codec):
    """Magnitude top-k per leaf (values + flat indices; ~``1/frac``× smaller
    at small ``frac``). Error feedback reinjects the dropped mass later."""

    name = "topk"

    def __init__(self, frac: float = 0.05):
        if not 0.0 < frac <= 1.0:
            raise ValueError(f"frac must be in (0, 1], got {frac}")
        self.frac = float(frac)

    def encode_leaf(self, arr: np.ndarray) -> dict:
        flat = arr.reshape(-1)
        k = max(1, int(np.ceil(self.frac * flat.size)))
        idx = np.argpartition(np.abs(flat), flat.size - k)[-k:]
        idx = idx.astype(np.int64 if flat.size > 2**31 else np.int32)
        return {"v": flat[idx], "i": idx, "n": list(arr.shape)}

    def decode_leaf(self, blob: dict) -> np.ndarray:
        shape = tuple(int(d) for d in blob["n"])
        out = np.zeros(int(np.prod(shape)), np.float32)
        out[blob["i"]] = blob["v"]
        return out.reshape(shape)


_REGISTRY = {"int8": Int8Codec, "topk": TopKCodec}


def resolve_codec(compression) -> Codec | None:
    """Trainer kwarg → codec: ``None``, a name, or a Codec instance."""
    if compression is None:
        return None
    if isinstance(compression, Codec):
        return compression
    if isinstance(compression, str):
        if compression in _REGISTRY:
            return _REGISTRY[compression]()
        raise ValueError(
            f"unknown compression {compression!r}; expected "
            f"{sorted(_REGISTRY)} or a Codec instance"
        )
    raise TypeError(f"compression must be None, str, or Codec, "
                    f"got {type(compression)}")


def is_encoded(payload) -> bool:
    return isinstance(payload, dict) and _MARK in payload


def maybe_decode(payload: Pytree) -> Pytree:
    """PS-side seam: decode an encoded commit, pass a raw tree through."""
    if not is_encoded(payload):
        return payload
    name = payload[_MARK]
    if name not in _REGISTRY:
        raise ValueError(f"commit encoded with unknown codec {name!r}")
    codec = _REGISTRY[name]()
    return codec.decode(payload)
