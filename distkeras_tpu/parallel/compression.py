"""Commit-payload compression for the asynchronous PS/DCN path.

Beyond-reference (the reference shipped full-precision pickled weight deltas
over TCP — reference ``distkeras/networking.py :: send_data``): the async
backend's pull/commit traffic is the one part of this framework that rides
DCN instead of ICI, so its bytes are the scarce resource. Two classic lossy
codecs compress the *commit* direction (worker → PS), combined with
worker-side **error feedback** (Seide et al. 2014; Karimireddy et al. 2019
— see PAPERS.md): the part of each window delta the codec dropped is
remembered and added to the next window's delta, so the transmitted stream
telescopes to the true update stream and convergence is preserved.

- :class:`Int8Codec` — symmetric per-leaf absmax int8: 4× fewer payload
  bytes, error bounded by half a quantization step per element.
- :class:`TopKCodec` — magnitude top-k sparsification per leaf (default 5%):
  ~10-20× fewer bytes; error feedback is what makes this converge.

Codecs encode a pytree into a **wire-safe** blob: plain dicts/lists of numpy
arrays and primitives, so it travels the existing restricted-pickle frames
(``networking.py``) unchanged, and the PS decodes before folding
(``ParameterServer.commit`` calls :func:`maybe_decode`). The pull direction
compresses separately via ``pull_compression="int8"``: the SERVER holds a
per-worker quantization residual and re-adds it to that worker's next pull
(bidirectional error feedback — DoubleSqueeze, Tang et al. 2019), so the
decoded-pull stream telescopes to the true center stream; worker-side
feedback alone could not see that error, which is why the server owns it.
Pulls default to exact f32.

Select with ``compression="int8"`` / ``"topk"`` / ``TopKCodec(0.01)`` on any
async trainer (PS backend; the collective backend's merges are XLA psums
over ICI, where compression has nothing to buy).
"""

from __future__ import annotations

from typing import Any

import numpy as np

Pytree = Any

#: blob key marking an encoded commit (never a param name in any model tree)
_MARK = "__dk_codec__"
_LEAF = "__dk_leaf__"


class Codec:
    """Commit-payload codec: ``encode(tree) → wire blob``, ``decode`` back.

    ``decode(encode(t))`` is the *transmitted* (lossy) tree — workers use it
    to compute the error-feedback residual; the PS folds exactly it.
    """

    name: str = "identity"
    #: leaves smaller than this pass through uncompressed (header overhead
    #: beats the savings); the native int8 wire sets 1 — every float leaf
    #: must ride the segmented wire
    min_size: int = 16

    def encode_leaf(self, arr: np.ndarray) -> dict:
        raise NotImplementedError

    def decode_leaf(self, blob: dict) -> np.ndarray:
        raise NotImplementedError

    # -- tree plumbing (structure travels as plain containers) --------------

    def encode(self, tree: Pytree) -> dict:
        def rec(node):
            if isinstance(node, dict):
                return {k: rec(v) for k, v in node.items()}
            if isinstance(node, (list, tuple)):
                enc = [rec(v) for v in node]
                return enc if isinstance(node, list) else tuple(enc)
            arr = np.asarray(node)
            # any floating dtype compresses (bf16/f16 via an f32 staging
            # cast; the original dtype is restored on decode so the PS fold
            # and the worker's feedback math see the dtypes they expect)
            if np.issubdtype(arr.dtype, np.floating) or arr.dtype.name in (
                "bfloat16", "float8_e4m3fn", "float8_e5m2"
            ):
                if arr.size >= self.min_size:
                    return {_LEAF: self.name, "dt": arr.dtype.name,
                            **self.encode_leaf(arr.astype(np.float32))}
            return arr  # tiny/integer leaves: not worth a codec round-trip
        return {_MARK: self.name, "tree": rec(tree)}

    def decode(self, blob: dict) -> Pytree:
        def rec(node):
            if isinstance(node, dict):
                if _LEAF in node:
                    return self.decode_leaf(node).astype(
                        _resolve_dtype(node.get("dt", "float32")),
                        copy=False,  # f32 (the common case) is a no-op
                    )
                return {k: rec(v) for k, v in node.items()}
            if isinstance(node, (list, tuple)):
                # preserve container types exactly: the worker's feedback
                # tree.map and the PS fold require identical treedefs
                enc = [rec(v) for v in node]
                return enc if isinstance(node, list) else tuple(enc)
            return node
        return rec(blob["tree"])


def _resolve_dtype(name: str) -> np.dtype:
    """Dtype from its wire name; extended floats resolve via ml_dtypes
    (jax's numpy extension — present wherever this framework runs)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


class Int8Codec(Codec):
    """Symmetric per-leaf absmax int8 (~4× smaller commits)."""

    name = "int8"

    def __init__(self, min_size: int = 16):
        self.min_size = int(min_size)

    def encode_leaf(self, arr: np.ndarray) -> dict:
        amax = float(np.max(np.abs(arr)))
        scale = amax / 127.0 if amax > 0 else 1.0
        q = np.clip(np.rint(arr / scale), -127, 127).astype(np.int8)
        return {"q": q, "s": scale}

    def decode_leaf(self, blob: dict) -> np.ndarray:
        # fused int8→f32 dequant: one pass, one allocation (bit-identical
        # to astype(float32) * scale — int8→f32 conversion is exact)
        return np.multiply(blob["q"], np.float32(blob["s"]),
                           dtype=np.float32)


class TopKCodec(Codec):
    """Magnitude top-k per leaf (values + flat indices; ~``1/frac``× smaller
    at small ``frac``). Error feedback reinjects the dropped mass later."""

    name = "topk"

    def __init__(self, frac: float = 0.05):
        if not 0.0 < frac <= 1.0:
            raise ValueError(f"frac must be in (0, 1], got {frac}")
        self.frac = float(frac)

    def encode_leaf(self, arr: np.ndarray) -> dict:
        flat = arr.reshape(-1)
        k = max(1, int(np.ceil(self.frac * flat.size)))
        idx = np.argpartition(np.abs(flat), flat.size - k)[-k:]
        idx = idx.astype(np.int64 if flat.size > 2**31 else np.int32)
        return {"v": flat[idx], "i": idx, "n": list(arr.shape)}

    def decode_leaf(self, blob: dict) -> np.ndarray:
        shape = tuple(int(d) for d in blob["n"])
        out = np.zeros(int(np.prod(shape)), np.float32)
        out[blob["i"]] = blob["v"]
        return out.reshape(shape)


_REGISTRY = {"int8": Int8Codec, "topk": TopKCodec}


def register_codec(cls: type[Codec]) -> type[Codec]:
    """Register a custom codec class under ``cls.name`` (usable as a
    decorator). The PS decodes commits by name with a fresh ``cls()``, so
    a codec's ``decode_leaf`` must not depend on constructor configuration
    (the built-ins obey this: top-k's ``frac`` only shapes *encoding*) —
    and the registration must run in the PS owner's process too when the
    server is external (nothing but the name crosses the wire)."""
    if not (isinstance(cls, type) and issubclass(cls, Codec)):
        raise TypeError(f"register_codec expects a Codec subclass, got {cls}")
    _REGISTRY[cls.name] = cls
    return cls


def resolve_codec(compression) -> Codec | None:
    """Trainer kwarg → codec: ``None``, a registered name, or a Codec
    instance (auto-registered by name so the in-process PS can decode;
    external PS processes must :func:`register_codec` themselves)."""
    if compression is None:
        return None
    if isinstance(compression, Codec):
        cls = type(compression)
        reg = _REGISTRY.get(cls.name)
        if reg is None:
            try:
                cls()  # the PS decodes with a fresh cls() — fail HERE,
            except TypeError as e:  # not mid-training in a handler thread
                raise ValueError(
                    f"codec class {cls.__name__} must be constructible "
                    f"with no arguments for PS-side decode (got: {e}); "
                    f"give constructor params defaults that leave decode "
                    f"semantics unchanged"
                ) from e
            _REGISTRY[cls.name] = cls
        elif reg is not cls:
            raise ValueError(
                f"codec name {cls.name!r} is already registered to "
                f"{reg.__name__}; give your codec a unique `name` (decode "
                f"dispatches by name on the PS side)"
            )
        return compression
    if isinstance(compression, str):
        if compression in _REGISTRY:
            return _REGISTRY[compression]()
        raise ValueError(
            f"unknown compression {compression!r}; expected "
            f"{sorted(_REGISTRY)} or a Codec instance"
        )
    raise TypeError(f"compression must be None, str, or Codec, "
                    f"got {type(compression)}")


def validate_pull_compression(value):
    """Shared validator for the ``pull_compression`` knob (trainer kwarg
    and every PS client constructor): only the int8 block/leaf scheme has
    a server-side error-feedback implementation today. Returns the value.
    """
    if value not in (None, "int8"):
        raise ValueError(
            f"pull_compression must be None or 'int8', got {value!r}"
        )
    return value


def is_encoded(payload) -> bool:
    return isinstance(payload, dict) and _MARK in payload


def maybe_decode(payload: Pytree) -> Pytree:
    """PS-side seam: decode an encoded commit, pass a raw tree through."""
    if not is_encoded(payload):
        return payload
    name = payload[_MARK]
    if name not in _REGISTRY:
        raise ValueError(f"commit encoded with unknown codec {name!r}")
    codec = _REGISTRY[name]()
    return codec.decode(payload)
